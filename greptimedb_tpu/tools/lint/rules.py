"""gtlint rules GT001-GT010.

Each rule encodes a hazard class this codebase has actually been
bitten by (see the PR log in CHANGES.md): silent exception swallows
that hid datanode failures, substring matching on error text that the
typed-error migration obsoleted, host/device sync inside jitted hot
paths that shows up only as tail latency, and locks held across
blocking Flight I/O that serialize the ingest dataplane.
"""

from __future__ import annotations

import ast

from greptimedb_tpu.tools.lint import callgraph
from greptimedb_tpu.tools.lint.core import (
    FileContext,
    Rule,
    dotted_name,
    register,
    traced_value_use,
)


def _is_swallow_body(body: list[ast.stmt]) -> bool:
    """True when a handler body does nothing: only pass/`...`."""
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)):
            continue
        return False
    return True


_BROAD = {"Exception", "BaseException"}


def _handler_catches_broad(node: ast.ExceptHandler) -> bool:
    if node.type is None:
        return True
    types = (node.type.elts if isinstance(node.type, ast.Tuple)
             else [node.type])
    for t in types:
        d = dotted_name(t)
        if d is not None and d.split(".")[-1] in _BROAD:
            return True
    return False


@register
class SilentSwallow(Rule):
    id = "GT001"
    name = "silent-exception-swallow"
    description = (
        "`except Exception: pass` (or a bare except) discards the "
        "error with no trace. Narrow the exception type, re-raise, or "
        "log with context."
    )

    def visit_ExceptHandler(self, node: ast.ExceptHandler,
                            ctx: FileContext):
        if node.type is None:
            ctx.report(self, node,
                       "bare `except:` also catches KeyboardInterrupt/"
                       "SystemExit; catch a concrete exception type")
            return
        if _handler_catches_broad(node) and _is_swallow_body(node.body):
            ctx.report(self, node,
                       "broad except with an empty body silently "
                       "swallows the error; narrow the type, re-raise, "
                       "or log with context")


_EXC_HINT_NAMES = {"e", "ex", "exc", "err", "error", "exception"}


def _unwrap_str_call(node: ast.AST) -> ast.AST | None:
    """For `str(x)`, `str(x).lower()`, ... return x; else None."""
    while isinstance(node, ast.Call) and isinstance(node.func,
                                                    ast.Attribute):
        node = node.func.value
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "str" and node.args):
        return node.args[0]
    return None


@register
class ErrorSubstringMatch(Rule):
    id = "GT002"
    name = "error-substring-match"
    description = (
        "Classifying an exception by substring-matching its message "
        "(`'...' in str(e)`) breaks the moment the wording changes. "
        "Use isinstance on a typed error, or the `[gtdb:<code>]` "
        "marker via errors.error_from_code."
    )

    def visit_Compare(self, node: ast.Compare, ctx: FileContext):
        if not all(isinstance(op, (ast.In, ast.NotIn))
                   for op in node.ops):
            return
        for comp in node.comparators:
            inner = _unwrap_str_call(comp)
            if inner is None or not isinstance(inner, ast.Name):
                continue
            if (inner.id in ctx.exc_names
                    or inner.id in _EXC_HINT_NAMES):
                ctx.report(self, node,
                           f"substring match on str({inner.id}) — "
                           "classify via typed errors "
                           "(errors.error_from_code / isinstance), "
                           "not message text")


@register
class UntypedRaise(Rule):
    id = "GT003"
    name = "untyped-raise"
    description = (
        "Raising a plain `Exception` defeats the errors.py taxonomy: "
        "callers cannot catch it without a broad except, and it "
        "crosses the Flight boundary as UNKNOWN. Raise a GreptimeError "
        "subclass."
    )

    def visit_Raise(self, node: ast.Raise, ctx: FileContext):
        if ctx.path.replace("\\", "/").endswith("errors.py"):
            return
        exc = node.exc
        if isinstance(exc, ast.Call):
            exc = exc.func
        d = dotted_name(exc) if exc is not None else None
        if d in ("Exception", "BaseException"):
            ctx.report(self, node,
                       f"raise {d} is untyped; raise a GreptimeError "
                       "subclass from greptimedb_tpu.errors")


_HOST_SYNC_ATTRS = {"item", "tolist"}
_HOST_SYNC_CALLS = {
    "np.asarray", "np.array", "np.fromiter", "numpy.asarray",
    "numpy.array", "onp.asarray", "onp.array", "jax.device_get",
}


@register
class HostSyncInJit(Rule):
    id = "GT004"
    name = "host-sync-in-jit"
    description = (
        "Inside a @jax.jit function or Pallas kernel, `.item()`, "
        "np.asarray(...), float(x)/int(x) on traced values force a "
        "device->host transfer (or fail to trace), stalling the "
        "pipeline. Keep host conversions outside the jitted region."
    )

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        fi = ctx.device_func
        if fi is None:
            return
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOST_SYNC_ATTRS):
            ctx.report(self, node,
                       f".{node.func.attr}() inside a jitted/device "
                       "function forces host sync")
            return
        d = dotted_name(node.func)
        if d in _HOST_SYNC_CALLS:
            ctx.report(self, node,
                       f"{d}(...) inside a jitted/device function "
                       "materializes on host; use jnp instead")
            return
        if (isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int", "bool")
                and node.args
                and any(traced_value_use(a, fi) for a in node.args)):
            ctx.report(self, node,
                       f"{node.func.id}() on a traced value forces "
                       "host sync inside jit")
            return
        # interprocedural: a module-local helper that (transitively)
        # does .item()/.tolist()/device_get, called on a traced value
        # from inside the jitted region, syncs just the same
        s = ctx.call_summary.resolve_call(node, ctx.current_class)
        if (s is not None and s.host_sync
                and any(traced_value_use(a, fi) for a in node.args)):
            chain = " -> ".join(s.sync_chain)
            ctx.report(self, node,
                       f"{s.qualname}(...) on a traced value inside a "
                       f"jitted/device function reaches a host sync "
                       f"({chain}); hoist it out of the jitted region")


@register
class TracedPythonBranch(Rule):
    id = "GT005"
    name = "traced-python-branch"
    description = (
        "A Python `if`/`while` on a traced value inside jit forces "
        "concretization (TracerBoolConversionError at best, silent "
        "host sync at worst). Use jnp.where / lax.cond / lax.select, "
        "or mark the argument static."
    )

    def _check(self, test: ast.AST, node: ast.AST, ctx: FileContext,
               kind: str):
        fi = ctx.device_func
        if fi is None:
            return
        while isinstance(test, ast.UnaryOp) and isinstance(test.op,
                                                           ast.Not):
            test = test.operand
        if traced_value_use(test, fi):
            ctx.report(self, node,
                       f"Python {kind} on a traced value inside a "
                       "jitted/device function; use jnp.where / "
                       "lax.cond or a static arg")

    def visit_If(self, node: ast.If, ctx: FileContext):
        self._check(node.test, node, ctx, "if")

    def visit_IfExp(self, node: ast.IfExp, ctx: FileContext):
        self._check(node.test, node, ctx, "conditional expression")

    def visit_While(self, node: ast.While, ctx: FileContext):
        self._check(node.test, node, ctx, "while")


def _is_jit_call(node: ast.Call) -> bool:
    f = dotted_name(node.func)
    if f in ("jax.jit", "jit", "jax.pjit", "pjit"):
        return True
    if f in ("functools.partial", "partial") and node.args:
        return dotted_name(node.args[0]) in ("jax.jit", "jit")
    return False


@register
class RecompileHazard(Rule):
    id = "GT006"
    name = "recompile-hazard"
    description = (
        "jax.jit(...) constructed inside a loop (or over a lambda "
        "inside a function body) builds a fresh cache entry per "
        "iteration/call — every invocation recompiles. Hoist the "
        "jitted callable to module scope or cache it."
    )

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        if not _is_jit_call(node):
            return
        if ctx.loop_depth > 0:
            ctx.report(self, node,
                       "jax.jit constructed inside a loop recompiles "
                       "every iteration; hoist it out")
        elif (ctx.func_stack
              and node.args
              and isinstance(node.args[-1], ast.Lambda)):
            ctx.report(self, node,
                       "jax.jit(lambda ...) inside a function creates "
                       "a new callable (and compile cache entry) per "
                       "call; define and jit it at module scope")


@register
class LockAcrossBlockingIO(Rule):
    id = "GT007"
    name = "lock-across-blocking-io"
    description = (
        "A threading.Lock held across blocking I/O (sockets, HTTP, "
        "Arrow Flight do_get/do_put/do_action, sleep) serializes every "
        "other thread on that lock for the full I/O latency — directly "
        "or through any chain of module-local helper calls. Copy the "
        "state out under the lock, do the I/O outside it."
    )

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        if ctx.lock_depth == 0:
            return
        label = callgraph.blocking_label(node)
        if label is not None:
            ctx.report(self, node,
                       f"{label}(...) called while holding a lock "
                       "blocks every other waiter for the full I/O "
                       "latency; move the call outside the lock")
            return
        # interprocedural: a module-local helper that (transitively)
        # blocks is just as bad as the direct call
        s = ctx.call_summary.resolve_call(node, ctx.current_class)
        if s is not None and s.blocking:
            chain = " -> ".join(s.block_chain)
            ctx.report(self, node,
                       f"{s.qualname}(...) called while holding a "
                       f"lock reaches blocking I/O ({chain}); move "
                       "the call outside the lock")


def _assign_target_segment(ctx: FileContext) -> str | None:
    """Last name segment of the Assign target the dispatched call
    feeds, e.g. '_worker' for `self._worker = threading.Thread(...)`,
    't' for `t = Thread(...)`. None when not directly assigned."""
    parent = ctx.parent(1)
    if isinstance(parent, (ast.Assign, ast.AnnAssign)):
        tgt = (parent.targets[0] if isinstance(parent, ast.Assign)
               else parent.target)
        d = dotted_name(tgt)
        if d:
            return d.split(".")[-1]
    return None


@register
class UnjoinedThread(Rule):
    id = "GT008"
    name = "unjoined-thread"
    description = (
        "A non-daemon Thread that is never join()ed (or a "
        "ThreadPoolExecutor never shutdown and not used as a context "
        "manager) leaks and can hang interpreter exit. Pass "
        "daemon=True, join it, or shut the pool down in close()."
    )

    def _has_kw(self, node: ast.Call, name: str, value=True) -> bool:
        for kw in node.keywords:
            if (kw.arg == name and isinstance(kw.value, ast.Constant)
                    and kw.value.value is value):
                return True
        return False

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        d = dotted_name(node.func)
        if d is None:
            return
        last = d.split(".")[-1]
        if last == "Thread":
            if self._has_kw(node, "daemon"):
                return
            seg = _assign_target_segment(ctx)
            scope = ctx.scope_text(cls=bool(ctx.class_stack))
            if seg is not None and f"{seg}.join(" in scope:
                return
            ctx.report(self, node,
                       "Thread without daemon=True and no matching "
                       ".join() in scope leaks on shutdown")
        elif last == "ThreadPoolExecutor":
            parent = ctx.parent(1)
            if isinstance(parent, (ast.withitem, ast.With)):
                return          # `with ThreadPoolExecutor(...) as ..`
            seg = _assign_target_segment(ctx)
            scope = (ctx.scope_text(cls=True) if ctx.class_stack
                     else ctx.source)
            # evidence the pool is torn down: either a direct
            # `<name>.shutdown(...)`, or the swap-to-local teardown
            # idiom (`pool, self._x = self._x, None` then
            # `pool.shutdown()` outside the lock) — approximated as
            # the name and a .shutdown( call both present in scope
            if seg is not None and (f"{seg}.shutdown(" in scope
                                    or f"{seg}.join(" in scope
                                    or (seg in scope
                                        and ".shutdown(" in scope)):
                return
            ctx.report(self, node,
                       "ThreadPoolExecutor with no shutdown() in "
                       "scope and not used as a context manager "
                       "leaks worker threads")


_INT64_DOTTED = {"jnp.int64", "jax.numpy.int64", "jnp.uint64",
                 "jax.numpy.uint64"}


@register
class Int64OnDevice(Rule):
    id = "GT009"
    name = "int64-on-device"
    description = (
        "jnp int64/uint64 silently downcasts to 32-bit unless x64 is "
        "enabled, and is slow on TPU where it is emulated. Use int32 "
        "(guard row counts < 2^31 on host), or gate explicitly on the "
        "x64 flag."
    )

    def visit_Attribute(self, node: ast.Attribute, ctx: FileContext):
        d = dotted_name(node)
        if d in _INT64_DOTTED:
            ctx.report(self, node,
                       f"{d} downcasts silently without x64 and is "
                       "emulated on TPU; prefer int32 or gate on x64")

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        d = dotted_name(node.func)
        if not d or not (d.startswith("jnp.")
                         or d.startswith("jax.numpy.")):
            return
        for kw in node.keywords:
            if kw.arg != "dtype":
                continue
            kd = dotted_name(kw.value)
            if (kd in ("np.int64", "numpy.int64", "np.uint64")
                    or (isinstance(kw.value, ast.Constant)
                        and kw.value.value in ("int64", "uint64"))):
                ctx.report(self, node,
                           f"{d}(dtype=int64) on device; prefer int32 "
                           "or gate on x64")


def _is_walltime_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and dotted_name(node.func) in (
        "time.time", "_time.time")


def _contains_walltime_call(expr: ast.AST) -> bool:
    """Does `expr` contain a time.time() call in the *interval* domain?

    The exact idiom `time.time() * 1000` (either operand order) is the
    codebase's epoch-ms DATA-timestamp constructor — arithmetic on the
    result compares against row timestamps, where wall clock is the
    point — so it is exempt.  `(time.time() - t0) * 1000` is NOT: the
    subtraction happens in the time domain and stays flagged."""

    def scan(node: ast.AST) -> bool:
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
            def ms(n):
                return (isinstance(n, ast.Constant)
                        and n.value in (1000, 1000.0))

            if ((_is_walltime_call(node.left) and ms(node.right))
                    or (_is_walltime_call(node.right) and ms(node.left))):
                return False        # epoch-ms data timestamp
        if _is_walltime_call(node):
            return True
        return any(scan(c) for c in ast.iter_child_nodes(node))

    return scan(expr)


@register
class WallClockDuration(Rule):
    id = "GT011"
    name = "wallclock-duration"
    description = (
        "Duration/deadline arithmetic on time.time() jumps with NTP "
        "slews and DST — a retry window or cooldown can silently "
        "double or go negative. Use time.monotonic() for elapsed/"
        "deadline math; time.time() is for *data* timestamps only "
        "(the epoch-ms constructor `time.time() * 1000` is exempt)."
    )

    @staticmethod
    def _scan_assigns(scope: ast.AST, *, skip_nested: bool) -> set[str]:
        """Names assigned from a wall-time expression within `scope`'s
        own statements (optionally not descending into nested function
        bodies — their bindings are a different scope)."""
        names: set[str] = set()
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if skip_nested and isinstance(node, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef,
                                                 ast.Lambda)):
                continue
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and _contains_walltime_call(node.value)):
                names.add(node.targets[0].id)
            stack.extend(ast.iter_child_nodes(node))
        return names

    def _wall_names(self, ctx: FileContext) -> set[str]:
        """Names bound to time.time() in the CURRENT scope: the
        enclosing function's own assignments plus module-level ones.
        Scoped per function — `now = time.time()` in one function must
        not poison a monotonic `now` in another."""
        cache = getattr(ctx, "_gt011_scopes", None)
        if cache is None:
            cache = ctx._gt011_scopes = {}
        if "module" not in cache:
            cache["module"] = self._scan_assigns(ctx.tree,
                                                 skip_nested=True)
        fi = ctx.current_func
        if fi is None:
            return cache["module"]
        key = id(fi.node)
        if key not in cache:
            cache[key] = self._scan_assigns(fi.node, skip_nested=True)
        return cache[key] | cache["module"]

    def visit_BinOp(self, node: ast.BinOp, ctx: FileContext):
        if not isinstance(node.op, (ast.Add, ast.Sub)):
            return
        for side in (node.left, node.right):
            if _contains_walltime_call(side):
                ctx.report(self, node,
                           "duration/deadline arithmetic on "
                           "time.time(); use time.monotonic() (wall "
                           "clock is for data timestamps, not "
                           "intervals)")
                return
            if (isinstance(side, ast.Name)
                    and side.id in self._wall_names(ctx)):
                ctx.report(self, node,
                           f"{side.id} holds time.time() and feeds "
                           "duration/deadline arithmetic; use "
                           "time.monotonic() for interval math")
                return


_FLIGHT_CLIENT_CALLS = {"do_get", "do_put", "do_action"}
_TIMEOUT_KW_CALLS = {"urlopen", "create_connection"}


@register
class UnboundedBlockingCall(Rule):
    id = "GT012"
    name = "unbounded-blocking-call"
    description = (
        "An Arrow Flight client call (do_get/do_put/do_action) without "
        "explicit call `options`, or urlopen/socket.create_connection "
        "without a `timeout`, waits on the gRPC/socket default — "
        "i.e. forever against a blackholed peer. Every blocking call "
        "carries an explicit deadline decision at the call site "
        "(sched/deadline.call_timeout for query-path calls); "
        "intentionally unbounded long-lived streams suppress with a "
        "justification."
    )

    @staticmethod
    def _has_kw(node: ast.Call, name: str) -> bool:
        return any(kw.arg == name for kw in node.keywords)

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        if not isinstance(node.func, ast.Attribute):
            # bare urlopen(...) from `from urllib.request import
            # urlopen` still needs the timeout (keyword OR positional:
            # urlopen(url, data, timeout) / create_connection(addr,
            # timeout) — same shapes the attribute branch accepts)
            if (isinstance(node.func, ast.Name)
                    and node.func.id in _TIMEOUT_KW_CALLS):
                pos_ok = (len(node.args) >= 3
                          if node.func.id == "urlopen"
                          else len(node.args) >= 2)
                if not pos_ok and not self._has_kw(node, "timeout"):
                    ctx.report(self, node,
                               f"{node.func.id}(...) without timeout= "
                               "blocks forever against a blackholed "
                               "peer; pass an explicit timeout")
            return
        attr = node.func.attr
        if attr in _FLIGHT_CLIENT_CALLS:
            # server-side handler plumbing (self._do_action and co.)
            # is not a Flight client call; the client calls go through
            # a connection object, never self/cls
            base = dotted_name(node.func.value)
            if base in ("self", "cls"):
                return
            if not self._has_kw(node, "options"):
                ctx.report(self, node,
                           f".{attr}(...) without explicit call "
                           "options carries no deadline — a "
                           "blackholed peer hangs the caller; pass "
                           "options=FlightCallOptions(timeout=...) "
                           "(None only as an explicit decision)")
        elif attr in _TIMEOUT_KW_CALLS:
            # positional timeout: urlopen(url, data, timeout) /
            # socket.create_connection(addr, timeout)
            pos_ok = (len(node.args) >= 3 if attr == "urlopen"
                      else len(node.args) >= 2)
            if not pos_ok and not self._has_kw(node, "timeout"):
                ctx.report(self, node,
                           f"{attr}(...) without timeout= blocks "
                           "forever against a blackholed peer; pass "
                           "an explicit timeout")


_MUTABLE_CTORS = {"list", "dict", "set"}


# collective -> index of its axis-name argument
_COLLECTIVES = {
    "psum": 1, "pmin": 1, "pmax": 1, "pmean": 1, "all_gather": 1,
    "ppermute": 1, "psum_scatter": 1, "all_to_all": 1, "axis_index": 0,
}


@register
class UnboundCollectiveAxis(Rule):
    id = "GT013"
    name = "unbound-collective-axis"
    description = (
        "A collective (psum/pmin/pmax/all_gather/...) inside a "
        "shard_map body references an axis name the enclosing "
        "shard_map call does not bind: it fails at trace time with an "
        "unbound-axis error, or silently reduces over the wrong axis "
        "when an outer mesh happens to share the name."
    )

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        f = dotted_name(node.func)
        if not f:
            return
        short = f.split(".")[-1]
        pos = _COLLECTIVES.get(short)
        if pos is None:
            return
        # innermost enclosing shard_map kernel with a known binding
        bound = None
        for fi in reversed(ctx.func_stack):
            axes = ctx.shard_map_axes.get((fi.name, fi.node.lineno))
            if axes:
                bound = axes
                break
        if not bound:
            return
        axis_node = None
        if len(node.args) > pos:
            axis_node = node.args[pos]
        else:
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    axis_node = kw.value
        if axis_node is None:
            return
        axis = ctx.axis_name_of(axis_node)
        if axis is None or axis in bound:
            return
        # only compare within one resolution space: an unresolved
        # identifier could still equal a literal axis name (and vice
        # versa), so mixed comparisons stay silent
        if axis.startswith("id:"):
            if not all(a.startswith("id:") for a in bound):
                return
        elif any(a.startswith("id:") for a in bound):
            return
        shown = sorted(a.removeprefix("id:") for a in bound)
        ctx.report(self, node,
                   f"collective {short}(...) references axis "
                   f"{axis.removeprefix('id:')!r} not bound by the "
                   f"enclosing shard_map (binds: {', '.join(shown)})")


# telemetry surfaces whose invocation inside a traced (device) scope
# is a hazard: span context managers allocate + touch contextvars and
# the ring lock; metric/stat calls take locks and read wall clocks.
# Inside jit/shard_map these either burn host work on every trace, or
# capture a Python-side value and silently stop updating after the
# first compilation — and any traced-value argument forces a host sync.
_STATS_MODULES = {"stats", "qstats"}
_STATS_FUNCS = {"add", "note", "timed"}
# mutating methods only: flagging .labels() too would double-report
# the idiomatic _METRIC.labels(x).inc(1) chain
_METRIC_METHODS = {"inc", "dec", "observe", "set"}


def _is_metric_constant(node: ast.AST) -> bool:
    """Module-level metric objects follow the ALL_CAPS constant idiom
    (`_STAGE_MS.labels(...).inc(...)`, `_REQS.inc()`)."""
    while isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        node = node.value
        while isinstance(node, (ast.Attribute, ast.Call)):
            node = (node.value if isinstance(node, ast.Attribute)
                    else node.func)
    if not isinstance(node, ast.Name):
        return False
    name = node.id.lstrip("_")
    return bool(name) and name.isupper()


@register
class TelemetryInDeviceScope(Rule):
    id = "GT014"
    name = "telemetry-in-device-scope"
    description = (
        "A tracing span or metrics/stats call inside a jit/shard_map/"
        "Pallas device scope is a host-sync and recompile hazard: the "
        "call runs at TRACE time (so it fires once per compilation, "
        "not once per execution — metrics silently freeze), touches "
        "locks/contextvars on the host, and any traced-value argument "
        "forces a device->host transfer. Wrap the CALL boundary from "
        "host scope instead (telemetry/device_trace.py)."
    )

    def _report(self, node, ctx: FileContext, what: str):
        ctx.report(self, node,
                   f"{what} inside a jitted/device function runs at "
                   "trace time, not execution time; move the "
                   "span/metric to the host-side call boundary "
                   "(telemetry/device_trace.py)")

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        if ctx.device_func is None:
            return
        f = dotted_name(node.func)
        if f:
            parts = f.split(".")
            if any(seg == "tracing" for seg in parts[:-1]) or (
                    len(parts) >= 2
                    and parts[-2] in ("tracing", "device_trace")):
                self._report(node, ctx, f"tracing call {f}(...)")
                return
            if f in ("span", "start_remote", "child_span",
                     "event_span", "device_call"):
                # bare-name telemetry entry points (from-imports)
                self._report(node, ctx, f"tracing call {f}(...)")
                return
            if (len(parts) == 2 and parts[0] in _STATS_MODULES
                    and parts[1] in _STATS_FUNCS):
                self._report(node, ctx, f"stats call {f}(...)")
                return
            if "global_registry" in parts:
                self._report(node, ctx, f"metrics call {f}(...)")
                return
        # metric-object method calls: _COUNTER.labels(x).inc(1) — the
        # receiver is a module-level ALL_CAPS metric constant
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS
                and _is_metric_constant(node.func)):
            self._report(
                node, ctx,
                f".{node.func.attr}() on a module-level metric"
            )


_READBACK_CALLS = {"np.asarray", "numpy.asarray", "onp.asarray",
                   "jax.device_get"}


@register
class FullBufferReadback(Rule):
    id = "GT015"
    name = "full-buffer-readback"
    description = (
        "np.asarray()/jax.device_get() on a device result buffer (a "
        "name this function called .block_until_ready() on) reads the "
        "WHOLE buffer back across the host<->device tunnel, "
        "unattributed. Route result readbacks through "
        "query/readback.read_full (bytes land on "
        "gtpu_readback_bytes_total) or read_delta (a since-cursor poll "
        "slices device-side and ships only the unseen rows)."
    )

    @staticmethod
    def _scan_blocked(scope, *, skip_nested: bool) -> set[str]:
        """Names `X` with an `X.block_until_ready()` call in `scope`'s
        own statements — the device-result-buffer idiom."""
        names: set[str] = set()
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if skip_nested and isinstance(node, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef,
                                                 ast.Lambda)):
                continue
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "block_until_ready"
                    and isinstance(node.func.value, ast.Name)):
                names.add(node.func.value.id)
            stack.extend(ast.iter_child_nodes(node))
        return names

    def _blocked_names(self, ctx: FileContext) -> set[str]:
        cache = getattr(ctx, "_gt015_scopes", None)
        if cache is None:
            cache = ctx._gt015_scopes = {}
        fi = ctx.current_func
        if fi is None:
            if "module" not in cache:
                cache["module"] = self._scan_blocked(ctx.tree,
                                                     skip_nested=True)
            return cache["module"]
        key = id(fi.node)
        if key not in cache:
            cache[key] = self._scan_blocked(fi.node, skip_nested=True)
        return cache[key]

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        if ctx.path.replace("\\", "/").endswith("query/readback.py"):
            return  # the helpers ARE the blessed readback point
        d = dotted_name(node.func)
        if d not in _READBACK_CALLS or not node.args:
            return
        arg = node.args[0]
        if not isinstance(arg, ast.Name):
            return
        if arg.id in self._blocked_names(ctx):
            ctx.report(self, node,
                       f"{d}({arg.id}) reads the whole device buffer "
                       "back unattributed; use query/readback."
                       "read_full (or read_delta for a since-cursor "
                       "slice) so the bytes land on "
                       "gtpu_readback_bytes_total")


# byte-budget attribute/value tokens: the LRU-with-byte-budget idiom
# assigns self.max_bytes / self.byte_budget / self.capacity =
# capacity_bytes / ... in __init__. Entry-count-only containers
# (capacity without "byte" anywhere) are not byte pools.
_GT016_BUDGET_TOKENS = ("max_bytes", "byte_budget", "budget_bytes",
                        "capacity_bytes", "hbm_bytes")
_GT016_DEVICE_PUTS = ("device_put", "asarray")


@register
class UnregisteredMemoryPool(Rule):
    id = "GT016"
    name = "unregistered-memory-pool"
    description = (
        "A byte-budgeted container (a class assigning a byte budget "
        "AND an entries dict, or a module-level dict cache holding "
        "device arrays) that never registers with the process-wide "
        "memory accountant (telemetry/memory.py register_pool) is an "
        "invisible memory pool: its bytes appear in no unified "
        "surface, the device census reads its buffers as leaks, and "
        "the global [memory] device_budget_bytes watermark cannot "
        "evict from it."
    )

    @staticmethod
    def _is_exempt(ctx: FileContext) -> bool:
        # the accountant itself is not a pool
        return ctx.path.replace("\\", "/").endswith(
            "telemetry/memory.py"
        )

    @staticmethod
    def _self_attr_target(node):
        """The attribute name of a `self.X = ...` / `self.X: T = ...`
        assignment, else None."""
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            tgt = node.target
        else:
            return None
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            return tgt.attr
        return None

    @staticmethod
    def _is_dict_value(node) -> bool:
        value = (node.value if isinstance(node, (ast.Assign,
                                                 ast.AnnAssign))
                 else None)
        if isinstance(value, ast.Dict):
            return True
        if isinstance(value, ast.Call):
            f = dotted_name(value.func)
            return f is not None and f.split(".")[-1] in (
                "dict", "OrderedDict"
            )
        return False

    def _budget_assign(self, node) -> bool:
        attr = self._self_attr_target(node)
        if attr is None:
            return False
        low = attr.lstrip("_").lower()
        if any(tok in low for tok in _GT016_BUDGET_TOKENS):
            return True
        value = node.value
        return any(
            isinstance(n, ast.Name)
            and any(tok in n.id.lower() for tok in _GT016_BUDGET_TOKENS)
            for n in ast.walk(value)
        )

    def visit_ClassDef(self, node: ast.ClassDef, ctx: FileContext):
        if self._is_exempt(ctx):
            return
        has_budget = False
        has_container = False
        registers = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                f = dotted_name(sub.func)
                if f and f.split(".")[-1] == "register_pool":
                    registers = True
                    break
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                if self._self_attr_target(sub) is not None:
                    if self._is_dict_value(sub):
                        has_container = True
                    if self._budget_assign(sub):
                        has_budget = True
        if has_budget and has_container and not registers:
            ctx.report(self, node,
                       f"class {node.name} holds a byte-budgeted "
                       "entries container but never calls "
                       "memory.register_pool(); register it so its "
                       "bytes land on gtpu_mem_* and the device "
                       "census/global watermark can see it")

    def visit_Module(self, node: ast.Module, ctx: FileContext):
        """Module-level dict caches holding device arrays: a
        `_GRIDS = {}` that gets `_GRIDS[k] = jax.device_put(...)` /
        `jnp.asarray(...)` somewhere in the module pins HBM outside
        any class — the accountant must know about it too."""
        if self._is_exempt(ctx):
            return
        module_dicts: set[str] = set()
        for stmt in node.body:
            name = None
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                name = stmt.targets[0].id
            elif (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                    and stmt.value is not None):
                name = stmt.target.id
            if name is not None and self._is_dict_value(stmt):
                module_dicts.add(name)
        if not module_dicts:
            return
        registers = any(
            isinstance(sub, ast.Call)
            and (dotted_name(sub.func) or "").split(".")[-1]
            == "register_pool"
            for sub in ast.walk(node)
        )
        if registers:
            return
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Assign)
                    and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Subscript)):
                continue
            base = sub.targets[0].value
            if not (isinstance(base, ast.Name)
                    and base.id in module_dicts):
                continue
            holds_device = any(
                isinstance(n, ast.Call)
                and (dotted_name(n.func) or "").split(".")[-1]
                in _GT016_DEVICE_PUTS
                and (dotted_name(n.func) or "").split(".")[0]
                in ("jax", "jnp")
                for n in ast.walk(sub.value)
            )
            if holds_device:
                ctx.report(self, sub,
                           f"module-level dict {base.id} caches device "
                           "arrays but the module never calls "
                           "memory.register_pool(); the census reads "
                           "these buffers as unaccounted leaks")
                return


@register
class MutableDefaultArg(Rule):
    id = "GT010"
    name = "mutable-default-arg"
    description = (
        "A mutable default ([], {}, set()) is shared across every "
        "call of a public function — state leaks between callers. "
        "Default to None and create inside."
    )

    def _check(self, node, ctx: FileContext):
        if node.name.startswith("_"):
            return
        defaults = list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]
        for d in defaults:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                ctx.report(self, d,
                           f"mutable default argument in public "
                           f"function {node.name}(); use None")
            elif (isinstance(d, ast.Call)
                  and isinstance(d.func, ast.Name)
                  and d.func.id in _MUTABLE_CTORS and not d.args
                  and not d.keywords):
                ctx.report(self, d,
                           f"mutable default argument in public "
                           f"function {node.name}(); use None")

    visit_FunctionDef = _check
    visit_AsyncFunctionDef = _check


# metric-registration receivers GT017 inspects: the in-process
# prometheus registries (global_registry / a local `registry` /
# `self._registry` handle). Unrelated `.counter(...)` methods on other
# objects stay silent.
_GT017_KINDS = ("counter", "gauge", "histogram")
_GT017_TIME_TOKENS = ("seconds", "duration", "latency", "_time",
                      "elapsed", "_ms")


@register
class UntrackedDeviceDispatch(Rule):
    id = "GT018"
    name = "untracked-device-dispatch"
    description = (
        "Calling a jit/shard_map-produced callable outside a "
        "`device_call` scope dispatches an XLA program the device "
        "profiler (telemetry/device_programs.py) cannot see: no "
        "compile/execute attribution, no registry row, no roofline "
        "verdict. Dispatch through "
        "`with device_trace.device_call(site, key=...) as d: "
        "d.run(fn, ...)` instead. Calls INSIDE jit/shard_map/Pallas "
        "scope are inlining (tracing), not dispatches, and stay "
        "silent; so do callables the walker cannot prove jit-produced "
        "(builder-returned programs), which the registry still counts "
        "at their device_call site."
    )

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        if not isinstance(node.func, ast.Name):
            return
        if node.func.id not in ctx.jit_callables:
            return
        if ctx.device_func is not None:
            return  # traced scope: inlined into the enclosing program
        if ctx.device_call_depth > 0:
            return  # tracked dispatch
        ctx.report(self, node,
                   f"jit-produced callable {node.func.id}() dispatched "
                   "outside a device_call scope — the device profiler "
                   "cannot attribute it; wrap the dispatch in `with "
                   "device_trace.device_call(site, key=...) as d: "
                   "d.run(...)`")


@register
class MetricNamingConvention(Rule):
    id = "GT017"
    name = "metric-naming-convention"
    description = (
        "Prometheus naming conventions keep the exported surface "
        "machine-readable: counter names end `_total`, a histogram "
        "measuring time carries its unit suffix (`_seconds` or `_ms`, "
        "matching what it observes), and label names are lowercase "
        "(dashboards and the self-export reingest key on exact label "
        "names)."
    )

    @staticmethod
    def _registry_receiver(node: ast.Call) -> bool:
        f = dotted_name(node.func)
        if f is None:
            return False
        parts = f.split(".")
        if parts[-1] not in _GT017_KINDS or len(parts) < 2:
            return False
        recv = parts[-2].lstrip("_").lower()
        return recv == "registry" or recv.endswith("registry")

    @staticmethod
    def _literal(node) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        return None

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        if not self._registry_receiver(node):
            return
        kind = dotted_name(node.func).split(".")[-1]
        name = self._literal(node.args[0]) if node.args else None
        if name is not None:
            if kind == "counter" and not name.endswith("_total"):
                ctx.report(self, node,
                           f"counter {name!r} must end in '_total' "
                           "(prometheus counter naming convention)")
            if kind == "histogram":
                low = name.lower()
                timeish = any(t in low for t in _GT017_TIME_TOKENS)
                if timeish and not (low.endswith("_seconds")
                                    or low.endswith("_ms")):
                    ctx.report(self, node,
                               f"time histogram {name!r} must carry "
                               "its unit suffix ('_seconds' or '_ms' "
                               "matching the observed unit)")
        # label names: the `labels=` keyword (or third positional arg)
        labels_node = None
        for kw in node.keywords:
            if kw.arg == "labels":
                labels_node = kw.value
        if labels_node is None and len(node.args) >= 3:
            labels_node = node.args[2]
        if isinstance(labels_node, (ast.Tuple, ast.List)):
            for el in labels_node.elts:
                lab = self._literal(el)
                if lab is not None and lab != lab.lower():
                    ctx.report(self, el,
                               f"label name {lab!r} must be lowercase "
                               "(exported label names are part of the "
                               "query surface)")


# scrape/heartbeat-path entry points GT019 guards: callbacks handed to
# a metrics registry's register_collector (run on EVERY /metrics
# render), the stats/buffers/evict hooks registered with the memory
# accountant (same scrape path), and the heartbeat-payload builder
# contract (telemetry/node_stats.build_node_stats rides every metasrv
# heartbeat).
_GT019_BUILDER_NAMES = {"build_node_stats"}


@register
class UnboundedScrapePathIO(Rule):
    id = "GT019"
    name = "unbounded-io-in-scrape-path"
    description = (
        "Blocking network I/O without an explicit bound inside a "
        "registered MetricsRegistry collector hook or a heartbeat-"
        "payload builder: collectors run on every /metrics render and "
        "the payload builder rides every metasrv heartbeat, so one "
        "hung peer would stall every scrape/heartbeat of this node — "
        "exactly the liveness channel that must never hang. Pass an "
        "explicit timeout/options bound, or move the I/O off the "
        "scrape path entirely (cache it from a background task)."
    )

    def _hooks(self, ctx: FileContext) -> set[str]:
        """Names of this file's scrape-path functions: anything handed
        to <registry>.register_collector(...), the named stats/evict/
        buffers callbacks of a register_pool(...) call, and the
        heartbeat-payload builder names."""
        cache = getattr(ctx, "_gt019_hooks", None)
        if cache is not None:
            return cache
        hooks = set(_GT019_BUILDER_NAMES)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = dotted_name(node.func)
            if f is None:
                continue
            short = f.split(".")[-1]
            if short == "register_collector" and node.args:
                a = node.args[0]
                if isinstance(a, ast.Name):
                    hooks.add(a.id)
            elif short == "register_pool":
                for kw in node.keywords:
                    if (kw.arg in ("stats", "buffers", "evict")
                            and isinstance(kw.value, ast.Name)):
                        hooks.add(kw.value.id)
        ctx._gt019_hooks = hooks
        return hooks

    @staticmethod
    def _has_kw(node: ast.Call, name: str) -> bool:
        return any(kw.arg == name for kw in node.keywords)

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        if not ctx.func_stack:
            return
        hooks = self._hooks(ctx)
        # nested defs inside a hook are still on the scrape path
        if not any(fi.name in hooks for fi in ctx.func_stack):
            return
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
        elif isinstance(node.func, ast.Name):
            attr = node.func.id
        else:
            return
        if attr in _FLIGHT_CLIENT_CALLS:
            # NO self/cls exemption here: inside a collector even an
            # internally-dispatched Flight call is wire I/O riding the
            # scrape path
            if not self._has_kw(node, "options"):
                ctx.report(self, node,
                           f".{attr}(...) inside a scrape/heartbeat "
                           "hook without explicit call options — a "
                           "hung peer stalls every scrape of this "
                           "node; pass options=FlightCallOptions("
                           "timeout=...) or move the I/O off the "
                           "scrape path")
        elif attr in _TIMEOUT_KW_CALLS:
            pos_ok = (len(node.args) >= 3 if attr == "urlopen"
                      else len(node.args) >= 2)
            if not pos_ok and not self._has_kw(node, "timeout"):
                ctx.report(self, node,
                           f"{attr}(...) inside a scrape/heartbeat "
                           "hook without a timeout — a hung peer "
                           "stalls every scrape/heartbeat of this "
                           "node; pass an explicit timeout")
        elif attr == "HTTPConnection":
            if not self._has_kw(node, "timeout"):
                ctx.report(self, node,
                           "HTTPConnection(...) inside a scrape/"
                           "heartbeat hook without a timeout — "
                           "requests on it block forever against a "
                           "blackholed peer; pass timeout=")


# runtime-mutable knob attributes GT021 guards (the standard knob set
# autotune/knobs.build_registry registers). The sanctioned writers:
# the autotune package (the registry's apply closures), the owning
# object's own methods (root `self`/`cls` — set_max_bytes and friends
# mutate their own field), and process-start config appliers
# (configure/from_options/__init__). GT020 is reserved.
_GT021_KNOB_ATTRS = {
    "max_concurrency", "shard_min_series", "shard_min_rows",
    "max_bytes", "workers", "l1_trigger_files", "l2_trigger_files",
}
_GT021_EXEMPT_FUNCS = {"__init__", "configure", "from_options",
                       "reset_for_tests"}


@register
class DirectKnobWrite(Rule):
    id = "GT021"
    name = "direct-knob-write"
    description = (
        "Direct assignment to a registered runtime-mutable knob "
        "attribute outside the owning object / the autotune package. "
        "Every runtime knob change must ride KnobRegistry.set (the "
        "autotune actuators and ADMIN set_config both do) so the "
        "bounds are validated, the change lands in the "
        "information_schema.autotune_decisions audit log, and the "
        "control loop stays the SINGLE writer — a second ad-hoc "
        "writer and a controller would silently fight over the knob."
    )

    def _flag(self, target: ast.expr, ctx: FileContext):
        if not isinstance(target, ast.Attribute):
            return
        if target.attr not in _GT021_KNOB_ATTRS:
            return
        root = target.value
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name) and root.id in ("self", "cls"):
            return  # the owning object mutating its own field
        path = ctx.path.replace("\\", "/")
        if "/autotune/" in path or path.startswith("autotune/"):
            return  # the registry's apply closures ARE the write path
        if any(fi.name in _GT021_EXEMPT_FUNCS
               for fi in ctx.func_stack):
            return  # process-start config applier
        ctx.report(self, target,
                   f"direct write to runtime-mutable knob attribute "
                   f"`.{target.attr}`; route it through "
                   f"KnobRegistry.set (ADMIN set_config / the "
                   f"autotune actuators) so bounds are validated and "
                   f"the change is audited")

    def visit_Assign(self, node: ast.Assign, ctx: FileContext):
        for t in node.targets:
            if isinstance(t, ast.Tuple):
                for e in t.elts:
                    self._flag(e, ctx)
            else:
                self._flag(t, ctx)

    def visit_AugAssign(self, node: ast.AugAssign, ctx: FileContext):
        self._flag(node.target, ctx)


@register
class PallasCallHygiene(Rule):
    id = "GT022"
    name = "pallas-call-hygiene"
    description = (
        "Pallas kernel dispatch hygiene. Every pallas_call must thread "
        "`interpret=` from the kernels config (interpret_mode() or a "
        "parameter): a hard-coded literal either pins the slow "
        "interpreter onto real TPUs (True) or breaks the CPU twin the "
        "CI runs on (False, or the keyword missing entirely). And a "
        "make_async_remote_copy whose device_id names a mesh axis the "
        "enclosing shard_map does not bind fails at trace time or "
        "RDMAs around the wrong ring — the same unbound-axis hazard "
        "GT013 guards for collectives. (Kernel bodies themselves are "
        "already device scope: GT004/GT014 apply inside them.)"
    )

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        f = dotted_name(node.func)
        if not f:
            return
        short = f.split(".")[-1]
        if short == "pallas_call":
            self._check_interpret(node, ctx)
        elif short == "make_async_remote_copy":
            self._check_device_id(node, ctx)

    def _check_interpret(self, node: ast.Call, ctx: FileContext):
        kw = None
        for k in node.keywords:
            if k.arg == "interpret":
                kw = k
        if kw is None:
            if any(k.arg is None for k in node.keywords):
                return  # a **kwargs splat may carry interpret=
            ctx.report(self, node,
                       "pallas_call without `interpret=` — thread it "
                       "from the kernels config (interpret_mode() or a "
                       "parameter); without it the CPU interpret twin "
                       "can never run this kernel")
        elif (isinstance(kw.value, ast.Constant)
                and isinstance(kw.value.value, bool)):
            ctx.report(self, node,
                       f"pallas_call with hard-coded interpret="
                       f"{kw.value.value} — thread it from the kernels "
                       "config (interpret_mode() or a parameter) so one "
                       "call site serves both the CPU interpret twin "
                       "and the native Mosaic path")

    def _check_device_id(self, node: ast.Call, ctx: FileContext):
        dev = None
        for k in node.keywords:
            if k.arg == "device_id":
                dev = k.value
        if dev is None:
            return
        # innermost enclosing shard_map kernel with a known binding
        # (same anchoring as GT013)
        bound = None
        for fi in reversed(ctx.func_stack):
            axes = ctx.shard_map_axes.get((fi.name, fi.node.lineno))
            if axes:
                bound = axes
                break
        if not bound:
            return
        # axis-name candidates inside the device_id expression. The
        # mesh-keyed form carries axis names as string literals (or
        # module constants resolving to them); axis_index(...) subtrees
        # are GT013's domain (it flags the call itself) and unresolved
        # bare identifiers are device-index arithmetic (`right`, `my`),
        # not axis names — both stay out of the candidate set.
        skip: set[int] = set()
        for n in ast.walk(dev):
            if isinstance(n, ast.Call):
                d = dotted_name(n.func)
                if d is not None and d.split(".")[-1] == "axis_index":
                    skip.update(id(c) for c in ast.walk(n))
                else:
                    skip.update(id(c) for c in ast.walk(n.func))
        if any(a.startswith("id:") for a in bound):
            return  # unresolved binding side: can't compare literals
        for n in ast.walk(dev):
            if id(n) in skip:
                continue
            axis = ctx.axis_name_of(n)
            if axis is None or axis in bound or axis.startswith("id:"):
                continue
            shown = sorted(bound)
            ctx.report(self, node,
                       f"make_async_remote_copy device_id references "
                       f"axis {axis!r} not bound by the enclosing "
                       f"shard_map (binds: {', '.join(shown)})")


# Registry label-plane accessors whose full-column results a matcher
# predicate must never compare directly (GT033). Gathers through them
# (decode, subscript-by-sid) are fine — only boolean verdicts over the
# whole column re-create the O(total series) scan the secondary index
# exists to kill.
_GT033_PLANE_FUNCS = {"tag_values", "codes_matrix"}
_GT033_CMP_CALLS = {"equal", "not_equal", "isin", "in1d"}


def _gt033_exempt_path(path: str) -> bool:
    p = path.replace("\\", "/")
    return ("/index/" in p or p.startswith("index/")
            or p.endswith("storage/series.py"))


def _gt033_plane_root(node: ast.AST, tracked: set[str]) -> str | None:
    """'tag_values' / 'codes_matrix' / a tracked local name when the
    expression (through any Subscript chain) roots at a label-plane
    call or a local bound to one; else None."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Call):
        d = dotted_name(node.func)
        if d is not None and d.split(".")[-1] in _GT033_PLANE_FUNCS:
            return d.split(".")[-1]
        return None
    if isinstance(node, ast.Name) and node.id in tracked:
        return node.id
    return None


@register
class FullLabelPlanePredicate(Rule):
    id = "GT033"
    name = "full-label-plane-predicate"
    description = (
        "A boolean compare over a series-registry label column "
        "(`tag_values()` / `codes_matrix()` results) outside the "
        "index package re-creates the O(total series) linear match "
        "the secondary tag index exists to kill: every evaluation "
        "pays the full plane even when postings answer it in O(1). "
        "Route matchers through index.match_sids / index.match_mask "
        "(posting lookups for eq/in, dictionary-domain evaluation "
        "for re/ne). Gathers — decoding values for matched sids, "
        "subscripting by a sid set — are fine; only whole-column "
        "predicates fire."
    )

    def _scopes(self, tree: ast.Module):
        """(scope node, statements owned by it) pairs: module body plus
        each def, with nested defs excluded from their enclosing
        scope's statement set (their locals shadow)."""
        defs = [n for n in ast.walk(tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        out = []
        for scope in [tree] + defs:
            owned = []
            stack = list(scope.body)
            while stack:
                n = stack.pop()
                owned.append(n)
                for child in ast.iter_child_nodes(n):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        continue
                    stack.append(child)
            out.append((scope, owned))
        return out

    def visit_Module(self, node: ast.Module, ctx: FileContext):
        if _gt033_exempt_path(ctx.path):
            return
        for _scope, owned in self._scopes(node):
            # names bound ONLY from label-plane calls in this scope; a
            # name also assigned from anything else is not tracked (it
            # may no longer hold the plane at the compare)
            tracked: set[str] = set()
            dirty: set[str] = set()
            for n in owned:
                if not (isinstance(n, ast.Assign)
                        and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Name)):
                    continue
                name = n.targets[0].id
                if _gt033_plane_root(n.value, set()) is not None:
                    tracked.add(name)
                else:
                    dirty.add(name)
            tracked -= dirty
            for n in owned:
                if isinstance(n, ast.Compare):
                    if not all(isinstance(op, (ast.Eq, ast.NotEq,
                                               ast.In, ast.NotIn))
                               for op in n.ops):
                        continue
                    sides = [n.left] + list(n.comparators)
                elif (isinstance(n, ast.Call)
                        and (dotted_name(n.func) or "").split(".")[-1]
                        in _GT033_CMP_CALLS):
                    sides = list(n.args)
                else:
                    continue
                for side in sides:
                    root = _gt033_plane_root(side, tracked)
                    if root is None:
                        continue
                    ctx.report(self, n,
                               f"boolean predicate over the full "
                               f"label plane (via {root!r}) — "
                               "O(total series) per evaluation; "
                               "route the matcher through "
                               "index.match_sids / index.match_mask "
                               "(postings + dictionary-domain "
                               "evaluation)")
                    break


# ----------------------------------------------------------------------
# --explain examples
# ----------------------------------------------------------------------
# Minimal firing / clean snippet pairs for `lint --explain GTxxx`,
# attached here so each rule body above stays focused on detection
# logic. The explain meta-test lints every pair under a per-rule
# select: the positive snippet must fire exactly that rule, the
# negative must stay silent.

_EXAMPLES = {
    "GT001": ('''\
try:
    x = 1
except Exception:
    pass
''', '''\
import logging
try:
    x = 1
except Exception as e:
    logging.getLogger("x").warning("boom: %s", e)
'''),
    "GT002": ('''\
def classify(e):
    return "unavailable" in str(e).lower()
''', '''\
def classify(e):
    return isinstance(e, ConnectionError)
'''),
    "GT003": ('''\
def f():
    raise Exception("boom")
''', '''\
def f():
    raise ValueError("bad arg")
'''),
    "GT004": ('''\
import jax

@jax.jit
def f(x):
    return x.item()
''', '''\
import numpy as np

def f(x):
    return float(x) + np.asarray(x).sum()
'''),
    "GT005": ('''\
import jax

@jax.jit
def f(x):
    if x > 0:
        return x
    return -x
''', '''\
import jax

@jax.jit
def f(x):
    if x.ndim == 2:
        x = x.sum(axis=1)
    return x
'''),
    "GT006": ('''\
import jax

def step(fns, x):
    for f in fns:
        x = jax.jit(f)(x)
    return x
''', '''\
import jax

def _impl(x):
    return x + 1

fast = jax.jit(_impl)
'''),
    "GT007": ('''\
import threading
import urllib.request

lock = threading.Lock()

def f():
    with lock:
        urllib.request.urlopen("http://x", timeout=5.0)
''', '''\
import threading
import urllib.request

lock = threading.Lock()

def f():
    with lock:
        snapshot = 1
    urllib.request.urlopen("http://x", timeout=5.0)
    return snapshot
'''),
    "GT008": ('''\
import threading

def fire(target):
    threading.Thread(target=target).start()
''', '''\
import threading

def ok(target):
    t = threading.Thread(target=target)
    t.start()
    t.join()
'''),
    "GT009": ('''\
import jax.numpy as jnp

def f(x):
    return jnp.asarray(x, jnp.int64)
''', '''\
import jax.numpy as jnp
import numpy as np

def f(x):
    return np.asarray(x, np.int64), jnp.asarray(x, jnp.int32)
'''),
    "GT010": ('''\
def public(a, xs=[]):
    return xs
''', '''\
def public(a, xs=None, t=()):
    return xs or t
'''),
    "GT011": ('''\
import time

def f(start):
    return time.time() - start
''', '''\
import time

def f(start):
    return time.monotonic() - start
'''),
    "GT012": ('''\
import urllib.request

def fetch(url):
    return urllib.request.urlopen(url).read()
''', '''\
import urllib.request

def fetch(url):
    return urllib.request.urlopen(url, timeout=5.0).read()
'''),
    "GT013": ('''\
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def run(mesh, x):
    def local(x):
        return jax.lax.psum(x, "time")

    return shard_map(local, mesh=mesh, in_specs=(P("shard"),),
                     out_specs=P())(x)
''', '''\
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def run(mesh, x):
    def local(x):
        return jax.lax.psum(x, "shard")

    return shard_map(local, mesh=mesh, in_specs=(P("shard"),),
                     out_specs=P())(x)
'''),
    "GT014": ('''\
import jax
from greptimedb_tpu.telemetry import tracing

@jax.jit
def kernel(x):
    with tracing.span("device.step"):
        return x + 1
''', '''\
import jax
from greptimedb_tpu.telemetry import tracing

@jax.jit
def kernel(x):
    return x + 1

def host(x):
    with tracing.span("device.execute"):
        return kernel(x)
'''),
    "GT015": ('''\
import numpy as np

def run(program, arrs):
    out = program(arrs)
    out.block_until_ready()
    return np.asarray(out)
''', '''\
from greptimedb_tpu.query import readback

def run(program, arrs, j0):
    out = program(arrs)
    out.block_until_ready()
    return readback.read_delta(out, j0, axis=-1)
'''),
    "GT016": ('''\
from collections import OrderedDict

class GridCache:
    def __init__(self, max_bytes):
        self.max_bytes = int(max_bytes)
        self._entries = OrderedDict()
''', '''\
from collections import OrderedDict
from greptimedb_tpu.telemetry import memory

class GridCache:
    def __init__(self, max_bytes):
        self.max_bytes = int(max_bytes)
        self._entries = OrderedDict()
        memory.register_pool("grids", "device", self,
                             stats=GridCache._stats)

    def _stats(self):
        return {"bytes": 0}
'''),
    "GT017": ('''\
from greptimedb_tpu.telemetry.metrics import global_registry

C = global_registry.counter("gtpu_things", "things counted")
''', '''\
from greptimedb_tpu.telemetry.metrics import global_registry

C = global_registry.counter("gtpu_calls_total", "calls",
                            labels=("db", "code"))
'''),
    "GT018": ('''\
import functools
import jax

@functools.partial(jax.jit, static_argnames=("g",))
def prog(x, *, g):
    return x + g

def serve(x):
    return prog(x, g=4)
''', '''\
import jax
from greptimedb_tpu.telemetry import device_trace

@jax.jit
def prog(x):
    return x * 2

def serve(x):
    with device_trace.device_call("site", key=("k",)) as d:
        return d.run(prog, x)
'''),
    "GT019": ('''\
from urllib.request import urlopen
from greptimedb_tpu.telemetry.metrics import global_registry

def _collect():
    urlopen("http://peer:4000/metrics")

global_registry.register_collector(_collect)
''', '''\
from urllib.request import urlopen
from greptimedb_tpu.telemetry.metrics import global_registry

def _collect():
    urlopen("http://peer:4000/metrics", timeout=2.0)

global_registry.register_collector(_collect)
'''),
    "GT021": ('''\
def detune(inst):
    inst.scheduler.config.max_concurrency = 4
''', '''\
def actuate(registry):
    registry.set("scheduler.max_concurrency", 4)
'''),
    "GT022": ('''\
import jax
from jax.experimental import pallas as pl

def kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] + x_ref[...]

def run(x):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=True,
    )(x)
''', '''\
import jax
from jax.experimental import pallas as pl

def kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] + x_ref[...]

def run(x, interpret):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x)
'''),
    "GT033": ('''\
import numpy as np

def match(reg, value):
    vals = reg.tag_values("host")
    return np.flatnonzero(vals == value)
''', '''\
from greptimedb_tpu import index

def match(reg, value):
    return index.match_sids(reg, [("host", "eq", value)])
'''),
}

for _cls in list(globals().values()):
    if (isinstance(_cls, type) and issubclass(_cls, Rule)
            and getattr(_cls, "id", None) in _EXAMPLES):
        _cls.example_pos, _cls.example_neg = _EXAMPLES[_cls.id]
del _cls
