"""gtlint suppression comments.

Three forms, all carrying explicit rule ids (or `all`):

    risky_call()            # gtlint: disable=GT007
    # gtlint: disable-next-line=GT001,GT004
    risky_call()
    # gtlint: disable-file=GT010        (anywhere in the first 10 lines)

Suppressed findings are dropped from the failure count but reported
in the JSON output so tooling can audit them.
"""

from __future__ import annotations

import re

_LINE_RE = re.compile(r"#\s*gtlint:\s*disable=([A-Za-z0-9, ]+)")
_NEXT_RE = re.compile(r"#\s*gtlint:\s*disable-next-line=([A-Za-z0-9, ]+)")
_FILE_RE = re.compile(r"#\s*gtlint:\s*disable-file=([A-Za-z0-9, ]+)")

_FILE_SCAN_LINES = 10


def _ids(match: re.Match) -> set[str]:
    return {p.strip().upper() for p in match.group(1).split(",")
            if p.strip()}


class Suppressions:
    """Parsed suppression comments for one file's source."""

    def __init__(self, source: str):
        self.per_line: dict[int, set[str]] = {}
        self.file_wide: set[str] = set()
        for i, line in enumerate(source.splitlines(), start=1):
            m = _LINE_RE.search(line)
            if m:
                self.per_line.setdefault(i, set()).update(_ids(m))
            m = _NEXT_RE.search(line)
            if m:
                self.per_line.setdefault(i + 1, set()).update(_ids(m))
            if i <= _FILE_SCAN_LINES:
                m = _FILE_RE.search(line)
                if m:
                    self.file_wide.update(_ids(m))

    def covers(self, rule: str, line: int) -> bool:
        rule = rule.upper()
        if rule in self.file_wide or "ALL" in self.file_wide:
            return True
        ids = self.per_line.get(line)
        return bool(ids) and (rule in ids or "ALL" in ids)
