import sys

from greptimedb_tpu.tools.lint.runner import main

sys.exit(main())
