"""gtlint baseline: grandfathered findings checked into the repo.

A baseline entry matches a finding by (rule, path, stripped source
text of the flagged line) — deliberately NOT by line number, so
unrelated edits above a grandfathered site don't break the gate.
Matching consumes entries with multiplicity: two identical findings
need two entries.  Entries that no longer match anything are reported
as stale so the file shrinks as debt is paid down.
"""

from __future__ import annotations

import collections
import json
import os

from greptimedb_tpu.tools.lint.core import Finding


def _key(rule: str, path: str, text: str) -> tuple:
    return rule, path.replace("\\", "/"), text.strip()


class Baseline:
    def __init__(self, entries: list[dict] | None = None):
        self.entries = entries or []

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls([])
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return cls(list(doc.get("entries", [])))

    def save(self, path: str):
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "entries": self.entries}, f,
                      indent=1, sort_keys=True)
            f.write("\n")

    @classmethod
    def from_findings(cls, findings: list[Finding],
                      line_text) -> "Baseline":
        """line_text(path, lineno) -> the flagged line's source."""
        entries = [
            {"rule": f.rule, "path": f.path.replace("\\", "/"),
             "line": f.line, "text": line_text(f.path, f.line).strip()}
            for f in sorted(findings,
                            key=lambda f: (f.path, f.line, f.rule))
        ]
        return cls(entries)

    def split(self, findings: list[Finding], line_text
              ) -> tuple[list[Finding], list[Finding], list[dict]]:
        """(new, grandfathered, stale_entries)."""
        budget: collections.Counter = collections.Counter(
            _key(e.get("rule", ""), e.get("path", ""),
                 e.get("text", "")) for e in self.entries
        )
        new, old = [], []
        for f in findings:
            k = _key(f.rule, f.path, line_text(f.path, f.line))
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                old.append(f)
            else:
                new.append(f)
        stale = []
        for e in self.entries:
            k = _key(e.get("rule", ""), e.get("path", ""),
                     e.get("text", ""))
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                stale.append(e)
        return new, old, stale
