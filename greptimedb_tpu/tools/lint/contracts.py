"""gtcontract: whole-program wire/config/metric contract model.

GreptimeDB's disaggregated layers talk through hand-maintained string
contracts — Flight ticket fields and action names, `[gtdb:<code>]`
error markers, `[section] knob` TOML paths, `gtpu_*` metric families.
Every rule before this file checks one function or one file; the drift
that actually bites crosses the producer/consumer boundary (the repo's
history re-discovered the ticket strip-set invariant three separate
times, once per new side-channel field).

This module harvests a **ContractModel** from the parsed-AST forest of
the whole program — the runner parses each file exactly once and hands
the same trees to the per-file walk and to this pass — and checks five
cross-file rules over it:

  GT028  ticket field spliced into a partial_sql ticket but missing
         from the datanode decode-memo strip set (or stale/unapplied
         strip entries, or stripped fields never re-anchored)
  GT029  config knob read-but-undeclared, declared-but-never-read, or
         declared-but-undocumented (README)
  GT030  typed error whose StatusCode has no wire representative in
         _CODE_CLASSES, inconsistent representatives, duplicate enum
         code numbers, dead HTTP status-table entries
  GT031  metric family referenced-but-unregistered, or registered at
         multiple sites with drifting kind/label sets
  GT032  Flight action dispatched with no server handler, handled but
         never dispatched, or out of sync with list_actions()

Every check requires ALL of its surfaces to be present in the forest
(a producer AND the decode module, a handler module AND a dispatcher,
...), so partial scans — one file under `--changed`, or a fixture
mini-project in a test — only fire checks they can actually decide.
The explain examples are single-file mini-projects that carry both
sides of their contract for exactly this reason.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from greptimedb_tpu.tools.lint.core import (
    Finding,
    Rule,
    dotted_name,
    register,
)

CONTRACT_RULE_IDS = ("GT028", "GT029", "GT030", "GT031", "GT032")

# a partial_sql ticket producer: the base JSON prefix every fan-out
# splice starts from (dist/dist_query.py builds tickets byte-wise so
# hot queries ship byte-identical tickets and hit the datanode's
# decode memo)
_PRODUCER_MARKERS = ('"rpc":"partial_sql"', '"rpc": "partial_sql"')
# a volatile side-channel splice: a bare `"field":<payload>,` JSON
# fragment concatenated into the ticket per call (deadline_s /
# traceparent / since_ms all take this shape); identity fields live in
# the base literal and are MEANT to key the memo
_FRAG_RE = re.compile(r'^"([a-z_][a-z0-9_]*)":.+,$', re.S)
# a strip-set entry: a compiled regex whose pattern removes one
# `"field":...` fragment from the raw ticket before the memo lookup
_STRIP_RE = re.compile(r'^"([a-z_][a-z0-9_]*)":')

_METRIC_NAME_RE = re.compile(r"^(?:gtpu|greptime)_[a-z0-9_]*[a-z0-9]$")
# bare string literals count as metric references only when they carry
# a conventional family suffix — bare `gtpu_span` / `greptime_value`
# style names are contextvars, column names, pool names
_METRIC_SUFFIXES = ("_total", "_seconds", "_ms", "_bytes",
                    "_bucket", "_sum", "_count")
# prometheus exposition derives these from a histogram family name
_HISTO_DERIVED = ("_bucket", "_sum", "_count")

_REG_KINDS = ("counter", "gauge", "histogram")


@dataclasses.dataclass(frozen=True)
class Site:
    path: str
    line: int
    col: int = 0

    def to_doc(self) -> dict:
        return {"path": self.path, "line": self.line}


def _const_str(node: ast.AST) -> str | None:
    """The text of a str/bytes constant (bytes decoded latin-1 — the
    ticket splices are bytes literals)."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, str):
            return node.value
        if isinstance(node.value, bytes):
            try:
                return node.value.decode("latin-1")
            except UnicodeDecodeError:
                return None
    return None


def _registry_receiver(func: ast.AST, attrs: tuple[str, ...]) -> bool:
    f = dotted_name(func)
    if f is None:
        return False
    parts = f.split(".")
    if parts[-1] not in attrs or len(parts) < 2:
        return False
    recv = parts[-2].lstrip("_").lower()
    return recv == "registry" or recv.endswith("registry")


def _opts_receiver(func: ast.AST) -> bool:
    f = dotted_name(func)
    if f is None or "." not in f:
        return False
    recv = f.split(".")[-2].lstrip("_").lower()
    return recv in ("opts", "options") or recv.endswith(("opts",
                                                         "options"))


class ContractModel:
    """Everything the cross-file rules need, with source locations."""

    def __init__(self):
        # -- partial_sql tickets ---------------------------------------
        self.ticket_producers: dict[str, list[Site]] = {}
        self.ticket_strips: dict[str, list[Site]] = {}
        self.ticket_strip_vars: dict[str, set[str]] = {}
        self.ticket_sub_applied: set[str] = set()   # strip var names
        self.ticket_reanchors: set[str] = set()     # decode-module keys
        self.has_producer_surface = False
        self.has_decode_surface = False
        # -- Flight actions --------------------------------------------
        self.action_dispatches: dict[str, list[Site]] = {}
        self.action_handlers: dict[str, list[Site]] = {}
        self.action_advertised: dict[str, list[Site]] = {}
        self.has_handler_surface = False
        self.has_advertise_surface = False
        # -- typed errors ----------------------------------------------
        self.status_codes: dict[str, tuple[int, Site]] = {}
        self.status_code_dups: list[tuple[str, str, int, Site]] = []
        self.error_classes: dict[str, tuple[str, Site]] = {}
        self.code_classes: dict[str, tuple[str, Site]] = {}
        self.http_status: dict[str, tuple[int, Site]] = {}
        self.has_error_surface = False
        self.has_code_map = False
        self.has_http_surface = False
        # -- config knobs ----------------------------------------------
        self.knob_defaults: dict[str, tuple[str, Site]] = {}
        self.knob_sections: dict[str, Site] = {}    # top-level dicts
        self.knob_dynamic: set[str] = set()         # `{}` leaves
        self.knob_reads: dict[str, list[Site]] = {}     # dotted gets
        self.section_reads: dict[str, list[Site]] = {}  # .section("s")
        self.opts_get_reads: dict[str, list[Site]] = {}
        # every identifier-shaped token in the program (names,
        # attributes, parameter names, string keys) EXCEPT the DEFAULTS
        # declaration keys themselves: section dicts are consumed
        # through dataclass fields, **kwargs, and key iteration the
        # extractor cannot resolve, so "never read" must mean the knob
        # name appears NOWHERE — anything weaker false-positives on
        # config objects built with from_options()-style constructors
        self.name_pool: set[str] = set()
        self.has_config_surface = False
        # -- metric families -------------------------------------------
        self.metric_regs: dict[
            str, list[tuple[str, tuple[str, ...] | None, Site]]] = {}
        self.metric_refs: dict[str, list[Site]] = {}
        # README text for the documentation check (None = not in scope,
        # e.g. fixture mini-projects — the check is skipped)
        self.readme_text: str | None = None

    # ------------------------------------------------------------------
    def to_doc(self) -> dict:
        def sites(m):
            return {k: [s.to_doc() for s in v]
                    for k, v in sorted(m.items())}

        return {
            "tickets": {
                "producers": sites(self.ticket_producers),
                "strips": sites(self.ticket_strips),
                "reanchors": sorted(self.ticket_reanchors),
            },
            "actions": {
                "dispatches": sites(self.action_dispatches),
                "handlers": sites(self.action_handlers),
                "advertised": sites(self.action_advertised),
            },
            "errors": {
                "codes": {k: {"value": v, **s.to_doc()}
                          for k, (v, s) in sorted(
                              self.status_codes.items())},
                "classes": {k: {"code": c, **s.to_doc()}
                            for k, (c, s) in sorted(
                                self.error_classes.items())},
                "code_classes": {k: {"class": c, **s.to_doc()}
                                 for k, (c, s) in sorted(
                                     self.code_classes.items())},
                "http_status": {k: {"status": v, **s.to_doc()}
                                for k, (v, s) in sorted(
                                    self.http_status.items())},
            },
            "knobs": {
                "declared": {k: {"default": d, **s.to_doc()}
                             for k, (d, s) in sorted(
                                 self.knob_defaults.items())},
                "reads": sites(self.knob_reads),
                "section_reads": sites(self.section_reads),
            },
            "metrics": {
                "registered": {
                    k: [{"kind": kind,
                         "labels": list(labels) if labels is not None
                         else None, **s.to_doc()}
                        for kind, labels, s in v]
                    for k, v in sorted(self.metric_regs.items())
                },
                "references": sites(self.metric_refs),
            },
        }


# ----------------------------------------------------------------------
# extraction
# ----------------------------------------------------------------------

# per-file partial models, keyed by (path -> hash(source)): extraction
# is a pure function of one file's text, so repeated extract_model
# calls in one process (the test suite runs dozens — every lint_paths
# call re-extracts the aux-harvested repo) only re-harvest files whose
# text actually changed. Cross-file state (StatusCode duplicate values)
# is reconstructed in _merge_model, never inside a partial.
_PARTIAL_CACHE: dict[str, tuple[int, "ContractModel"]] = {}


def extract_model(forest: dict[str, tuple[str, ast.Module]],
                  readme_text: str | None = None) -> ContractModel:
    """Harvest the contract model from {path: (source, tree)}."""
    model = ContractModel()
    model.readme_text = readme_text
    for path in sorted(forest):
        source, tree = forest[path]
        key = hash(source)
        hit = _PARTIAL_CACHE.get(path)
        if hit is not None and hit[0] == key:
            part = hit[1]
        else:
            part = ContractModel()
            _harvest_module(part, path, tree)
            _PARTIAL_CACHE[path] = (key, part)
        _merge_model(model, part)
    return model


def _merge_model(model: ContractModel, part: ContractModel) -> None:
    """Fold one file's partial model into the whole-program model.
    Cached partials are shared across calls: copy container contents,
    never alias them."""
    for attr in ("ticket_producers", "ticket_strips",
                 "action_dispatches", "action_handlers",
                 "action_advertised", "knob_reads", "section_reads",
                 "opts_get_reads", "metric_regs", "metric_refs"):
        dst = getattr(model, attr)
        for k, v in getattr(part, attr).items():
            dst.setdefault(k, []).extend(v)
    for k, v in part.ticket_strip_vars.items():
        model.ticket_strip_vars.setdefault(k, set()).update(v)
    for attr in ("ticket_sub_applied", "ticket_reanchors",
                 "knob_dynamic", "name_pool"):
        getattr(model, attr).update(getattr(part, attr))
    for attr in ("has_producer_surface", "has_decode_surface",
                 "has_handler_surface", "has_advertise_surface",
                 "has_error_surface", "has_code_map",
                 "has_http_surface", "has_config_surface"):
        if getattr(part, attr):
            setattr(model, attr, True)
    # within-file duplicates were found by the partial harvest;
    # cross-file duplicates are found here, against everything merged
    # from earlier (sorted-path) files — same order the single-pass
    # accumulation used
    model.status_code_dups.extend(part.status_code_dups)
    prior_items = list(model.status_codes.items())
    for name, (val, site) in part.status_codes.items():
        for prior, (pval, _) in prior_items:
            if pval == val:
                model.status_code_dups.append((name, prior, val, site))
        model.status_codes[name] = (val, site)
    for attr in ("error_classes", "code_classes", "http_status",
                 "knob_defaults", "knob_sections"):
        getattr(model, attr).update(getattr(part, attr))


def _harvest_module(model: ContractModel, path: str, tree: ast.Module):
    nodes = list(ast.walk(tree))
    _harvest_tickets(model, path, nodes)
    _harvest_actions(model, path, nodes)
    _harvest_errors(model, path, nodes)
    _harvest_knobs(model, path, tree, nodes)
    _harvest_metrics(model, path, nodes)


def _site(path: str, node: ast.AST) -> Site:
    return Site(path, getattr(node, "lineno", 1),
                getattr(node, "col_offset", 0))


# -- tickets -----------------------------------------------------------

def _harvest_tickets(model: ContractModel, path: str,
                     nodes: list[ast.AST]):
    # name -> fragment constants reachable through an assignment to it
    # (dist_query builds `dl_field = b'' if ... else b'"deadline_s":...,'`
    # then concatenates the names into the base literal)
    assigned_frags: dict[str, list[tuple[str, ast.AST]]] = {}
    assigned_base: set[str] = set()
    produced = False
    for node in nodes:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            for sub in ast.walk(node.value):
                s = _const_str(sub)
                if s is None:
                    continue
                if any(m in s for m in _PRODUCER_MARKERS):
                    assigned_base.add(name)
                m = _FRAG_RE.match(s)
                if m and not s.startswith("{"):
                    assigned_frags.setdefault(name, []).append(
                        (m.group(1), sub))

    def chain_parts(b: ast.AST) -> list[ast.AST]:
        if isinstance(b, ast.BinOp) and isinstance(b.op, ast.Add):
            return chain_parts(b.left) + chain_parts(b.right)
        return [b]

    for node in nodes:
        s = _const_str(node)
        if s is not None and any(m in s for m in _PRODUCER_MARKERS):
            produced = True
        if not (isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Add)):
            continue
        parts = chain_parts(node)
        has_base = False
        frags: list[tuple[str, ast.AST]] = []
        for part in parts:
            for sub in ast.walk(part):
                ps = _const_str(sub)
                if ps is not None and any(
                        m in ps for m in _PRODUCER_MARKERS):
                    has_base = True
                m = _FRAG_RE.match(ps) if ps is not None else None
                if m and not ps.startswith("{"):
                    frags.append((m.group(1), sub))
                if isinstance(sub, ast.Name):
                    if sub.id in assigned_base:
                        has_base = True
                    frags.extend(assigned_frags.get(sub.id, ()))
        if has_base:
            model.has_producer_surface = True
            for field, fnode in frags:
                model.ticket_producers.setdefault(field, []).append(
                    _site(path, fnode))
    if produced:
        model.has_producer_surface = True

    # decode/strip surface: the module owning the ticket decode memo
    decode_here = False
    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "_decode_ticket":
            decode_here = True
        if isinstance(node, ast.Call):
            f = dotted_name(node.func)
            if f is not None and f.split(".")[-1] == "_decode_ticket":
                decode_here = True
    if not decode_here:
        return
    model.has_decode_surface = True
    for node in nodes:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            f = dotted_name(node.value.func)
            if f in ("re.compile", "compile") and node.value.args:
                pat = _const_str(node.value.args[0])
                m = _STRIP_RE.match(pat) if pat is not None else None
                if m:
                    field = m.group(1)
                    model.ticket_strips.setdefault(field, []).append(
                        _site(path, node))
                    model.ticket_strip_vars.setdefault(field, set()).add(
                        node.targets[0].id)
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "sub":
            recv = dotted_name(node.func.value)
            if recv is not None:
                model.ticket_sub_applied.add(recv.split(".")[-1])
        # re-anchor reads: doc.get("field") / doc["field"] in the
        # decode module — the stripped value must be consumed from the
        # PARSED doc, not the memo-keyed raw bytes
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" and node.args:
            key = _const_str(node.args[0])
            if key is not None:
                model.ticket_reanchors.add(key)
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            key = _const_str(node.slice)
            if key is not None:
                model.ticket_reanchors.add(key)


# -- Flight actions ----------------------------------------------------

def _harvest_actions(model: ContractModel, path: str,
                     nodes: list[ast.AST]):
    # handler functions live only in modules that define the Flight
    # do_action entry point — `kind == "x"` matching in unrelated
    # `*_action` helpers (e.g. the manifest's apply_action) is a
    # different string namespace entirely
    module_has_do_action = any(
        isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n.name in ("do_action", "_do_action")
        for n in nodes)
    for node in nodes:
        if isinstance(node, ast.Call) and node.args:
            name = _const_str(node.args[0])
            # `<anything>.action("x", ...)` — the receiver may itself
            # be a call (`self._flow_client_for(addr).action(...)`),
            # and `flight.Action("x", ...)` / `Action("x", ...)`
            attr = (node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else node.func.id
                    if isinstance(node.func, ast.Name) else None)
            if name is not None and attr in ("action", "Action"):
                model.action_dispatches.setdefault(name, []).append(
                    _site(path, node))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in ("do_action", "_do_action"):
                model.has_handler_surface = True
            if module_has_do_action and (
                    node.name.endswith("_action")
                    or node.name == "do_action"):
                _harvest_handler_names(model, path, node)
            if node.name == "list_actions":
                model.has_advertise_surface = True
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Tuple) and len(sub.elts) == 2:
                        name = _const_str(sub.elts[0])
                        desc = _const_str(sub.elts[1])
                        if name is not None and desc is not None:
                            model.action_advertised.setdefault(
                                name, []).append(_site(path, sub))


def _harvest_handler_names(model: ContractModel, path: str,
                           fn: ast.AST):
    """Action names an action-handler function matches: `kind == "x"`
    comparisons and `kind in ("a", "b")` membership tests."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            continue
        op = node.ops[0]
        lhs, rhs = node.left, node.comparators[0]
        if isinstance(op, (ast.Eq, ast.NotEq)):
            for a, b in ((lhs, rhs), (rhs, lhs)):
                name = _const_str(a)
                if name is not None and isinstance(b, ast.Name):
                    model.action_handlers.setdefault(name, []).append(
                        _site(path, node))
        elif isinstance(op, (ast.In, ast.NotIn)) \
                and isinstance(lhs, ast.Name) \
                and isinstance(rhs, (ast.Tuple, ast.List, ast.Set)):
            for el in rhs.elts:
                name = _const_str(el)
                if name is not None:
                    model.action_handlers.setdefault(name, []).append(
                        _site(path, el))


# -- typed errors ------------------------------------------------------

def _harvest_errors(model: ContractModel, path: str,
                    nodes: list[ast.AST]):
    for node in nodes:
        if isinstance(node, ast.ClassDef) and node.name == "StatusCode":
            model.has_error_surface = True
            for st in node.body:
                if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Name) \
                        and isinstance(st.value, ast.Constant) \
                        and isinstance(st.value.value, int):
                    name = st.targets[0].id
                    val = st.value.value
                    for prior, (pval, _) in model.status_codes.items():
                        if pval == val:
                            model.status_code_dups.append(
                                (name, prior, val, _site(path, st)))
                    model.status_codes[name] = (val, _site(path, st))
        elif isinstance(node, ast.ClassDef):
            for st in node.body:
                if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                        and getattr(st.targets[0], "id", None) \
                        == "status_code":
                    code = dotted_name(st.value)
                    if code is not None and "StatusCode" in code:
                        model.error_classes[node.name] = (
                            code.split(".")[-1], _site(path, node))
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and getattr(node.targets[0], "id", None) \
                == "_CODE_CLASSES" and isinstance(node.value, ast.Dict):
            model.has_code_map = True
            for k, v in zip(node.value.keys, node.value.values):
                code = dotted_name(k) if k is not None else None
                cls = dotted_name(v)
                if code is not None and "StatusCode" in code \
                        and cls is not None:
                    model.code_classes[code.split(".")[-1]] = (
                        cls.split(".")[-1], _site(path, k))
        # an HTTP status table: a dict literal mapping StatusCode
        # attributes to integer statuses (servers/http.py)
        if isinstance(node, ast.Dict) and len(node.keys) >= 3:
            entries = []
            for k, v in zip(node.keys, node.values):
                code = dotted_name(k) if k is not None else None
                if code is None or "StatusCode" not in code:
                    entries = None
                    break
                if not (isinstance(v, ast.Constant)
                        and isinstance(v.value, int)):
                    entries = None
                    break
                entries.append((code.split(".")[-1], v.value,
                                _site(path, k)))
            if entries:
                model.has_http_surface = True
                for code, status, site in entries:
                    model.http_status[code] = (status, site)


# -- config knobs ------------------------------------------------------

_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _harvest_knobs(model: ContractModel, path: str, tree: ast.Module,
                   nodes: list[ast.AST]):
    declared_keys: set[int] = set()
    for node in tree.body:
        value = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and getattr(node.targets[0], "id", None) == "DEFAULTS":
            value = node.value
        elif isinstance(node, ast.AnnAssign) \
                and getattr(node.target, "id", None) == "DEFAULTS":
            value = node.value
        if isinstance(value, ast.Dict):
            model.has_config_surface = True
            for sub in ast.walk(value):
                if isinstance(sub, ast.Dict):
                    declared_keys.update(id(k) for k in sub.keys
                                         if k is not None)
            _walk_defaults(model, path, value, [])
    for node in nodes:
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) and node.args:
            key = _const_str(node.args[0])
            if key is not None and node.func.attr == "get" \
                    and "." in key:
                model.knob_reads.setdefault(key, []).append(
                    _site(path, node))
                model.section_reads.setdefault(
                    key.split(".")[0], []).append(_site(path, node))
            elif key is not None and node.func.attr == "get" \
                    and _opts_receiver(node.func):
                model.opts_get_reads.setdefault(key, []).append(
                    _site(path, node))
            elif key is not None and node.func.attr == "section":
                model.section_reads.setdefault(key, []).append(
                    _site(path, node))
        if isinstance(node, ast.Name):
            model.name_pool.add(node.id)
        elif isinstance(node, ast.Attribute):
            model.name_pool.add(node.attr)
        elif isinstance(node, ast.arg):
            model.name_pool.add(node.arg)
        elif isinstance(node, ast.keyword) and node.arg is not None:
            model.name_pool.add(node.arg)
        elif isinstance(node, ast.Constant) \
                and id(node) not in declared_keys:
            s = _const_str(node)
            if s is not None and _IDENT_RE.match(s):
                model.name_pool.add(s)


def _walk_defaults(model: ContractModel, path: str, d: ast.Dict,
                   prefix: list[str]):
    for k, v in zip(d.keys, d.values):
        key = _const_str(k) if k is not None else None
        if key is None:
            continue
        dotted = ".".join(prefix + [key])
        if isinstance(v, ast.Dict) and v.keys:
            if not prefix:
                model.knob_sections[dotted] = _site(path, k)
            _walk_defaults(model, path, v, prefix + [key])
        elif isinstance(v, ast.Dict):
            # `{}` default: a dynamic table (e.g. scheduler.tenants) —
            # reads underneath it cannot be checked statically
            model.knob_dynamic.add(dotted)
            model.knob_defaults[dotted] = ("{}", _site(path, k))
        else:
            try:
                default = ast.unparse(v)
            except Exception:   # pragma: no cover - unparse is total
                default = "?"
            model.knob_defaults[dotted] = (default, _site(path, k))


# -- metric families ---------------------------------------------------

def _harvest_metrics(model: ContractModel, path: str,
                     nodes: list[ast.AST]):
    reg_name_nodes: set[int] = set()
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        f = dotted_name(node.func)
        if f is not None and f.split(".")[-1] == "ContextVar" \
                and node.args:
            # ContextVar("gtpu_since_ms") names a contextvar, not a
            # metric family — even when it carries a unit suffix
            reg_name_nodes.add(id(node.args[0]))
        if _registry_receiver(node.func, _REG_KINDS) and node.args:
            name = _const_str(node.args[0])
            if name is None:
                continue
            reg_name_nodes.add(id(node.args[0]))
            kind = dotted_name(node.func).split(".")[-1]
            labels_node = None
            for kw in node.keywords:
                if kw.arg == "labels":
                    labels_node = kw.value
            if labels_node is None and len(node.args) >= 3:
                labels_node = node.args[2]
            labels: tuple[str, ...] | None = None
            if isinstance(labels_node, (ast.Tuple, ast.List)):
                lab = [_const_str(el) for el in labels_node.elts]
                if all(x is not None for x in lab):
                    labels = tuple(lab)
            model.metric_regs.setdefault(name, []).append(
                (kind, labels, _site(path, node)))
        elif _registry_receiver(node.func, ("get",)) and node.args:
            name = _const_str(node.args[0])
            if name is not None and _METRIC_NAME_RE.match(name):
                model.metric_refs.setdefault(name, []).append(
                    _site(path, node))
    for node in nodes:
        if id(node) in reg_name_nodes:
            continue
        s = _const_str(node)
        if s is None or not isinstance(node, ast.Constant) \
                or not isinstance(node.value, str):
            continue
        if _METRIC_NAME_RE.match(s) and s.endswith(_METRIC_SUFFIXES):
            model.metric_refs.setdefault(s, []).append(
                _site(path, node))


# ----------------------------------------------------------------------
# rules
# ----------------------------------------------------------------------

class ContractRule(Rule):
    """Cross-file rule: no visit_* methods; the runner calls check()
    with the whole-program model after the per-file walk."""

    def check(self, model: ContractModel) -> list[Finding]:
        raise NotImplementedError

    def _finding(self, site: Site, message: str) -> Finding:
        return Finding(rule=self.id, path=site.path, line=site.line,
                       col=site.col, message=message)


@register
class TicketFieldNotStripped(ContractRule):
    id = "GT028"
    name = "ticket-field-not-stripped"
    description = (
        "The frontend splices volatile per-call fields (deadline_s, "
        "traceparent, since_ms, ...) into the partial_sql ticket as "
        "`\"field\":...,` fragments; the datanode memoizes plan decode "
        "on the RAW ticket bytes, so every such field must be removed "
        "by the strip-set regexes in the decode module before the memo "
        "lookup — and re-anchored from the parsed doc. A spliced field "
        "with no strip entry silently defeats the decode memo for "
        "every query that carries it; a strip entry for a field no "
        "longer produced is dead; a strip regex never applied via "
        ".sub() strips nothing; a stripped field never read back from "
        "the doc is lost server-side. Fires only when both the "
        "producer and the decode module are in the linted set."
    )
    example_pos = '''\
import re

def encode(deadline, epoch):
    dl_field = b'' if deadline is None \\
        else b'"deadline_s":%.3f,' % deadline
    ep_field = b'"epoch_ms":%d,' % epoch
    return (b'{"rpc":"partial_sql",' + dl_field + ep_field
            + b'"mode":"plan","plan":null}')

_DEADLINE_FIELD_RE = re.compile(r'"deadline_s":[0-9.eE+-]+,')

def _decode_ticket(raw, doc):
    return raw

def exec_partial(raw, doc):
    raw = _DEADLINE_FIELD_RE.sub("", raw, count=1)
    plan = _decode_ticket(raw, doc)
    return plan, doc.get("deadline_s")
'''
    example_neg = '''\
import re

def encode(deadline, epoch):
    dl_field = b'' if deadline is None \\
        else b'"deadline_s":%.3f,' % deadline
    ep_field = b'"epoch_ms":%d,' % epoch
    return (b'{"rpc":"partial_sql",' + dl_field + ep_field
            + b'"mode":"plan","plan":null}')

_DEADLINE_FIELD_RE = re.compile(r'"deadline_s":[0-9.eE+-]+,')
_EPOCH_FIELD_RE = re.compile(r'"epoch_ms":-?\\d+,')

def _decode_ticket(raw, doc):
    return raw

def exec_partial(raw, doc):
    raw = _DEADLINE_FIELD_RE.sub("", raw, count=1)
    raw = _EPOCH_FIELD_RE.sub("", raw, count=1)
    plan = _decode_ticket(raw, doc)
    return plan, (doc.get("deadline_s"), doc.get("epoch_ms"))
'''

    def check(self, model: ContractModel) -> list[Finding]:
        out: list[Finding] = []
        if model.has_decode_surface:
            for field, sites in sorted(model.ticket_producers.items()):
                if field not in model.ticket_strips:
                    out.append(self._finding(
                        sites[0],
                        f"ticket field {field!r} is spliced into the "
                        "partial_sql ticket per call but has no strip-"
                        "set regex in the decode module — it becomes "
                        "part of the datanode's decode-memo key and "
                        "defeats the plan cache; add a "
                        f"`\"{field}\":...` strip regex and re-anchor "
                        "the value from the parsed doc"))
                elif field not in model.ticket_reanchors:
                    out.append(self._finding(
                        model.ticket_strips[field][0],
                        f"ticket field {field!r} is stripped from the "
                        "decode-memo key but never read back "
                        f"(doc.get({field!r})) in the decode module — "
                        "the side-channel value is lost server-side"))
        if model.has_producer_surface:
            for field, sites in sorted(model.ticket_strips.items()):
                if field not in model.ticket_producers:
                    out.append(self._finding(
                        sites[0],
                        f"strip-set regex for ticket field {field!r} "
                        "matches nothing any producer splices — stale "
                        "entry (or the producer-side splice was "
                        "removed without its strip)"))
        for field, varnames in sorted(model.ticket_strip_vars.items()):
            if not varnames & model.ticket_sub_applied:
                out.append(self._finding(
                    model.ticket_strips[field][0],
                    f"strip regex for ticket field {field!r} is "
                    "compiled but never applied via .sub() — the "
                    "field still reaches the decode-memo key"))
        return out


@register
class ConfigKnobContract(ContractRule):
    id = "GT029"
    name = "config-knob-contract"
    description = (
        "Every `[section] knob` must exist in three places at once: "
        "config.py DEFAULTS (so TOML can set it and code has a "
        "fallback), at least one read site (opts.get(\"sec.knob\") or "
        "a section-dict read — a declared-but-never-read knob is dead "
        "weight that operators tune with no effect), and the README "
        "knob documentation. Fires on dotted reads of undeclared "
        "knobs, on whole sections and individual knobs no code path "
        "consults, and — when README text is in scope — on knobs the "
        "docs never mention. Dynamic tables (`{}` defaults, e.g. "
        "per-tenant maps) are exempt below their prefix."
    )
    example_pos = '''\
DEFAULTS = {
    "http": {"addr": "127.0.0.1:4000"},
    "opentsdb": {"enable": True},
}

def serve(opts):
    return opts.get("http.addr")
'''
    example_neg = '''\
DEFAULTS = {
    "http": {"addr": "127.0.0.1:4000"},
    "opentsdb": {"enable": True},
}

def serve(opts):
    if opts.get("opentsdb.enable"):
        return opts.get("http.addr")
'''

    def check(self, model: ContractModel) -> list[Finding]:
        if not model.has_config_surface:
            return []
        out: list[Finding] = []
        sections = set(model.knob_sections)
        top_scalars = {k for k in model.knob_defaults if "." not in k}
        dotted_read_prefixes = {k.split(".")[0]
                                for k in model.knob_reads}
        # read-but-undeclared (anchored at the read site)
        for key, sites in sorted(model.knob_reads.items()):
            first = key.split(".")[0]
            if first not in sections:
                continue    # not a config path (.get on a plain dict)
            if key in model.knob_defaults:
                continue
            if any(d.startswith(key + ".") for d in model.knob_defaults):
                continue    # a section-level read
            if any(key == dyn or key.startswith(dyn + ".")
                   for dyn in model.knob_dynamic):
                continue
            out.append(self._finding(
                sites[0],
                f"config knob {key!r} is read but not declared in "
                "config DEFAULTS — TOML can never set it and there is "
                "no documented default; add it to the "
                f"[{first}] section"))
        # declared-but-never-consulted sections
        for sec, site in sorted(model.knob_sections.items()):
            if sec in model.section_reads \
                    or sec in dotted_read_prefixes:
                continue
            out.append(self._finding(
                site,
                f"config section [{sec}] is declared in DEFAULTS but "
                "no code path consults it (no opts.section() or "
                "dotted get) — plumb it or delete it"))
        # declared-but-never-read knobs inside consulted sections
        for key, (_, site) in sorted(model.knob_defaults.items()):
            if "." not in key:
                if key not in model.name_pool:
                    out.append(self._finding(
                        site,
                        f"top-level config knob {key!r} is declared "
                        "but never read — plumb it or delete it"))
                continue
            sec = key.split(".")[0]
            if sec not in model.section_reads \
                    and sec not in dotted_read_prefixes:
                continue    # whole section already reported above
            if key in model.knob_reads:
                continue
            if key.split(".")[-1] in model.name_pool:
                continue    # consumed through a section dict / config
                #             object field somewhere
            if any(key == dyn or key.startswith(dyn + ".")
                   for dyn in model.knob_dynamic):
                continue
            out.append(self._finding(
                site,
                f"config knob {key!r} is declared in DEFAULTS but "
                "never read anywhere — operators can tune it with no "
                "effect; plumb it or delete it"))
        # declared-but-undocumented (only when README text is in scope)
        if model.readme_text is not None:
            for key, (_, site) in sorted(model.knob_defaults.items()):
                leaf = key.split(".")[-1]
                if leaf not in model.readme_text:
                    out.append(self._finding(
                        site,
                        f"config knob {key!r} is not documented in the "
                        "README knob tables — add a row (name, "
                        "default, one-line meaning)"))
        return out


@register
class ErrorCodeContract(ContractRule):
    id = "GT030"
    name = "error-code-contract"
    description = (
        "Typed errors cross the wire as `[gtdb:<code>]` markers and "
        "come back through error_from_code(), which needs a "
        "representative class per StatusCode in _CODE_CLASSES — a "
        "typed error whose code has no representative decodes to the "
        "generic base class on the client, losing the typed retry/"
        "degrade semantics. Also fires on _CODE_CLASSES entries whose "
        "representative class carries a different code, on duplicate "
        "integer code values (IntEnum silently aliases the second "
        "name), and on HTTP status-table entries for codes no typed "
        "error carries (dead mapping rows)."
    )
    example_pos = '''\
class StatusCode:
    RATE_LIMITED = 6001
    QUERY_TIMEOUT = 3002

class RateLimitedError(Exception):
    status_code = StatusCode.RATE_LIMITED

class QueryTimeoutError(Exception):
    status_code = StatusCode.QUERY_TIMEOUT

_CODE_CLASSES = {StatusCode.RATE_LIMITED: RateLimitedError}
'''
    example_neg = '''\
class StatusCode:
    RATE_LIMITED = 6001
    QUERY_TIMEOUT = 3002

class RateLimitedError(Exception):
    status_code = StatusCode.RATE_LIMITED

class QueryTimeoutError(Exception):
    status_code = StatusCode.QUERY_TIMEOUT

_CODE_CLASSES = {
    StatusCode.RATE_LIMITED: RateLimitedError,
    StatusCode.QUERY_TIMEOUT: QueryTimeoutError,
}
'''

    def check(self, model: ContractModel) -> list[Finding]:
        out: list[Finding] = []
        for name, prior, val, site in model.status_code_dups:
            out.append(self._finding(
                site,
                f"StatusCode.{name} duplicates code number {val} "
                f"already used by StatusCode.{prior} — IntEnum "
                "silently aliases the second name and the wire marker "
                "becomes ambiguous"))
        used_codes = {code for code, _ in model.error_classes.values()}
        if model.has_code_map:
            for cls, (code, site) in sorted(
                    model.error_classes.items()):
                if code not in model.code_classes:
                    out.append(self._finding(
                        site,
                        f"typed error {cls} carries StatusCode.{code} "
                        "but _CODE_CLASSES has no representative for "
                        "that code — error_from_code() will decode "
                        "the wire marker to the generic base class"))
            for code, (cls, site) in sorted(model.code_classes.items()):
                actual = model.error_classes.get(cls)
                if actual is not None and actual[0] != code:
                    out.append(self._finding(
                        site,
                        f"_CODE_CLASSES maps StatusCode.{code} to "
                        f"{cls}, whose own status_code is "
                        f"StatusCode.{actual[0]} — the wire round-"
                        "trip re-tags the error with a different "
                        "code"))
        if model.has_error_surface and model.has_http_surface \
                and model.error_classes:
            for code, (status, site) in sorted(
                    model.http_status.items()):
                if code not in model.status_codes:
                    out.append(self._finding(
                        site,
                        f"HTTP status table maps StatusCode.{code} "
                        "which is not a defined StatusCode member"))
                elif code not in used_codes:
                    out.append(self._finding(
                        site,
                        f"HTTP status table maps StatusCode.{code} "
                        f"to {status}, but no typed error carries "
                        "that code — dead mapping row"))
        return out


@register
class MetricFamilyContract(ContractRule):
    id = "GT031"
    name = "metric-family-contract"
    description = (
        "A `gtpu_*`/`greptime_*` metric family name referenced by a "
        "renderer, bench probe, or test (registry.get(), or a string "
        "literal carrying a conventional family suffix: _total, "
        "_seconds, _ms, _bytes, _bucket, _sum, _count) must be "
        "registered somewhere in the program — an unregistered "
        "reference raises KeyError on the scrape path or silently "
        "asserts against a family that can never exist. Registering "
        "the same family at multiple sites with different kinds or "
        "label sets fires too: exposition merges them into one "
        "family, and the self-export reingest keys on exact label "
        "names. `_bucket`/`_sum`/`_count` references resolve to their "
        "base histogram."
    )
    example_pos = '''\
from greptimedb_tpu.telemetry.metrics import global_registry

global_registry.counter("gtpu_rows_total", "rows written", ("table",))

def render(registry):
    return registry.get("gtpu_bytes_total").value()
'''
    example_neg = '''\
from greptimedb_tpu.telemetry.metrics import global_registry

global_registry.counter("gtpu_rows_total", "rows written", ("table",))

def render(registry):
    return registry.get("gtpu_rows_total").value()
'''

    def check(self, model: ContractModel) -> list[Finding]:
        out: list[Finding] = []
        for name, regs in sorted(model.metric_regs.items()):
            kinds = {k for k, _, _ in regs}
            if len(kinds) > 1:
                out.append(self._finding(
                    regs[1][2],
                    f"metric family {name!r} is registered with "
                    f"inconsistent kinds {sorted(kinds)} across sites "
                    "— exposition merges them into one family"))
            label_sets = {labels for _, labels, _ in regs
                          if labels is not None}
            if len(label_sets) > 1:
                out.append(self._finding(
                    regs[1][2],
                    f"metric family {name!r} is registered with "
                    "inconsistent label sets "
                    f"{sorted(map(list, label_sets))} — dashboards "
                    "and the self-export reingest key on exact label "
                    "names"))
        if not model.metric_regs:
            return out  # no registration surface in the linted set
        for name, sites in sorted(model.metric_refs.items()):
            if name in model.metric_regs:
                continue
            base = None
            for suf in _HISTO_DERIVED:
                if name.endswith(suf):
                    base = name[: -len(suf)]
                    break
            if base is not None and any(
                    kind == "histogram"
                    for kind, _, _ in model.metric_regs.get(base, ())):
                continue
            out.append(self._finding(
                sites[0],
                f"metric family {name!r} is referenced but never "
                "registered with any registry — registry.get() "
                "raises KeyError on this name (or the assertion can "
                "never match a live family)"))
        return out


@register
class FlightActionContract(ContractRule):
    id = "GT032"
    name = "flight-action-contract"
    description = (
        "Flight actions are a string-keyed RPC surface: every "
        "client-side dispatch (client.action(\"x\", ...) or a raw "
        "flight.Action(\"x\", ...)) needs a matching `kind == \"x\"` "
        "branch in the server's do_action handler, every handler "
        "branch needs at least one dispatcher (dead wire surface "
        "otherwise), and list_actions() must advertise exactly the "
        "handled set — clients discover capabilities from it. Fires "
        "only when the counterpart surface is in the linted set."
    )
    example_pos = '''\
def flush(client):
    return client.action("flush_region", b"{}")

def reset(client):
    return client.action("reset_region", b"{}")

class Server:
    def do_action(self, kind, body):
        if kind == "flush_region":
            return b"ok"
        raise KeyError(kind)

    def list_actions(self, context):
        return [("flush_region", "flush one region")]
'''
    example_neg = '''\
def flush(client):
    return client.action("flush_region", b"{}")

def reset(client):
    return client.action("reset_region", b"{}")

class Server:
    def do_action(self, kind, body):
        if kind == "flush_region":
            return b"ok"
        if kind == "reset_region":
            return b"ok"
        raise KeyError(kind)

    def list_actions(self, context):
        return [("flush_region", "flush one region"),
                ("reset_region", "reset one region")]
'''

    def check(self, model: ContractModel) -> list[Finding]:
        out: list[Finding] = []
        if model.has_handler_surface:
            for name, sites in sorted(model.action_dispatches.items()):
                if name not in model.action_handlers:
                    out.append(self._finding(
                        sites[0],
                        f"Flight action {name!r} is dispatched but no "
                        "do_action handler matches it — the server "
                        "returns unknown-action for every call"))
        if model.action_dispatches:
            for name, sites in sorted(model.action_handlers.items()):
                if name not in model.action_dispatches:
                    out.append(self._finding(
                        sites[0],
                        f"Flight action {name!r} has a server handler "
                        "but no dispatcher anywhere — dead wire "
                        "surface (add a client wrapper or remove the "
                        "branch)"))
        if model.has_advertise_surface and model.has_handler_surface:
            for name, sites in sorted(model.action_handlers.items()):
                if name not in model.action_advertised:
                    out.append(self._finding(
                        sites[0],
                        f"Flight action {name!r} is handled but not "
                        "advertised by list_actions() — clients "
                        "discovering capabilities never see it"))
            for name, sites in sorted(model.action_advertised.items()):
                if name not in model.action_handlers:
                    out.append(self._finding(
                        sites[0],
                        f"list_actions() advertises {name!r} but no "
                        "do_action branch handles it"))
        return out


def contract_findings(model: ContractModel,
                      rules: dict[str, Rule]) -> list[Finding]:
    """Run every selected contract rule over the model."""
    out: list[Finding] = []
    for rid in CONTRACT_RULE_IDS:
        rule = rules.get(rid)
        if isinstance(rule, ContractRule):
            out.extend(rule.check(model))
    return out
