"""gtlint: AST-based correctness linter for greptimedb-tpu.

Rules target the hazard classes this codebase has been bitten by —
silent exception swallows, error-text substring matching, host/device
sync and Python branching inside jitted code, recompile churn, locks
held across blocking Flight I/O, leaked threads/pools, int64-on-TPU
dtypes, and mutable default arguments.  See README "Static analysis".

    python -m greptimedb_tpu.tools.lint greptimedb_tpu/ --format=json
"""

from greptimedb_tpu.tools.lint.baseline import Baseline
from greptimedb_tpu.tools.lint.core import Finding, Rule, all_rules
from greptimedb_tpu.tools.lint.runner import (
    lint_paths,
    lint_source,
    main,
    run,
)

__all__ = [
    "Baseline", "Finding", "Rule", "all_rules", "lint_paths",
    "lint_source", "main", "run",
]
