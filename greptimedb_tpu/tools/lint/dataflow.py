"""Intraprocedural abstract interpretation for the dataflow rules.

One :class:`FileAnalyses` per linted file hands out per-scope
:class:`ScopeAnalysis` objects on demand (rules only pay for scopes
they ask about). Each scope gets a small CFG over its statement list
and a worklist fixpoint over an abstract-value lattice:

* ``AV`` values track what the rules need -- python ints/strs/tuples
  with optional concrete payloads, array shape/dtype (each dimension
  independently ``int`` or unknown ``None``), ``ShapeDtypeStruct``,
  ``BlockSpec``, ``PartitionSpec``, VMEM scratch shapes -- and a
  single TOP element for everything else.
* The lattice has finite height (join degrades unequal payloads to
  "unknown of the same kind", then to TOP), so loop re-entry widening
  is just join; a per-block visit cap backstops pathological inputs.
* Conservatism is the contract: rules must treat ``None``/TOP as
  "no fact" and stay silent, so an unknown shape can never fire.

Module scope is scanned linearly to seed constants (including
``FOLD_BLOCKS`` imported from ``parallel.mesh`` -- the cross-path
fold-block padding contract GT025 verifies).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

# Module constants the lattice knows even across files. FOLD_BLOCKS
# is the greptimedb_tpu.parallel.mesh padding contract every device
# twin relies on for bit-identity; a unit test pins this against the
# real module so the model cannot drift.
KNOWN_CONSTANTS = {"FOLD_BLOCKS": 8}

_DTYPE_NAMES = frozenset({
    "float64", "float32", "float16", "bfloat16",
    "int64", "int32", "int16", "int8",
    "uint64", "uint32", "uint16", "uint8",
    "bool_", "complex64", "complex128",
    "float8_e4m3fn", "float8_e5m2",
})

# -- abstract values ---------------------------------------------------


@dataclass(frozen=True)
class AV:
    """One lattice element.

    kind:
      top      -- no information
      int/float/str/bool/none -- python scalar; ``value`` holds the
                  concrete payload when known (None = known kind only)
      tuple    -- ``value`` is a tuple of AVs, or None when the length
                  is unknown
      array    -- device/host ndarray: ``shape`` is a tuple of
                  int-or-None dims (or None when even the rank is
                  unknown), ``dtype`` a numpy-style name or None.
                  ``weak`` marks values born from python scalars
                  (JAX weak types: they do not widen the other side).
      sds      -- jax.ShapeDtypeStruct (same shape/dtype payload)
      blockspec-- pl.BlockSpec; shape holds the block dims (None entry
                  = squeezed/unknown dim)
      pspec    -- PartitionSpec; ``value`` is the axis tuple
      dtype    -- a dtype object/name; ``value`` is the name
      sem      -- pltpu semaphore scratch (0 VMEM bytes)
      func     -- a locally-defined function object
    """

    kind: str = "top"
    value: object = None
    shape: tuple | None = None
    dtype: str | None = None
    weak: bool = False


TOP = AV()
NONE = AV(kind="none")


def _join_dim(a, b):
    return a if a == b else None


def join_shape(a: tuple | None, b: tuple | None) -> tuple | None:
    if a is None or b is None or len(a) != len(b):
        return None
    return tuple(_join_dim(x, y) for x, y in zip(a, b))


def join(a: AV, b: AV) -> AV:
    if a == b:
        return a
    if a.kind != b.kind:
        return TOP
    k = a.kind
    if k in ("int", "float", "str", "bool", "dtype"):
        if a.value == b.value:
            return AV(kind=k, value=a.value)
        return AV(kind=k)
    if k == "tuple":
        if (a.value is not None and b.value is not None
                and len(a.value) == len(b.value)):
            return AV(kind=k, value=tuple(
                join(x, y) for x, y in zip(a.value, b.value)))
        return AV(kind=k)
    if k in ("array", "sds", "blockspec"):
        return AV(kind=k,
                  shape=join_shape(a.shape, b.shape),
                  dtype=a.dtype if a.dtype == b.dtype else None,
                  weak=a.weak and b.weak)
    if k == "pspec":
        return AV(kind=k) if a.value != b.value else a
    if k in ("none", "sem"):
        return a
    return TOP


def join_env(a: dict, b: dict) -> dict:
    """Pointwise env join; a name bound on only one path is TOP (it
    may be unbound or hold an unknown prior value on the other)."""
    out = {}
    for name in set(a) | set(b):
        va, vb = a.get(name), b.get(name)
        out[name] = TOP if va is None or vb is None else join(va, vb)
    return out


# -- dtype promotion ---------------------------------------------------

_FLOATS = ("bfloat16", "float16", "float32", "float64")
_INTS = ("bool_", "int8", "uint8", "int16", "uint16",
         "int32", "uint32", "int64", "uint64")


def _rank(name: str, order) -> int:
    try:
        return order.index(name)
    except ValueError:
        return -1


def promote(a: str | None, b: str | None,
            a_weak: bool = False, b_weak: bool = False) -> str | None:
    """JAX-style binary dtype promotion (the subset the repo uses).

    Weak operands (python scalars) adopt the other side's dtype
    instead of widening it; bf16+f16 promotes to f32; int+float takes
    the float side. Returns None when either side is unknown."""
    if a is None or b is None:
        return None
    if a == b:
        return a
    if a_weak and not b_weak:
        # weak float against an int array still promotes to float
        if a in _FLOATS and b in _INTS:
            return "float32" if b != "float64" else "float64"
        return b
    if b_weak and not a_weak:
        if b in _FLOATS and a in _INTS:
            return "float32" if a != "float64" else "float64"
        return a
    a_f, b_f = a in _FLOATS, b in _FLOATS
    if a_f and b_f:
        if {a, b} == {"bfloat16", "float16"}:
            return "float32"
        return a if _rank(a, _FLOATS) >= _rank(b, _FLOATS) else b
    if a_f != b_f:  # int x float -> the float side
        return a if a_f else b
    ra, rb = _rank(a, _INTS), _rank(b, _INTS)
    if ra < 0 or rb < 0:
        return None
    return a if ra >= rb else b


# -- helpers -----------------------------------------------------------


def dotted(node) -> str | None:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _dtype_of(av: AV) -> str | None:
    """Interpret an AV used in a dtype= position."""
    if av.kind == "dtype":
        return av.value
    if av.kind == "str" and av.value in _DTYPE_NAMES:
        return str(av.value).rstrip("_") if av.value == "bool_" else av.value
    return None


def _as_shape(av: AV) -> tuple | None:
    """Interpret an AV used in a shape position: int -> (n,), tuple of
    ints/Nones -> dims. None = unknown."""
    if av.kind == "int":
        return (av.value,) if isinstance(av.value, int) else (None,)
    if av.kind == "tuple" and av.value is not None:
        dims = []
        for el in av.value:
            if el.kind == "int" and isinstance(el.value, int):
                dims.append(el.value)
            elif el.kind == "none":
                dims.append(None)
            else:
                dims.append(None)
        return tuple(dims)
    return None


def _broadcast(a: tuple | None, b: tuple | None) -> tuple | None:
    if a is None or b is None:
        return None
    if len(a) < len(b):
        a = (1,) * (len(b) - len(a)) + a
    elif len(b) < len(a):
        b = (1,) * (len(a) - len(b)) + b
    out = []
    for x, y in zip(a, b):
        if x == 1:
            out.append(y)
        elif y == 1 or x == y:
            out.append(x)
        elif x is None or y is None:
            out.append(None)
        else:  # static mismatch -- not this analysis's error to report
            out.append(None)
    return tuple(out)


def _assigned_names(nodes) -> set:
    """Names (re)bound anywhere inside the given statements."""
    out = set()
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, (ast.Store, ast.Del)):
                out.add(sub.id)
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                out.add(sub.name)
            elif isinstance(sub, ast.ExceptHandler) and sub.name:
                out.add(sub.name)
    return out


# -- CFG ---------------------------------------------------------------

# block events: ("stmt", node) | ("eval", expr) |
#               ("bind_iter", target, iter_expr) | ("degrade", names)


class _CFG:
    def __init__(self):
        self.blocks: list[list] = []
        self.succ: list[list[int]] = []
        self.entry = self._new()

    def _new(self) -> int:
        self.blocks.append([])
        self.succ.append([])
        return len(self.blocks) - 1

    def _edge(self, a: int, b: int):
        if b not in self.succ[a]:
            self.succ[a].append(b)

    def build(self, body) -> None:
        end = self._seq(body, self.entry, [])
        self.exit_blocks = [i for i in range(len(self.blocks))
                            if not self.succ[i]]
        del end

    def _seq(self, stmts, cur, loops):
        for s in stmts:
            if cur is None:  # unreachable tail: park it in a fresh
                cur = self._new()  # block with no predecessors
            cur = self._stmt(s, cur, loops)
        return cur

    def _stmt(self, s, cur, loops):
        if isinstance(s, ast.If):
            self.blocks[cur].append(("eval", s.test))
            then_b = self._new()
            self._edge(cur, then_b)
            end_then = self._seq(s.body, then_b, loops)
            join_b = self._new()
            if s.orelse:
                else_b = self._new()
                self._edge(cur, else_b)
                end_else = self._seq(s.orelse, else_b, loops)
                if end_else is not None:
                    self._edge(end_else, join_b)
            else:
                self._edge(cur, join_b)
            if end_then is not None:
                self._edge(end_then, join_b)
            return join_b
        if isinstance(s, (ast.While, ast.For, ast.AsyncFor)):
            header = self._new()
            if isinstance(s, ast.While):
                self.blocks[header].append(("eval", s.test))
            else:
                self.blocks[cur].append(("eval", s.iter))
                self.blocks[header].append(
                    ("bind_iter", s.target, s.iter))
            self._edge(cur, header)
            body_b = self._new()
            exit_b = self._new()
            self._edge(header, body_b)
            self._edge(header, exit_b)
            end = self._seq(s.body, body_b, loops + [(header, exit_b)])
            if end is not None:
                self._edge(end, header)
            if s.orelse:
                return self._seq(s.orelse, exit_b, loops)
            return exit_b
        if isinstance(s, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            body_b = self._new()
            self._edge(cur, body_b)
            end_body = self._seq(s.body, body_b, loops)
            if s.orelse and end_body is not None:
                end_body = self._seq(s.orelse, end_body, loops)
            join_b = self._new()
            if end_body is not None:
                self._edge(end_body, join_b)
            degraded = _assigned_names(s.body)
            for h in s.handlers:
                h_b = self._new()
                self._edge(cur, h_b)
                # an exception may interrupt the body anywhere: every
                # name it assigns is unknown at handler entry
                self.blocks[h_b].append(("degrade", degraded))
                if h.name:
                    self.blocks[h_b].append(("degrade", {h.name}))
                end_h = self._seq(h.body, h_b, loops)
                if end_h is not None:
                    self._edge(end_h, join_b)
            if s.finalbody:
                return self._seq(s.finalbody, join_b, loops)
            return join_b
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self.blocks[cur].append(("eval", item.context_expr))
                if item.optional_vars is not None:
                    names = {sub.id for sub in
                             ast.walk(item.optional_vars)
                             if isinstance(sub, ast.Name)}
                    self.blocks[cur].append(("degrade", names))
            return self._seq(s.body, cur, loops)
        if isinstance(s, (ast.Return, ast.Raise)):
            self.blocks[cur].append(("stmt", s))
            return None
        if isinstance(s, ast.Break):
            if loops:
                self._edge(cur, loops[-1][1])
            return None
        if isinstance(s, ast.Continue):
            if loops:
                self._edge(cur, loops[-1][0])
            return None
        if isinstance(s, ast.Match):
            self.blocks[cur].append(("eval", s.subject))
            join_b = self._new()
            for case in s.cases:
                c_b = self._new()
                self._edge(cur, c_b)
                self.blocks[c_b].append(
                    ("degrade", _assigned_names([case.pattern])))
                end_c = self._seq(case.body, c_b, loops)
                if end_c is not None:
                    self._edge(end_c, join_b)
            self._edge(cur, join_b)  # no case may match
            return join_b
        # simple statement (incl. nested def/class, assignments, ...)
        self.blocks[cur].append(("stmt", s))
        return cur


# -- the interpreter ---------------------------------------------------

_MAX_VISITS = 50  # per-block fixpoint backstop; join makes real code
                  # converge in 2-3 passes


class ScopeAnalysis:
    """Fixpoint analysis of one function (or the module body).

    ``value(node)`` returns the AV recorded for any expression node in
    the scope after convergence (TOP when the node was unreachable or
    never evaluated)."""

    def __init__(self, body, module_env: dict, args: ast.arguments | None):
        self.values: dict[int, AV] = {}
        self._cfg = _CFG()
        self._cfg.build(body)
        entry = dict(module_env)
        if args is not None:
            for a in (list(args.posonlyargs) + list(args.args)
                      + list(args.kwonlyargs)):
                entry[a.arg] = TOP
            if args.vararg:
                entry[args.vararg.arg] = TOP
            if args.kwarg:
                entry[args.kwarg.arg] = TOP
        self._solve(entry)

    def value(self, node) -> AV:
        return self.values.get(id(node), TOP)

    # fixpoint ---------------------------------------------------------

    def _solve(self, entry_env: dict):
        cfg = self._cfg
        n = len(cfg.blocks)
        in_env: list[dict | None] = [None] * n
        in_env[cfg.entry] = entry_env
        visits = [0] * n
        work = [cfg.entry]
        preds: list[list[int]] = [[] for _ in range(n)]
        for a in range(n):
            for b in cfg.succ[a]:
                preds[b].append(a)
        out_env: list[dict | None] = [None] * n
        while work:
            b = work.pop()
            env = in_env[b]
            if env is None:
                continue
            visits[b] += 1
            if visits[b] > _MAX_VISITS:
                env = {k: TOP for k in env}
            out = self._transfer(b, dict(env), record=False)
            if out_env[b] is not None and out == out_env[b]:
                continue
            out_env[b] = out
            for s in cfg.succ[b]:
                merged = out if in_env[s] is None else join_env(
                    in_env[s], out)
                if in_env[s] is None or merged != in_env[s]:
                    in_env[s] = merged
                    if s not in work:
                        work.append(s)
        # recording pass over the converged envs
        for b in range(n):
            if in_env[b] is not None:
                self._transfer(b, dict(in_env[b]), record=True)

    # transfer ---------------------------------------------------------

    def _transfer(self, block: int, env: dict, record: bool) -> dict:
        for ev in self._cfg.blocks[block]:
            tag = ev[0]
            if tag == "eval":
                self._eval(ev[1], env, record)
            elif tag == "bind_iter":
                self._bind_iter(ev[1], ev[2], env, record)
            elif tag == "degrade":
                for name in ev[1]:
                    env[name] = TOP
            else:
                self._exec(ev[1], env, record)
        return env

    def _exec(self, s, env, record):
        if isinstance(s, ast.Assign):
            v = self._eval(s.value, env, record)
            for t in s.targets:
                self._assign(t, v, env)
        elif isinstance(s, ast.AnnAssign):
            v = (self._eval(s.value, env, record)
                 if s.value is not None else TOP)
            self._assign(s.target, v, env)
        elif isinstance(s, ast.AugAssign):
            # model x += y as x = x <op> y
            self._eval(s.value, env, record)
            if isinstance(s.target, ast.Name):
                cur = env.get(s.target.id, TOP)
                rhs = self._eval(s.value, env, False)
                env[s.target.id] = self._binop(
                    type(s.op), cur, rhs)
        elif isinstance(s, (ast.Expr, ast.Return)):
            if getattr(s, "value", None) is not None:
                self._eval(s.value, env, record)
        elif isinstance(s, ast.Raise):
            if s.exc is not None:
                self._eval(s.exc, env, record)
        elif isinstance(s, ast.Assert):
            self._eval(s.test, env, record)
        elif isinstance(s, ast.ImportFrom):
            mod = (s.module or "").rsplit(".", 1)[-1]
            for alias in s.names:
                name = alias.asname or alias.name
                if alias.name in KNOWN_CONSTANTS and mod == "mesh":
                    env[name] = AV(kind="int",
                                   value=KNOWN_CONSTANTS[alias.name])
                else:
                    env[name] = TOP
        elif isinstance(s, ast.Import):
            for alias in s.names:
                env[(alias.asname or alias.name).split(".")[0]] = TOP
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            env[s.name] = AV(kind="func", value=s.name)
        elif isinstance(s, ast.ClassDef):
            env[s.name] = TOP
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    env[t.id] = TOP
        # Pass/Global/Nonlocal/etc: no effect

    def _assign(self, target, v: AV, env: dict):
        if isinstance(target, ast.Name):
            env[target.id] = v
        elif isinstance(target, (ast.Tuple, ast.List)):
            els = (v.value if v.kind == "tuple" and v.value is not None
                   and len(v.value) == len(target.elts) else None)
            for i, t in enumerate(target.elts):
                if isinstance(t, ast.Starred):
                    self._assign(t.value, AV(kind="tuple"), env)
                else:
                    self._assign(t, els[i] if els else TOP, env)
        elif isinstance(target, ast.Starred):
            self._assign(target.value, TOP, env)
        # subscript/attribute stores: no tracked effect

    def _bind_iter(self, target, iter_expr, env, record):
        it = self._eval(iter_expr, env, False)
        el = TOP
        if it.kind == "tuple" and it.value:
            el = it.value[0]
            for x in it.value[1:]:
                el = join(el, x)
        elif it.kind == "array" and it.shape is not None and it.shape:
            el = AV(kind="array", shape=tuple(it.shape[1:]),
                    dtype=it.dtype)
        elif it.kind == "int":  # range() modelled as int stream
            el = AV(kind="int")
        self._assign(target, el, env)

    # expressions ------------------------------------------------------

    def _eval(self, node, env, record) -> AV:
        v = self._eval_inner(node, env, record)
        if record:
            self.values[id(node)] = v
        return v

    def _eval_inner(self, node, env, record) -> AV:
        if isinstance(node, ast.Constant):
            c = node.value
            if isinstance(c, bool):
                return AV(kind="bool", value=c)
            if isinstance(c, int):
                return AV(kind="int", value=c)
            if isinstance(c, float):
                return AV(kind="float", value=c)
            if isinstance(c, str):
                return AV(kind="str", value=c)
            if c is None:
                return NONE
            return TOP
        if isinstance(node, ast.Name):
            return env.get(node.id, TOP)
        if isinstance(node, (ast.Tuple, ast.List)):
            els = []
            star = False
            for e in node.elts:
                if isinstance(e, ast.Starred):
                    self._eval(e.value, env, record)
                    star = True
                else:
                    els.append(self._eval(e, env, record))
            return AV(kind="tuple",
                      value=None if star else tuple(els))
        if isinstance(node, ast.Attribute):
            return self._attribute(node, env, record)
        if isinstance(node, ast.Call):
            return self._call(node, env, record)
        if isinstance(node, ast.BinOp):
            left = self._eval(node.left, env, record)
            right = self._eval(node.right, env, record)
            return self._binop(type(node.op), left, right)
        if isinstance(node, ast.UnaryOp):
            v = self._eval(node.operand, env, record)
            if isinstance(node.op, ast.USub) and v.kind == "int" \
                    and isinstance(v.value, int):
                return AV(kind="int", value=-v.value)
            if isinstance(node.op, ast.Not):
                return AV(kind="bool")
            return v if v.kind in ("int", "float", "array") else TOP
        if isinstance(node, ast.Subscript):
            return self._subscript(node, env, record)
        if isinstance(node, ast.Compare):
            self._eval(node.left, env, record)
            for c in node.comparators:
                self._eval(c, env, record)
            return AV(kind="bool")
        if isinstance(node, ast.BoolOp):
            vals = [self._eval(v, env, record) for v in node.values]
            out = vals[0]
            for v in vals[1:]:
                out = join(out, v)
            return out
        if isinstance(node, ast.IfExp):
            self._eval(node.test, env, record)
            a = self._eval(node.body, env, record)
            b = self._eval(node.orelse, env, record)
            return join(a, b)
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self._eval(v.value, env, record)
            return AV(kind="str")
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            # comprehension scopes are opaque; evaluate iterables for
            # recording, result length unknown
            for gen in node.generators:
                self._eval(gen.iter, env, record)
            return (AV(kind="tuple")
                    if isinstance(node, (ast.ListComp, ast.GeneratorExp))
                    else TOP)
        if isinstance(node, ast.Starred):
            self._eval(node.value, env, record)
            return TOP
        if isinstance(node, ast.Lambda):
            return AV(kind="func")
        if isinstance(node, ast.NamedExpr):
            v = self._eval(node.value, env, record)
            self._assign(node.target, v, env)
            return v
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if k is not None:
                    self._eval(k, env, record)
            for v in node.values:
                self._eval(v, env, record)
            return TOP
        if isinstance(node, (ast.Await, ast.YieldFrom)):
            self._eval(node.value, env, record)
            return TOP
        if isinstance(node, ast.Yield):
            if node.value is not None:
                self._eval(node.value, env, record)
            return TOP
        if isinstance(node, ast.Slice):
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    self._eval(part, env, record)
            return AV(kind="slice")
        return TOP

    def _binop(self, op, left: AV, right: AV) -> AV:
        if left.kind == "int" and right.kind == "int":
            if isinstance(left.value, int) and isinstance(
                    right.value, int):
                try:
                    v = {
                        ast.Add: lambda a, b: a + b,
                        ast.Sub: lambda a, b: a - b,
                        ast.Mult: lambda a, b: a * b,
                        ast.FloorDiv: lambda a, b: a // b,
                        ast.Mod: lambda a, b: a % b,
                        ast.Pow: lambda a, b: a ** b
                        if abs(b) < 64 else None,
                        ast.LShift: lambda a, b: a << b
                        if 0 <= b < 256 else None,
                        ast.RShift: lambda a, b: a >> b
                        if 0 <= b < 256 else None,
                        ast.BitAnd: lambda a, b: a & b,
                        ast.BitOr: lambda a, b: a | b,
                        ast.BitXor: lambda a, b: a ^ b,
                    }.get(op, lambda a, b: None)(left.value, right.value)
                except (ZeroDivisionError, OverflowError, ValueError):
                    v = None
                if op is ast.Div:
                    return AV(kind="float")
                return AV(kind="int", value=v)
            return AV(kind="float" if op is ast.Div else "int")
        if left.kind == "tuple" and right.kind == "tuple" \
                and op is ast.Add:
            if left.value is not None and right.value is not None:
                return AV(kind="tuple", value=left.value + right.value)
            return AV(kind="tuple")
        if op is ast.Mult and {left.kind, right.kind} == {"tuple", "int"}:
            tup, n = (left, right) if left.kind == "tuple" else (right,
                                                                 left)
            if tup.value is not None and isinstance(n.value, int) \
                    and 0 <= n.value <= 64:
                return AV(kind="tuple", value=tup.value * n.value)
            return AV(kind="tuple")
        kinds = {left.kind, right.kind}
        if "array" in kinds and kinds <= {"array", "int", "float",
                                          "bool"}:
            la = left if left.kind == "array" else _scalar_array(left)
            ra = right if right.kind == "array" else _scalar_array(right)
            dt = promote(la.dtype, ra.dtype, la.weak, ra.weak)
            if op is ast.Div and dt in ("int8", "int16", "int32",
                                        "int64", "uint8", "uint16",
                                        "uint32", "uint64"):
                dt = "float32"
            return AV(kind="array",
                      shape=_broadcast(la.shape, ra.shape),
                      dtype=dt, weak=la.weak and ra.weak)
        if kinds <= {"int", "float"}:
            return AV(kind="float")
        if kinds == {"str"} and op is ast.Add:
            return AV(kind="str")
        return TOP

    def _subscript(self, node, env, record) -> AV:
        base = self._eval(node.value, env, record)
        idx = self._eval(node.slice, env, record)
        if base.kind == "tuple" and base.value is not None:
            if idx.kind == "int" and isinstance(idx.value, int):
                if -len(base.value) <= idx.value < len(base.value):
                    return base.value[idx.value]
                return TOP
            if isinstance(node.slice, ast.Slice):
                lo = node.slice.lower
                hi = node.slice.upper
                if node.slice.step is None:
                    lo_v = (lo.value if isinstance(lo, ast.Constant)
                            and isinstance(lo.value, int) else
                            0 if lo is None else None)
                    hi_v = (hi.value if isinstance(hi, ast.Constant)
                            and isinstance(hi.value, int) else
                            len(base.value) if hi is None else None)
                    if lo_v is not None and hi_v is not None:
                        return AV(kind="tuple",
                                  value=base.value[lo_v:hi_v])
            return AV(kind="tuple")
        if base.kind == "array":
            shape = base.shape
            if shape is not None and shape:
                if idx.kind == "int":
                    return AV(kind="array", shape=tuple(shape[1:]),
                              dtype=base.dtype, weak=base.weak)
                if isinstance(node.slice, ast.Slice):
                    return AV(kind="array",
                              shape=(None,) + tuple(shape[1:]),
                              dtype=base.dtype, weak=base.weak)
            # unknown indexing keeps the dtype fact (accumulators)
            return AV(kind="array", dtype=base.dtype, weak=base.weak)
        return TOP

    def _attribute(self, node, env, record) -> AV:
        base = self._eval(node.value, env, record)
        attr = node.attr
        if attr in _DTYPE_NAMES and base.kind == "top":
            # jnp.float32 / np.int64 on an (untracked) module alias
            return AV(kind="dtype",
                      value="bool" if attr == "bool_" else attr)
        if base.kind in ("array", "sds"):
            if attr == "shape":
                if base.shape is None:
                    return AV(kind="tuple")
                return AV(kind="tuple", value=tuple(
                    AV(kind="int", value=dd) if dd is not None
                    else AV(kind="int") for dd in base.shape))
            if attr == "dtype":
                return (AV(kind="dtype", value=base.dtype)
                        if base.dtype else AV(kind="dtype"))
            if attr == "ndim":
                return (AV(kind="int", value=len(base.shape))
                        if base.shape is not None else AV(kind="int"))
            if attr == "size":
                if base.shape is not None and all(
                        dd is not None for dd in base.shape):
                    n = 1
                    for dd in base.shape:
                        n *= dd
                    return AV(kind="int", value=n)
                return AV(kind="int")
            if attr == "T":
                return AV(kind="array",
                          shape=(tuple(reversed(base.shape))
                                 if base.shape is not None else None),
                          dtype=base.dtype)
        if base.kind == "dtype" and attr == "itemsize":
            from . import device_model
            size = device_model.itemsize(base.value)
            return AV(kind="int", value=size)
        return TOP

    # calls ------------------------------------------------------------

    def _call(self, node, env, record) -> AV:
        args = [self._eval(a, env, record) for a in node.args
                if not isinstance(a, ast.Starred)]
        for a in node.args:
            if isinstance(a, ast.Starred):
                self._eval(a.value, env, record)
        kw = {}
        for k in node.keywords:
            v = self._eval(k.value, env, record)
            if k.arg is not None:
                kw[k.arg] = v
        d = dotted(node.func) or ""
        if isinstance(node.func, ast.Attribute):
            # evaluate the receiver for method calls (records x in
            # x.reshape(...)); dotted-name bases double-evaluate
            # harmlessly
            base = self._eval(node.func.value, env, record)
        else:
            base = None
            if not isinstance(node.func, ast.Name):
                # curried calls -- pl.pallas_call(...)(x): the inner
                # call only appears as .func, so record it here
                self._eval(node.func, env, record)
        short = d.rsplit(".", 1)[-1] if d else ""

        def kw_dtype(default=None, pos=None):
            if "dtype" in kw:
                return _dtype_of(kw["dtype"]) or None
            if pos is not None and len(args) > pos:
                got = _dtype_of(args[pos])
                if got is not None:
                    return got
            return default

        if short in ("zeros", "ones", "empty", "full"):
            shape = _as_shape(args[0]) if args else None
            dt = kw_dtype("float32", pos=2 if short == "full" else 1)
            if short == "full" and dt is None:
                dt = "float32"
            return AV(kind="array", shape=shape, dtype=dt)
        if short in ("zeros_like", "ones_like", "empty_like",
                     "full_like"):
            src = args[0] if args else TOP
            return AV(kind="array",
                      shape=src.shape if src.kind in ("array", "sds")
                      else None,
                      dtype=kw_dtype(src.dtype if src.kind in
                                     ("array", "sds") else None))
        if short in ("asarray", "array"):
            src = args[0] if args else TOP
            shape = src.shape if src.kind in ("array", "sds") else None
            if src.kind == "tuple" and src.value is not None and all(
                    e.kind in ("int", "float") for e in src.value):
                shape = (len(src.value),)
            dt = kw_dtype(src.dtype if src.kind in ("array", "sds")
                          else None)
            return AV(kind="array", shape=shape, dtype=dt)
        if short == "arange":
            n = None
            if len(args) == 1 and args[0].kind == "int" and isinstance(
                    args[0].value, int):
                n = args[0].value
            return AV(kind="array",
                      shape=(n,) if n is not None else (None,),
                      dtype=kw_dtype("int32"))
        if short == "reshape":
            if base is not None and base.kind in ("array", "sds"):
                src = base  # x.reshape(...)
                dims_args = args
            elif args and args[0].kind in ("array", "sds"):
                src = args[0]  # jnp.reshape(x, shape)
                dims_args = args[1:]
            else:
                return TOP
            new = self._reshape_dims(dims_args, src)
            return AV(kind="array", shape=new, dtype=src.dtype,
                      weak=src.weak)
        if short == "astype" and base is not None:
            dt = _dtype_of(args[0]) if args else None
            return AV(kind="array",
                      shape=base.shape if base.kind in ("array", "sds")
                      else None, dtype=dt)
        if short == "ShapeDtypeStruct":
            shape = _as_shape(kw.get("shape", args[0] if args else TOP))
            dtv = kw.get("dtype", args[1] if len(args) > 1 else TOP)
            return AV(kind="sds", shape=shape, dtype=_dtype_of(dtv))
        if short == "BlockSpec":
            shape_av = kw.get("block_shape",
                              args[0] if args else None)
            shape = _as_shape(shape_av) if shape_av is not None else None
            return AV(kind="blockspec", shape=shape)
        if short in ("PrefetchScalarGridSpec", "GridSpec"):
            # carry the parts so a grid_spec built in a local still
            # reaches the pallas_call geometry; pairs keep AV hashable
            return AV(kind="gridspec", value=tuple(
                (k, v) for k, v in sorted(kw.items())))
        if short in ("PartitionSpec", "P"):
            return AV(kind="pspec", value=tuple(
                a.value if a.kind in ("str", "none") else None
                for a in args))
        if short == "VMEM":
            shape = _as_shape(args[0]) if args else None
            dt = _dtype_of(args[1]) if len(args) > 1 else None
            return AV(kind="array", shape=shape, dtype=dt)
        if "SemaphoreType" in d:
            return AV(kind="sem")
        if short in ("sum", "max", "min", "mean", "prod"):
            src = base if base is not None and base.kind == "array" \
                else (args[0] if args and args[0].kind == "array"
                      else None)
            if src is None:
                return TOP
            dt = src.dtype
            if short == "mean" and dt in _INTS:
                dt = "float32"
            dt = kw_dtype(dt)
            axis = kw.get("axis")
            keep = kw.get("keepdims")
            shape = None
            if axis is None and "axis" not in kw:
                shape = ()
            elif (axis is not None and axis.kind == "int"
                  and isinstance(axis.value, int)
                  and src.shape is not None
                  and (keep is None or keep.value is False)):
                ax = axis.value
                if -len(src.shape) <= ax < len(src.shape):
                    lst = list(src.shape)
                    del lst[ax]
                    shape = tuple(lst)
            return AV(kind="array", shape=shape, dtype=dt)
        if short == "where" and len(args) >= 3:
            a, b = args[1], args[2]
            la = a if a.kind == "array" else _scalar_array(a)
            rb = b if b.kind == "array" else _scalar_array(b)
            return AV(kind="array",
                      shape=_broadcast(la.shape, rb.shape),
                      dtype=promote(la.dtype, rb.dtype, la.weak,
                                    rb.weak))
        if short == "broadcast_to" and len(args) >= 2:
            src = args[0]
            return AV(kind="array", shape=_as_shape(args[1]),
                      dtype=src.dtype if src.kind in ("array", "sds")
                      else None)
        if short == "transpose":
            src = base if base is not None and base.kind == "array" \
                else (args[0] if args and args[0].kind == "array"
                      else None)
            if src is not None and len(args) <= (
                    0 if src is base else 1):
                return AV(kind="array",
                          shape=(tuple(reversed(src.shape))
                                 if src.shape is not None else None),
                          dtype=src.dtype)
            return TOP
        if short in ("concatenate", "stack", "dot", "matmul",
                     "einsum", "take", "gather"):
            dts = [a.dtype for a in args if a.kind == "array"]
            if args and args[0].kind == "tuple" and args[0].value:
                dts += [e.dtype for e in args[0].value
                        if e.kind == "array"]
            dt = dts[0] if dts and all(x == dts[0] for x in dts) \
                else None
            return AV(kind="array", dtype=dt)
        if short == "range":
            if args and args[-1].kind == "int":
                return AV(kind="int", value=None)
            return AV(kind="int")
        if short == "len":
            src = args[0] if args else TOP
            if src.kind == "tuple" and src.value is not None:
                return AV(kind="int", value=len(src.value))
            if src.kind in ("array", "sds") and src.shape:
                return (AV(kind="int", value=src.shape[0])
                        if src.shape[0] is not None else AV(kind="int"))
            return AV(kind="int")
        if short in ("int", "round"):
            return AV(kind="int",
                      value=args[0].value if args
                      and args[0].kind == "int" else None)
        if short == "float":
            return AV(kind="float")
        if short == "tuple" and args:
            return args[0] if args[0].kind == "tuple" else AV(
                kind="tuple")
        if short == "dtype" and args:  # jnp.dtype("float32")
            return AV(kind="dtype", value=_dtype_of(args[0]))
        return TOP

    def _reshape_dims(self, dims_args, src: AV) -> tuple | None:
        if len(dims_args) == 1 and dims_args[0].kind == "tuple":
            new = _as_shape(dims_args[0])
        elif dims_args and all(a.kind == "int" for a in dims_args):
            new = tuple(a.value if isinstance(a.value, int) else None
                        for a in dims_args)
        else:
            return None
        if new is None:
            return None
        if -1 in new:
            if (src.shape is not None
                    and all(dd is not None for dd in src.shape)
                    and all(dd is not None for dd in new)):
                total = 1
                for dd in src.shape:
                    total *= dd
                rest = 1
                for dd in new:
                    if dd != -1:
                        rest *= dd
                if rest and total % rest == 0:
                    return tuple(total // rest if dd == -1 else dd
                                 for dd in new)
            return tuple(None if dd == -1 else dd for dd in new)
        return new


def _scalar_array(av: AV) -> AV:
    """A python scalar entering array arithmetic: weakly-typed 0-d."""
    if av.kind == "int" or av.kind == "bool":
        return AV(kind="array", shape=(), dtype="int32", weak=True)
    if av.kind == "float":
        return AV(kind="array", shape=(), dtype="float32", weak=True)
    return AV(kind="array")


# -- per-file entry point ----------------------------------------------


@dataclass
class FileAnalyses:
    """Lazy per-scope analyses for one parsed file."""

    tree: ast.Module
    _scopes: dict = field(default_factory=dict)
    _module_env: dict | None = None

    def module_env(self) -> dict:
        if self._module_env is None:
            self._module_env = _scan_module(self.tree)
        return self._module_env

    def scope(self, func_node=None) -> ScopeAnalysis:
        """Analysis for a def node (or the module body when None)."""
        key = id(func_node) if func_node is not None else 0
        hit = self._scopes.get(key)
        if hit is None:
            if func_node is None:
                hit = ScopeAnalysis(self.tree.body, self.module_env(),
                                    None)
            else:
                hit = ScopeAnalysis(func_node.body, self.module_env(),
                                    func_node.args)
            self._scopes[key] = hit
        return hit


def _scan_module(tree: ast.Module) -> dict:
    """Linear scan of module-level constants: ints, strings, simple
    tuples, and KNOWN_CONSTANTS imports (module-level control flow for
    constants is rare enough to ignore)."""
    env: dict[str, AV] = {}

    def const_av(node) -> AV | None:
        if isinstance(node, ast.Constant):
            c = node.value
            if isinstance(c, bool):
                return AV(kind="bool", value=c)
            if isinstance(c, int):
                return AV(kind="int", value=c)
            if isinstance(c, float):
                return AV(kind="float", value=c)
            if isinstance(c, str):
                return AV(kind="str", value=c)
            return None
        if isinstance(node, ast.Tuple):
            els = [const_av(e) for e in node.elts]
            if all(e is not None for e in els):
                return AV(kind="tuple", value=tuple(els))
        if isinstance(node, ast.BinOp):
            left, right = const_av(node.left), const_av(node.right)
            if (left is not None and right is not None
                    and left.kind == right.kind == "int"
                    and isinstance(left.value, int)
                    and isinstance(right.value, int)):
                try:
                    op = {ast.Add: int.__add__, ast.Sub: int.__sub__,
                          ast.Mult: int.__mul__,
                          ast.FloorDiv: int.__floordiv__}.get(
                              type(node.op))
                    if op is not None:
                        return AV(kind="int",
                                  value=op(left.value, right.value))
                except (ZeroDivisionError, OverflowError):
                    return None
        return None

    for s in tree.body:
        if isinstance(s, ast.Assign) and len(s.targets) == 1 \
                and isinstance(s.targets[0], ast.Name):
            v = const_av(s.value)
            env[s.targets[0].id] = v if v is not None else TOP
        elif isinstance(s, ast.AnnAssign) and isinstance(
                s.target, ast.Name) and s.value is not None:
            v = const_av(s.value)
            env[s.target.id] = v if v is not None else TOP
        elif isinstance(s, ast.ImportFrom):
            mod = (s.module or "").rsplit(".", 1)[-1]
            for alias in s.names:
                if alias.name in KNOWN_CONSTANTS and mod == "mesh":
                    env[alias.asname or alias.name] = AV(
                        kind="int", value=KNOWN_CONSTANTS[alias.name])
    return env
