"""Static TPU device model for the dataflow lint rules (GT023-GT026).

Constants describe the TPU v5e core the paper targets, sourced from the
Pallas TPU programming guide (tiling and memory-space tables):

* vector lanes: the LAST block dimension must be a multiple of 128
  (one vector lane row) for every dtype;
* sublanes: the SECOND-TO-LAST block dimension tiles by dtype width --
  8 for 4-byte types (f32/i32), 16 for 2-byte types (bf16/f16/i16),
  32 for 1-byte types (i8/fp8) -- packing narrower types two/four per
  32-bit sublane word;
* VMEM: ~16 MiB per core. Pallas double-buffers every *blocked* ref in
  a pipelined grid, so a blocked operand costs two block buffers;
* 64-bit dtypes (f64/i64/u64) do not exist on the device datapath:
  refs reaching a kernel in a 64-bit dtype are a compile error under
  Mosaic (and a silent x64-disabled downcast on host paths, GT009).

These are *model* numbers for static verdicts, not measurements: the
rules built on them only fire when the dataflow lattice has concrete
facts, so an unknown shape/dtype can never produce a finding.
"""

from __future__ import annotations

# one vector-lane row: required multiple for the last block dim
LANE = 128

# usable VMEM per v5e core (the guide's ~16 MiB figure); the compiler
# reserves a slice, so rules compare against the full budget only --
# anything over this is unconditionally overcommitted
VMEM_BYTES = 16 * 1024 * 1024

# dtype -> itemsize in bytes, for the dtypes the codebase touches
ITEMSIZE = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "float16": 2, "bfloat16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1, "bool_": 1,
    "float8_e4m3fn": 1, "float8_e5m2": 1,
}

# itemsize -> sublane multiple (second-to-last block dim)
_SUBLANE_BY_ITEMSIZE = {4: 8, 2: 16, 1: 32}

# dtypes with no device representation: a ref in one of these reaching
# a kernel cannot compile under Mosaic
ILLEGAL_DEVICE_DTYPES = frozenset({"float64", "int64", "uint64"})

# 64-bit result dtypes a promotion can silently produce (GT026)
WIDE_DTYPES = frozenset({"float64", "int64", "uint64", "complex128"})


def itemsize(dtype: str | None) -> int | None:
    """Bytes per element, or None for an unknown dtype name."""
    if dtype is None:
        return None
    return ITEMSIZE.get(dtype)


def sublane(dtype: str | None) -> int | None:
    """Required multiple for the second-to-last block dim, or None
    when the dtype (hence packing) is unknown."""
    size = itemsize(dtype)
    if size is None:
        return None
    return _SUBLANE_BY_ITEMSIZE.get(size, 8)


def tile_ok(dim: int, multiple: int) -> bool:
    return dim % multiple == 0


def buffer_bytes(shape, dtype: str | None) -> int | None:
    """Static VMEM footprint of one buffer of ``shape``/``dtype``,
    padded up to the (sublane, lane) tile the hardware allocates.
    Returns None unless every dimension and the dtype are known."""
    if shape is None or any(d is None for d in shape):
        return None
    size = itemsize(dtype)
    if size is None:
        return None
    dims = [d for d in shape]
    if dims:
        dims[-1] = _round_up(max(dims[-1], 1), LANE)
    if len(dims) >= 2:
        sub = sublane(dtype) or 8
        dims[-2] = _round_up(max(dims[-2], 1), sub)
    n = 1
    for d in dims:
        n *= max(int(d), 1)
    return n * size


def _round_up(n: int, m: int) -> int:
    return ((int(n) + m - 1) // m) * m


def fmt_bytes(n: int) -> str:
    if n >= 1024 * 1024:
        return f"{n / (1024 * 1024):.1f}MiB"
    if n >= 1024:
        return f"{n / 1024:.1f}KiB"
    return f"{n}B"
