"""gtlint device-contract rules (GT023-GT027).

These rules sit on top of the abstract interpreter (dataflow.py) and
the static TPU model (device_model.py): the CPU interpreter tier-1
runs does not enforce Mosaic's tiling/VMEM/dtype legality, so a
kernel can fuzz green on the CPU mesh and still fail to compile (or
silently spill) on the v5e the paper targets. Every check here only
fires on a *known* lattice fact -- an unknown shape or dtype is
silence, never a finding.
"""

from __future__ import annotations

import ast

from greptimedb_tpu.tools.lint import device_model as dm
from greptimedb_tpu.tools.lint.core import (
    FileContext, Rule, dotted_name, register,
)
from greptimedb_tpu.tools.lint.dataflow import AV, promote

_WIDE = dm.ILLEGAL_DEVICE_DTYPES
_NARROW_INTS = frozenset({"int8", "int16", "int32",
                          "uint8", "uint16", "uint32"})


def _is_pallas_call(node: ast.Call) -> bool:
    f = dotted_name(node.func)
    return bool(f) and f.split(".")[-1] == "pallas_call"


class _Geom:
    """Static geometry of one pallas_call: paired (spec node, spec AV,
    operand AV) rows, grid, scratch, out shapes."""

    def __init__(self, node: ast.Call, ctx: FileContext):
        an = ctx.dataflow_scope()
        self.an = an
        self.node = node
        kws = {k.arg: k.value for k in node.keywords if k.arg}
        grid_node = kws.get("grid")
        in_specs_node = kws.get("in_specs")
        out_specs_node = kws.get("out_specs")
        scratch_node = kws.get("scratch_shapes")
        self.nsp = 0
        gs = kws.get("grid_spec")
        gs_parts: dict[str, AV] = {}
        if isinstance(gs, ast.Call):
            gkws = {k.arg: k.value for k in gs.keywords if k.arg}
            grid_node = gkws.get("grid", grid_node)
            in_specs_node = gkws.get("in_specs", in_specs_node)
            out_specs_node = gkws.get("out_specs", out_specs_node)
            scratch_node = gkws.get("scratch_shapes", scratch_node)
            nsp_node = gkws.get("num_scalar_prefetch")
            if nsp_node is not None:
                v = an.value(nsp_node)
                if v.kind == "int" and isinstance(v.value, int):
                    self.nsp = v.value
        elif gs is not None:
            # grid_spec built in a local: resolve through the lattice
            v = an.value(gs)
            if v.kind == "gridspec" and v.value is not None:
                gs_parts = dict(v.value)
                nsp_av = gs_parts.get("num_scalar_prefetch")
                if nsp_av is not None and nsp_av.kind == "int" \
                        and isinstance(nsp_av.value, int):
                    self.nsp = nsp_av.value
        self.has_grid = (grid_node is not None
                         or gs_parts.get("grid") is not None)
        self.in_specs = (self._items(in_specs_node)
                         or self._av_items(gs, gs_parts.get("in_specs")))
        self.out_specs = (self._items(out_specs_node)
                          or self._av_items(gs,
                                            gs_parts.get("out_specs")))
        self.scratch = (self._items(scratch_node)
                        or self._av_items(gs,
                                          gs_parts.get("scratch_shapes")))
        self.out_shapes = self._items(kws.get("out_shape"))
        # operand AVs from the curried outer call, when visible
        self.call_args: list[tuple[ast.AST, AV]] = []
        parent = ctx.parent(1)
        if isinstance(parent, ast.Call) and parent.func is node:
            self.call_args = [
                (a, an.value(a)) for a in parent.args
                if not isinstance(a, ast.Starred)]

    def _items(self, list_node) -> list[tuple[ast.AST, AV]]:
        """(node, AV) per element of a literal list/tuple keyword; a
        single non-list value is one item; None/unresolvable -> []."""
        if list_node is None:
            return []
        if isinstance(list_node, (ast.List, ast.Tuple)):
            return [(el, self.an.value(el)) for el in list_node.elts
                    if not isinstance(el, ast.Starred)]
        v = self.an.value(list_node)
        if v.kind == "tuple" and v.value is not None:
            return [(list_node, el) for el in v.value]
        return [(list_node, v)]

    @staticmethod
    def _av_items(anchor, av: AV | None) -> list[tuple[ast.AST, AV]]:
        """Items from a lattice value (grid_spec resolved through a
        local); findings anchor on the grid_spec expression node."""
        if av is None or anchor is None:
            return []
        if av.kind == "tuple" and av.value is not None:
            return [(anchor, el) for el in av.value]
        if av.kind in ("blockspec", "array", "sds", "sem"):
            return [(anchor, av)]
        return []

    def spec_rows(self):
        """Yield (spec_node, block AV, operand AV | None, label) for
        every BlockSpec paired positionally with its ref."""
        ops = self.call_args[self.nsp:]
        for i, (sn, sv) in enumerate(self.in_specs):
            if sv.kind != "blockspec":
                continue
            op = ops[i][1] if i < len(ops) else None
            yield sn, sv, op, f"in_specs[{i}]"
        outs = [av for _, av in self.out_shapes
                if av.kind in ("sds", "array")]
        for i, (sn, sv) in enumerate(self.out_specs):
            if sv.kind != "blockspec":
                continue
            op = outs[i] if i < len(outs) else None
            yield sn, sv, op, f"out_specs[{i}]"


@register
class PallasBlockTiling(Rule):
    id = "GT023"
    name = "pallas-block-tiling"
    description = (
        "Pallas BlockSpec tiling contract (TPU v5e). The last block "
        "dimension must be a multiple of 128 (one vector-lane row) "
        "and the second-to-last a multiple of the dtype's sublane "
        "tile (8 for 4-byte, 16 for 2-byte, 32 for 1-byte types) — "
        "unless the block spans the WHOLE array dimension, where "
        "Mosaic masks the edge. A narrower block still compiles but "
        "buys an implicit relayout/padding on every grid step; the "
        "CPU interpreter tier-1 runs never shows it. Deliberate "
        "narrow blocks (per-column gathers) carry a suppression with "
        "a contract comment. Unknown block dims never fire."
    )
    example_pos = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]

def call(x, interpret):
    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 100), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 100), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 400), jnp.float32),
        interpret=interpret,
    )(x)
"""
    example_neg = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]

def call(x, interpret):
    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 512), jnp.float32),
        interpret=interpret,
    )(x)
"""

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        if not _is_pallas_call(node):
            return
        g = _Geom(node, ctx)
        for sn, sv, op, label in g.spec_rows():
            bs = sv.shape
            if bs is None or not bs:
                continue
            arr = (op.shape if op is not None
                   and op.kind in ("array", "sds") else None)
            dtype = (op.dtype if op is not None
                     and op.kind in ("array", "sds") else None)
            last = bs[-1]
            if (last is not None and last % dm.LANE != 0
                    and not (arr is not None and arr
                             and arr[-1] == last)):
                ctx.report(self, sn,
                           f"{label} block shape {bs} — last dim "
                           f"{last} is not a multiple of {dm.LANE} "
                           f"(TPU lane tile) and does not span the "
                           f"whole array dim: Mosaic pads/relayouts "
                           f"every grid step")
                continue
            if len(bs) >= 2:
                sub = dm.sublane(dtype)
                sl = bs[-2]
                if (sl is not None and sub is not None
                        and sl % sub != 0
                        and not (arr is not None and len(arr) >= 2
                                 and arr[-2] == sl)):
                    ctx.report(self, sn,
                               f"{label} block shape {bs} — dim "
                               f"{sl} is not a multiple of the "
                               f"{dtype} sublane tile ({sub})")


@register
class PallasVmemBudget(Rule):
    id = "GT024"
    name = "pallas-vmem-budget"
    description = (
        "Static VMEM overcommit per pallas_call. Sums the tile-padded "
        "bytes of every ref the kernel holds resident — block-spec "
        "blocks (×2 when gridded: Pallas double-buffers pipelined "
        "refs), whole-array refs without a spec, and VMEM scratch — "
        "and flags when the KNOWN contributions alone exceed the "
        "~16 MiB v5e core budget. Unknown shapes only ever add, so "
        "this is a sound lower bound; a kernel that trips it spills "
        "or fails to compile on hardware while the CPU interpreter "
        "runs it happily."
    )
    example_pos = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def kernel(x_ref, o_ref, scratch):
    o_ref[...] = x_ref[...]

def call(x, interpret):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1024, 8192), jnp.float32),
        scratch_shapes=[pltpu.VMEM((512, 8192), jnp.float32)],
        interpret=interpret,
    )(x)
"""
    example_neg = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def kernel(x_ref, o_ref, scratch):
    o_ref[...] = x_ref[...]

def call(x, interpret):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((256, 1024), jnp.float32),
        scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
        interpret=interpret,
    )(x)
"""

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        if not _is_pallas_call(node):
            return
        g = _Geom(node, ctx)
        total = 0
        parts: list[str] = []

        def add(shape, dtype, what, double=False):
            nonlocal total
            b = dm.buffer_bytes(shape, dtype)
            if b is None:
                return
            if double:
                b *= 2
            total += b
            parts.append(f"{what}={dm.fmt_bytes(b)}")

        specs = dict(enumerate(g.in_specs))
        ops = g.call_args[g.nsp:]
        for i, (_, op) in enumerate(ops):
            if op.kind not in ("array", "sds"):
                # no static fact for this ref: contributes unknown>=0
                continue
            spec = specs.get(i)
            if spec is not None and spec[1].kind == "blockspec" \
                    and spec[1].shape is not None:
                bshape = tuple(d for d in spec[1].shape)
                add(bshape, op.dtype, f"in[{i}]", double=g.has_grid)
            else:
                add(op.shape, op.dtype, f"in[{i}]")
        outs = [av for _, av in g.out_shapes
                if av.kind in ("sds", "array")]
        ospecs = dict(enumerate(g.out_specs))
        for i, out in enumerate(outs):
            spec = ospecs.get(i)
            if spec is not None and spec[1].kind == "blockspec" \
                    and spec[1].shape is not None:
                add(spec[1].shape, out.dtype, f"out[{i}]",
                    double=g.has_grid)
            else:
                add(out.shape, out.dtype, f"out[{i}]")
        for i, (_, sc) in enumerate(g.scratch):
            if sc.kind == "array":
                add(sc.shape, sc.dtype, f"scratch[{i}]")
            # sem scratch is VMEM-free
        if total > dm.VMEM_BYTES:
            ctx.report(self, node,
                       f"pallas_call holds ≥{dm.fmt_bytes(total)} "
                       f"resident in VMEM ({', '.join(parts)}), over "
                       f"the ~{dm.fmt_bytes(dm.VMEM_BYTES)} v5e core "
                       f"budget — shrink blocks/scratch or raise the "
                       f"grid")


@register
class PallasGridDivisibility(Rule):
    id = "GT025"
    name = "pallas-grid-divisibility"
    description = (
        "Block-vs-array divisibility per pallas_call ref. When a "
        "known array dim is not a multiple of the known block dim, "
        "the last grid step reads a partial block: Mosaic masks it, "
        "but every twin in this codebase relies on EXACT division "
        "(the FOLD_BLOCKS padding contract pads inputs up front "
        "precisely so device and host fold bit-identically). A "
        "non-dividing block means the padding contract was skipped. "
        "Unknown dims never fire."
    )
    example_pos = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]

def call(interpret):
    x = jnp.zeros((8, 320), dtype=jnp.float32)
    return pl.pallas_call(
        kernel,
        grid=(3,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, i))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((8, 384), jnp.float32),
        interpret=interpret,
    )(x)
"""
    example_neg = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]

def call(interpret):
    x = jnp.zeros((8, 384), dtype=jnp.float32)
    return pl.pallas_call(
        kernel,
        grid=(3,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, i))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((8, 384), jnp.float32),
        interpret=interpret,
    )(x)
"""

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        if not _is_pallas_call(node):
            return
        g = _Geom(node, ctx)
        for sn, sv, op, label in g.spec_rows():
            bs = sv.shape
            arr = (op.shape if op is not None
                   and op.kind in ("array", "sds") else None)
            if bs is None or arr is None or len(bs) != len(arr):
                continue
            for d, (b, a) in enumerate(zip(bs, arr)):
                if (b is not None and a is not None and b > 0
                        and a % b != 0):
                    ctx.report(self, sn,
                               f"{label} block dim {d} ({b}) does "
                               f"not divide the array dim ({a}): the "
                               f"last grid step reads a partial "
                               f"block — pad the input first "
                               f"(FOLD_BLOCKS contract) or pick a "
                               f"dividing block")


@register
class DevicePromotionHazard(Rule):
    id = "GT026"
    name = "device-promotion-hazard"
    description = (
        "Dataflow-precise dtype-promotion hazard in device scope "
        "(subsumes the pattern-only GT009 wherever the lattice has "
        "facts). Flags: an arithmetic op whose inferred result is a "
        "64-bit dtype while an operand is narrower (a float32 "
        "accumulator silently becomes float64 — doubled VMEM and no "
        "f64 on the v5e datapath); an int literal outside int32 "
        "range meeting a ≤32-bit int array (trace-time overflow "
        "under x64-disabled, wrong dtype under x64); creation/astype "
        "whose dtype RESOLVES to a 64-bit type through the dataflow "
        "even when no 64-bit token appears at the call site; and a "
        "pallas_call operand/out_shape in a 64-bit dtype (Mosaic "
        "compile error). Unknown dtypes never fire."
    )
    example_pos = """\
import jax
import jax.numpy as jnp

@jax.jit
def f(n):
    acc = jnp.zeros((8, 128), dtype=jnp.float32)
    wide = jnp.asarray(n, dtype=jnp.float64)
    return acc + wide
"""
    example_neg = """\
import jax
import jax.numpy as jnp

@jax.jit
def f(n):
    acc = jnp.zeros((8, 128), dtype=jnp.float32)
    return acc + jnp.asarray(n, dtype=jnp.float32) * 1.5
"""

    _ARITH = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv,
              ast.Mod, ast.Pow, ast.MatMult)
    # dtype spellings GT009 already flags syntactically: skip them
    # here so one bug reports under one rule
    _GT009_TOKENS = ("int64", "uint64")

    def visit_BinOp(self, node: ast.BinOp, ctx: FileContext):
        if ctx.device_func is None or not isinstance(
                node.op, self._ARITH):
            return
        an = ctx.dataflow_scope()
        left, right = an.value(node.left), an.value(node.right)
        ld, lw = self._as_operand(left)
        rd, rw = self._as_operand(right)
        if ld is None or rd is None:
            return
        res = promote(ld, rd, lw, rw)
        if res in _WIDE or res == "complex128":
            if ld not in _WIDE or rd not in _WIDE:
                narrow = ld if ld not in _WIDE else rd
                ctx.report(self, node,
                           f"{ld} ⊕ {rd} silently promotes to {res} "
                           f"in device scope — the {narrow} side is "
                           f"widened (doubled VMEM; no 64-bit "
                           f"datapath on TPU): cast explicitly")
            return
        for scalar, arr_d in ((left, rd), (right, ld)):
            if (scalar.kind == "int" and isinstance(scalar.value, int)
                    and arr_d in _NARROW_INTS
                    and not -2 ** 31 <= scalar.value < 2 ** 31):
                ctx.report(self, node,
                           f"int literal {scalar.value} does not fit "
                           f"int32 but meets a {arr_d} array in "
                           f"device scope: trace-time overflow (or a "
                           f"silent 64-bit upcast under x64)")
                return

    @staticmethod
    def _as_operand(v: AV):
        """(dtype, weak) for one binop side; (None, _) = no fact."""
        if v.kind in ("array", "sds") and v.dtype is not None:
            return v.dtype, v.weak
        if v.kind == "int" or v.kind == "bool":
            return "int32", True
        if v.kind == "float":
            return "float32", True
        return None, False

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        if _is_pallas_call(node):
            self._check_pallas_refs(node, ctx)
            return
        if ctx.device_func is None:
            return
        f = dotted_name(node.func)
        short = (f or "").split(".")[-1]
        is_creation = short in (
            "zeros", "ones", "full", "empty", "asarray", "array",
            "arange", "zeros_like", "ones_like", "full_like")
        is_astype = (isinstance(node.func, ast.Attribute)
                     and node.func.attr == "astype")
        if not (is_creation or is_astype):
            return
        dt_node = None
        if is_astype and node.args:
            dt_node = node.args[0]
        else:
            for k in node.keywords:
                if k.arg == "dtype":
                    dt_node = k.value
            if dt_node is None:
                # positional dtype: zeros(shape, dt) / full(shape, v, dt)
                pos = 2 if short in ("full", "full_like") else 1
                if len(node.args) > pos:
                    dt_node = node.args[pos]
        if dt_node is None:
            return
        # GT009 owns the syntactic int64 spellings
        txt = dotted_name(dt_node) or (
            dt_node.value if isinstance(dt_node, ast.Constant) else "")
        if any(t in str(txt) for t in self._GT009_TOKENS):
            return
        an = ctx.dataflow_scope()
        v = an.value(dt_node)
        dt = v.value if v.kind == "dtype" else (
            v.value if v.kind == "str" else None)
        if dt in _WIDE:
            ctx.report(self, node,
                       f"array created/cast to {dt} in device scope "
                       f"(dtype resolves through the dataflow): no "
                       f"64-bit datapath on TPU — use the 32-bit "
                       f"dtype")

    def _check_pallas_refs(self, node: ast.Call, ctx: FileContext):
        g = _Geom(node, ctx)
        for an_node, av in g.call_args + g.out_shapes:
            if av.kind in ("array", "sds") and av.dtype in _WIDE:
                ctx.report(self, an_node,
                           f"pallas_call ref carries dtype "
                           f"{av.dtype}: 64-bit refs do not exist on "
                           f"the TPU datapath (Mosaic compile "
                           f"error) — cast before the kernel "
                           f"boundary")


@register
class CtxvarReadUnderPool(Rule):
    id = "GT027"
    name = "ctxvar-read-under-pool"
    description = (
        "Request contextvar read under a pool/Thread. Request state "
        "here rides contextvars (deadline, tracing span, query/stmt "
        "stats, session `since`); a function submitted to a pool or "
        "Thread runs with EMPTY context, so a transitive read sees "
        "'no deadline'/'no trace' instead of the submitting "
        "request's state — the bug class PRs 8/9/13 each re-fixed "
        "by hand. The taint follows module-local calls (closures "
        "included); an explicit rebind breaks it: pass a captured "
        "parent (`child_span(..., _parent=parent)` — the "
        "engine.open_region idiom), bind the family inside the "
        "worker, or wrap with contextvars.copy_context().run."
    )
    example_pos = """\
from greptimedb_tpu.telemetry import tracing

def job():
    with tracing.span("work"):
        pass

def schedule(pool):
    return pool.submit(job)
"""
    example_neg = """\
from greptimedb_tpu.telemetry import tracing

def job(parent):
    with tracing.child_span("work", _parent=parent):
        pass

def schedule(pool):
    return pool.submit(job, tracing.current_span())
"""

    _SUBMIT_ATTRS = {"submit", "map", "apply_async"}

    def visit_Call(self, node: ast.Call, ctx: FileContext):
        cand = None
        how = None
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in self._SUBMIT_ATTRS and node.args:
                cand, how = node.args[0], f".{f.attr}()"
        d = dotted_name(f)
        if d and d.split(".")[-1] == "Thread":
            for k in node.keywords:
                if k.arg == "target":
                    cand, how = k.value, "Thread(target=...)"
        if cand is None:
            return
        name = None
        if isinstance(cand, ast.Name):
            name = cand.id
        elif (isinstance(cand, ast.Attribute)
                and isinstance(cand.value, ast.Name)
                and cand.value.id in ("self", "cls")):
            name = cand.attr
        if name is None:
            return
        eff = ctx.ctxvars().effective_reads(name, node.lineno)
        if not eff:
            return
        fams = sorted(eff)
        chain = " -> ".join(eff[fams[0]])
        ctx.report(self, cand,
                   f"`{name}` runs via {how} with an empty context "
                   f"but reads request contextvar "
                   f"famil{'ies' if len(fams) > 1 else 'y'} "
                   f"{', '.join(fams)} ({chain}): capture the state "
                   f"at submit time and rebind explicitly "
                   f"(`_parent=`/bind/copy_context().run)")
