"""gtlint reporters: human text and machine-readable JSON."""

from __future__ import annotations

import json


def render_text(result: dict) -> str:
    out = []
    for f in result["findings"]:
        out.append(f"{f['path']}:{f['line']}:{f['col'] + 1}: "
                   f"{f['rule']} {f['message']}")
    for e in result["stale_baseline"]:
        out.append(f"{e.get('path')}: stale baseline entry "
                   f"{e.get('rule')} (line {e.get('line')}) no longer "
                   "matches; remove it")
    for p, msg in result["errors"]:
        out.append(f"{p}: error: {msg}")
    c = result["counts"]
    out.append(
        f"gtlint: {c['files']} files, {c['new']} findings "
        f"({c['baselined']} baselined, {c['suppressed']} suppressed, "
        f"{c['stale_baseline']} stale baseline entries)"
    )
    return "\n".join(out)


def render_json(result: dict) -> str:
    return json.dumps(result, indent=1, sort_keys=True)
