"""gtlint interprocedural layer: module-level call graph + taint.

PR 3's GT007 (lock-across-blocking-I/O) and GT004 (host-sync-in-jit)
only saw *direct* hazards: `with lock: client.do_put(...)` fired, but
`with lock: self._send(...)` where `_send` does the do_put two helpers
down did not.  This module gives each file a call graph over its
project-local functions and a per-function "blocking" / "host-sync"
taint summary computed to a fixpoint, so the rules follow calls any
number of levels deep through helpers defined in the same module.

Resolution is deliberately conservative (no false edges across
modules or duck-typed receivers):

- `foo(...)`            -> module-level `def foo`
- `self.foo(...)` /
  `cls.foo(...)`        -> method `foo` of the enclosing class
- `SomeClass.foo(...)`  -> method `foo` of a class defined in this
                           module

Nested `def`s are *not* edges: a closure handed to a Thread/pool runs
asynchronously, so charging its blocking work to the definer would be
a false positive.
"""

from __future__ import annotations

import ast
import dataclasses

from greptimedb_tpu.tools.lint.core import dotted_name

# shared blocking tables (rules.py re-exports these for GT007)
BLOCKING_ATTRS = {
    "urlopen", "do_get", "do_put", "do_action", "read_all",
    "recv", "recvfrom", "sendall", "accept", "getresponse",
    "create_connection", "getaddrinfo", "read_chunk",
}
BLOCKING_DOTTED = {"time.sleep", "urllib.request.urlopen",
                   "socket.create_connection"}

# definite device->host sync ops for the GT004 taint (np.asarray et al
# are excluded here: helpers legitimately materialize *static* data at
# trace time; the call-site check requires a traced argument anyway)
_SYNC_ATTRS = {"item", "tolist"}
_SYNC_DOTTED = {"jax.device_get"}


@dataclasses.dataclass
class FuncSummary:
    qualname: str
    node: ast.AST
    # direct ops: (label, lineno)
    blocking: bool = False
    host_sync: bool = False
    # taint witness: ["helper (line 12)", ..., "do_put (line 88)"] —
    # the chain of calls from this function down to the leaf op
    block_chain: list = dataclasses.field(default_factory=list)
    sync_chain: list = dataclasses.field(default_factory=list)
    # unresolved edges: (callee qualname, call lineno)
    calls: list = dataclasses.field(default_factory=list)


def blocking_label(call: ast.Call) -> str | None:
    """The blocking-op label for a direct call, or None."""
    d = dotted_name(call.func)
    if d in BLOCKING_DOTTED:
        return d
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in BLOCKING_ATTRS):
        return call.func.attr
    return None


def _sync_label(call: ast.Call) -> str | None:
    d = dotted_name(call.func)
    if d in _SYNC_DOTTED:
        return d
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in _SYNC_ATTRS):
        return "." + call.func.attr + "()"
    return None


def _callee_qualname(call: ast.Call, cls: str | None,
                     classes: set[str], funcs: set[str]) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id if f.id in funcs else None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        recv = f.value.id
        if recv in ("self", "cls") and cls is not None:
            q = f"{cls}.{f.attr}"
            return q if q in funcs else None
        if recv in classes:
            q = f"{recv}.{f.attr}"
            return q if q in funcs else None
    return None


class ModuleSummary:
    """Call graph + taint for one module's top-level functions and
    first-level methods."""

    def __init__(self, tree: ast.Module):
        self.funcs: dict[str, FuncSummary] = {}
        self.classes: set[str] = set()
        self._collect(tree)
        self._propagate()

    # -- collection ----------------------------------------------------
    def _collect(self, tree: ast.Module):
        pairs: list[tuple[str | None, ast.AST]] = []
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                pairs.append((None, node))
            elif isinstance(node, ast.ClassDef):
                self.classes.add(node.name)
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        pairs.append((node.name, sub))
        names = {f"{c}.{n.name}" if c else n.name for c, n in pairs}
        for cls, node in pairs:
            q = f"{cls}.{node.name}" if cls else node.name
            s = FuncSummary(q, node)
            for call in self._own_calls(node):
                label = blocking_label(call)
                if label is not None and not s.blocking:
                    s.blocking = True
                    s.block_chain = [f"{label} (line {call.lineno})"]
                sl = _sync_label(call)
                if sl is not None and not s.host_sync:
                    s.host_sync = True
                    s.sync_chain = [f"{sl} (line {call.lineno})"]
                callee = _callee_qualname(call, cls, self.classes,
                                          names)
                if callee is not None and callee != q:
                    s.calls.append((callee, call.lineno))
            self.funcs[q] = s

    @staticmethod
    def _own_calls(func: ast.AST):
        """Call nodes in `func`'s own body, not descending into nested
        function definitions (they run on their own schedule)."""
        stack = list(ast.iter_child_nodes(func))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- fixpoint ------------------------------------------------------
    def _propagate(self):
        changed = True
        while changed:
            changed = False
            for s in self.funcs.values():
                for callee, lineno in s.calls:
                    c = self.funcs[callee]
                    if c.blocking and not s.blocking:
                        s.blocking = True
                        s.block_chain = [
                            f"{callee} (line {lineno})"
                        ] + c.block_chain
                        changed = True
                    if c.host_sync and not s.host_sync:
                        s.host_sync = True
                        s.sync_chain = [
                            f"{callee} (line {lineno})"
                        ] + c.sync_chain
                        changed = True

    # -- rule-facing API -----------------------------------------------
    def resolve_call(self, call: ast.Call, cls: str | None
                     ) -> FuncSummary | None:
        q = _callee_qualname(call, cls, self.classes,
                             set(self.funcs))
        return self.funcs.get(q) if q is not None else None


# -- contextvar-read taint (GT027) -------------------------------------
#
# Request state in this codebase travels in contextvars; a function
# handed to a pool/Thread runs with EMPTY context, so any transitive
# read of one of these families silently sees "no deadline" / "no
# trace" / "no stats sink" instead of the submitting request's state.
# The tables below name the module-facade readers per family; a read
# with an explicit parent (`child_span(..., _parent=x)`) is a REBIND,
# not a read -- that is exactly the hand-fix engine.open_region and
# dist_query ship.

# (module alias, function) -> family; aliases are matched on the last
# two dotted segments with leading underscores stripped, so
# `tracing.span`, `_deadline.check` and `sessions.current_since` all
# resolve regardless of import spelling
CTXVAR_READERS: dict[tuple[str, str], str] = {
    ("deadline", "current"): "deadline",
    ("deadline", "remaining"): "deadline",
    ("deadline", "call_timeout"): "deadline",
    ("deadline", "check"): "deadline",
    ("cancellation", "checkpoint"): "deadline",
    ("tracing", "span"): "tracing",
    ("tracing", "child_span"): "tracing",
    ("tracing", "event_span"): "tracing",
    ("tracing", "current_span"): "tracing",
    ("tracing", "current_trace_id"): "tracing",
    ("tracing", "traceparent"): "tracing",
    ("tracing", "set_attr"): "tracing",
    ("tracing", "mark_keep"): "tracing",
    ("stats", "add"): "stats",
    ("stats", "note"): "stats",
    ("stats", "timed"): "stats",
    ("stats", "active"): "stats",
    ("stmt_stats", "add"): "stmt_stats",
    ("stmt_stats", "note"): "stmt_stats",
    ("stmt_stats", "active"): "stmt_stats",
    ("stmt_stats", "note_program"): "stmt_stats",
    ("stmt_stats", "note_exec_path"): "stmt_stats",
    ("sessions", "current_since"): "since",
}

# bare-name readers for `from ... import X` spellings; only names
# unambiguous enough to never collide with local helpers
CTXVAR_BARE_READERS: dict[str, str] = {
    "checkpoint": "deadline",
    "child_span": "tracing",
    "event_span": "tracing",
    "current_span": "tracing",
    "current_trace_id": "tracing",
    "traceparent": "tracing",
    "current_since": "since",
}

# calls that REBIND a family for the code under them (context managers
# or setters); "*" = rebinds everything (contextvars.copy_context)
CTXVAR_BINDERS: dict[tuple[str, str], str] = {
    ("deadline", "bind"): "deadline",
    ("sessions", "bind_since"): "since",
    ("tracing", "start_remote"): "tracing",
    ("stats", "collect"): "stats",
    ("stmt_stats", "observe"): "stmt_stats",
}

# readers that accept an explicit parent kwarg: passing a non-None
# `_parent`/`parent` turns the call from a read into a rebind
_PARENTED_READERS = {"span", "child_span"}


def _reader_key(call: ast.Call) -> tuple[str, str] | None:
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return (f.value.id.lstrip("_"), f.attr)
    return None


def _has_explicit_parent(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg in ("_parent", "parent"):
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    return False


@dataclasses.dataclass
class CtxFuncInfo:
    key: tuple[str, int]            # (name, def lineno)
    node: ast.AST
    # family -> witness chain down to the leaf read
    reads: dict = dataclasses.field(default_factory=dict)
    binds: set = dataclasses.field(default_factory=set)
    calls: list = dataclasses.field(default_factory=list)
    eff: dict = dataclasses.field(default_factory=dict)


class CtxVarSummary:
    """Per-def contextvar-read taint over ALL defs in the module
    (nested closures included -- they are exactly what gets handed to
    pools), with module-local call edges resolved to the nearest
    preceding def of the callee's bare name."""

    def __init__(self, tree: ast.Module):
        self.defs: dict[tuple[str, int], CtxFuncInfo] = {}
        self._by_name: dict[str, list[int]] = {}
        # module-level ContextVar names: reads/sets on them are their
        # own per-variable family
        self.local_cvars: set[str] = set()
        for s in tree.body:
            if (isinstance(s, ast.Assign) and len(s.targets) == 1
                    and isinstance(s.targets[0], ast.Name)
                    and isinstance(s.value, ast.Call)):
                d = dotted_name(s.value.func) or ""
                if d.rsplit(".", 1)[-1] == "ContextVar":
                    self.local_cvars.add(s.targets[0].id)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = (node.name, node.lineno)
                self.defs[key] = self._summarize(key, node)
                self._by_name.setdefault(node.name, []).append(
                    node.lineno)
        for lns in self._by_name.values():
            lns.sort()
        self._propagate()

    def _summarize(self, key, func) -> CtxFuncInfo:
        info = CtxFuncInfo(key=key, node=func)
        for call in ModuleSummary._own_calls(func):
            d = dotted_name(call.func) or ""
            if "copy_context" in d:
                info.binds.add("*")
                continue
            rk = _reader_key(call)
            if rk is not None:
                if rk in CTXVAR_BINDERS:
                    info.binds.add(CTXVAR_BINDERS[rk])
                    continue
                fam = CTXVAR_READERS.get(rk)
                if fam is not None:
                    if (rk[1] in _PARENTED_READERS
                            and _has_explicit_parent(call)):
                        # explicit parent = rebind for the body
                        info.binds.add(fam)
                    else:
                        info.reads.setdefault(fam, [
                            f"{rk[0]}.{rk[1]} (line {call.lineno})"])
                    continue
                # module-level ContextVar accessed directly
                recv = call.func.value.id
                if recv in self.local_cvars:
                    if call.func.attr == "get":
                        info.reads.setdefault(f"ctxvar {recv}", [
                            f"{recv}.get (line {call.lineno})"])
                    elif call.func.attr == "set":
                        info.binds.add(f"ctxvar {recv}")
                    continue
            elif isinstance(call.func, ast.Name):
                fam = CTXVAR_BARE_READERS.get(call.func.id)
                if fam is not None:
                    if (call.func.id in _PARENTED_READERS
                            and _has_explicit_parent(call)):
                        info.binds.add(fam)
                    else:
                        info.reads.setdefault(fam, [
                            f"{call.func.id} (line {call.lineno})"])
                    continue
            # call edge by bare name (module func, self/cls method, or
            # nested def -- nearest preceding def wins)
            name = None
            if isinstance(call.func, ast.Name):
                name = call.func.id
            elif (isinstance(call.func, ast.Attribute)
                    and isinstance(call.func.value, ast.Name)
                    and call.func.value.id in ("self", "cls")):
                name = call.func.attr
            if name is not None:
                info.calls.append((name, call.lineno))
        return info

    def _resolve(self, name: str, use_line: int
                 ) -> CtxFuncInfo | None:
        lns = self._by_name.get(name)
        if not lns:
            return None
        prior = [ln for ln in lns if ln <= use_line]
        return self.defs[(name, prior[-1] if prior else lns[0])]

    def _propagate(self):
        for info in self.defs.values():
            info.eff = {f: c for f, c in info.reads.items()
                        if "*" not in info.binds
                        and f not in info.binds}
        changed = True
        while changed:
            changed = False
            for info in self.defs.values():
                if "*" in info.binds:
                    continue
                for name, lineno in info.calls:
                    callee = self._resolve(name, lineno)
                    if callee is None or callee is info:
                        continue
                    for fam, chain in callee.eff.items():
                        if fam in info.binds or fam in info.eff:
                            continue
                        info.eff[fam] = [
                            f"{name} (line {lineno})"] + chain
                        changed = True

    # rule-facing: the families `name` (a def visible at use_line)
    # transitively reads without rebinding, with witness chains
    def effective_reads(self, name: str, use_line: int) -> dict | None:
        info = self._resolve(name, use_line)
        return dict(info.eff) if info is not None else None
