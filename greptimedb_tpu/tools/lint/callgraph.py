"""gtlint interprocedural layer: module-level call graph + taint.

PR 3's GT007 (lock-across-blocking-I/O) and GT004 (host-sync-in-jit)
only saw *direct* hazards: `with lock: client.do_put(...)` fired, but
`with lock: self._send(...)` where `_send` does the do_put two helpers
down did not.  This module gives each file a call graph over its
project-local functions and a per-function "blocking" / "host-sync"
taint summary computed to a fixpoint, so the rules follow calls any
number of levels deep through helpers defined in the same module.

Resolution is deliberately conservative (no false edges across
modules or duck-typed receivers):

- `foo(...)`            -> module-level `def foo`
- `self.foo(...)` /
  `cls.foo(...)`        -> method `foo` of the enclosing class
- `SomeClass.foo(...)`  -> method `foo` of a class defined in this
                           module

Nested `def`s are *not* edges: a closure handed to a Thread/pool runs
asynchronously, so charging its blocking work to the definer would be
a false positive.
"""

from __future__ import annotations

import ast
import dataclasses

from greptimedb_tpu.tools.lint.core import dotted_name

# shared blocking tables (rules.py re-exports these for GT007)
BLOCKING_ATTRS = {
    "urlopen", "do_get", "do_put", "do_action", "read_all",
    "recv", "recvfrom", "sendall", "accept", "getresponse",
    "create_connection", "getaddrinfo", "read_chunk",
}
BLOCKING_DOTTED = {"time.sleep", "urllib.request.urlopen",
                   "socket.create_connection"}

# definite device->host sync ops for the GT004 taint (np.asarray et al
# are excluded here: helpers legitimately materialize *static* data at
# trace time; the call-site check requires a traced argument anyway)
_SYNC_ATTRS = {"item", "tolist"}
_SYNC_DOTTED = {"jax.device_get"}


@dataclasses.dataclass
class FuncSummary:
    qualname: str
    node: ast.AST
    # direct ops: (label, lineno)
    blocking: bool = False
    host_sync: bool = False
    # taint witness: ["helper (line 12)", ..., "do_put (line 88)"] —
    # the chain of calls from this function down to the leaf op
    block_chain: list = dataclasses.field(default_factory=list)
    sync_chain: list = dataclasses.field(default_factory=list)
    # unresolved edges: (callee qualname, call lineno)
    calls: list = dataclasses.field(default_factory=list)


def blocking_label(call: ast.Call) -> str | None:
    """The blocking-op label for a direct call, or None."""
    d = dotted_name(call.func)
    if d in BLOCKING_DOTTED:
        return d
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in BLOCKING_ATTRS):
        return call.func.attr
    return None


def _sync_label(call: ast.Call) -> str | None:
    d = dotted_name(call.func)
    if d in _SYNC_DOTTED:
        return d
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in _SYNC_ATTRS):
        return "." + call.func.attr + "()"
    return None


def _callee_qualname(call: ast.Call, cls: str | None,
                     classes: set[str], funcs: set[str]) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id if f.id in funcs else None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        recv = f.value.id
        if recv in ("self", "cls") and cls is not None:
            q = f"{cls}.{f.attr}"
            return q if q in funcs else None
        if recv in classes:
            q = f"{recv}.{f.attr}"
            return q if q in funcs else None
    return None


class ModuleSummary:
    """Call graph + taint for one module's top-level functions and
    first-level methods."""

    def __init__(self, tree: ast.Module):
        self.funcs: dict[str, FuncSummary] = {}
        self.classes: set[str] = set()
        self._collect(tree)
        self._propagate()

    # -- collection ----------------------------------------------------
    def _collect(self, tree: ast.Module):
        pairs: list[tuple[str | None, ast.AST]] = []
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                pairs.append((None, node))
            elif isinstance(node, ast.ClassDef):
                self.classes.add(node.name)
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        pairs.append((node.name, sub))
        names = {f"{c}.{n.name}" if c else n.name for c, n in pairs}
        for cls, node in pairs:
            q = f"{cls}.{node.name}" if cls else node.name
            s = FuncSummary(q, node)
            for call in self._own_calls(node):
                label = blocking_label(call)
                if label is not None and not s.blocking:
                    s.blocking = True
                    s.block_chain = [f"{label} (line {call.lineno})"]
                sl = _sync_label(call)
                if sl is not None and not s.host_sync:
                    s.host_sync = True
                    s.sync_chain = [f"{sl} (line {call.lineno})"]
                callee = _callee_qualname(call, cls, self.classes,
                                          names)
                if callee is not None and callee != q:
                    s.calls.append((callee, call.lineno))
            self.funcs[q] = s

    @staticmethod
    def _own_calls(func: ast.AST):
        """Call nodes in `func`'s own body, not descending into nested
        function definitions (they run on their own schedule)."""
        stack = list(ast.iter_child_nodes(func))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- fixpoint ------------------------------------------------------
    def _propagate(self):
        changed = True
        while changed:
            changed = False
            for s in self.funcs.values():
                for callee, lineno in s.calls:
                    c = self.funcs[callee]
                    if c.blocking and not s.blocking:
                        s.blocking = True
                        s.block_chain = [
                            f"{callee} (line {lineno})"
                        ] + c.block_chain
                        changed = True
                    if c.host_sync and not s.host_sync:
                        s.host_sync = True
                        s.sync_chain = [
                            f"{callee} (line {lineno})"
                        ] + c.sync_chain
                        changed = True

    # -- rule-facing API -----------------------------------------------
    def resolve_call(self, call: ast.Call, cls: str | None
                     ) -> FuncSummary | None:
        q = _callee_qualname(call, cls, self.classes,
                             set(self.funcs))
        return self.funcs.get(q) if q is not None else None
