"""gtlint runner + CLI.

    python -m greptimedb_tpu.tools.lint [paths...] [--format=json]
    greptimedb-tpu lint [paths...]

Exit status: 0 clean, 1 unsuppressed/non-baselined findings (or stale
baseline entries), 2 usage/parse errors.
"""

from __future__ import annotations

import argparse
import ast
import os
import sys

from greptimedb_tpu.tools.lint.baseline import Baseline
from greptimedb_tpu.tools.lint.core import (
    FileContext,
    Finding,
    ModuleLinter,
    all_rules,
)
from greptimedb_tpu.tools.lint.report import render_json, render_text
from greptimedb_tpu.tools.lint.suppress import Suppressions

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")

# repo root (parent of the greptimedb_tpu package): finding paths are
# anchored here, NOT to os.getcwd(), so the checked-in baseline and
# the lint gate behave identically from any working directory
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def _norm_path(path: str) -> str:
    ap = os.path.abspath(path)
    try:
        rel = os.path.relpath(ap, _REPO_ROOT)
    except ValueError:      # Windows: different drive
        rel = None
    if rel is not None and not rel.startswith(".."):
        return rel.replace("\\", "/")
    return ap.replace("\\", "/")


def _select_rules(select: set[str] | None):
    rules = all_rules()
    if select:
        rules = {k: v for k, v in rules.items() if k in select}
    return rules


def _walk_findings(path: str, source: str, tree: ast.Module,
                   rules) -> list[Finding]:
    """The per-file AST walk over an already-parsed tree."""
    ctx = FileContext(path, source, tree)
    ModuleLinter(ctx, rules).run()
    return ctx.findings


def lint_source(path: str, source: str, *, select: set[str] | None = None
                ) -> tuple[list[Finding], list[Finding]]:
    """Lint one file's text. Returns (active, suppressed) findings.

    Runs the per-file walk AND the contracts pass over a one-file
    forest: fixture mini-projects and the `--explain` examples carry
    both sides of their contract in a single module, so the cross-file
    rules are testable here too (checks whose counterpart surface is
    absent stay silent by construction)."""
    from greptimedb_tpu.tools.lint.contracts import (
        contract_findings,
        extract_model,
    )

    rules = _select_rules(select)
    tree = ast.parse(source, filename=path)
    findings = _walk_findings(path, source, tree, rules)
    findings = findings + contract_findings(
        extract_model({path: (source, tree)}), rules)
    sup = Suppressions(source)
    active = [f for f in findings if not sup.covers(f.rule, f.line)]
    suppressed = [f for f in findings if sup.covers(f.rule, f.line)]
    return active, suppressed


def iter_py_files(paths: list[str]):
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)


def changed_files(ref: str) -> set[str] | None:
    """Absolute paths of .py files differing from `ref` (tracked
    changes plus untracked files); None when git cannot answer."""
    import subprocess

    out: list[str] = []
    for cmd in (
        ["git", "diff", "--name-only", "-z", ref, "--", "*.py"],
        ["git", "ls-files", "--others", "--exclude-standard", "-z",
         "--", "*.py"],
    ):
        try:
            r = subprocess.run(cmd, cwd=_REPO_ROOT,
                               capture_output=True, text=True,
                               timeout=30)
        except (OSError, subprocess.SubprocessError):
            return None
        if r.returncode != 0:
            return None
        out.extend(n for n in r.stdout.split("\0") if n)
    return {os.path.normpath(os.path.join(_REPO_ROOT, n))
            for n in out}


def _aux_paths(done: set[str]) -> list[str]:
    """Harvest-only files for the whole-program contracts pass: the
    rest of the package plus the repo's reference surfaces (tests and
    bench.py hold metric-name references and action dispatches the
    contract model must see). Returns paths not already in `done`."""
    out: list[str] = []
    roots = [os.path.join(_REPO_ROOT, "greptimedb_tpu"),
             os.path.join(_REPO_ROOT, "tests")]
    for root in roots:
        if os.path.isdir(root):
            out.extend(iter_py_files([root]))
    bench = os.path.join(_REPO_ROOT, "bench.py")
    if os.path.isfile(bench):
        out.append(bench)
    return [p for p in out if _norm_path(p) not in done]


# text markers covering every construct the contract harvesters match:
# a scanned set containing NONE of these contributes nothing to the
# contract model, so the whole-repo aux harvest (which exists to supply
# the missing half of a contract whose other half IS in the scan) can
# be skipped and the pass run scan-only. Keeps `gtlint <tmp-fixture>`
# runs from re-parsing the repo to check fixtures that cannot
# participate in any contract.
_CONTRACT_MARKERS = (
    '"rpc":', "'rpc':", "_decode_ticket",            # tickets
    ".action(", "Action(", "do_action", "list_actions",  # actions
    "StatusCode", "_CODE_CLASSES",                   # errors
    "DEFAULTS", ".get(", ".section(",                # knobs
    "gtpu_", "greptime_", "registry",                # metrics
)


def _scan_has_contract_markers(
        forest: dict[str, tuple[str, ast.Module]]) -> bool:
    return any(any(m in text for m in _CONTRACT_MARKERS)
               for text, _ in forest.values())


# harvest-only files are parsed for the contract model, never walked
# by per-file rules, so their (text, tree, suppressions) triples are
# safe to reuse across lint_paths calls in one process — the test
# suite runs dozens, each of which would otherwise re-read and
# re-parse the whole repo. Keyed by (mtime_ns, size); an edit
# invalidates.
_AUX_CACHE: dict[str, tuple[int, int, str, ast.Module,
                            Suppressions]] = {}


def _load_aux(path: str, norm: str
              ) -> tuple[str, ast.Module, Suppressions] | None:
    try:
        st = os.stat(path)
        hit = _AUX_CACHE.get(norm)
        if hit is not None and hit[0] == st.st_mtime_ns \
                and hit[1] == st.st_size:
            return hit[2], hit[3], hit[4]
        with open(path, encoding="utf-8") as f:
            text = f.read()
        tree = ast.parse(text, filename=norm)
    except (SyntaxError, UnicodeDecodeError, OSError):
        return None
    sup = Suppressions(text)
    _AUX_CACHE[norm] = (st.st_mtime_ns, st.st_size, text, tree, sup)
    return text, tree, sup


def _readme_text() -> str | None:
    readme = os.path.join(_REPO_ROOT, "README.md")
    try:
        with open(readme, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return None


def lint_paths(paths: list[str], *, baseline: Baseline | None = None,
               select: set[str] | None = None,
               only: set[str] | None = None) -> dict:
    """Lint every .py under `paths`; returns the report document.
    `only` (absolute paths) restricts the walk — the --changed mode.

    Each file is parsed exactly ONCE: the tree feeds both the per-file
    walk and the whole-program contracts pass (GT028-GT032). The
    contracts pass is whole-program by construction — besides the
    scanned files it harvests the rest of the package, tests/, bench.py
    and README.md, so a subdirectory run still checks against the full
    contract surfaces. `--changed` runs skip it (a partial forest
    cannot decide cross-file contracts; the full gate run catches the
    drift)."""
    from greptimedb_tpu.tools.lint.contracts import (
        CONTRACT_RULE_IDS,
        contract_findings,
        extract_model,
    )

    rules = _select_rules(select)
    findings: list[Finding] = []
    suppressed: list[Finding] = []
    errors: list[tuple[str, str]] = []
    sources: dict[str, list[str]] = {}
    forest: dict[str, tuple[str, ast.Module]] = {}
    sup_cache: dict[str, Suppressions] = {}
    nfiles = 0
    for p in paths:
        if not os.path.exists(p):
            # a typo'd/renamed path must not lint 0 files and pass
            errors.append((p, "path does not exist"))
    for path in iter_py_files(paths):
        if only is not None and os.path.normpath(
                os.path.abspath(path)) not in only:
            continue
        nfiles += 1
        norm = _norm_path(path)
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
            tree = ast.parse(text, filename=norm)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append((norm, str(e)))
            continue
        sources[norm] = text.splitlines()
        forest[norm] = (text, tree)
        sup = sup_cache[norm] = Suppressions(text)
        for f in _walk_findings(norm, text, tree, rules):
            (suppressed if sup.covers(f.rule, f.line)
             else findings).append(f)

    if only is None and any(r in rules for r in CONTRACT_RULE_IDS):
        harvest = dict(forest)
        aux = (_aux_paths(set(forest))
               if _scan_has_contract_markers(forest) else [])
        for path in aux:
            norm = _norm_path(path)
            loaded = _load_aux(path, norm)
            if loaded is None:
                continue    # per-file lint of it reports the error
            text, tree, sup = loaded
            harvest[norm] = (text, tree)
            sources[norm] = text.splitlines()
            sup_cache[norm] = sup
        model = extract_model(harvest, readme_text=_readme_text())
        for f in contract_findings(model, rules):
            sup = sup_cache.get(f.path)
            if sup is not None and sup.covers(f.rule, f.line):
                suppressed.append(f)
            else:
                findings.append(f)

    def line_text(path: str, lineno: int) -> str:
        lines = sources.get(path, [])
        return lines[lineno - 1].strip() if 1 <= lineno <= len(lines) \
            else ""

    if baseline is not None:
        new, old, stale = baseline.split(findings, line_text)
        if only is not None:
            # a --changed run must not call entries for files it never
            # scanned "stale"; full runs keep full stale detection so
            # entries for DELETED files still get reported
            stale = [e for e in stale if e.get("path") in sources]
    else:
        new, old, stale = findings, [], []
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    return {
        "findings": [f.to_doc() for f in new],
        "baselined": [f.to_doc() for f in old],
        "suppressed": [f.to_doc() for f in suppressed],
        "stale_baseline": stale,
        "errors": errors,
        "counts": {
            "files": nfiles, "new": len(new), "baselined": len(old),
            "suppressed": len(suppressed), "stale_baseline": len(stale),
        },
        "clean": not new and not stale and not errors,
        # internal (stripped before reporting): for --write-baseline
        "_line_text": line_text,
        "_scanned_paths": list(sources),
    }


def contracts_dump(paths: list[str], *, out=None) -> int:
    """`lint --contracts-dump`: emit the extracted whole-program
    contract model (tickets, actions, error codes, knobs, metric
    families, each with source locations) as JSON with stable key
    order. Debugging aid and docs-generation input; always exits 0."""
    import json

    from greptimedb_tpu.tools.lint.contracts import extract_model

    out = out or sys.stdout
    forest: dict[str, tuple[str, ast.Module]] = {}
    scan = list(iter_py_files(paths))
    scan += _aux_paths({_norm_path(p) for p in scan})
    for path in scan:
        norm = _norm_path(path)
        loaded = _load_aux(path, norm)
        if loaded is None:
            continue
        forest[norm] = (loaded[0], loaded[1])
    model = extract_model(forest, readme_text=_readme_text())
    print(json.dumps(model.to_doc(), indent=2, sort_keys=True),
          file=out)
    return 0


def explain_rule(rule_id: str, *, out=None) -> int:
    """`lint --explain GTxxx`: the rule's doc, its firing/clean
    examples (the same snippets the explain meta-test validates), and
    how to suppress it. Exit 2 on an unknown id."""
    import textwrap

    out = out or sys.stdout
    rid = rule_id.strip().upper()
    rule = all_rules().get(rid)
    if rule is None:
        known = ", ".join(all_rules())
        print(f"gtlint: unknown rule id {rule_id!r} (known: {known})",
              file=sys.stderr)
        return 2
    print(f"{rid} — {rule.name}", file=out)
    print("", file=out)
    print(textwrap.fill(rule.description, width=72), file=out)
    if rule.example_pos:
        print("\nFires on:\n", file=out)
        print(textwrap.indent(rule.example_pos.rstrip(), "    "),
              file=out)
    if rule.example_neg:
        print("\nStays silent on:\n", file=out)
        print(textwrap.indent(rule.example_neg.rstrip(), "    "),
              file=out)
    print(f"""
Suppression:

    <line>  # gtlint: disable={rid}        (this line)
    # gtlint: disable-next-line={rid}      (the next line)
    # gtlint: disable-file={rid}           (whole file; first 10 lines)

A suppression must carry an inline comment stating the contract that
makes the flagged code correct.""", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="gtlint",
        description="AST-based correctness linter for greptimedb-tpu "
                    "(JAX/TPU + concurrency hazards).",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint "
                         "(default: the greptimedb_tpu package)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON path (default: the checked-in "
                         "package baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings to the baseline file "
                         "and exit 0")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (e.g. "
                         "GT001,GT007)")
    ap.add_argument("--changed", default=None, metavar="REF",
                    help="lint only files differing from this git ref "
                         "(tracked diff + untracked) — fast pre-commit "
                         "runs, e.g. --changed HEAD or --changed "
                         "origin/main")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--contracts-dump", action="store_true",
                    help="emit the extracted whole-program contract "
                         "model (tickets, actions, error codes, knobs, "
                         "metric families with source locations) as "
                         "JSON and exit 0")
    ap.add_argument("--explain", default=None, metavar="GTxxx",
                    help="print one rule's doc, a minimal firing and "
                         "clean example, and the suppression syntax; "
                         "exit 2 on an unknown id")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in all_rules().items():
            print(f"{rid} {rule.name}: {rule.description}")
        return 0

    if args.explain:
        return explain_rule(args.explain)

    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))]

    if args.contracts_dump:
        return contracts_dump(paths)
    select = ({s.strip().upper() for s in args.select.split(",")
               if s.strip()} if args.select else None)
    baseline = None
    if not args.no_baseline and not args.write_baseline:
        baseline = Baseline.load(args.baseline)

    only = None
    if args.changed:
        only = changed_files(args.changed)
        if only is None:
            print(f"gtlint: git could not diff against "
                  f"{args.changed!r} (not a repo, or unknown ref?)",
                  file=sys.stderr)
            return 2
        if args.write_baseline:
            print("gtlint: --write-baseline cannot be combined with "
                  "--changed (a partial run would clobber the rest)",
                  file=sys.stderr)
            return 2

    result = lint_paths(paths, baseline=baseline, select=select,
                        only=only)
    line_text = result.pop("_line_text")
    scanned = set(result.pop("_scanned_paths", []))

    if args.write_baseline:
        if select:
            # a rule-filtered run would clobber other rules' entries
            # for the scanned files
            print("gtlint: --write-baseline cannot be combined with "
                  "--select", file=sys.stderr)
            return 2
        if result["errors"]:
            for p, msg in result["errors"]:
                print(f"{p}: error: {msg}", file=sys.stderr)
            print("gtlint: refusing to write a baseline from an "
                  "errored run", file=sys.stderr)
            return 2
        findings = [Finding(**d) for d in result["findings"]]
        new_base = Baseline.from_findings(findings, line_text)
        # merge: keep existing entries for files OUTSIDE this run's
        # scope so a subdirectory run doesn't discard the rest of the
        # grandfathered debt
        kept = [e for e in Baseline.load(args.baseline).entries
                if e.get("path") not in scanned]
        new_base.entries = kept + new_base.entries
        new_base.save(args.baseline)
        print(f"gtlint: wrote {len(new_base.entries)} entries to "
              f"{args.baseline}"
              + (f" ({len(kept)} kept from outside this run's scope)"
                 if kept else ""))
        return 0

    out = (render_json(result) if args.format == "json"
           else render_text(result))
    print(out)
    if result["errors"]:
        return 2
    return 0 if result["clean"] else 1


def run(paths: list[str], *, baseline_path: str | None = None,
        no_baseline: bool = False) -> dict:
    """Library entry: lint `paths`, returning the report document
    (used by tests/test_lint_clean.py and cli.py)."""
    baseline = None
    if not no_baseline:
        baseline = Baseline.load(baseline_path or DEFAULT_BASELINE)
    result = lint_paths(paths, baseline=baseline)
    result.pop("_line_text", None)
    result.pop("_scanned_paths", None)
    return result
