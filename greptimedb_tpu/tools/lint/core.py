"""gtlint core: findings, rule registry, and the AST walk context.

The linter is one recursive AST walk per file (`ModuleLinter`).  The
walker maintains the semantic state rules need — enclosing function
stack with jit/Pallas device info, `with <lock>:` nesting, loop depth,
live exception-handler variable names — and dispatches each node to
every rule that registered a `visit_<NodeType>` method.  Rules are
stateless singletons; all per-file state lives on the context so a
single registry instance lints any number of files.
"""

from __future__ import annotations

import ast
import dataclasses


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def to_doc(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message,
        }


class Rule:
    """One lint rule. Subclasses set `id`/`name`/`description` and
    implement `visit_<NodeType>(node, ctx)` for the AST node types
    they care about."""

    id: str = ""
    name: str = ""
    description: str = ""
    # minimal firing / clean snippets for `lint --explain` (validated
    # by the explain meta-test: pos must fire, neg must stay silent)
    example_pos: str = ""
    example_neg: str = ""


_REGISTRY: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    inst = cls()
    if inst.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {inst.id}")
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> dict[str, Rule]:
    # rules live in rules.py (AST rules), rules_device.py (dataflow
    # device-contract rules), and contracts.py (whole-program wire/
    # config/metric contracts); importing them populates the registry
    from greptimedb_tpu.tools.lint import contracts as _contracts  # noqa: F401,E501
    from greptimedb_tpu.tools.lint import rules as _rules  # noqa: F401
    from greptimedb_tpu.tools.lint import (  # noqa: F401
        rules_device as _rules_device,
    )

    return dict(sorted(_REGISTRY.items()))


def dotted_name(node: ast.AST) -> str | None:
    """'jax.jit' for Attribute(Name jax, jit); None if not a plain
    dotted path."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


_JIT_NAMES = {"jax.jit", "jit", "jax.pjit", "pjit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}


def _static_names(call: ast.Call, params: list[str]) -> set[str]:
    """Param names declared static via static_argnames/static_argnums."""
    out: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for el in vals:
                if isinstance(el, ast.Constant) and isinstance(el.value,
                                                               str):
                    out.add(el.value)
        elif kw.arg == "static_argnums":
            v = kw.value
            vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for el in vals:
                if isinstance(el, ast.Constant) and isinstance(el.value,
                                                               int):
                    if 0 <= el.value < len(params):
                        out.add(params[el.value])
    return out


def jit_decorator_info(dec: ast.AST, params: list[str]
                       ) -> tuple[bool, set[str]]:
    """(is_jit, static param names) for one decorator expression.
    Recognises @jax.jit, @jit, @functools.partial(jax.jit, ...) and
    @jax.jit(...) call forms."""
    d = dotted_name(dec)
    if d in _JIT_NAMES:
        return True, set()
    if isinstance(dec, ast.Call):
        f = dotted_name(dec.func)
        if f in _JIT_NAMES:
            return True, _static_names(dec, params)
        if f in _PARTIAL_NAMES and dec.args:
            g = dotted_name(dec.args[0])
            if g in _JIT_NAMES:
                return True, _static_names(dec, params)
    return False, set()


@dataclasses.dataclass
class FuncInfo:
    node: ast.AST
    name: str
    params: set[str]
    jitted: bool
    static: set[str]
    device: bool        # jitted, a Pallas kernel, or nested in one


class FileContext:
    """Per-file lint state, visible to rules during the walk."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.findings: list[Finding] = []
        self.func_stack: list[FuncInfo] = []
        self.class_stack: list[ast.ClassDef] = []
        self.node_stack: list[ast.AST] = []
        self.lock_depth = 0
        self.loop_depth = 0
        # `with device_call(...):` nesting (telemetry/device_trace):
        # GT018 allows jit dispatches only inside one
        self.device_call_depth = 0
        self.exc_names: list[str] = []
        # names of functions passed to pl.pallas_call(...) anywhere in
        # the module: their bodies run traced on device
        self.pallas_kernels: set[str] = set()
        # functions passed to shard_map(f, ...): traced device bodies
        # too, mapped to the axis names their call site binds (resolved
        # from in_specs/out_specs/mesh literals + module string
        # constants; GT013 checks collectives against this set). Keyed
        # by (name, def lineno) — the call anchors to the NEAREST
        # preceding def of that name, so same-named closures in one
        # module (promql/fast.py has several `def local` shard_map
        # bodies) neither merge their axis bindings nor mark an
        # unrelated same-named helper as device scope.
        self.shard_map_axes: dict[tuple[str, int], set[str]] = {}
        func_lines: dict[str, list[int]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func_lines.setdefault(node.name, []).append(node.lineno)
        for lines in func_lines.values():
            lines.sort()

        def _def_key(name: str, call_line: int) -> tuple[str, int] | None:
            lines = func_lines.get(name)
            if not lines:
                return None  # imported callee: no body in this module
            prior = [ln for ln in lines if ln <= call_line]
            return (name, prior[-1] if prior else lines[0])
        # names bound to jit-PRODUCED callables anywhere in the module
        # (GT018): @jax.jit / @functools.partial(jax.jit, ...)
        # decorated defs, and NAME = jax.jit(...) assignments. Calling
        # one from host scope outside a device_call is an untracked
        # device dispatch.
        self.jit_callables: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                params = [a.arg for a in (
                    node.args.posonlyargs + node.args.args
                    + node.args.kwonlyargs
                )]
                for dec in node.decorator_list:
                    if jit_decorator_info(dec, params)[0]:
                        self.jit_callables.add(node.name)
                        break
            elif (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and dotted_name(node.value.func) in _JIT_NAMES):
                self.jit_callables.add(node.targets[0].id)
        # module-level NAME = "str" constants (axis-name resolution)
        self.str_constants: dict[str, str] = {}
        for node in tree.body:
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                self.str_constants[node.targets[0].id] = node.value.value
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                f = dotted_name(node.func)
                if f and f.endswith("pallas_call") and node.args:
                    k = dotted_name(node.args[0])
                    if k:
                        self.pallas_kernels.add(k.split(".")[-1])
                if f and f.split(".")[-1] == "shard_map" and node.args:
                    k = dotted_name(node.args[0])
                    if k:
                        # axis names live in the specs (positional mesh
                        # is args[1]); from a mesh expression only
                        # string LITERALS count — a bare `mesh` variable
                        # is not an axis name
                        axes: set[str] = set()
                        spec_nodes = list(node.args[2:])
                        mesh_nodes = list(node.args[1:2])
                        for kw in node.keywords:
                            (mesh_nodes if kw.arg == "mesh"
                             else spec_nodes).append(kw.value)
                        for sub in spec_nodes:
                            axes |= self._axis_names_in(sub)
                        for sub in mesh_nodes:
                            axes |= {
                                n.value for n in ast.walk(sub)
                                if isinstance(n, ast.Constant)
                                and isinstance(n.value, str)
                            }
                        key = _def_key(k.split(".")[-1], node.lineno)
                        if key is not None:
                            self.shard_map_axes.setdefault(
                                key, set()
                            ).update(axes)
        # interprocedural layer: per-function blocking/host-sync taint
        # over the module-local call graph (import here — callgraph
        # imports this module)
        from greptimedb_tpu.tools.lint.callgraph import ModuleSummary

        self.call_summary = ModuleSummary(tree)
        # lazy heavyweight layers: built on first rule demand so files
        # no dataflow rule cares about pay nothing
        self._dataflow = None
        self._ctxvars = None

    def dataflow(self):
        """Lazy per-file abstract interpretation (dataflow.py)."""
        if self._dataflow is None:
            from greptimedb_tpu.tools.lint.dataflow import FileAnalyses

            self._dataflow = FileAnalyses(self.tree)
        return self._dataflow

    def dataflow_scope(self):
        """ScopeAnalysis for the function being visited (module scope
        when the walk is at top level)."""
        fi = self.current_func
        return self.dataflow().scope(fi.node if fi is not None else None)

    def ctxvars(self):
        """Lazy per-file contextvar-read taint (callgraph.py)."""
        if self._ctxvars is None:
            from greptimedb_tpu.tools.lint.callgraph import CtxVarSummary

            self._ctxvars = CtxVarSummary(self.tree)
        return self._ctxvars

    def _axis_names_in(self, node: ast.AST) -> set[str]:
        """Axis-name candidates inside a shard_map spec subtree: string
        literals plus identifiers (resolved through module string
        constants when possible, kept as `id:NAME` markers otherwise so
        unresolved-but-identical names still match). Callee names
        (`P(...)`, `PartitionSpec(...)`) are NOT axis candidates."""
        callee_ids: set[int] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                callee_ids.update(id(c) for c in ast.walk(n.func))
        out: set[str] = set()
        for n in ast.walk(node):
            if id(n) in callee_ids:
                continue
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                out.add(n.value)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                v = self.str_constants.get(n.id)
                out.add(v if v is not None else f"id:{n.id}")
        return out

    def axis_name_of(self, node: ast.AST) -> str | None:
        """The axis-name value of one collective argument, in the same
        resolution space as _axis_names_in; None when dynamic."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            v = self.str_constants.get(node.id)
            return v if v is not None else f"id:{node.id}"
        return None

    @property
    def current_class(self) -> str | None:
        return self.class_stack[-1].name if self.class_stack else None

    # -- helpers rules use ---------------------------------------------
    @property
    def current_func(self) -> FuncInfo | None:
        return self.func_stack[-1] if self.func_stack else None

    @property
    def device_func(self) -> FuncInfo | None:
        """Innermost enclosing traced/device function, if any."""
        fi = self.current_func
        return fi if fi is not None and fi.device else None

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def scope_text(self, *, cls: bool = False) -> str:
        """Source text of the enclosing function (or, with cls=True,
        the enclosing class — teardown methods like close() commonly
        live beside the constructor that owns the resource).  Falls
        back to the whole module."""
        node: ast.AST | None = None
        if cls and self.class_stack:
            node = self.class_stack[-1]
        elif self.func_stack:
            node = self.func_stack[-1].node
        if node is None or not hasattr(node, "end_lineno"):
            return self.source
        return "\n".join(self.lines[node.lineno - 1:node.end_lineno])

    def parent(self, up: int = 1) -> ast.AST | None:
        """Enclosing AST node `up` levels above the node currently
        being dispatched (node_stack[-1] is that node itself)."""
        i = len(self.node_stack) - 1 - up
        return self.node_stack[i] if i >= 0 else None

    def report(self, rule: Rule, node: ast.AST, message: str):
        self.findings.append(Finding(
            rule=rule.id, path=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
        ))


def traced_value_use(expr: ast.AST, fi: FuncInfo) -> bool:
    """Does `expr` consume the *value* of a traced parameter?  Uses
    that stay static at trace time — `.shape`/`.ndim`/`.dtype`/`.size`
    attributes, `len(x)`, `isinstance(x, ...)`, `x is None` — do not
    count: branching on those is fine inside jit."""
    traced = fi.params - fi.static

    def scan(node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute) and node.attr in (
                "shape", "ndim", "dtype", "size"):
            return False            # static metadata access
        if isinstance(node, ast.Call):
            f = dotted_name(node.func)
            if f in ("len", "isinstance", "type"):
                return False        # static at trace time
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False            # identity tests (x is None)
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            return node.id in traced
        return any(scan(c) for c in ast.iter_child_nodes(node))

    return scan(expr)


class ModuleLinter(ast.NodeVisitor):
    """The walk: dispatches nodes to rules while tracking scope state."""

    def __init__(self, ctx: FileContext, rules: dict[str, Rule]):
        self.ctx = ctx
        # node-type name -> [(rule, bound visit method)]
        self.dispatch: dict[str, list] = {}
        for rule in rules.values():
            for attr in dir(rule):
                if attr.startswith("visit_"):
                    self.dispatch.setdefault(attr[6:], []).append(
                        getattr(rule, attr)
                    )

    def run(self):
        self.visit(self.ctx.tree)
        return self.ctx.findings

    def visit(self, node: ast.AST):
        self.ctx.node_stack.append(node)
        try:
            for meth in self.dispatch.get(type(node).__name__, ()):
                meth(node, self.ctx)
            handler = getattr(self, f"scope_{type(node).__name__}", None)
            if handler is not None:
                handler(node)
            else:
                super().generic_visit(node)
        finally:
            self.ctx.node_stack.pop()

    # -- scope-tracking handlers ---------------------------------------
    def _scope_func(self, node):
        ctx = self.ctx
        params = [a.arg for a in (
            node.args.posonlyargs + node.args.args + node.args.kwonlyargs
        )]
        jitted, static = False, set()
        for dec in node.decorator_list:
            self.visit(dec)
            is_jit, st = jit_decorator_info(dec, params)
            if is_jit:
                jitted, static = True, st
        # Pallas kernels and shard_map bodies both run traced on device:
        # host syncs / recompile hazards inside them are as real as in
        # a @jax.jit function
        kernel = (node.name in ctx.pallas_kernels
                  or (node.name, node.lineno) in ctx.shard_map_axes)
        enclosing_device = bool(ctx.func_stack and ctx.func_stack[-1].device)
        fi = FuncInfo(
            node=node, name=node.name,
            params={p for p in params if p not in ("self", "cls")},
            jitted=jitted, static=static,
            device=jitted or kernel or enclosing_device,
        )
        ctx.func_stack.append(fi)
        # loops/locks/device_call scopes of the enclosing scope don't
        # wrap this body (a nested def's body runs later, elsewhere;
        # lambdas are NOT defs and keep the enclosing scope)
        saved_loop, saved_lock = ctx.loop_depth, ctx.lock_depth
        saved_dev = ctx.device_call_depth
        ctx.loop_depth = ctx.lock_depth = ctx.device_call_depth = 0
        try:
            for child in ast.iter_child_nodes(node):
                if child in node.decorator_list:
                    continue
                self.visit(child)
        finally:
            ctx.loop_depth, ctx.lock_depth = saved_loop, saved_lock
            ctx.device_call_depth = saved_dev
            ctx.func_stack.pop()

    scope_FunctionDef = _scope_func
    scope_AsyncFunctionDef = _scope_func

    def _scope_loop(self, node):
        self.ctx.loop_depth += 1
        try:
            super().generic_visit(node)
        finally:
            self.ctx.loop_depth -= 1

    scope_For = _scope_loop
    scope_While = _scope_loop

    def scope_With(self, node):
        ctx = self.ctx
        holds_lock = False
        in_device_call = False
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            if _looks_like_lock(item.context_expr):
                holds_lock = True
            if _looks_like_device_call(item.context_expr):
                in_device_call = True
        if holds_lock:
            ctx.lock_depth += 1
        if in_device_call:
            ctx.device_call_depth += 1
        try:
            for stmt in node.body:
                self.visit(stmt)
        finally:
            if holds_lock:
                ctx.lock_depth -= 1
            if in_device_call:
                ctx.device_call_depth -= 1

    def scope_ClassDef(self, node):
        self.ctx.class_stack.append(node)
        try:
            super().generic_visit(node)
        finally:
            self.ctx.class_stack.pop()

    def scope_ExceptHandler(self, node):
        ctx = self.ctx
        pushed = False
        if node.name:
            ctx.exc_names.append(node.name)
            pushed = True
        try:
            super().generic_visit(node)
        finally:
            if pushed:
                ctx.exc_names.pop()


def _looks_like_device_call(expr: ast.AST) -> bool:
    """`with device_call(...)` / `with device_trace.device_call(...)`:
    the tracked-dispatch scope GT018 requires around jit calls. Chained
    context managers (`with stats.timed(...), device_call(...) as d:`)
    are handled per-item by scope_With."""
    if not isinstance(expr, ast.Call):
        return False
    d = dotted_name(expr.func)
    return d is not None and d.split(".")[-1] == "device_call"


def _looks_like_lock(expr: ast.AST) -> bool:
    """`with self._lock:` / `with lock:` / `with threading.Lock():`.
    Condition variables are excluded — their wait() *releases* the
    lock, so blocking under them is the intended pattern."""
    d = dotted_name(expr)
    if d is None and isinstance(expr, ast.Call):
        d = dotted_name(expr.func)
    if d is None:
        return False
    last = d.split(".")[-1].lower()
    if "cond" in last:
        return False
    return "lock" in last or last in ("mutex",)
