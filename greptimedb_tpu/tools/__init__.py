"""Offline tools package.

Submodules: this module (export/import of a data home),
`greptimedb_tpu.tools.lint` (gtlint, the AST-based correctness
linter — see README "Static analysis"), and
`greptimedb_tpu.tools.san` (gtsan, the cooperative concurrency
sanitizer — see README "Concurrency sanitizer").

Offline data tools: export / import a data home.

Capability counterpart of the reference's CLI subtools
(/root/reference/src/cmd/src/cli/export.rs, import.rs): dump every
database's schema (CREATE TABLE / CREATE VIEW statements) and data
(per-table Parquet via the COPY path) into a directory tree, and load
such a tree back into an empty data home.

Layout (mirrors the reference's per-db dirs):
    <out>/<db>/create_tables.sql
    <out>/<db>/<table>.parquet
"""

from __future__ import annotations

import os

_SYSTEM_DBS = {"information_schema"}


def _qstr(s: str) -> str:
    """Escape a value for a single-quoted SQL literal."""
    return s.replace("'", "''")


def _qid(s: str) -> str:
    """Escape an identifier for double quotes."""
    return s.replace('"', '""')


def _open(data_home: str):
    from greptimedb_tpu.instance import Standalone

    return Standalone(data_home, prefer_device=False, warm_start=False)


def export_data(data_home: str, output_dir: str, *, target: str = "all",
                database: str | None = None) -> dict:
    """Dump schema and/or data. target: all | schema | data.
    Returns {db: {"tables": n, "rows": n}} for reporting."""
    from greptimedb_tpu.session import QueryContext

    if target not in ("all", "schema", "data"):
        raise ValueError(f"bad target {target!r}")
    inst = _open(data_home)
    report: dict = {}
    try:
        dbs = [database] if database else [
            d for d in inst.catalog.database_names()
            if d not in _SYSTEM_DBS
        ]
        for db in dbs:
            ctx = QueryContext(database=db)
            db_dir = os.path.join(output_dir, db)
            os.makedirs(db_dir, exist_ok=True)
            tables = inst.catalog.table_names(db)
            rows_total = 0
            if target in ("all", "schema"):
                stmts = []
                for t in tables:
                    r = inst.sql(
                        f'SHOW CREATE TABLE "{_qid(t)}"', ctx
                    )
                    stmts.append(str(r.cols[1].values[0]).rstrip(";"))
                for v in inst.catalog.view_names(db):
                    text = inst.catalog.maybe_view(db, v)
                    if text:
                        stmts.append(f'CREATE VIEW "{v}" AS {text}')
                with open(os.path.join(db_dir, "create_tables.sql"),
                          "w") as f:
                    f.write(";\n\n".join(stmts) + (";\n" if stmts else ""))
            if target in ("all", "data"):
                for t in tables:
                    path = os.path.join(db_dir, f"{t}.parquet")
                    out = inst.execute_sql(
                        f"COPY \"{_qid(t)}\" TO '{_qstr(path)}' "
                        f"WITH (format = 'parquet')",
                        ctx,
                    )
                    rows_total += out[-1].affected_rows or 0
            report[db] = {"tables": len(tables), "rows": rows_total}
        return report
    finally:
        inst.close()


def import_data(data_home: str, input_dir: str, *,
                database: str | None = None) -> dict:
    """Load an export_data tree into a data home (created if missing)."""
    from greptimedb_tpu.session import QueryContext

    inst = _open(data_home)
    report: dict = {}
    try:
        dbs = sorted(
            d for d in os.listdir(input_dir)
            if os.path.isdir(os.path.join(input_dir, d))
            and (database is None or d == database)
        )
        for db in dbs:
            db_dir = os.path.join(input_dir, db)
            inst.catalog.create_database(db, if_not_exists=True)
            ctx = QueryContext(database=db)
            schema_path = os.path.join(db_dir, "create_tables.sql")
            n_tables = 0
            if os.path.exists(schema_path):
                with open(schema_path) as f:
                    sql = f.read()
                if sql.strip():
                    n_tables = len(inst.execute_sql(sql, ctx))
            rows_total = 0
            for fn in sorted(os.listdir(db_dir)):
                if not fn.endswith(".parquet"):
                    continue
                t = fn[:-len(".parquet")]
                path = os.path.join(db_dir, fn)
                out = inst.execute_sql(
                    f"COPY \"{_qid(t)}\" FROM '{_qstr(path)}' "
                    f"WITH (format = 'parquet')",
                    ctx,
                )
                rows_total += out[-1].affected_rows or 0
            report[db] = {"tables": n_tables, "rows": rows_total}
        return report
    finally:
        inst.close()
