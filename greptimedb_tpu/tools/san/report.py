"""gtsan reporting: reuses gtlint's reporters, baseline, suppressions.

A sanitizer finding is shaped exactly like a lint finding (rule, path,
line, col, message) with runtime stacks folded into the message, so:

- `# gtlint: disable=GTS10x` comments at the *anchor line* (the
  acquisition / creation site) suppress it,
- `tools/san/baseline.json` grandfathers it with the same
  rule+path+line-text matching as the lint baseline (line drift safe),
- text/JSON rendering is `tools.lint.report.render_text/render_json`.
"""

from __future__ import annotations

import json
import os

from greptimedb_tpu.tools.lint.baseline import Baseline
from greptimedb_tpu.tools.lint.core import Finding
from greptimedb_tpu.tools.lint.suppress import Suppressions

from greptimedb_tpu.tools.lint.runner import (
    _REPO_ROOT,
    _norm_path as norm_path,
)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")


def _source_lines(path: str, cache: dict) -> list[str]:
    if path not in cache:
        full = path if os.path.isabs(path) \
            else os.path.join(_REPO_ROOT, path)
        try:
            with open(full, encoding="utf-8") as f:
                cache[path] = f.read().splitlines()
        except OSError:
            cache[path] = []
    return cache[path]


def result_doc(findings: list[dict], *,
               baseline_path: str | None = DEFAULT_BASELINE,
               files_checked: int = 0) -> dict:
    """Raw sanitizer findings -> the lint-shaped report document
    (suppressions applied, baseline split, counts, `clean`)."""
    cache: dict[str, list[str]] = {}
    sup_cache: dict[str, Suppressions] = {}
    active: list[Finding] = []
    suppressed: list[Finding] = []
    for d in findings:
        f = Finding(rule=d["rule"], path=d["path"], line=d["line"],
                    col=d.get("col", 0), message=d["message"])
        if f.path not in sup_cache:
            sup_cache[f.path] = Suppressions(
                "\n".join(_source_lines(f.path, cache)))
        if sup_cache[f.path].covers(f.rule, f.line):
            suppressed.append(f)
        else:
            active.append(f)

    def line_text(path: str, lineno: int) -> str:
        lines = _source_lines(path, cache)
        return lines[lineno - 1].strip() if 1 <= lineno <= len(lines) \
            else ""

    baseline = Baseline.load(baseline_path) if baseline_path else \
        Baseline([])
    new, old, stale = baseline.split(active, line_text)
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    return {
        "findings": [f.to_doc() for f in new],
        "baselined": [f.to_doc() for f in old],
        "suppressed": [f.to_doc() for f in suppressed],
        "stale_baseline": stale,
        "errors": [],
        "counts": {
            "files": files_checked, "new": len(new),
            "baselined": len(old), "suppressed": len(suppressed),
            "stale_baseline": len(stale),
        },
        "clean": not new and not stale,
    }


def attach_exit_report(san):
    """atexit hook for long-running processes enabled via the
    `[sanitizer]` TOML section: render the findings to stderr at exit
    so an instrumented server run is never a silent no-op."""
    import atexit
    import sys

    def _render():
        try:
            san.leak_findings(0)
            doc = result_doc(san.snapshot_findings())
            from greptimedb_tpu.tools.lint.report import render_text

            print("gtsan: " + ("clean"
                               if doc["clean"] else "findings below"),
                  file=sys.stderr)
            if not doc["clean"]:
                print(render_text(doc), file=sys.stderr)
        except Exception as e:  # noqa: BLE001 - exit path, keep going
            print(f"gtsan: exit report failed: {e}", file=sys.stderr)

    atexit.register(_render)


def write_report(san, path: str):
    """atexit hook for `greptimedb-tpu san -- <cmd>` child processes:
    dump raw findings (leaks included) for the parent to render."""
    try:
        san.leak_findings(0)
        doc = {"version": 1, "findings": san.snapshot_findings()}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
    except OSError:
        pass


def load_raw_report(path: str) -> list[dict]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    return list(doc.get("findings", []))
