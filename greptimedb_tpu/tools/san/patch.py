"""gtsan blocking-call patches.

Installed while at least one sanitizer scope is active; uninstalled
when the last scope pops.  Each patch forwards to the real callable —
only the held-lock check is added — so behavior is unchanged.

Patched blockers:
- `time.sleep` (yield-style sleeps under `sleep_min_s` are ignored)
- Arrow Flight client calls: `do_get` / `do_put` / `do_action`
- `socket.create_connection` (TCP connect latency)
"""

from __future__ import annotations

import socket
import time

from greptimedb_tpu.tools.san import core

_real: dict = {}


def _sleep(secs):
    for san in core.all_active():
        if secs >= san.cfg.sleep_min_s:
            san.on_blocking(f"time.sleep({secs:g})", skip=2)
    return _real["sleep"](secs)


def _create_connection(*args, **kwargs):
    for san in core.all_active():
        san.on_blocking("socket.create_connection", skip=2)
    return _real["create_connection"](*args, **kwargs)


class _SanFlightClient:
    """Proxy over a pyarrow FlightClient (the C type is immutable, so
    methods cannot be patched in place): do_get/do_put/do_action gain
    the held-lock check, everything else delegates."""

    __slots__ = ("_inner",)

    def __init__(self, inner):
        object.__setattr__(self, "_inner", inner)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _blocking(self, label, *args, **kwargs):
        for san in core.all_active():
            san.on_blocking(label, skip=3)
        return getattr(self._inner, label.split(".")[-1])(*args,
                                                          **kwargs)

    def do_get(self, *args, **kwargs):
        return self._blocking("FlightClient.do_get", *args, **kwargs)

    def do_put(self, *args, **kwargs):
        return self._blocking("FlightClient.do_put", *args, **kwargs)

    def do_action(self, *args, **kwargs):
        return self._blocking("FlightClient.do_action", *args,
                              **kwargs)


def _connect(*args, **kwargs):
    return _SanFlightClient(_real["flight.connect"](*args, **kwargs))


def install():
    if _real:
        return          # nested scope: already installed
    _real["sleep"] = time.sleep
    time.sleep = _sleep
    _real["create_connection"] = socket.create_connection
    socket.create_connection = _create_connection
    try:
        import pyarrow.flight as flight
    except ImportError:
        return
    _real["flight.connect"] = flight.connect
    flight.connect = _connect


def uninstall():
    if not _real:
        return
    time.sleep = _real.pop("sleep")
    socket.create_connection = _real.pop("create_connection")
    real_connect = _real.pop("flight.connect", None)
    if real_connect is not None:
        import pyarrow.flight as flight

        flight.connect = real_connect
    _real.clear()
