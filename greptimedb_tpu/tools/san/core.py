"""gtsan engine: lock-order graph, blocking detection, lifecycles.

Design constraints:

- The sanitizer's own synchronization uses RAW `threading` primitives
  so instrumentation never recurses into itself.
- Wrappers consult `current()` on every operation instead of binding a
  sanitizer at construction: objects created while one sanitizer was
  active keep working (untracked) after it is popped, which is what
  nested pytest runs (pytester) need.
- Per-acquire cost when ON is one `sys._getframe` walk over a handful
  of frames (no linecache, no traceback objects); edges and cycle
  checks only run on *nested* acquisitions, which are rare.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
import time
import weakref

_WRAP_ID = itertools.count(1)

# frames inside these path fragments are instrumentation, not user code
_SELF_FRAGMENTS = ("/tools/san/", "/concurrency.py", "/threading.py",
                   "/concurrent/futures/")

_STACK_DEPTH = 12


def _capture_stack(skip: int = 2) -> list[tuple[str, int, str]]:
    """(filename, lineno, funcname) frames, innermost first, skipping
    instrumentation frames. Cheap: raw frame walk, no source lookup."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return []
    out: list[tuple[str, int, str]] = []
    while f is not None and len(out) < _STACK_DEPTH:
        fn = f.f_code.co_filename.replace("\\", "/")
        if not any(s in fn for s in _SELF_FRAGMENTS):
            out.append((fn, f.f_lineno, f.f_code.co_name))
        f = f.f_back
    return out


def _fmt_stack(stack: list[tuple[str, int, str]], indent: str = "      "
               ) -> str:
    from greptimedb_tpu.tools.san.report import norm_path

    return "\n".join(
        f"{indent}{norm_path(fn)}:{ln} in {name}"
        for fn, ln, name in stack
    )


def _site_of(stack: list[tuple[str, int, str]]) -> tuple[str, int]:
    """First project frame of a captured stack -> (path, line)."""
    from greptimedb_tpu.tools.san.report import norm_path

    for fn, ln, _name in stack:
        return norm_path(fn), ln
    return "<unknown>", 0


class SanConfig:
    """Knobs, resolved from the `[sanitizer]` TOML section or
    `GTPU_SAN_*` env vars (env wins inside `greptimedb-tpu san`)."""

    def __init__(self, *, hold_time_ms: float = 1000.0,
                 fail_on_cycle: bool = True,
                 sleep_min_s: float = 0.001):
        self.hold_time_ms = float(hold_time_ms)
        self.fail_on_cycle = bool(fail_on_cycle)
        # sleeps shorter than this are yield-style and not reported
        self.sleep_min_s = float(sleep_min_s)

    @classmethod
    def from_env(cls, env=None) -> "SanConfig":
        env = os.environ if env is None else env
        kw = {}
        if env.get("GTPU_SAN_HOLD_MS"):
            kw["hold_time_ms"] = float(env["GTPU_SAN_HOLD_MS"])
        if env.get("GTPU_SAN_FAIL_ON_CYCLE"):
            kw["fail_on_cycle"] = env["GTPU_SAN_FAIL_ON_CYCLE"].lower() \
                not in ("0", "false", "off")
        return cls(**kw)

    @classmethod
    def from_options(cls, section: dict) -> "SanConfig":
        kw = {}
        if "hold_time_ms" in section:
            kw["hold_time_ms"] = float(section["hold_time_ms"])
        if "fail_on_cycle" in section:
            kw["fail_on_cycle"] = bool(section["fail_on_cycle"])
        return cls(**kw)


class _Held:
    """One entry on a thread's held-lock stack."""

    __slots__ = ("node", "label", "t0", "stack", "count", "waiting")

    def __init__(self, node: int, label: str, t0: float,
                 stack: list[tuple[str, int, str]]):
        self.node = node
        self.label = label
        self.t0 = t0
        self.stack = stack
        self.count = 1          # reentrancy (RLock / Condition)
        self.waiting = False    # True while cv.wait() has it released


class Sanitizer:
    """Global state for one enabled sanitizer scope."""

    def __init__(self, config: SanConfig | None = None):
        self.cfg = config or SanConfig()
        self._mu = threading.Lock()          # raw: guards graph+findings
        self._tls = threading.local()
        self.findings: list[dict] = []
        self._finding_keys: set[tuple] = set()
        # lock-order graph over wrapper ids: edge a->b = "b acquired
        # while a held"; each edge remembers the stacks that created it
        self._adj: dict[int, set[int]] = {}
        self._edges: dict[tuple[int, int], dict] = {}
        self._labels: dict[int, str] = {}
        self._cycles_seen: set[frozenset] = set()
        # lifecycle registries (weakrefs: a collected object cannot leak)
        self._threads: dict[int, dict] = {}
        self._executors: dict[int, dict] = {}

    # ---- held-lock stack ---------------------------------------------
    def _held(self) -> list[_Held]:
        st = getattr(self._tls, "held", None)
        if st is None:
            st = self._tls.held = []
        return st

    def held_labels(self) -> list[str]:
        return [h.label for h in self._held() if not h.waiting]

    def _add_finding(self, rule: str, path: str, line: int, message: str,
                     key: tuple | None = None):
        with self._mu:
            if key is not None:
                if key in self._finding_keys:
                    return
                self._finding_keys.add(key)
            self.findings.append({
                "rule": rule, "path": path, "line": line, "col": 0,
                "message": message,
            })

    # ---- lock-order graph --------------------------------------------
    def before_acquire(self, node: int, label: str,
                       stack: list[tuple[str, int, str]]):
        """Record ordering edges held->node; runs BEFORE the real
        acquire so a would-be deadlock is still reported."""
        held = [h for h in self._held() if not h.waiting
                and h.node != node]
        if not held:
            return
        with self._mu:
            self._labels[node] = label
            for h in held:
                self._labels.setdefault(h.node, h.label)
                key = (h.node, node)
                if key in self._edges:
                    continue
                self._edges[key] = {
                    "held_stack": h.stack, "acq_stack": stack,
                }
                self._adj.setdefault(h.node, set()).add(node)
                self._check_cycle_locked(h.node, node)

    def _check_cycle_locked(self, a: int, b: int):
        """After adding a->b, a path b ~> a closes a cycle."""
        path = self._find_path_locked(b, a)
        if path is None:
            return
        cycle = [a] + path          # [a, b, ..., a]
        key = frozenset(cycle)
        if key in self._cycles_seen:
            return
        self._cycles_seen.add(key)
        fwd = self._edges[(a, b)]

        def lbl(n: int) -> str:
            return self._labels.get(n, f"lock#{n}")

        lines = [
            "potential deadlock: lock-order cycle "
            + " -> ".join(lbl(n) for n in cycle),
            f"    this thread acquired {lbl(b)} while holding {lbl(a)}:",
            _fmt_stack(fwd["acq_stack"]),
            f"    with {lbl(a)} held at:",
            _fmt_stack(fwd["held_stack"]),
        ]
        # the return path b -> ... -> a: every edge carries the stacks
        # recorded when that (reverse-order) acquisition happened
        for x, y in zip(cycle[1:], cycle[2:]):
            e = self._edges.get((x, y))
            if e is None:
                continue
            lines.append(f"    elsewhere {lbl(y)} was acquired while "
                         f"holding {lbl(x)}:")
            lines.append(_fmt_stack(e["acq_stack"]))
            lines.append(f"    with {lbl(x)} held at:")
            lines.append(_fmt_stack(e["held_stack"]))
        path_site, line_no = _site_of(fwd["acq_stack"])
        self.findings.append({
            "rule": "GTS101", "path": path_site, "line": line_no,
            "col": 0, "message": "\n".join(lines),
        })

    def _find_path_locked(self, src: int, dst: int) -> list[int] | None:
        """DFS src ~> dst over the order graph; returns the node path
        [src, ..., dst] or None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def after_acquired(self, node: int, label: str,
                       stack: list[tuple[str, int, str]]):
        held = self._held()
        for h in reversed(held):
            if h.node == node and not h.waiting:
                h.count += 1        # reentrant re-acquire
                return
        held.append(_Held(node, label, time.monotonic(), stack))

    def on_release(self, node: int):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            h = held[i]
            if h.node == node and not h.waiting:
                h.count -= 1
                if h.count > 0:
                    return
                del held[i]
                held_ms = (time.monotonic() - h.t0) * 1000.0
                if held_ms > self.cfg.hold_time_ms:
                    path, line = _site_of(h.stack)
                    self._add_finding(
                        "GTS103", path, line,
                        f"{h.label} held for {held_ms:.0f}ms "
                        f"(threshold {self.cfg.hold_time_ms:.0f}ms); "
                        "long critical sections serialize every other "
                        "waiter — move the slow work outside the lock"
                        "\n    acquired at:\n" + _fmt_stack(h.stack),
                        key=("GTS103", path, line),
                    )
                return

    # ---- condvar wait bracketing -------------------------------------
    def wait_begin(self, node: int) -> _Held | None:
        """cv.wait() releases the underlying lock: mark the entry
        waiting so it neither counts as held nor accrues hold time."""
        for h in reversed(self._held()):
            if h.node == node and not h.waiting:
                h.waiting = True
                return h
        return None

    def wait_end(self, entry: _Held | None):
        if entry is not None:
            entry.waiting = False
            entry.t0 = time.monotonic()     # re-acquired: fresh clock

    # ---- blocking calls ----------------------------------------------
    def on_blocking(self, label: str, *, skip: int = 2):
        """Called from patched blockers (sleep/Flight/socket) and from
        cv/event wait wrappers. Reports GTS102 when any instrumented
        lock is held by this thread."""
        held = [h for h in self._held() if not h.waiting]
        if not held:
            return
        stack = _capture_stack(skip)
        # anchor at the innermost lock ACQUISITION site: that is where
        # "this lock intentionally covers blocking work" is decided, so
        # that is where a fix (or a justified suppression) belongs
        path, line = _site_of(held[-1].stack)
        locks = ", ".join(h.label for h in held)
        # dedup on the CALL KIND, not the full label: a variable
        # backoff ("time.sleep(0.48)", "time.sleep(0.96)", ...) is ONE
        # defect, and per-value keys would grow findings without bound
        # in a long-lived instrumented server
        kind = label.split("(")[0]
        self._add_finding(
            "GTS102", path, line,
            f"blocking call {label} while holding {locks} stalls every "
            "other waiter for the full blocking latency; move it "
            "outside the lock\n    blocking call at:\n"
            + _fmt_stack(stack)
            + "\n    lock acquired at:\n" + _fmt_stack(held[-1].stack),
            key=("GTS102", path, line, kind),
        )

    # ---- thread / executor lifecycle ---------------------------------
    def register_thread(self, thread, stack: list[tuple[str, int, str]]):
        tid = next(_WRAP_ID)
        with self._mu:
            self._threads[tid] = {
                "ref": weakref.ref(thread), "stack": stack,
                "joined": False, "name": thread.name,
            }
        return tid

    def thread_joined(self, tid: int):
        with self._mu:
            info = self._threads.get(tid)
            if info is not None:
                info["joined"] = True

    def register_executor(self, pool, stack: list[tuple[str, int, str]],
                          *, shared: bool = False):
        pid = next(_WRAP_ID)
        info = {
            "ref": None, "stack": stack,
            "shutdown": False, "shared": shared,
            # an executor COLLECTED without shutdown still leaks: its
            # worker threads sit in the stdlib's detached queues until
            # interpreter exit. The weakref callback records that.
            "leaked_at_gc": False,
        }

        def _collected(_ref, info=info):
            if not info["shutdown"]:
                info["leaked_at_gc"] = True

        info["ref"] = weakref.ref(pool, _collected)
        with self._mu:
            self._executors[pid] = info
        return pid

    def executor_shutdown(self, pid: int):
        with self._mu:
            info = self._executors.get(pid)
            if info is not None:
                info["shutdown"] = True

    def lifecycle_token(self) -> int:
        """Watermark: objects registered after this are 'new'."""
        with self._mu:
            keys = list(self._threads) + list(self._executors)
        return max(keys, default=0)

    def leak_findings(self, since: int = 0, *, record: bool = True
                      ) -> list[dict]:
        """GTS104/GTS105 findings for threads/pools registered after
        `since` that are still live and unreleased. Called by the
        pytest plugin at test teardown and session finish."""
        out: list[dict] = []
        with self._mu:
            threads = [(k, dict(v)) for k, v in self._threads.items()
                       if k > since]
            pools = [(k, dict(v)) for k, v in self._executors.items()
                     if k > since]
        for _tid, info in threads:
            t = info["ref"]()
            if t is None or info["joined"] or t.daemon:
                continue
            if not t.is_alive():
                continue
            path, line = _site_of(info["stack"])
            out.append({
                "rule": "GTS104", "path": path, "line": line, "col": 0,
                "message": f"non-daemon thread {info['name']!r} still "
                           "alive and never joined — it can hang "
                           "interpreter exit\n    created at:\n"
                           + _fmt_stack(info["stack"]),
            })
        for _pid, info in pools:
            if info["shutdown"] or info["shared"]:
                continue
            if info["ref"]() is None and not info["leaked_at_gc"]:
                continue
            path, line = _site_of(info["stack"])
            out.append({
                "rule": "GTS105", "path": path, "line": line, "col": 0,
                "message": "ThreadPoolExecutor never shut down (and "
                           "not marked shared=True) leaks its worker "
                           "threads\n    created at:\n"
                           + _fmt_stack(info["stack"]),
            })
        if record and out:
            with self._mu:
                for f in out:
                    k = (f["rule"], f["path"], f["line"])
                    if k not in self._finding_keys:
                        self._finding_keys.add(k)
                        self.findings.append(f)
        return out

    def snapshot_findings(self) -> list[dict]:
        with self._mu:
            return [dict(f) for f in self.findings]


# ---- enable / disable scopes -----------------------------------------

_active: list[Sanitizer] = []
_env_enabled = False


def current() -> Sanitizer | None:
    return _active[-1] if _active else None


def all_active() -> list[Sanitizer]:
    """Every live scope, innermost last. Patched global blockers
    (sleep/Flight/socket) notify each: held-lock stacks are per-scope
    thread-locals, so only the scope whose locks this thread holds
    produces a finding — nested pytester runs stay attributed."""
    return list(_active)


def is_active(san: Sanitizer | None) -> bool:
    return san is not None and san in _active


def enabled() -> bool:
    return bool(_active)


def enable(config: SanConfig | None = None) -> Sanitizer:
    """Push a sanitizer scope and switch the concurrency facade to
    instrumented factories. Returns the new scope (pass to
    `disable`). Nested enables stack (pytester runs inside a
    sanitized suite)."""
    from greptimedb_tpu import concurrency
    from greptimedb_tpu.tools.san import patch

    san = Sanitizer(config)
    _active.append(san)
    concurrency._set_enabled(True)
    patch.install()
    return san


def disable(san: Sanitizer | None = None):
    """Pop a sanitizer scope (the given one, or the innermost)."""
    from greptimedb_tpu import concurrency
    from greptimedb_tpu.tools.san import patch

    if san is None and _active:
        _active.pop()
    elif san in _active:
        _active.remove(san)
    if not _active:
        patch.uninstall()
        concurrency._set_enabled(False)


def _env_truthy(val: str | None) -> bool:
    return (val or "").strip().lower() in ("1", "true", "on", "yes")


def ensure_enabled_from_env(env=None) -> Sanitizer | None:
    """`GTPU_SAN=1` auto-enable: called once from the concurrency
    facade on first use. Registers an atexit report writer when
    `GTPU_SAN_REPORT` names a path (the `greptimedb-tpu san` driver
    sets both)."""
    global _env_enabled
    env = os.environ if env is None else env
    if _env_enabled or not _env_truthy(env.get("GTPU_SAN")):
        return current()
    _env_enabled = True
    san = enable(SanConfig.from_env(env))
    report_path = env.get("GTPU_SAN_REPORT")
    if report_path:
        import atexit

        from greptimedb_tpu.tools.san.report import write_report

        atexit.register(write_report, san, report_path)
    return san
