"""gtsan: cooperative concurrency sanitizer.

A zero-cost-when-off runtime companion to gtlint's static rules: the
codebase creates its locks, condition variables, threads, and pools
through `greptimedb_tpu.concurrency`, and when the sanitizer is off
those factories return *raw stdlib objects* (no wrapper frames, no
overhead).  When enabled (`GTPU_SAN=1`, the `[sanitizer]` TOML
section, or `greptimedb-tpu san -- <cmd>`), the factories return
instrumented wrappers and gtsan maintains:

- per-thread lock acquisition stacks and a global lock-order graph
  with cycle detection — a potential ABBA deadlock is reported with
  BOTH acquisition stacks without the process ever deadlocking
  (GTS101);
- blocking-call detection: `time.sleep`, Arrow Flight
  do_get/do_put/do_action, socket connects, and condvar/event waits
  executed while an instrumented lock is held (GTS102);
- a configurable hold-time threshold — any lock held longer than
  `hold_time_ms` is reported with its acquisition stack (GTS103);
- thread / executor lifecycle tracking, so the pytest plugin
  (`greptimedb_tpu.tools.san.pytest_plugin`) can fail any test that
  leaks a non-daemon thread (GTS104) or an un-shutdown pool (GTS105).

Findings flow through the same reporter / suppression / baseline
machinery as gtlint (`# gtlint: disable=GTS1xx` comments and
`tools/san/baseline.json`).
"""

from greptimedb_tpu.tools.san.core import (
    SanConfig,
    Sanitizer,
    current,
    disable,
    enable,
    enabled,
    ensure_enabled_from_env,
)
from greptimedb_tpu.tools.san.report import result_doc

__all__ = [
    "SanConfig",
    "Sanitizer",
    "current",
    "disable",
    "enable",
    "enabled",
    "ensure_enabled_from_env",
    "result_doc",
]
