import sys

from greptimedb_tpu.tools.san.runner import main

sys.exit(main())
