"""gtsan pytest plugin: every tier-1 run is also a race/deadlock audit.

Loaded by tests/conftest.py when `GTPU_SAN=1` (or explicitly with
`-p greptimedb_tpu.tools.san.pytest_plugin`).  It

- enables the sanitizer at configure time, before test modules import
  the package (so module-level locks are instrumented too),
- fails any test that leaks a non-daemon thread (GTS104) or an
  un-shutdown ThreadPoolExecutor (GTS105) — checked after the test's
  own fixture finalizers have run,
- at session end renders every finding (cycles, blocking-under-lock,
  hold-time, leaks) through the baseline/suppression machinery and
  fails the session when unsuppressed findings remain.

All state lives on the `config` object, NOT at module level: a nested
pytest run (pytester, used by tests/test_san.py) shares this module
object but gets its own config, so the inner session's sanitizer scope
never clobbers the outer one.
"""

from __future__ import annotations

import pytest

from greptimedb_tpu.tools import san


class SanLeakError(AssertionError):
    """A test leaked a thread or pool (report in the message)."""


def pytest_configure(config):
    config._gtsan_scope = san.enable(san.SanConfig.from_env())
    config._gtsan_token = 0


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_setup(item):
    scope = getattr(item.config, "_gtsan_scope", None)
    if scope is not None:
        item.config._gtsan_token = scope.lifecycle_token()
    yield


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_teardown(item, nextitem):
    # after yield, this test's function-scoped finalizers have run;
    # anything still alive that the test created is a leak
    yield
    scope = getattr(item.config, "_gtsan_scope", None)
    if scope is None:
        return
    leaks = scope.leak_findings(item.config._gtsan_token)
    if leaks:
        msg = "\n".join(
            f"{f['rule']} {f['path']}:{f['line']}: {f['message']}"
            for f in leaks
        )
        raise SanLeakError(
            f"gtsan: {item.nodeid} leaked concurrency resources:\n"
            + msg
            + "\n(join the thread / shut the pool down before the "
            "test ends; a resource owned by a longer-lived fixture "
            "should be created eagerly in that fixture's setup, or "
            "marked shared=True if intentionally process-wide)"
        )


def pytest_sessionfinish(session, exitstatus):
    scope = getattr(session.config, "_gtsan_scope", None)
    if scope is None:
        return
    # session-scoped fixtures are already finalized here; a final
    # whole-run sweep catches leaks attributed to no single test
    scope.leak_findings(0)
    doc = san.result_doc(scope.snapshot_findings())
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    write = tr.write_line if tr is not None else print
    c = doc["counts"]
    if doc["clean"]:
        write(
            f"gtsan: clean ({c['baselined']} baselined, "
            f"{c['suppressed']} suppressed)"
        )
    else:
        from greptimedb_tpu.tools.lint.report import render_text

        for line in render_text(doc).splitlines():
            write(line)
        if scope.cfg.fail_on_cycle and session.exitstatus == 0:
            session.exitstatus = 1


def pytest_unconfigure(config):
    scope = getattr(config, "_gtsan_scope", None)
    if scope is not None:
        san.disable(scope)
        config._gtsan_scope = None
