"""gtsan instrumented primitives.

Each wrapper delegates to a raw stdlib object and reports acquire /
release / wait / lifecycle events to the sanitizer scope that was
active when the object was CREATED (nested scopes — a pytester run
inside a sanitized suite — stay correctly attributed).  Once that
scope is popped the wrapper keeps functioning, untracked.  The
concurrency facade returns these only when the sanitizer is enabled;
with it off the facade hands out raw stdlib objects and none of this
code is on any path.
"""

from __future__ import annotations

import itertools
import threading
from concurrent.futures import ThreadPoolExecutor

from greptimedb_tpu.tools.san import core
from greptimedb_tpu.tools.san.core import _capture_stack, _site_of

_IDS = itertools.count(1)


def _make_label(kind: str, name: str | None,
                stack: list[tuple[str, int, str]]) -> str:
    if name:
        return f"{kind}({name})"
    path, line = _site_of(stack)
    return f"{kind}@{path}:{line}"


class _LockBase:
    """Shared acquire/release instrumentation for Lock and RLock.

    The owning sanitizer is bound at CONSTRUCTION: during a nested
    sanitizer scope (a pytester run inside a sanitized suite), outer
    objects keep reporting to the outer scope and vice versa. Once the
    owning scope is popped the wrapper keeps working, untracked."""

    _kind = "Lock"

    def __init__(self, name: str | None = None):
        self._raw = self._make_raw()
        self.gtsan_id = next(_IDS)
        self.gtsan_label = _make_label(self._kind, name,
                                       _capture_stack(2))
        self._owner = core.current()

    def _san(self):
        owner = self._owner
        return owner if core.is_active(owner) else None

    def _make_raw(self):
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        san = self._san()
        stack = None
        if san is not None:
            stack = _capture_stack(2)
            if blocking:
                san.before_acquire(self.gtsan_id, self.gtsan_label,
                                   stack)
        ok = self._raw.acquire(blocking, timeout)
        if ok and san is not None:
            san.after_acquired(self.gtsan_id, self.gtsan_label, stack)
        return ok

    def release(self):
        san = self._san()
        if san is not None:
            san.on_release(self.gtsan_id)
        self._raw.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._raw.locked()

    def __repr__(self):
        return f"<gtsan {self.gtsan_label} wrapping {self._raw!r}>"


class SanLock(_LockBase):
    _kind = "Lock"


class SanRLock(_LockBase):
    _kind = "RLock"

    def _make_raw(self):
        return threading.RLock()


class SanCondition:
    """Condition over an (instrumented) lock.  `with cv:` acquisitions
    participate in the lock-order graph; `wait()` marks the lock
    released for its duration — blocking while *another* instrumented
    lock is held is reported, waiting on your own condvar is not."""

    def __init__(self, lock: _LockBase | None = None,
                 name: str | None = None):
        if lock is None:
            lock = SanRLock(name)
        elif not isinstance(lock, _LockBase):
            # a raw stdlib lock (created before the sanitizer was
            # enabled): wrap it so tracking still works
            wrapped = SanRLock.__new__(SanRLock)
            wrapped._raw = lock
            wrapped.gtsan_id = next(_IDS)
            wrapped.gtsan_label = _make_label("RLock", name,
                                              _capture_stack(2))
            wrapped._owner = core.current()
            lock = wrapped
        self._slock = lock
        # the stdlib Condition drives the RAW lock; our wrapper methods
        # below do the tracking around it
        self._raw = threading.Condition(lock._raw)

    @property
    def gtsan_id(self):
        return self._slock.gtsan_id

    @property
    def gtsan_label(self):
        return self._slock.gtsan_label

    def acquire(self, *a, **kw):
        return self._slock.acquire(*a, **kw)

    def release(self):
        self._slock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout: float | None = None):
        san = self._slock._san()
        entry = None
        if san is not None:
            entry = san.wait_begin(self.gtsan_id)
            # waiting on this cv while holding OTHER locks blocks them
            san.on_blocking(f"{self.gtsan_label}.wait()", skip=2)
        try:
            return self._raw.wait(timeout)
        finally:
            if san is not None:
                san.wait_end(entry)

    def wait_for(self, predicate, timeout: float | None = None):
        # stdlib logic, re-expressed over self.wait so waits are tracked
        import time as _time

        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = _time.monotonic() + timeout
                waittime = endtime - _time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1):
        self._raw.notify(n)

    def notify_all(self):
        self._raw.notify_all()


class SanEvent:
    """Event whose blocking wait() is visible to the sanitizer (an
    event wait while holding an instrumented lock is a stall)."""

    def __init__(self):
        self._raw = threading.Event()
        self._owner = core.current()

    def is_set(self):
        return self._raw.is_set()

    def set(self):
        self._raw.set()

    def clear(self):
        self._raw.clear()

    def wait(self, timeout: float | None = None):
        owner = self._owner
        san = owner if core.is_active(owner) else None
        if san is not None and (timeout is None
                                or timeout >= san.cfg.sleep_min_s):
            san.on_blocking(
                f"Event.wait({'' if timeout is None else timeout})",
                skip=2)
        return self._raw.wait(timeout)


class SanThread(threading.Thread):
    """Thread registered with the sanitizer's lifecycle registry; the
    pytest plugin fails tests that leave one alive, non-daemon, and
    unjoined."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._gtsan_tid = None
        self._gtsan_owner = core.current()
        if self._gtsan_owner is not None:
            self._gtsan_tid = self._gtsan_owner.register_thread(
                self, _capture_stack(2))

    def join(self, timeout: float | None = None):
        super().join(timeout)
        if not self.is_alive() and self._gtsan_tid is not None:
            self._gtsan_owner.thread_joined(self._gtsan_tid)


class SanThreadPoolExecutor(ThreadPoolExecutor):
    """Executor registered with the lifecycle registry. Pass
    `shared=True` through the facade for intentionally process-wide
    pools (module-level singletons) that are exempt from the
    un-shutdown-pool check."""

    def __init__(self, *args, shared: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self._gtsan_pid = None
        self._gtsan_owner = core.current()
        if self._gtsan_owner is not None:
            self._gtsan_pid = self._gtsan_owner.register_executor(
                self, _capture_stack(2), shared=shared)

    def shutdown(self, *args, **kwargs):
        if self._gtsan_pid is not None:
            self._gtsan_owner.executor_shutdown(self._gtsan_pid)
        return super().shutdown(*args, **kwargs)
