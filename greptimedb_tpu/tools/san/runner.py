"""gtsan CLI.

    greptimedb-tpu san [options] -- <command ...>
    python -m greptimedb_tpu.tools.san [options] -- <command ...>

Runs `<command>` with the sanitizer enabled (GTPU_SAN=1 plus a
GTPU_SAN_REPORT drop file), then renders the child's findings through
the shared baseline/suppression machinery.  Exit status: the child's
non-zero status wins; otherwise 1 when unsuppressed findings (or
stale baseline entries) remain, 0 clean.

    greptimedb-tpu san --report findings.json

re-renders a previously captured raw report without running anything.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

from greptimedb_tpu.tools.lint.report import render_json, render_text
from greptimedb_tpu.tools.san.report import (
    DEFAULT_BASELINE,
    load_raw_report,
    result_doc,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="gtsan",
        description="cooperative concurrency sanitizer driver: run a "
                    "command with GTPU_SAN=1 and report lock-order "
                    "cycles, blocking-under-lock, hold-time, and "
                    "thread/pool leaks.",
    )
    ap.add_argument("cmd", nargs="*",
                    help="command to run (prefix with `--`)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--hold-time-ms", type=float, default=None,
                    help="lock hold-time threshold (GTS103)")
    ap.add_argument("--report", default=None,
                    help="render an existing raw report file instead "
                         "of running a command")
    args = ap.parse_args(argv)

    child_rc = 0
    if args.report:
        try:
            findings = load_raw_report(args.report)
        except (OSError, ValueError) as e:
            print(f"gtsan: cannot read report {args.report}: {e}",
                  file=sys.stderr)
            return 2
    else:
        if not args.cmd:
            ap.error("no command given (greptimedb-tpu san -- <cmd>)")
        fd, drop = tempfile.mkstemp(prefix="gtsan_", suffix=".json")
        os.close(fd)
        env = dict(os.environ)
        env["GTPU_SAN"] = "1"
        env["GTPU_SAN_REPORT"] = drop
        if args.hold_time_ms is not None:
            env["GTPU_SAN_HOLD_MS"] = str(args.hold_time_ms)
        try:
            child_rc = subprocess.call(args.cmd, env=env)
            try:
                findings = load_raw_report(drop)
            except (OSError, ValueError):
                # the report is written lazily from the child's first
                # facade use: a child that never imported the package
                # legitimately writes none
                print("gtsan: child wrote no report (it never used "
                      "greptimedb_tpu.concurrency, or crashed before "
                      "exit handlers ran)", file=sys.stderr)
                findings = []
        finally:
            try:
                os.unlink(drop)
            except OSError:
                pass

    doc = result_doc(
        findings,
        baseline_path=None if args.no_baseline else args.baseline,
    )
    print(render_json(doc) if args.format == "json"
          else render_text(doc))
    if child_rc != 0:
        return child_rc
    return 0 if doc["clean"] else 1
