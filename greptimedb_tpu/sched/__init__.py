"""Query admission control and scheduling dataplane.

The frontend's overload surface (ROADMAP open item 4): per-tenant
token buckets + concurrency limits with a bounded priority queue
(`admission.py`), and end-to-end deadline propagation (`deadline.py`)
so a slow or blackholed datanode BOUNDS a query instead of blocking
it. Modeled on tf.data's pipelining-and-backpressure design
(PAPERS.md): the accepting edge sheds typed errors under overload —
`QueryOverloadedError` (429), `QueryQueueTimeoutError` (503),
`QueryDeadlineExceededError` (503) — never a hang.
"""

from greptimedb_tpu.sched.admission import (
    AdmissionController,
    SchedulerConfig,
    tenant_of,
)
from greptimedb_tpu.sched.deadline import Deadline

__all__ = [
    "AdmissionController",
    "Deadline",
    "SchedulerConfig",
    "tenant_of",
]
