"""Frontend admission control: per-tenant token buckets + concurrency
limits over a bounded priority queue.

Counterpart of tf.data's pipelining-and-backpressure design (PAPERS.md)
applied to the query path: under overload the accepting edge sheds a
TYPED error immediately (over-quota tenant, full queue) or after a
bounded queue-time SLO — the p99 of admitted work stays bounded because
the queue's sojourn time is, and memory stays bounded because its depth
is. A statement admitted here also gets its absolute deadline bound
into the execution context (deadline.py), so admission is the single
choke point where "never a hang" is enforced end to end.

Defaults are permissive (no qps quota, unlimited concurrency): the
controller rides the hot path of every statement, but without limits
configured it never queues and never sheds — the `[scheduler]` TOML
section turns the limits on.
"""

from __future__ import annotations

import contextvars
import heapq
import time
from collections import OrderedDict

from greptimedb_tpu import concurrency
from greptimedb_tpu.errors import (
    QueryDeadlineExceededError,
    QueryOverloadedError,
    QueryQueueTimeoutError,
)
from greptimedb_tpu.sched import deadline as _deadline
from greptimedb_tpu.telemetry import stmt_stats
from greptimedb_tpu.telemetry.metrics import global_registry

_QUEUE_DEPTH = global_registry.gauge(
    "gtpu_sched_queue_depth",
    "statements waiting for an execution slot",
)
_RUNNING = global_registry.gauge(
    "gtpu_sched_running",
    "statements holding an execution slot",
)
_ADMITTED = global_registry.counter(
    "gtpu_sched_admitted_total",
    "statements admitted to execution, per tenant",
    labels=("tenant",),
)
_SHED = global_registry.counter(
    "gtpu_sched_shed_total",
    "statements shed by admission control, per tenant and reason",
    labels=("tenant", "reason"),
)
_QUEUE_TIME = global_registry.histogram(
    "gtpu_sched_queue_time_seconds",
    "admission-queue sojourn time of admitted/expired statements",
)
_DEADLINE_EXPIRED = global_registry.counter(
    "gtpu_sched_deadline_expired_total",
    "statements whose deadline lapsed before or during execution",
    labels=("tenant",),
)
_PARTIAL_RESULTS = global_registry.counter(
    "gtpu_sched_partial_results_total",
    "queries answered with a typed partial result after per-datanode "
    "deadline expiry or unavailability",
)

# The tenant string is CLIENT-controlled when unauthenticated (the
# HTTP `db` param), so everything keyed on it must stay bounded under
# a hostile storm rotating names: per-tenant metric label series
# collapse to "_other" past this many distinct unconfigured tenants,
# and token buckets live in a same-sized LRU (an evicted bucket
# refills to burst — that only under-counts a name-rotating client,
# whose per-name bucket was full anyway).
_TENANT_STATE_MAX = 4096
_LABEL_TENANTS_MAX = 64
_label_tenants: set = set()


def _metric_tenant(tenant: str, configured: bool) -> str:
    if configured or tenant in _label_tenants:
        return tenant
    if len(_label_tenants) < _LABEL_TENANTS_MAX:
        _label_tenants.add(tenant)
        return tenant
    return "_other"


def tenant_of(ctx) -> str:
    """Tenant identity of a session: the authenticated user when there
    is one, else the database the session is scoped to."""
    if ctx is None:
        return "public"
    return getattr(ctx, "username", "") or getattr(
        ctx, "database", "") or "public"


class _TenantLimits:
    __slots__ = ("qps", "burst", "concurrency", "priority")

    def __init__(self, qps: float, burst: float, concurrency: int,
                 priority: int):
        self.qps = float(qps)
        self.burst = float(burst) if burst > 0 else max(1.0, 2 * self.qps)
        self.concurrency = int(concurrency)
        self.priority = int(priority)


class SchedulerConfig:
    """`[scheduler]` options (config.py DEFAULTS documents each knob).

    0 means "unlimited" for every limit knob; `tenants` holds per-tenant
    overrides: {name: {qps, burst, concurrency, priority}}."""

    def __init__(self, *, enable: bool = True, max_concurrency: int = 0,
                 queue_depth: int = 256, queue_timeout_s: float = 10.0,
                 default_deadline_s: float = 0.0,
                 tenant_qps: float = 0.0, tenant_burst: float = 0.0,
                 tenant_concurrency: int = 0,
                 allow_partial_results: bool = False,
                 tenants: dict | None = None):
        self.enable = bool(enable)
        self.max_concurrency = int(max_concurrency)
        self.queue_depth = int(queue_depth)
        self.queue_timeout_s = float(queue_timeout_s)
        self.default_deadline_s = float(default_deadline_s)
        self.tenant_qps = float(tenant_qps)
        self.tenant_burst = float(tenant_burst)
        self.tenant_concurrency = int(tenant_concurrency)
        self.allow_partial_results = bool(allow_partial_results)
        self.tenants = dict(tenants or {})
        self._limits_cache: dict[str, _TenantLimits] = {}
        # every unconfigured tenant shares ONE limits object: the
        # cache then only ever holds configured tenants (bounded by
        # the config), never client-invented names
        self._default_limits = _TenantLimits(
            qps=self.tenant_qps, burst=self.tenant_burst,
            concurrency=self.tenant_concurrency, priority=100,
        )

    @classmethod
    def from_options(cls, options: dict | None) -> "SchedulerConfig":
        o = options or {}
        return cls(
            enable=o.get("enable", True),
            max_concurrency=o.get("max_concurrency", 0),
            queue_depth=o.get("queue_depth", 256),
            queue_timeout_s=o.get("queue_timeout_s", 10.0),
            default_deadline_s=o.get("default_deadline_s", 0.0),
            tenant_qps=o.get("tenant_qps", 0.0),
            tenant_burst=o.get("tenant_burst", 0.0),
            tenant_concurrency=o.get("tenant_concurrency", 0),
            allow_partial_results=o.get("allow_partial_results", False),
            tenants={
                k: dict(v) for k, v in (o.get("tenants") or {}).items()
                if isinstance(v, dict)
            },
        )

    def limits(self, tenant: str) -> _TenantLimits:
        over = self.tenants.get(tenant)
        if over is None:
            return self._default_limits
        lim = self._limits_cache.get(tenant)
        if lim is None:
            lim = _TenantLimits(
                qps=over.get("qps", self.tenant_qps),
                burst=over.get("burst", self.tenant_burst),
                concurrency=over.get("concurrency",
                                     self.tenant_concurrency),
                priority=over.get("priority", 100),
            )
            self._limits_cache[tenant] = lim
        return lim

    def configured(self, tenant: str) -> bool:
        return tenant in self.tenants


class _Waiter:
    __slots__ = ("tenant", "limits", "event", "admitted", "abandoned")

    def __init__(self, tenant: str, limits: _TenantLimits):
        self.tenant = tenant
        self.limits = limits
        self.event = concurrency.Event()
        self.admitted = False
        self.abandoned = False


# re-entrancy guard: a statement executing INSIDE an admitted statement
# (EXECUTE of a prepared statement, flow ticks calling execute_sql,
# COPY's internal SELECT) rides the parent's slot and deadline instead
# of deadlocking against its own tenant's concurrency limit
_active: contextvars.ContextVar = contextvars.ContextVar(
    "gtpu_sched_active", default=False
)


class AdmissionController:
    """One per instance; `admit(ctx)` guards one statement execution."""

    def __init__(self, config: SchedulerConfig | None = None):
        self.config = config or SchedulerConfig()
        self._lock = concurrency.Lock()
        self._running_total = 0
        self._running_tenant: dict[str, int] = {}
        self._heap: list[tuple[int, int, _Waiter]] = []
        self._queued = 0
        self._seq = 0
        self._buckets: OrderedDict[str, list[float]] = OrderedDict()

    # ---- public surface ----------------------------------------------
    def admit(self, ctx=None, *, tenant: str | None = None,
              timeout_s: float | None = None) -> "_Admission":
        return _Admission(self, ctx, tenant, timeout_s)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "running": self._running_total,
                "queued": self._queued,
                "tenants": dict(self._running_tenant),
            }

    # ---- internals ----------------------------------------------------
    def _can_run_locked(self, tenant: str, lim: _TenantLimits) -> bool:
        cfg = self.config
        if 0 < cfg.max_concurrency <= self._running_total:
            return False
        if 0 < lim.concurrency <= self._running_tenant.get(tenant, 0):
            return False
        return True

    def _start_locked(self, tenant: str):
        self._running_total += 1
        self._running_tenant[tenant] = \
            self._running_tenant.get(tenant, 0) + 1
        _RUNNING.set(self._running_total)

    def _take_token_locked(self, tenant: str, lim: _TenantLimits) -> bool:
        if lim.qps <= 0:
            return True
        now = time.monotonic()
        b = self._buckets.get(tenant)
        if b is None:
            if len(self._buckets) >= _TENANT_STATE_MAX:
                self._buckets.popitem(last=False)
            self._buckets[tenant] = b = [lim.burst, now]
        else:
            self._buckets.move_to_end(tenant)
            b[0] = min(lim.burst, b[0] + (now - b[1]) * lim.qps)
            b[1] = now
        if b[0] < 1.0:
            return False
        b[0] -= 1.0
        return True

    def _acquire(self, tenant: str, dl: _deadline.Deadline | None):
        cfg = self.config
        if not cfg.enable:
            return
        mt = _metric_tenant(tenant, cfg.configured(tenant))
        if dl is not None and dl.expired():
            # an already-spent budget never reaches execution: the
            # bound holds regardless of which path would run the query
            _SHED.labels(mt, "deadline").inc()
            _DEADLINE_EXPIRED.labels(mt).inc()
            raise QueryDeadlineExceededError(
                "query deadline expired before admission"
            )
        lim = cfg.limits(tenant)
        t0 = time.monotonic()
        with self._lock:
            if not self._take_token_locked(tenant, lim):
                _SHED.labels(mt, "qps").inc()
                raise QueryOverloadedError(
                    f"tenant {tenant!r} is over its rate quota "
                    f"({lim.qps:g} qps); back off and retry"
                )
            if self._can_run_locked(tenant, lim):
                self._start_locked(tenant)
                _ADMITTED.labels(mt).inc()
                return
            if 0 < cfg.queue_depth <= self._queued:
                _SHED.labels(mt, "queue_full").inc()
                raise QueryOverloadedError(
                    f"admission queue is full ({cfg.queue_depth}); "
                    "back off and retry"
                )
            w = _Waiter(tenant, lim)
            self._seq += 1
            heapq.heappush(self._heap, (lim.priority, self._seq, w))
            self._queued += 1
            _QUEUE_DEPTH.set(self._queued)
        # queue_timeout_s 0 = no SLO (like every other limit knob):
        # wait until a slot frees or the deadline lapses — with
        # neither bound set, unbounded queueing is the operator's
        # explicit configuration choice
        wait_s = cfg.queue_timeout_s if cfg.queue_timeout_s > 0 else None
        if dl is not None:
            rem = dl.remaining()
            wait_s = rem if wait_s is None else min(wait_s, rem)
        # +epsilon: Event.wait can return a hair early; when the
        # deadline is the binding constraint it must have ACTUALLY
        # lapsed afterwards so the shed classifies as deadline, not
        # queue-timeout
        w.event.wait(None if wait_s is None else wait_s + 0.02)
        with self._lock:
            admitted = w.admitted
            if not admitted:
                # lazily removed from the heap by the next _wake pass
                w.abandoned = True
                self._queued -= 1
                _QUEUE_DEPTH.set(self._queued)
        _QUEUE_TIME.observe(time.monotonic() - t0)
        if admitted:
            _ADMITTED.labels(mt).inc()
            return
        if dl is not None and dl.expired():
            _SHED.labels(mt, "deadline").inc()
            _DEADLINE_EXPIRED.labels(mt).inc()
            raise QueryDeadlineExceededError(
                "query deadline expired in the admission queue"
            )
        _SHED.labels(mt, "queue_timeout").inc()
        raise QueryQueueTimeoutError(
            f"no execution slot within the {cfg.queue_timeout_s:g}s "
            "queue-time SLO; the instance is saturated"
        )

    def set_max_concurrency(self, v: int) -> None:
        """Runtime limit update (autotune/knobs.py is the sanctioned
        caller — GT021). Raising the limit hands the new slots to the
        best queued waiters immediately; _can_run_locked reads the
        config live, so a lowered limit takes effect as running
        statements release (running work is never preempted)."""
        wakes: list[_Waiter] = []
        with self._lock:
            self.config.max_concurrency = int(v)
            stash = []
            while self._heap:
                prio, seq, w = heapq.heappop(self._heap)
                if w.abandoned:
                    continue
                if self._can_run_locked(w.tenant, w.limits):
                    self._start_locked(w.tenant)
                    w.admitted = True
                    self._queued -= 1
                    _QUEUE_DEPTH.set(self._queued)
                    wakes.append(w)
                    continue
                stash.append((prio, seq, w))
            for item in stash:
                heapq.heappush(self._heap, item)
        for w in wakes:
            w.event.set()

    def _release(self, tenant: str):
        if not self.config.enable:
            return
        wake: _Waiter | None = None
        with self._lock:
            self._running_total = max(0, self._running_total - 1)
            n = self._running_tenant.get(tenant, 0) - 1
            if n > 0:
                self._running_tenant[tenant] = n
            else:
                self._running_tenant.pop(tenant, None)
            _RUNNING.set(self._running_total)
            # hand the freed slot to the best eligible waiter; waiters
            # whose tenant is at ITS cap are skipped (and re-pushed),
            # abandoned ones are dropped
            stash = []
            while self._heap:
                prio, seq, w = heapq.heappop(self._heap)
                if w.abandoned:
                    continue
                if self._can_run_locked(w.tenant, w.limits):
                    self._start_locked(w.tenant)
                    w.admitted = True
                    self._queued -= 1
                    _QUEUE_DEPTH.set(self._queued)
                    wake = w
                    break
                stash.append((prio, seq, w))
            for item in stash:
                heapq.heappush(self._heap, item)
        if wake is not None:
            wake.event.set()


class _Admission:
    """Context manager for one admitted statement: resolves the tenant
    and deadline, acquires (or queues for) an execution slot, binds the
    deadline for cooperative checks, and releases on exit."""

    __slots__ = ("_c", "_ctx", "_tenant", "_timeout_s", "_noop",
                 "_dl_token", "_active_token", "deadline")

    def __init__(self, controller: AdmissionController, ctx,
                 tenant: str | None, timeout_s: float | None):
        self._c = controller
        self._ctx = ctx
        self._tenant = tenant
        self._timeout_s = timeout_s
        self._noop = False
        self._dl_token = None
        self._active_token = None
        self.deadline: _deadline.Deadline | None = None

    def _resolve_timeout(self) -> float | None:
        if self._timeout_s is not None:
            return self._timeout_s
        ctx = self._ctx
        if ctx is not None:
            hint = getattr(ctx, "extensions", {}).get("deadline_s")
            if hint is not None:
                return float(hint)
            # MySQL-compatible session knob: SET max_execution_time=<ms>
            ms = getattr(ctx, "variables", {}).get("max_execution_time")
            try:
                if ms is not None and float(ms) > 0:
                    return float(ms) / 1000.0
            except (TypeError, ValueError):
                pass
        return self._c.config.default_deadline_s

    def __enter__(self) -> "_Admission":
        if _active.get():
            self._noop = True  # nested statement: ride the parent slot
            return self
        self._tenant = self._tenant or tenant_of(self._ctx)
        self.deadline = _deadline.Deadline.from_timeout(
            self._resolve_timeout()
        )
        self._dl_token = _deadline.bind(self.deadline)
        try:
            # the admit span's duration IS the queue wait: a trace of a
            # statement that queued shows its sojourn next to the
            # execution spans (and a shed raises inside the span, so
            # shed traces carry the error and survive tail sampling).
            # The same sojourn lands on the statement's statistics row
            # (stmt_stats queue-time histogram); a shed raises typed
            # and is classified by status code at the fold.
            from greptimedb_tpu.telemetry import tracing

            t0 = time.monotonic()
            with tracing.child_span("sched.admit",
                                    tenant=self._tenant):
                self._c._acquire(self._tenant, self.deadline)
            stmt_stats.add("queue_ms",
                           (time.monotonic() - t0) * 1000.0)
        except BaseException:
            _deadline.reset(self._dl_token)
            self._dl_token = None
            raise
        self._active_token = _active.set(True)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._noop:
            return False
        _active.reset(self._active_token)
        self._c._release(self._tenant)
        _deadline.reset(self._dl_token)
        if exc_type is not None and issubclass(
                exc_type, QueryDeadlineExceededError):
            _DEADLINE_EXPIRED.labels(_metric_tenant(
                self._tenant, self._c.config.configured(self._tenant)
            )).inc()
        return False


def note_partial_result():
    """Record a degraded (partial) answer (dist/dist_query.py)."""
    _PARTIAL_RESULTS.inc()
