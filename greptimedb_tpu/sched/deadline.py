"""End-to-end query deadlines.

Every admitted query carries an absolute deadline (monotonic clock;
wall clock would jump with NTP slews, GT011). The deadline flows:

- frontend: bound into a contextvar for the statement's lifetime, so
  every `cancellation.checkpoint()` along the execution path (per-
  region scans, fan-out boundaries) raises the typed
  `QueryDeadlineExceededError` the moment it lapses;
- fan-out: the REMAINING budget becomes each datanode Flight call's
  timeout (`FlightCallOptions`) and rides the partial-plan ticket as
  `deadline_s`, so the datanode runs its own cooperative checks — a
  blackholed datanode bounds, not blocks, the query;
- datanode: `exec_partial` (dist/merge.py) re-binds the shipped
  budget before executing.

Monotonic deadlines do not transfer between processes, so only the
remaining BUDGET crosses the wire and is re-anchored on arrival.
"""

from __future__ import annotations

import contextvars
import math
import time

from greptimedb_tpu.errors import QueryDeadlineExceededError

_current: contextvars.ContextVar = contextvars.ContextVar(
    "gtpu_query_deadline", default=None
)


class Deadline:
    """Absolute monotonic deadline for one query."""

    __slots__ = ("at",)

    def __init__(self, timeout_s: float):
        self.at = time.monotonic() + float(timeout_s)

    @classmethod
    def from_timeout(cls, timeout_s) -> "Deadline | None":
        """None / <=0 / non-finite means unbounded (no deadline) —
        nan or inf would make `at` arithmetic nonsense (never-firing
        expired() but 0-second remaining()); the protocol edges
        reject them as client errors before they get here."""
        if timeout_s is None:
            return None
        t = float(timeout_s)
        if not math.isfinite(t) or t <= 0:
            return None
        return cls(t)

    def remaining(self) -> float:
        return max(0.0, self.at - time.monotonic())

    def expired(self) -> bool:
        return time.monotonic() >= self.at

    def check(self, what: str = "query"):
        if self.expired():
            raise QueryDeadlineExceededError(
                f"{what} deadline exceeded"
            )


def bind(deadline: Deadline | None):
    """Install `deadline` for the current context; returns a token for
    `reset`. Binding None clears an inherited deadline."""
    return _current.set(deadline)


def reset(token):
    _current.reset(token)


def current() -> Deadline | None:
    return _current.get()


def remaining() -> float | None:
    """Seconds left on the active deadline; None when unbounded."""
    d = _current.get()
    return None if d is None else d.remaining()


def call_timeout(cap_s: float | None = None) -> float | None:
    """Per-RPC timeout derived from the active deadline, optionally
    capped: min(remaining, cap). None = no bound requested anywhere."""
    r = remaining()
    if r is None:
        return cap_s
    return r if cap_s is None else min(r, cap_s)


def check(what: str = "query"):
    """Raise QueryDeadlineExceededError when the active deadline has
    lapsed; no-op when unbounded. Called from every
    cancellation.checkpoint()."""
    d = _current.get()
    if d is not None:
        d.check(what)
