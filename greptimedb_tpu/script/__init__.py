from greptimedb_tpu.script.engine import PyEngine, copr

__all__ = ["PyEngine", "copr"]
