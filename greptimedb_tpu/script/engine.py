"""Python coprocessors.

Capability counterpart of /root/reference/src/script/src/python/ (the
`@copr` decorated scripts run by an embedded RustPython/PyO3 over
RecordBatches, engine.rs:345, ffi_types/copr.rs:300-344). Here the host
language IS Python, so coprocessor vectors are handed over zero-copy as
jax arrays — a script's arithmetic runs on the TPU via jit instead of an
embedded interpreter.

    @copr(args=["cpu", "mem"], returns=["load"],
          sql="select cpu, mem from host_metrics")
    def load(cpu, mem):
        return cpu * 0.6 + mem * 0.4

Scripts are stored through the object store (the reference keeps them in a
`scripts` system table, src/script/src/table.rs) and recompiled on boot.
"""

from __future__ import annotations

import json

import numpy as np

from greptimedb_tpu.errors import InvalidArgumentError, UnsupportedError
from greptimedb_tpu.query.executor import Col, QueryResult

from greptimedb_tpu import concurrency

SCRIPTS_PATH = "meta/scripts.json"


def copr(*, args: list[str] | None = None, returns: list[str],
         sql: str | None = None, backend: str = "jax"):
    """Coprocessor annotation (the reference's @copr/@coprocessor)."""

    def wrap(fn):
        fn.__copr_meta__ = {
            "args": args or [], "returns": returns, "sql": sql,
            "backend": backend,
        }
        return fn

    return wrap


coprocessor = copr


class CompiledScript:
    def __init__(self, name: str, source: str):
        self.name = name
        self.source = source
        namespace: dict = {"copr": copr, "coprocessor": copr, "np": np}
        try:
            import jax
            import jax.numpy as jnp

            namespace["jax"] = jax
            namespace["jnp"] = jnp
        except ImportError:  # pragma: no cover
            pass
        exec(compile(source, f"<script {name}>", "exec"), namespace)
        self.entry = None
        for v in namespace.values():
            if callable(v) and hasattr(v, "__copr_meta__"):
                self.entry = v
        if self.entry is None:
            raise InvalidArgumentError(
                f"script {name!r} has no @copr-annotated function"
            )
        self.meta = self.entry.__copr_meta__


class PyEngine:
    """Compiles + runs coprocessor scripts against the instance."""

    def __init__(self, instance):
        self.instance = instance
        self._scripts: dict[str, CompiledScript] = {}
        self._lock = concurrency.RLock()
        self._load()

    # ------------------------------------------------------------------
    def _load(self):
        store = self.instance.engine.store
        if not store.exists(SCRIPTS_PATH):
            return
        for name, src in json.loads(store.read(SCRIPTS_PATH)).items():
            try:
                self._scripts[name] = CompiledScript(name, src)
            except Exception:
                import traceback

                traceback.print_exc()

    def _persist(self):
        doc = {name: s.source for name, s in self._scripts.items()}
        self.instance.engine.store.write(
            SCRIPTS_PATH, json.dumps(doc).encode()
        )

    # ------------------------------------------------------------------
    def insert_script(self, name: str, source: str) -> CompiledScript:
        s = CompiledScript(name, source)
        with self._lock:
            self._scripts[name] = s
            self._persist()
        return s

    def script_names(self) -> list[str]:
        with self._lock:
            return sorted(self._scripts)

    def delete_script(self, name: str):
        with self._lock:
            self._scripts.pop(name, None)
            self._persist()

    # ------------------------------------------------------------------
    def run_script(self, name: str, *, params: dict | None = None,
                   ctx=None) -> QueryResult:
        with self._lock:
            script = self._scripts.get(name)
        if script is None:
            raise InvalidArgumentError(f"script not found: {name}")
        return self.run_compiled(script, params=params, ctx=ctx)

    def run_inline(self, source: str, *, params: dict | None = None,
                   ctx=None) -> QueryResult:
        return self.run_compiled(
            CompiledScript("<inline>", source), params=params, ctx=ctx
        )

    def run_compiled(self, script: CompiledScript, *,
                     params: dict | None = None, ctx=None) -> QueryResult:
        meta = script.meta
        arg_values = []
        if meta["sql"]:
            from greptimedb_tpu.session import QueryContext

            res = self.instance.sql(meta["sql"], ctx or QueryContext())
            for arg in meta["args"]:
                if arg not in res.names:
                    raise InvalidArgumentError(
                        f"query does not produce column {arg!r}"
                    )
                col = res.column(arg)
                arg_values.append(self._to_vector(col, meta["backend"]))
        else:
            params = params or {}
            for arg in meta["args"]:
                if arg not in params:
                    raise InvalidArgumentError(f"missing param {arg!r}")
                arg_values.append(params[arg])
        out = script.entry(*arg_values)
        return self._to_result(out, meta["returns"])

    # ------------------------------------------------------------------
    @staticmethod
    def _to_vector(col: Col, backend: str):
        v = col.values
        if v.dtype == object or backend == "numpy":
            return v
        import jax.numpy as jnp

        return jnp.asarray(v)

    @staticmethod
    def _to_result(out, returns: list[str]) -> QueryResult:
        if not isinstance(out, tuple):
            out = (out,)
        if len(out) != len(returns):
            raise UnsupportedError(
                f"script returned {len(out)} values, declared "
                f"{len(returns)}"
            )
        cols = []
        n = None
        arrays = []
        for v in out:
            a = np.asarray(v)
            if a.ndim == 0:
                a = a[None]
            arrays.append(a)
            n = max(n or 0, len(a))
        for a in arrays:
            if len(a) == 1 and n > 1:
                a = np.broadcast_to(a, (n,)).copy()
            cols.append(Col(a))
        return QueryResult(list(returns), cols)
