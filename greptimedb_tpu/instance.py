"""Standalone instance: the statement executor over catalog + query engine.

Capability counterpart of the reference's frontend Instance + operator
StatementExecutor (/root/reference/src/frontend/src/instance.rs:111,
src/operator/src/statement.rs:130-312): one entry point that parses SQL,
dispatches every statement kind, routes DML to storage, and runs queries
through the planner/executor. Protocol servers (HTTP/gRPC) call into this.
"""

from __future__ import annotations

import logging

import numpy as np

from greptimedb_tpu.catalog import CatalogManager
from greptimedb_tpu.catalog.manager import region_options_from_table
from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema, SemanticType
from greptimedb_tpu.datatypes.types import ConcreteDataType
from greptimedb_tpu.errors import (
    DatabaseNotFoundError,
    ExecutionError,
    InvalidArgumentError,
    PlanError,
    TableNotFoundError,
    UnsupportedError,
)
from greptimedb_tpu.query.executor import Col, QueryEngine, QueryResult
from greptimedb_tpu.query.expr import eval_const, parse_ts_literal
from greptimedb_tpu.query.planner import plan_select
from greptimedb_tpu.session import QueryContext
from greptimedb_tpu.sql import ast as A
from greptimedb_tpu.sql.parser import parse_sql
from greptimedb_tpu.storage.engine import EngineConfig, TsdbEngine

from greptimedb_tpu import concurrency

class Output:
    """Statement execution result: either affected rows or a result set."""

    def __init__(self, *, affected_rows: int | None = None,
                 result: QueryResult | None = None):
        self.affected_rows = affected_rows
        self.result = result

    @staticmethod
    def rows(n: int) -> "Output":
        return Output(affected_rows=n)

    @staticmethod
    def records(r: QueryResult) -> "Output":
        return Output(result=r)


def _result_from_lists(names: list[str], columns: list[list]) -> QueryResult:
    cols = []
    for vals in columns:
        validity = np.asarray([v is not None for v in vals], bool)
        if all(isinstance(v, (int, np.integer)) or v is None for v in vals):
            arr = np.asarray([0 if v is None else v for v in vals], np.int64)
        elif all(isinstance(v, (int, float, np.floating)) or v is None
                 for v in vals):
            arr = np.asarray(
                [0.0 if v is None else float(v) for v in vals], np.float64
            )
        else:
            arr = np.asarray(["" if v is None else v for v in vals], object)
        cols.append(Col(arr, None if validity.all() else validity))
    return QueryResult(names, cols)


class _ProcessList:
    """In-process running-statement registry backing SHOW PROCESSLIST and
    ADMIN kill (reference: src/catalog/src/process_manager.rs). A killed
    id raises in the owning thread at its next cancellation checkpoint."""

    def __init__(self):
        import threading

        self._lock = concurrency.Lock()
        self._next_id = 1
        self._running: dict[int, dict] = {}

    def register(self, query: str, ctx) -> int:
        import time

        with self._lock:
            pid = self._next_id
            self._next_id += 1
            self._running[pid] = {
                "id": pid, "query": query, "db": ctx.database,
                "user": ctx.username or "greptime", "start": time.time(),
                # elapsed_s math uses the monotonic clock (GT011): an
                # NTP slew must not show negative or absurd elapsed
                "_start_mono": time.monotonic(),
                "killed": False,
                # Queued until the admission controller grants a slot
                # (sched/admission.py) — SHOW PROCESSLIST separates
                # waiting work from running work under overload
                "state": "Queued",
            }
            return pid

    def set_state(self, pid: int, state: str):
        with self._lock:
            entry = self._running.get(pid)
            if entry is not None:
                entry["state"] = state

    def unregister(self, pid: int):
        with self._lock:
            self._running.pop(pid, None)

    def kill(self, pid_text: str) -> bool:
        try:
            pid = int(pid_text)
        except ValueError:
            return False
        with self._lock:
            entry = self._running.get(pid)
            if entry is None:
                return False
            entry["killed"] = True
            return True

    def check_killed(self, pid: int):
        with self._lock:
            entry = self._running.get(pid)
            killed = entry is not None and entry["killed"]
        if killed:
            from greptimedb_tpu.errors import ExecutionError

            raise ExecutionError(f"query {pid} was killed")

    def snapshot(self) -> list[dict]:
        import time

        with self._lock:
            now = time.monotonic()
            return [
                {**{k: v for k, v in e.items()
                    if not k.startswith("_")},
                 "elapsed_s": now - e["_start_mono"]}
                for e in self._running.values()
            ]


# statement kinds that consume engine/storage resources and therefore
# pass through the admission controller; everything else (SHOW, SET,
# ADMIN kill, DESCRIBE, ...) is control-plane and bypasses it
_ADMITTED_STATEMENTS = (
    A.Select, A.SetOp, A.Tql, A.Insert, A.Delete, A.Copy, A.Explain,
)

_xla_cache_enabled = False


def _enable_xla_persistent_cache(data_root: str):
    """Persist XLA compilations under the data dir so a restarted process
    skips recompiles (the reference has no compile step; this removes the
    cold-start cliff unique to the XLA design). First instance in the
    process wins — the cache is content-addressed, so sharing is safe."""
    global _xla_cache_enabled
    import os

    if _xla_cache_enabled or os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return
    try:
        import jax

        path = os.path.join(os.path.abspath(data_root), ".xla_cache")
        # jax won't create the directory itself; a missing dir turns
        # every cache write into a warning
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _xla_cache_enabled = True
    except Exception as e:  # noqa: BLE001
        # purely a warm-start optimisation; run uncached without it
        logging.getLogger("greptimedb_tpu.instance").debug(
            "xla persistent cache unavailable: %s", e)


class Standalone:
    """Single-process database instance (frontend + datanode + flownode in
    one, like `greptime standalone start`,
    /root/reference/src/cmd/src/standalone.rs:432)."""

    def __init__(self, data_root: str = "./greptimedb_tpu_data", *,
                 engine_config: EngineConfig | None = None,
                 prefer_device: bool | None = None, mesh=None,
                 mesh_opts=None, warm_start: bool = True, store=None,
                 cold_store=None):
        cfg = engine_config or EngineConfig(data_root=data_root,
                                            enable_background=False)
        _enable_xla_persistent_cache(cfg.data_root)
        self.engine = TsdbEngine(cfg, store=store, cold_store=cold_store)
        self.catalog = CatalogManager(self.engine)
        self.query_engine = QueryEngine(prefer_device=prefer_device,
                                        mesh=mesh, mesh_opts=mesh_opts)
        self.flows = None  # wired by flow.FlowManager when enabled
        self._procedures = []
        self._process_list = _ProcessList()
        # fleet identity (telemetry/node_stats.py): the role this
        # process plays and the address peers dial it on; cli.py stamps
        # the real values once servers are bound (DistInstance flips
        # the role to frontend/flownode)
        self.node_role = "standalone"
        self.node_addr = ""
        self.node_id = 0
        # admission control + deadline scheduling (sched/): default
        # config is permissive (no quotas/limits => never queues or
        # sheds); cli.py swaps in the [scheduler]-configured one
        from greptimedb_tpu.sched import AdmissionController

        self.scheduler = AdmissionController()
        # frontend result-set cache (query/result_cache.py): disabled
        # by default — cli.py swaps in the [result_cache]-configured
        # one. The catalog gets a handle so drop_table can purge.
        from greptimedb_tpu.query.result_cache import ResultCache

        self.result_cache = ResultCache(enabled=False)
        self.catalog.result_cache = self.result_cache
        from greptimedb_tpu.telemetry.slow_query import SlowQueryLog

        self.slow_query_log = SlowQueryLog()
        # adaptive control plane (autotune/): the knob registry backs
        # ADMIN set_config + information_schema.autotune_* even when
        # the controller loop is off; cli.py applies the [autotune]
        # section and starts the tick thread when enabled
        from greptimedb_tpu.autotune import build_runtime

        self.knobs, self.autotune = build_runtime(self)
        if warm_start:
            # restore device grid snapshots in the background so the
            # first query after a restart skips the SST rescan
            import threading

            def _warm():
                try:
                    from greptimedb_tpu.query.device_range import (
                        warm_from_snapshots,
                    )

                    warm_from_snapshots(self.query_engine, self.catalog)
                except Exception as e:  # noqa: BLE001
                    # cold caches are only slower, never wrong
                    logging.getLogger("greptimedb_tpu.instance").debug(
                        "device cache warm-start skipped: %s", e)

            concurrency.Thread(
                target=_warm, daemon=True, name="device-cache-warm"
            ).start()

    def close(self):
        # stop the control loop FIRST: a tick racing teardown would
        # read sensors over closing pools
        self.autotune.close()
        if self.flows is not None:
            self.flows.stop()
        # fence the region server FIRST: a parked ingest stream must
        # get typed errors, not apply writes into a closing engine
        rs = getattr(self, "region_server", None)
        if rs is not None and hasattr(rs, "close"):
            rs.close()
        self.engine.close()

    # ------------------------------------------------------------------
    # entry points
    # ------------------------------------------------------------------
    def execute_sql(self, sql: str, ctx: QueryContext | None = None
                    ) -> list[Output]:
        import time as _time

        from greptimedb_tpu.telemetry import stmt_stats, tracing

        ctx = ctx or QueryContext()
        outputs = []
        t0 = _time.perf_counter()
        trace_id = None
        # per-statement fingerprints resolved from the raw TEXT (the
        # AST has no literal spans left to fold); aligned with
        # parse_sql's statement order by the shared ';' split
        fps = (stmt_stats.fingerprint_sql(sql)
               if stmt_stats.enabled() else [])
        try:
            # one span per statement batch: the root on wires that
            # carry no traceparent (mysql/postgres/flight), a child of
            # the server's request span on HTTP — and the trace_id the
            # slow-query log links back to
            with tracing.span("sql.execute", db=ctx.database,
                              channel=ctx.channel) as root:
                trace_id = root.trace_id or None
                for i, stmt in enumerate(parse_sql(sql)):
                    token = stmt_stats.bind_fingerprint(
                        fps[i] if i < len(fps) else None
                    )
                    try:
                        outputs.append(self.execute_statement(stmt, ctx))
                    finally:
                        stmt_stats.reset_fingerprint(token)
        finally:
            # duration from the monotonic perf counter (GT011), never
            # wall-clock arithmetic
            self.slow_query_log.maybe_record(
                sql, _time.perf_counter() - t0,
                db=ctx.database, channel=ctx.channel,
                trace_id=trace_id,
                fingerprint=fps[0].fp if fps else "",
            )
        return outputs

    def sql(self, sql: str, ctx: QueryContext | None = None) -> QueryResult:
        """Convenience: single-statement query returning a result set."""
        outs = self.execute_sql(sql, ctx)
        out = outs[-1]
        if out.result is None:
            return _result_from_lists(
                ["affected_rows"], [[out.affected_rows or 0]]
            )
        return out.result

    # ------------------------------------------------------------------
    def execute_statement(self, stmt: A.Statement, ctx: QueryContext
                          ) -> Output:
        from greptimedb_tpu.telemetry import stmt_stats, tracing

        from greptimedb_tpu import cancellation

        kind = type(stmt).__name__
        pid = self._process_list.register(kind, ctx)
        token = cancellation.set_check(
            lambda: self._process_list.check_killed(pid)
        )
        try:
            # one statement-statistics observation per statement:
            # everything the execution layers attribute (queue time,
            # exec path, compile/cache hits, transfer bytes, dist rpc
            # time) folds into the fingerprint's registry row on exit
            with stmt_stats.global_stmt_stats.observe(ctx, kind) as obs, \
                    tracing.span(f"sql.{kind}"):
                if isinstance(stmt, _ADMITTED_STATEMENTS):
                    # data-plane statements go through admission
                    # control (quota/slot/deadline); control-plane
                    # statements (SHOW/SET/USE/ADMIN kill...) bypass so
                    # an operator can still inspect and kill work on an
                    # overloaded instance
                    with self.scheduler.admit(ctx):
                        self._process_list.set_state(pid, "Running")
                        out = self._execute_statement(stmt, ctx)
                else:
                    self._process_list.set_state(pid, "Running")
                    out = self._execute_statement(stmt, ctx)
                if obs is not None:
                    obs.add("rows", out.result.num_rows
                            if out.result is not None
                            else (out.affected_rows or 0))
                return out
        finally:
            cancellation.reset(token)
            self._process_list.unregister(pid)

    def _execute_statement(self, stmt: A.Statement, ctx: QueryContext
                           ) -> Output:
        if isinstance(stmt, A.Select):
            return Output.records(self._select(stmt, ctx))
        if isinstance(stmt, A.SetOp):
            from greptimedb_tpu.query import relational

            return Output.records(relational.execute(self, stmt, ctx))
        if isinstance(stmt, A.CreateView):
            db, name = self._resolve(stmt.name, ctx)
            if stmt.text is None:
                raise UnsupportedError("CREATE VIEW requires query text")
            self.catalog.create_view(
                db, name, stmt.text, or_replace=stmt.or_replace
            )
            return Output.rows(0)
        if isinstance(stmt, A.DropView):
            db, name = self._resolve(stmt.name, ctx)
            self.catalog.drop_view(db, name, if_exists=stmt.if_exists)
            return Output.rows(0)
        if isinstance(stmt, A.Insert):
            return Output.rows(self._insert(stmt, ctx))
        if isinstance(stmt, A.Delete):
            return Output.rows(self._delete(stmt, ctx))
        if isinstance(stmt, A.CreateTable):
            self._create_table(stmt, ctx)
            return Output.rows(0)
        if isinstance(stmt, A.CreateDatabase):
            self.catalog.create_database(
                stmt.name, if_not_exists=stmt.if_not_exists
            )
            return Output.rows(1)
        if isinstance(stmt, A.DropDatabase):
            self.catalog.drop_database(stmt.name, if_exists=stmt.if_exists)
            return Output.rows(0)
        if isinstance(stmt, A.DropTable):
            for name in stmt.names:
                db, tname = self._resolve(name, ctx)
                self.catalog.drop_table(db, tname, if_exists=stmt.if_exists)
            return Output.rows(0)
        if isinstance(stmt, A.TruncateTable):
            db, tname = self._resolve(stmt.name, ctx)
            self.catalog.table(db, tname).truncate()
            return Output.rows(0)
        if isinstance(stmt, A.AlterTable):
            return Output.rows(self._alter(stmt, ctx))
        if isinstance(stmt, A.Use):
            if not self.catalog.has_database(stmt.database):
                raise DatabaseNotFoundError(
                    f"database not found: {stmt.database}"
                )
            ctx.database = stmt.database
            return Output.rows(0)
        if isinstance(stmt, A.ShowDatabases):
            return Output.records(self._show_databases(stmt))
        if isinstance(stmt, A.ShowTables):
            return Output.records(self._show_tables(stmt, ctx))
        if isinstance(stmt, A.ShowCreateTable):
            return Output.records(self._show_create_table(stmt, ctx))
        if isinstance(stmt, A.DescribeTable):
            return Output.records(self._describe(stmt, ctx))
        if isinstance(stmt, A.Explain):
            return Output.records(self._explain(stmt, ctx))
        if isinstance(stmt, A.Tql):
            return Output.records(self._tql(stmt, ctx))
        if isinstance(stmt, A.CreateFlow):
            return self._create_flow(stmt, ctx)
        if isinstance(stmt, A.DropFlow):
            return self._drop_flow(stmt, ctx)
        if isinstance(stmt, A.ShowFlows):
            return Output.records(self._show_flows())
        if isinstance(stmt, A.ShowViews):
            return Output.records(_result_from_lists(
                ["Views"], [self.catalog.view_names(ctx.database)]
            ))
        if isinstance(stmt, A.ShowCreateFlow):
            if self.flows is None:
                raise UnsupportedError("flows are not enabled")
            flow = self.flows.maybe_flow(stmt.name)
            if flow is None:
                raise TableNotFoundError(f"flow not found: {stmt.name}")
            return Output.records(_result_from_lists(
                ["Flow", "Create Flow"], [[stmt.name], [flow.raw_sql]]
            ))
        if isinstance(stmt, A.ShowCreateView):
            db, name = self._resolve(stmt.name, ctx)
            sql_text = self.catalog.maybe_view(db, name)
            if sql_text is None:
                raise TableNotFoundError(f"view not found: {name}")
            return Output.records(_result_from_lists(
                ["View", "Create View"],
                [[name], [f"CREATE VIEW {name} AS {sql_text}"]],
            ))
        if isinstance(stmt, A.Copy):
            return Output.rows(self._copy(stmt, ctx))
        if isinstance(stmt, A.Admin):
            return self._admin(stmt, ctx)
        if isinstance(stmt, A.SetVariable):
            return self._set_variable(stmt, ctx)
        if isinstance(stmt, A.ShowVariables):
            return Output.records(self._show_variables(stmt, ctx))
        if isinstance(stmt, A.ShowColumns):
            return Output.records(self._show_columns(stmt, ctx))
        if isinstance(stmt, A.ShowIndex):
            return Output.records(self._show_index(stmt, ctx))
        if isinstance(stmt, A.ShowStatus):
            return Output.records(_result_from_lists(
                ["Variable_name", "Value"], [["Uptime"], ["0"]]
            ))
        if isinstance(stmt, A.ShowCharset):
            return Output.records(_result_from_lists(
                ["Charset", "Description", "Default collation", "Maxlen"],
                [["utf8mb4"], ["UTF-8 Unicode"], ["utf8mb4_bin"], [4]],
            ))
        if isinstance(stmt, A.ShowCollation):
            return Output.records(_result_from_lists(
                ["Collation", "Charset", "Id", "Default", "Compiled",
                 "Sortlen"],
                [["utf8mb4_bin"], ["utf8mb4"], [46], ["Yes"], ["Yes"], [1]],
            ))
        if isinstance(stmt, A.ShowProcesslist):
            return Output.records(self._show_processlist(stmt))
        if isinstance(stmt, A.Prepare):
            ctx.extensions.setdefault("prepared", {})[
                stmt.name.lower()
            ] = stmt.sql_text
            return Output.rows(0)
        if isinstance(stmt, A.Execute):
            prepared = ctx.extensions.get("prepared", {})
            text = prepared.get(stmt.name.lower())
            if text is None:
                raise InvalidArgumentError(
                    f"prepared statement {stmt.name!r} does not exist"
                )
            args = [eval_const(a) for a in stmt.args]
            sub = substitute_placeholders(text, args)
            stmts = parse_sql(sub)
            if len(stmts) != 1:
                raise InvalidArgumentError(
                    "prepared statement must be a single statement"
                )
            return self._execute_statement(stmts[0], ctx)
        if isinstance(stmt, A.Deallocate):
            prepared = ctx.extensions.get("prepared", {})
            if stmt.name == "all":
                prepared.clear()
            elif prepared.pop(stmt.name.lower(), None) is None:
                raise InvalidArgumentError(
                    f"prepared statement {stmt.name!r} does not exist"
                )
            return Output.rows(0)
        raise UnsupportedError(
            f"statement not supported yet: {type(stmt).__name__}"
        )

    # ------------------------------------------------------------------
    # ADMIN maintenance functions (reference:
    # src/sql/src/statements/admin.rs dispatching to the admin function
    # set — flush/compact region + table, migrate_region)
    # ------------------------------------------------------------------
    def _admin(self, stmt: A.Admin, ctx: QueryContext) -> Output:
        def arg(i: int) -> A.Expr:
            if i >= len(stmt.args):
                raise InvalidArgumentError(
                    f"admin {stmt.func}: missing argument {i + 1}"
                )
            return stmt.args[i]

        def const_str(i: int) -> str:
            v = eval_const(arg(i))
            if not isinstance(v, str):
                raise InvalidArgumentError(
                    f"admin {stmt.func}: arg {i} must be a string"
                )
            return v

        def const_int(i: int) -> int:
            v = eval_const(arg(i))
            if isinstance(v, bool) or not isinstance(v, (int, np.integer)):
                raise InvalidArgumentError(
                    f"admin {stmt.func}: arg {i} must be an integer"
                )
            return int(v)

        name = stmt.func
        if name in ("flush_table", "compact_table"):
            ident = const_str(0)
            db, tname = self._resolve(ident, ctx)
            table = self.catalog.table(db, tname)
            # ride the engine's bounded compaction pool: regions fan
            # out under the same concurrency cap as background merges
            # ([compaction] workers — at the default of 1 they
            # serialize, and an in-flight background merge is awaited
            # first). Errors stay typed across every wire
            # ([gtdb:<code>]). ADMIN compaction is FORCED: every
            # multi-file window merges to the top level.
            sched = self.engine.compaction
            if name == "flush_table":
                results = sched.map_sync(
                    lambda r: r.flush() is not None, table.regions
                )
            else:
                results = sched.map_sync(
                    lambda r: bool(r.compact(force=True)), table.regions
                )
            n = sum(1 for ok in results if ok)
            return Output.records(_result_from_lists(
                [f"ADMIN {name}('{ident}')"], [[n]]
            ))
        if name in ("flush_region", "compact_region"):
            rid = const_int(0)
            region = self._region_by_id(rid)
            if name == "flush_region":
                n = 1 if region.flush() is not None else 0
            else:
                n = 1 if region.compact(force=True) else 0
            return Output.records(_result_from_lists(
                [f"ADMIN {name}({rid})"], [[n]]
            ))
        if name == "flush_flow":
            fname = const_str(0)
            n = 1 if self._flush_flow_admin(fname) else 0
            return Output.records(_result_from_lists(
                [f"ADMIN flush_flow('{fname}')"], [[n]]
            ))
        if name == "migrate_region":
            metasrv = getattr(self, "metasrv", None)
            if metasrv is None:
                raise UnsupportedError(
                    "migrate_region requires a metasrv-managed cluster"
                )
            rid, to_node = const_int(0), const_int(1)
            pid = metasrv.migrate_region(rid, to_node)
            return Output.records(_result_from_lists(
                [f"ADMIN migrate_region({rid}, {to_node})"], [[str(pid)]]
            ))
        if name == "kill":
            target = eval_const(arg(0))
            ok = self._process_list.kill(str(target))
            return Output.records(_result_from_lists(
                [f"ADMIN kill('{target}')"], [[1 if ok else 0]]
            ))
        if name == "set_config":
            # the validated runtime-knob update API (autotune/knobs.py):
            # typed bounds, change log, gtpu_autotune_knob_value —
            # the same single write path the controllers use
            path = const_str(0)
            value = eval_const(arg(1))
            old, new = self.knobs.set(path, value, source="admin")
            return Output.records(_result_from_lists(
                [f"ADMIN set_config('{path}')"], [[f"{old} -> {new}"]]
            ))
        if name == "autotune_freeze":
            # hard freeze: controllers stop moving knobs until
            # autotune_unfreeze(); set_config stays available
            self.autotune.freeze(True)
            return Output.records(_result_from_lists(
                ["ADMIN autotune_freeze()"], [[1]]
            ))
        if name == "autotune_unfreeze":
            self.autotune.freeze(False)
            return Output.records(_result_from_lists(
                ["ADMIN autotune_unfreeze()"], [[1]]
            ))
        if name == "reset_device_profiler":
            # drops every device-program registry row; the exported
            # gtpu_device_program_* series zero at the next scrape so
            # all three surfaces stay equal (documented counter reset)
            from greptimedb_tpu.telemetry.device_programs import (
                global_programs,
            )

            n = global_programs.reset()
            return Output.records(_result_from_lists(
                ["ADMIN reset_device_profiler()"], [[n]]
            ))
        if name == "reset_statement_statistics":
            # pg_stat_statements_reset() analog: drops every registry
            # row; the monotone gtpu_stmt_* counters keep counting
            from greptimedb_tpu.telemetry.stmt_stats import (
                global_stmt_stats,
            )

            n = global_stmt_stats.reset()
            return Output.records(_result_from_lists(
                ["ADMIN reset_statement_statistics()"], [[n]]
            ))
        raise UnsupportedError(f"unknown admin function {name!r}")

    def _set_variable(self, stmt: A.SetVariable, ctx: QueryContext
                      ) -> Output:
        for name, value_expr in stmt.assignments:
            value = eval_const(value_expr)
            if name in ("time_zone", "timezone", "session_time_zone"):
                ctx.timezone = str(value)
                ctx.variables["time_zone"] = str(value)
            else:
                ctx.variables[name] = (
                    value if isinstance(value, str) else str(value)
                )
        return Output.rows(0)

    def _show_variables(self, stmt: A.ShowVariables, ctx: QueryContext):
        from greptimedb_tpu.query.expr import like_to_regex
        from greptimedb_tpu.session import DEFAULT_VARIABLES

        merged = dict(DEFAULT_VARIABLES)
        merged.update(ctx.variables)
        items = sorted(merged.items())
        if stmt.like:
            pat = like_to_regex(stmt.like.lower())
            items = [
                (k, v) for k, v in items if pat.fullmatch(k.lower())
            ]
        return _result_from_lists(
            ["Variable_name", "Value"],
            [[k for k, _ in items], [v for _, v in items]],
        )

    def _show_columns(self, stmt: A.ShowColumns, ctx: QueryContext):
        from greptimedb_tpu.query.expr import like_to_regex

        db = stmt.database or ctx.database
        table = self.catalog.table(db, stmt.table)
        pat = like_to_regex(stmt.like.lower()) if stmt.like else None
        names, types, nulls, keys, defaults, semantics = [], [], [], [], [], []
        for cs in table.schema.columns:
            if pat is not None and not pat.fullmatch(cs.name.lower()):
                continue
            names.append(cs.name)
            types.append(cs.data_type.name)
            nulls.append("Yes" if cs.nullable else "No")
            if cs.semantic_type == SemanticType.TIMESTAMP:
                keys.append("TIME INDEX")
            elif cs.semantic_type == SemanticType.TAG:
                keys.append("PRI")
            else:
                keys.append("")
            defaults.append(default_display(cs.default))
            semantics.append(cs.semantic_type.name)
        cols = [names, types, nulls, keys, defaults]
        headers = ["Column", "Type", "Null", "Key", "Default"]
        if stmt.full:
            headers.append("Semantic Type")
            cols.append(semantics)
        return _result_from_lists(headers, cols)

    def _show_index(self, stmt: A.ShowIndex, ctx: QueryContext):
        db = stmt.database or ctx.database
        table = self.catalog.table(db, stmt.table)
        names, key_names, seqs = [], [], []
        for i, tag in enumerate(table.tag_names):
            names.append(stmt.table)
            key_names.append("PRIMARY")
            seqs.append(i + 1)
        names.append(stmt.table)
        key_names.append("TIME INDEX")
        seqs.append(1)
        cols = [names, key_names, seqs,
                table.tag_names + [table.ts_name]]
        return _result_from_lists(
            ["Table", "Key_name", "Seq_in_index", "Column_name"], cols
        )

    def _show_processlist(self, stmt: A.ShowProcesslist):
        entries = self._process_list.snapshot()
        return _result_from_lists(
            ["Id", "User", "db", "Command", "State", "Time", "Info"],
            [[e["id"] for e in entries],
             [e["user"] for e in entries],
             [e["db"] for e in entries],
             ["Query"] * len(entries),
             [e.get("state", "Running") for e in entries],
             [round(e["elapsed_s"], 3) for e in entries],
             [e["query"] for e in entries]],
        )

    # ------------------------------------------------------------------
    # COPY TO/FROM (reference: src/operator/src/statement/copy_table_*.rs
    # + src/common/datasource format readers/writers)
    # ------------------------------------------------------------------
    def _copy(self, stmt: A.Copy, ctx: QueryContext) -> int:
        import pyarrow as pa

        db, name = self._resolve(stmt.table, ctx)
        table = self.catalog.table(db, name)
        fmt = stmt.format
        if stmt.direction == "to":
            res = self._select(A.Select(
                items=[A.SelectItem(A.Star())], from_table=stmt.table,
            ), ctx)
            arrays = {}
            for i, n in enumerate(res.names):
                col = res.cols[i]
                cs = table.schema.maybe_column(n)
                mask = None if col.validity is None else ~col.validity
                if cs is not None and cs.data_type.is_timestamp():
                    arrays[n] = pa.array(
                        col.values.astype("datetime64[ms]"), mask=mask
                    )
                elif cs is not None and cs.data_type.is_decimal():
                    arrays[n] = pa.array(
                        np.asarray(col.values, np.float64), mask=mask
                    ).cast(cs.data_type.to_arrow(), safe=False)
                else:
                    arrays[n] = pa.array(col.values, mask=mask)
            pa_table = pa.table(arrays)
            return _write_format(pa_table, stmt.path, fmt)
        # COPY FROM
        pa_table = _read_format(stmt.path, fmt)
        data = {}
        valid = {}
        from greptimedb_tpu.datatypes.batch import HostColumn

        for n in pa_table.column_names:
            if n not in table.schema:
                continue
            hc = HostColumn.from_arrow(n, pa_table.column(n))
            vals = hc.values
            if hc.data_type.is_timestamp():
                # normalize to ms regardless of the file's inferred unit;
                # divide first (ns ticks * 1000 would overflow int64)
                tps = hc.data_type.ticks_per_second
                if tps >= 1000:
                    vals = vals // (tps // 1000)
                else:
                    vals = vals * (1000 // tps)
            data[n] = vals
            valid[n] = hc.valid_mask
        written = self._write_columns(table, data, valid)
        self._notify_flows(db, name, table, data, valid)
        return written

    # ------------------------------------------------------------------
    # DDL
    # ------------------------------------------------------------------
    def _create_table(self, stmt: A.CreateTable, ctx: QueryContext):
        db, name = self._resolve(stmt.name, ctx)
        if stmt.like_table is not None:
            # CREATE TABLE t LIKE s: clone the source's schema + options
            # (reference: src/operator/src/statement.rs CreateTableLike)
            sdb, sname = self._resolve(stmt.like_table, ctx)
            src = self.catalog.table(sdb, sname)
            self.catalog.create_table(
                db, name, Schema(list(src.schema.columns)),
                engine=src.info.engine,
                options=dict(src.info.options),
                num_regions=len(src.regions),
                if_not_exists=stmt.if_not_exists,
                partition=src.info.partition,
            )
            return
        cols = []
        pk = set(stmt.primary_keys)
        for cd in stmt.columns:
            if cd.time_index or (stmt.time_index == cd.name):
                sem = SemanticType.TIMESTAMP
            elif cd.primary_key or cd.name in pk:
                sem = SemanticType.TAG
            else:
                sem = SemanticType.FIELD
            if sem == SemanticType.TAG and not cd.data_type.is_string():
                # numeric tags are legal in the reference; stored as strings
                # in the series registry (dense-sid design), decoded on read.
                pass
            cols.append(ColumnSchema(
                name=cd.name, data_type=cd.data_type, semantic_type=sem,
                nullable=cd.nullable and sem == SemanticType.FIELD,
                default=_const_default(cd.default), fulltext=cd.fulltext,
            ))
        schema = Schema(cols)
        num_regions = 1
        partition = None
        if stmt.partitions:
            from greptimedb_tpu.catalog.partition import PartitionRule

            num_regions = max(1, len(stmt.partitions))
            rule = PartitionRule.from_ast(
                stmt.partition_columns, stmt.partitions
            )
            for c in rule.columns:
                col = schema.maybe_column(c)
                if col is None or not col.is_tag:
                    raise InvalidArgumentError(
                        f"PARTITION ON column {c!r} must be a tag "
                        "(PRIMARY KEY) column"
                    )
            partition = rule.to_json()
        elif "num_regions" in stmt.options:
            num_regions = int(stmt.options.pop("num_regions"))
        self.catalog.create_table(
            db, name, schema, engine=stmt.engine, options=stmt.options,
            num_regions=num_regions, if_not_exists=stmt.if_not_exists,
            partition=partition,
        )

    def _alter(self, stmt: A.AlterTable, ctx: QueryContext) -> int:
        db, name = self._resolve(stmt.name, ctx)
        if stmt.action == "add_column":
            cd = stmt.column
            sem = SemanticType.TAG if cd.primary_key else SemanticType.FIELD
            self.catalog.alter_add_column(db, name, ColumnSchema(
                name=cd.name, data_type=cd.data_type, semantic_type=sem,
                nullable=True, default=_const_default(cd.default),
            ))
        elif stmt.action == "drop_column":
            self.catalog.alter_drop_column(db, name, stmt.old_name)
        elif stmt.action == "rename":
            self.catalog.rename_table(db, name, stmt.new_name)
        else:
            raise UnsupportedError(f"ALTER action: {stmt.action}")
        return 0

    # ------------------------------------------------------------------
    # DML
    # ------------------------------------------------------------------
    def _insert(self, stmt: A.Insert, ctx: QueryContext) -> int:
        db, name = self._resolve(stmt.table, ctx)
        table = self.catalog.table(db, name)
        schema = table.schema
        if stmt.select is not None:
            res = self._select(stmt.select, ctx)
            cols = stmt.columns or res.names
            data = {
                c: np.asarray(col.values)
                for c, col in zip(cols, res.cols)
            }
            valid = {
                c: col.valid_mask
                for c, col in zip(cols, res.cols)
            }
            _apply_defaults(schema, data, valid, res.num_rows)
            written = self._write_columns(table, data, valid)
            self._notify_flows(db, name, table, data, valid)
            return written

        cols = stmt.columns or schema.column_names
        n = len(stmt.values)
        raw = {c: [] for c in cols}
        for row in stmt.values:
            if len(row) != len(cols):
                raise InvalidArgumentError(
                    f"INSERT row has {len(row)} values, expected {len(cols)}"
                )
            for c, e in zip(cols, row):
                raw[c].append(eval_const(e))
        data = {}
        valid = {}
        for c, vals in raw.items():
            col_schema = schema.column(c)
            arr, v = _coerce_insert(vals, col_schema.data_type)
            data[c] = arr
            valid[c] = v
        _apply_defaults(schema, data, valid, n)
        written = self._write_columns(table, data, valid)
        self._notify_flows(db, name, table, data, valid)
        return written

    def _write_columns(self, table, data: dict, valid: dict) -> int:
        schema = table.schema
        ts_name = schema.time_index.name
        if ts_name not in data:
            raise InvalidArgumentError(
                f"INSERT missing TIME INDEX column {ts_name}"
            )
        n = len(data[ts_name])
        tags = {}
        fields = {}
        fvalid = {}
        for cname, arr in data.items():
            cs = schema.column(cname)
            if cs.is_time_index:
                continue
            if cs.is_tag:
                tags[cname] = np.asarray(
                    ["" if v is None else str(v) for v in arr], object
                )
            else:
                fields[cname] = arr
                if cname in valid and not valid[cname].all():
                    fvalid[cname] = valid[cname]
        ts = np.asarray(data[ts_name], np.int64)
        return table.write(tags, ts, fields, field_valid=fvalid or None)

    def _delete(self, stmt: A.Delete, ctx: QueryContext) -> int:
        db, name = self._resolve(stmt.table, ctx)
        table = self.catalog.table(db, name)
        # select the matching (tags, ts) then write tombstones
        sel = A.Select(
            items=[A.SelectItem(A.Column(t)) for t in table.tag_names]
            + [A.SelectItem(A.Column(table.ts_name))],
            from_table=stmt.table, where=stmt.where,
        )
        res = self._select(sel, ctx)
        if res.num_rows == 0:
            return 0
        tags = {
            t: np.asarray(res.cols[i].values, object)
            for i, t in enumerate(table.tag_names)
        }
        ts = np.asarray(res.cols[-1].values, np.int64)
        table.delete(tags, ts)
        return len(ts)

    def _notify_flows(self, db, name, table, data, valid):
        if self.flows is not None:
            self.flows.on_insert(db, name, table, data, valid)

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------
    def _select(self, stmt: A.Select, ctx: QueryContext) -> QueryResult:
        from greptimedb_tpu.query import relational

        if relational.needs_relational(self, stmt, ctx):
            return relational.execute(self, stmt, ctx)
        return self._select_single(stmt, ctx)

    def _select_single(self, stmt: A.Select, ctx: QueryContext) -> QueryResult:
        """Single-table fast path: plan straight onto the storage scan +
        device grid caches."""
        table = None
        ts_name = None
        tag_names: list[str] = []
        all_columns = None
        if stmt.from_table:
            if self._is_information_schema(stmt.from_table, ctx):
                return self._query_information_schema(stmt, ctx)
            if self._is_pg_catalog(stmt.from_table, ctx):
                from greptimedb_tpu.information_schema import (
                    query_pg_catalog,
                )

                return query_pg_catalog(self, stmt, ctx)
            db, name = self._resolve(stmt.from_table, ctx)
            table = self.catalog.table(db, name)
            ts_name = table.ts_name
            tag_names = table.tag_names
            all_columns = table.schema.column_names
        from greptimedb_tpu.telemetry import tracing

        with tracing.child_span("query.plan",
                                table=stmt.from_table or ""):
            plan = plan_select(
                stmt, ts_name=ts_name, tag_names=tag_names,
                all_columns=all_columns,
            )
        return self._execute_select_plan(plan, table, ctx)

    def _execute_select_plan(self, plan, table, ctx: QueryContext):
        """Run a planned single-table SELECT through the device-resident
        result path: frontend result cache first (query/result_cache.py
        — a repeated poll on unchanged physical versions never touches
        the datanode or the device), then the `since` delta cursor bound
        for the execution layers (sliced device readback / scan ts
        tightening)."""
        from greptimedb_tpu.query import sessions
        from greptimedb_tpu.query import stats as qstats
        from greptimedb_tpu.telemetry import stmt_stats, tracing

        since = ctx.extensions.get("since_ms")
        rc = getattr(self, "result_cache", None)
        fp = versions = None
        # EXPLAIN ANALYZE collects real execution stats: bypass so its
        # metrics reflect an actual run, never a cached payload
        use_cache = (rc is not None and rc.eligible(plan, table)
                     and qstats.active() is None)
        if use_cache and since is not None:
            from greptimedb_tpu.query import result_cache as RC

            # a since-poll can only be served from the cached FULL
            # payload when the host row filter is equivalent to the
            # execution-path cursor (applied BEFORE ORDER BY/LIMIT):
            # LIMIT/OFFSET plans and row-returning plans that do not
            # project the time index must execute the delta instead.
            # Aggregates ignore the cursor entirely, so their cached
            # payload stays equivalent.
            if plan.kind != "aggregate" and (
                plan.limit is not None or bool(plan.offset)
                or RC.ts_output_name(plan, table) is None
            ):
                use_cache = False
        if use_cache:
            from greptimedb_tpu.query import result_cache as RC

            db = table.info.database
            fp = RC.plan_fingerprint(plan)
            try:
                versions = rc.current_versions(table)
            except Exception:  # noqa: BLE001 - datanode down/unreachable
                # version validation must never own failure semantics:
                # the execution path below maps unreachable datanodes to
                # the typed unavailable error or a degraded partial
                # result ([scheduler] allow_partial_results)
                use_cache = False
                versions = None
        if use_cache:
            entry = rc.get(db, table, fp, versions)
            if entry is not None:
                tracing.set_attr(result_cache="hit")
                qstats.note("result_cache", "hit")
                stmt_stats.add("result_cache_hits")
                # truthful path attribution: the cached payload came
                # from this execution path (bench/EXPLAIN assertions)
                self.query_engine.last_exec_path = entry.exec_path
                res = entry.result
                if since is not None:
                    res = RC.filter_since(res, entry.ts_name, since)
                return res
            tracing.set_attr(result_cache="miss")
            qstats.note("result_cache", "miss")
            stmt_stats.add("result_cache_misses")
        elif rc is not None and rc.enabled:
            tracing.set_attr(result_cache="bypass")
            qstats.note("result_cache", "bypass")
            stmt_stats.add("result_cache_bypass")
        token = sessions.bind_since(since) if since is not None else None
        try:
            res = self._run_select_plan(plan, table)
        finally:
            if token is not None:
                sessions.reset_since(token)
        if use_cache and since is None and not getattr(res, "partial",
                                                       False):
            # only FULL, complete results are cached: a delta answer
            # under a cursor (or a degraded partial) must never be
            # served as the statement's payload
            from greptimedb_tpu.query import result_cache as RC

            rc.put(table.info.database, table, fp, versions, res,
                   RC.ts_output_name(plan, table),
                   self.query_engine.last_exec_path)
        return res

    def _run_select_plan(self, plan, table):
        if table is not None and getattr(table, "remote", False):
            # distributed tables: try the MergeScan split first (partial
            # plans execute datanode-side, only partial states cross the
            # wire); None falls through to remote region scans
            from greptimedb_tpu.dist.dist_query import try_dist_query

            res = try_dist_query(self, plan, table)
            if res is not None:
                return res
        return self.query_engine.execute(plan, table)

    def plan(self, stmt: A.Select, ctx: QueryContext):
        table = None
        ts_name, tag_names, all_columns = None, [], None
        if stmt.from_table:
            db, name = self._resolve(stmt.from_table, ctx)
            table = self.catalog.table(db, name)
            ts_name, tag_names = table.ts_name, table.tag_names
            all_columns = table.schema.column_names
        return plan_select(stmt, ts_name=ts_name, tag_names=tag_names,
                           all_columns=all_columns), table

    def _explain(self, stmt: A.Explain, ctx: QueryContext) -> QueryResult:
        if not isinstance(stmt.statement, (A.Select, A.SetOp)):
            raise UnsupportedError("EXPLAIN supports SELECT only")
        if isinstance(stmt.statement, A.Select) and not (
            stmt.statement.ctes or isinstance(
                stmt.statement.source, (A.JoinSource, A.SubquerySource)
            )
        ):
            plan, _ = self.plan(stmt.statement, ctx)
            lines = plan.explain_lines()
        else:
            lines = ["SelectPlan[relational]"]
        if stmt.analyze:
            import time as _time

            from greptimedb_tpu.query import stats as qstats
            from greptimedb_tpu.telemetry import tracing

            t0 = _time.perf_counter()
            with qstats.collect() as st, tracing.export_spans() as tspans:
                # stamp the ANALYZED statement's fingerprint so the
                # rendered metrics join its statement_statistics row
                # (the inner fingerprint: "EXPLAIN ANALYZE <q>" and a
                # plain "<q>" share it)
                from greptimedb_tpu.telemetry import stmt_stats

                sfp = stmt_stats.explain_fingerprint()
                if sfp:
                    st.note("stmt_fingerprint", sfp)
                if isinstance(stmt.statement, A.SetOp):
                    from greptimedb_tpu.query import relational

                    res = relational.execute(self, stmt.statement, ctx)
                else:
                    res = self._select(stmt.statement, ctx)
            dt = (_time.perf_counter() - t0) * 1000
            lines.append(
                f"  Metrics: rows={res.num_rows} elapsed={dt:.3f}ms"
            )
            lines.extend(st.lines())
            if tspans:
                # the span tree of THIS execution, inline (sched queue,
                # scan cache hit/miss, fan-out, device compile/execute/
                # transfer) — same spans /v1/traces serves
                tid = tracing.current_trace_id()
                remote = tracing.global_traces.trace(tid) if tid else []
                local_ids = {s.span_id for s in tspans}
                docs = [s.to_json() for s in tspans] + [
                    d for d in remote
                    if d["span_id"] not in local_ids
                    and d.get("duration_ms") is not None
                ]
                lines.append(f"  Trace: {tid or '(sampling disabled)'}")
                lines.extend(
                    "    " + ln for ln in tracing.render_tree(docs)
                )
        return _result_from_lists(["plan"], [lines])

    def _tql(self, stmt: A.Tql, ctx: QueryContext) -> QueryResult:
        try:
            from greptimedb_tpu.promql.engine import PromEngine
        except ImportError as e:
            raise UnsupportedError(f"TQL requires the promql module: {e}")

        start = _tql_time(stmt.start)
        end = _tql_time(stmt.end)
        step_ms = _tql_interval(stmt.step)
        lookback_ms = (
            _tql_interval(stmt.lookback) if stmt.lookback is not None
            else 300_000
        )
        engine = PromEngine(self, ctx)
        if stmt.kind == "explain":
            from greptimedb_tpu.promql.parser import parse_promql

            return _result_from_lists(
                ["plan"], [[repr(parse_promql(stmt.query))]]
            )
        return engine.query_range_result(
            stmt.query, start, end, step_ms, lookback_ms=lookback_ms
        )

    # ------------------------------------------------------------------
    # SHOW / DESCRIBE
    # ------------------------------------------------------------------
    def _show_databases(self, stmt: A.ShowDatabases) -> QueryResult:
        names = self.catalog.database_names()
        if stmt.like:
            from greptimedb_tpu.query.expr import like_to_regex

            rx = like_to_regex(stmt.like)
            names = [n for n in names if rx.fullmatch(n)]
        return _result_from_lists(["Database"], [names])

    def _show_tables(self, stmt: A.ShowTables, ctx: QueryContext
                     ) -> QueryResult:
        db = stmt.database or ctx.database
        names = self.catalog.table_names(db)
        if stmt.like:
            from greptimedb_tpu.query.expr import like_to_regex

            rx = like_to_regex(stmt.like)
            names = [n for n in names if rx.fullmatch(n)]
        return _result_from_lists(["Tables"], [names])

    def _describe(self, stmt: A.DescribeTable, ctx: QueryContext
                  ) -> QueryResult:
        db, name = self._resolve(stmt.name, ctx)
        table = self.catalog.table(db, name)
        names, types, keys, nulls, defaults, semantics = [], [], [], [], [], []
        for c in table.schema.columns:
            names.append(c.name)
            types.append(_sql_type_name(c.data_type))
            keys.append("PRI" if c.is_tag or c.is_time_index else "")
            nulls.append("YES" if c.nullable else "NO")
            defaults.append(default_display(c.default))
            semantics.append(
                "TIMESTAMP" if c.is_time_index
                else ("TAG" if c.is_tag else "FIELD")
            )
        return _result_from_lists(
            ["Column", "Type", "Key", "Null", "Default", "Semantic Type"],
            [names, types, keys, nulls, defaults, semantics],
        )

    def _show_create_table(self, stmt: A.ShowCreateTable, ctx: QueryContext
                           ) -> QueryResult:
        db, name = self._resolve(stmt.name, ctx)
        table = self.catalog.table(db, name)
        lines = [f"CREATE TABLE IF NOT EXISTS `{name}` ("]
        defs = []
        for c in table.schema.columns:
            d = f"  `{c.name}` {_sql_type_name(c.data_type)}"
            if not c.nullable:
                d += " NOT NULL"
            dflt = default_sql(c.default)
            if dflt is not None:
                d += f" DEFAULT {dflt}"
            defs.append(d)
        ts = table.schema.time_index.name
        defs.append(f"  TIME INDEX (`{ts}`)")
        if table.tag_names:
            defs.append(
                "  PRIMARY KEY (" +
                ", ".join(f"`{t}`" for t in table.tag_names) + ")"
            )
        lines.append(",\n".join(defs))
        lines.append(")")
        part = getattr(table.info, "partition", None)
        if part:
            cols_txt = ", ".join(f"`{c}`" for c in part["columns"])
            lines.append(
                f"PARTITION ON COLUMNS ({cols_txt}) ("
                + ", ".join(part["exprs"]) + ")"
            )
        lines.append(f"ENGINE={table.info.engine}")
        if table.info.options:
            opts = ", ".join(
                f"{k!r}={v!r}" for k, v in table.info.options.items()
            )
            lines.append(f"WITH({opts})")
        return _result_from_lists(
            ["Table", "Create Table"], [[name], ["\n".join(lines)]]
        )

    # ------------------------------------------------------------------
    # information_schema
    # ------------------------------------------------------------------
    def _is_information_schema(self, name: str, ctx: QueryContext) -> bool:
        if "." in name:
            return name.split(".", 1)[0].lower() == "information_schema"
        return ctx.database.lower() == "information_schema"

    def _is_pg_catalog(self, name: str, ctx: QueryContext) -> bool:
        """pg_catalog shims for psql/ORM introspection (reference:
        src/catalog/src/system_schema/pg_catalog/). Bare names resolve
        here only when no user table shadows them."""
        from greptimedb_tpu.information_schema import PG_CATALOG_TABLES

        if "." in name:
            return name.split(".", 1)[0].lower() == "pg_catalog"
        low = name.lower()
        if low not in PG_CATALOG_TABLES:
            return False
        try:
            db, tname = self._resolve(name, ctx)
            return self.catalog.maybe_table(db, tname) is None
        except Exception:  # noqa: BLE001 - unresolvable db: serve shim
            return True

    def _query_information_schema(self, stmt: A.Select, ctx: QueryContext
                                  ) -> QueryResult:
        from greptimedb_tpu.information_schema import query_information_schema

        return query_information_schema(self, stmt, ctx)

    # ------------------------------------------------------------------
    # flows (wired by flow.FlowManager; stubs raise otherwise)
    # ------------------------------------------------------------------
    def enable_flows(self, *, tick_interval_s: float | None = None):
        if self.flows is None:
            try:
                from greptimedb_tpu.flow import FlowManager
            except ImportError as e:
                raise UnsupportedError(
                    f"flows require the flow module: {e}"
                )
            self.flows = FlowManager(self, tick_interval_s=tick_interval_s)
        elif tick_interval_s is not None:
            # retarget the running ticker; takes effect at its next wait
            self.flows.tick_interval_s = tick_interval_s
        return self.flows

    def _flush_flow_admin(self, fname: str) -> bool:
        """ADMIN flush_flow on the local flow manager; DistInstance
        overrides to forward to the routed flownode."""
        if self.flows is None:
            raise UnsupportedError("flows are not enabled")
        return self.flows.flush_flow(fname)

    def _create_flow(self, stmt: A.CreateFlow, ctx: QueryContext) -> Output:
        self.enable_flows()
        self.flows.create_flow(stmt, ctx)
        return Output.rows(0)

    def _drop_flow(self, stmt: A.DropFlow, ctx: QueryContext) -> Output:
        self.enable_flows()
        self.flows.drop_flow(stmt.name, if_exists=stmt.if_exists)
        return Output.rows(0)

    def _show_flows(self) -> QueryResult:
        if self.flows is None:
            return _result_from_lists(["Flows"], [[]])
        return _result_from_lists(["Flows"], [self.flows.flow_names()])

    # ------------------------------------------------------------------
    def _region_by_id(self, rid: int):
        """Region handle for ADMIN by-id calls: the local engine's region
        in standalone; on a distributed frontend (which owns no storage)
        the catalog's remote-region proxy for that id."""
        from greptimedb_tpu.errors import RegionNotFoundError

        try:
            return self.engine.region(rid)
        except RegionNotFoundError:
            for db in self.catalog.database_names():
                for tname in self.catalog.table_names(db):
                    table = self.catalog.maybe_table(db, tname)
                    for region in (table.regions if table else []):
                        if region.meta.region_id == rid:
                            return region
            raise

    def _resolve(self, name: str, ctx: QueryContext) -> tuple[str, str]:
        if "." in name:
            db, t = name.split(".", 1)
            return db, t
        return ctx.database, name


def format_sql_literal(v) -> str:
    """Python value -> SQL literal text (prepared-statement binding).
    Backslashes are escaped because the lexer treats \\x as an escape
    inside strings — an unescaped trailing backslash would swallow the
    closing quote (injection risk on the wire paths)."""
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, (int, np.integer)):
        return str(int(v))
    if isinstance(v, (float, np.floating)):
        return repr(float(v))
    s = str(v).replace("\\", "\\\\").replace("'", "''")
    return f"'{s}'"


def _scan_sql_segments(text: str):
    """Yields ('text'|'quoted'|'qmark'|'dollar', segment) pieces; the ONE
    quoting state machine shared by placeholder substitution and the
    MySQL COM_STMT_PREPARE parameter counter."""
    import re as _re

    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "-" and text[i:i + 2] == "--":
            j = text.find("\n", i)
            j = n if j < 0 else j
            yield "text", text[i:j]
            i = j
            continue
        if c == "/" and text[i:i + 2] == "/*":
            j = text.find("*/", i + 2)
            j = n if j < 0 else j + 2
            yield "text", text[i:j]
            i = j
            continue
        if c in ("'", '"', "`"):
            close = c
            j = i + 1
            while j < n:
                if text[j] == close and j + 1 < n and text[j + 1] == close:
                    j += 2
                elif text[j] == "\\" and close == "'" and j + 1 < n:
                    j += 2
                elif text[j] == close:
                    break
                else:
                    j += 1
            yield "quoted", text[i:j + 1]
            i = j + 1
            continue
        if c == "?":
            yield "qmark", "?"
            i += 1
            continue
        if c == "$":
            m = _re.match(r"\$(\d+)", text[i:])
            if m:
                yield "dollar", m.group(1)
                i += m.end()
                continue
        yield "text", c
        i += 1


def count_placeholders(text: str) -> int:
    """`?` placeholders outside string/quoted-identifier regions."""
    return sum(1 for kind, _ in _scan_sql_segments(text) if kind == "qmark")


def substitute_placeholders(text: str, args: list) -> str:
    """Replace ? (positional) and $n placeholders outside string/quoted
    regions with literal-formatted args (PREPARE/EXECUTE binding — the
    reference binds through sqlparser placeholders; this engine binds at
    the text layer before parsing)."""
    out = []
    pos = 0  # next ? index
    for kind, seg in _scan_sql_segments(text):
        if kind == "qmark":
            if pos >= len(args):
                raise InvalidArgumentError(
                    f"not enough parameters: need > {pos}, have {len(args)}"
                )
            out.append(format_sql_literal(args[pos]))
            pos += 1
        elif kind == "dollar":
            k = int(seg)
            if not (1 <= k <= len(args)):
                raise InvalidArgumentError(
                    f"parameter ${k} out of range (have {len(args)})"
                )
            out.append(format_sql_literal(args[k - 1]))
        else:
            out.append(seg)
    return "".join(out)


def _const_default(default):
    """Normalize a DDL DEFAULT for catalog persistence: pure-literal
    expressions fold to plain values; expressions with function calls
    (now(), current_timestamp()...) persist as {"__expr__": text} and
    re-evaluate on EVERY insert — folding them would freeze the
    table-creation time into all future rows."""
    if not isinstance(default, A.Expr):
        return default

    def has_call(e) -> bool:
        if isinstance(e, A.FuncCall):
            return True
        if isinstance(e, A.BinaryOp):
            return has_call(e.left) or has_call(e.right)
        if isinstance(e, (A.UnaryOp, A.Cast, A.IsNull)):
            return has_call(e.operand)
        if isinstance(e, A.Between):
            return any(has_call(x) for x in (e.operand, e.low, e.high))
        if isinstance(e, A.InList):
            return has_call(e.operand) or any(has_call(x) for x in e.items)
        if isinstance(e, A.Case):
            parts = ([e.operand] if e.operand else []) \
                + [x for w in e.whens for x in w] \
                + ([e.else_] if e.else_ else [])
            return any(has_call(x) for x in parts)
        return False

    if has_call(default):
        return {"__expr__": _default_expr_sql(default)}
    return eval_const(default)


def _default_expr_sql(e: A.Expr) -> str:
    """Serialize a DEFAULT expression for round-trip re-parsing.
    Unlike format_expr (display names), every compound operand is
    parenthesized so precedence survives the round trip exactly."""
    if isinstance(e, A.BinaryOp):
        return (f"({_default_expr_sql(e.left)}) {e.op} "
                f"({_default_expr_sql(e.right)})")
    if isinstance(e, A.UnaryOp):
        return f"{e.op} ({_default_expr_sql(e.operand)})"
    if isinstance(e, A.Cast):
        return f"CAST(({_default_expr_sql(e.operand)}) AS {e.to.name})"
    if isinstance(e, A.FuncCall):
        args = ", ".join(f"({_default_expr_sql(a)})" for a in e.args)
        return f"{e.name}({args})"
    if isinstance(e, A.Case):
        parts = ["CASE"]
        if e.operand is not None:
            parts.append(f"({_default_expr_sql(e.operand)})")
        for c, t in e.whens:
            parts.append(f"WHEN ({_default_expr_sql(c)}) "
                         f"THEN ({_default_expr_sql(t)})")
        if e.else_ is not None:
            parts.append(f"ELSE ({_default_expr_sql(e.else_)})")
        parts.append("END")
        return " ".join(parts)
    if isinstance(e, A.IsNull):
        neg = " NOT" if e.negated else ""
        return f"({_default_expr_sql(e.operand)}) IS{neg} NULL"
    if isinstance(e, A.Between):
        neg = "NOT " if e.negated else ""
        return (f"({_default_expr_sql(e.operand)}) {neg}BETWEEN "
                f"({_default_expr_sql(e.low)}) AND "
                f"({_default_expr_sql(e.high)})")
    if isinstance(e, A.InList):
        neg = "NOT " if e.negated else ""
        items = ", ".join(f"({_default_expr_sql(x)})" for x in e.items)
        return f"({_default_expr_sql(e.operand)}) {neg}IN ({items})"
    from greptimedb_tpu.query.expr import format_expr

    return format_expr(e)


def default_display(default) -> str:
    """Human form of a stored default (SHOW/DESCRIBE)."""
    if default is None:
        return ""
    if isinstance(default, dict) and "__expr__" in default:
        return default["__expr__"]
    return str(default)


def default_sql(default) -> str | None:
    """DDL form of a stored default, exact enough that SHOW CREATE TABLE
    output re-parses to the same constraint (export->import must not
    drop defaults). String literals re-quote; dynamic defaults emit
    their expression text verbatim; None means no DEFAULT clause."""
    if default is None:
        return None
    if isinstance(default, dict) and "__expr__" in default:
        return default["__expr__"]
    return format_sql_literal(default)


import functools


@functools.lru_cache(maxsize=512)
def _parse_default_expr(text: str) -> A.Expr:
    # stored default text is immutable; parsing once keeps the hot
    # single-row insert path off the SQL tokenizer
    from greptimedb_tpu.sql.parser import Parser

    return Parser(text).expr()


def _eval_default(default):
    """Stored default -> concrete value for this insert."""
    if isinstance(default, dict) and "__expr__" in default:
        return eval_const(_parse_default_expr(default["__expr__"]))
    if isinstance(default, A.Expr):
        return eval_const(default)
    return default


def _apply_defaults(schema, data: dict, valid: dict, n: int):
    """Declared DEFAULTs fill columns omitted from an INSERT (explicit
    NULLs stay NULL — standard SQL, ref src/datatypes/src/schema/
    column_schema.rs default constraints). The time index participates
    too (TIMESTAMP TIME INDEX DEFAULT current_timestamp())."""
    for cs in schema.columns:
        if cs.name in data or cs.default is None:
            continue
        arr, v = _coerce_insert([_eval_default(cs.default)] * n,
                                cs.data_type)
        data[cs.name] = arr
        valid[cs.name] = v


def _coerce_insert(vals: list, dt: ConcreteDataType):
    n = len(vals)
    validity = np.asarray([v is not None for v in vals], bool)
    if dt.is_timestamp():
        out = np.zeros(n, np.int64)
        for i, v in enumerate(vals):
            if v is None:
                continue
            out[i] = parse_ts_literal(v) if isinstance(v, str) else int(v)
        return out, validity
    if dt.is_string():
        return (
            np.asarray(["" if v is None else str(v) for v in vals], object),
            validity,
        )
    if dt.is_decimal():
        out = np.zeros(n, np.float64)
        for i, v in enumerate(vals):
            if v is not None:
                out[i] = float(v)
        return out, validity
    np_t = dt.to_numpy()
    out = np.zeros(n, np_t)
    for i, v in enumerate(vals):
        if v is None:
            continue
        out[i] = v
    return out, validity


def _sql_type_name(dt: ConcreteDataType) -> str:
    names = {
        "int8": "TINYINT", "int16": "SMALLINT", "int32": "INT",
        "int64": "BIGINT", "uint8": "TINYINT UNSIGNED",
        "uint16": "SMALLINT UNSIGNED", "uint32": "INT UNSIGNED",
        "uint64": "BIGINT UNSIGNED", "float32": "FLOAT", "float64": "DOUBLE",
        "string": "STRING", "binary": "VARBINARY", "bool": "BOOLEAN",
        "timestamp_s": "TIMESTAMP(0)", "timestamp_ms": "TIMESTAMP(3)",
        "timestamp_us": "TIMESTAMP(6)", "timestamp_ns": "TIMESTAMP(9)",
        "date": "DATE", "json": "JSON",
    }
    return names.get(dt.name, dt.name.upper())


def _write_format(pa_table, path: str, fmt: str) -> int:
    import pyarrow as pa

    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(pa_table, path)
    elif fmt == "csv":
        import pyarrow.csv as pacsv

        pacsv.write_csv(pa_table, path)
    elif fmt == "json":
        import json as _json

        rows = pa_table.to_pylist()
        with open(path, "w") as f:
            for r in rows:
                f.write(_json.dumps(r, default=str) + "\n")
    else:
        raise UnsupportedError(f"COPY format {fmt}")
    return pa_table.num_rows


def _read_format(path: str, fmt: str):
    if fmt == "parquet":
        import pyarrow.parquet as pq

        return pq.read_table(path)
    if fmt == "csv":
        import pyarrow.csv as pacsv

        return pacsv.read_csv(path)
    if fmt == "json":
        import pyarrow.json as pajson

        return pajson.read_json(path)
    raise UnsupportedError(f"COPY format {fmt}")


def _tql_time(e: A.Expr) -> int:
    v = eval_const(e)
    if isinstance(v, str):
        try:
            return int(float(v) * 1000)
        except ValueError:
            return parse_ts_literal(v)
    return int(float(v) * 1000)


def _tql_interval(e: A.Expr) -> int:
    if isinstance(e, A.IntervalLit):
        return e.ms
    v = eval_const(e)
    if isinstance(v, str):
        from greptimedb_tpu.sql.parser import parse_interval_ms

        return parse_interval_ms(v)
    return int(float(v) * 1000)
