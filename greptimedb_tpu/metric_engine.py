"""Metric engine: many logical tables over one physical storage table.

Capability counterpart of /root/reference/src/metric-engine/ (engine.rs:60-
115, engine/put.rs:36-186): thousands of small Prometheus-style metrics
share one physical region pair instead of each costing a region. The
reference synthesizes `__table_id` + a murmur3 `__tsid` per row; here the
physical table gets a `__table_id` TAG and the dense-sid series registry
plays the tsid role (a (table_id, tags...) combination IS a distinct
series). Logical tables are thin views: writes inject their table id,
scans add a `__table_id` matcher and expose only the logical columns.
"""

from __future__ import annotations

import numpy as np

from greptimedb_tpu.catalog.table import Table, TableScanData
from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema, SemanticType
from greptimedb_tpu.datatypes.types import ConcreteDataType
from greptimedb_tpu.storage.memtable import OP_PUT

PHYSICAL_TABLE = "greptime_physical_table"
TABLE_ID_TAG = "__table_id"


def physical_schema() -> Schema:
    return Schema([
        ColumnSchema(TABLE_ID_TAG, ConcreteDataType.string(),
                     SemanticType.TAG, nullable=False),
        ColumnSchema("greptime_value", ConcreteDataType.float64(),
                     SemanticType.FIELD),
        ColumnSchema("greptime_timestamp",
                     ConcreteDataType.timestamp_millisecond(),
                     SemanticType.TIMESTAMP, nullable=False),
    ])


class LogicalTable(Table):
    """A logical metric table backed by the shared physical table."""

    def __init__(self, info, physical: Table):
        self.info = info
        self.physical = physical

    @property
    def regions(self):  # diagnostics only; data ops go through physical
        return self.physical.regions

    @property
    def _tid(self) -> str:
        return str(self.info.table_id)

    def write(self, tag_columns, ts, fields, *, field_valid=None,
              op=OP_PUT):
        n = len(ts)
        tags = dict(tag_columns)
        tags[TABLE_ID_TAG] = np.full(n, self._tid, object)
        # map logical ts/fields onto physical columns
        return self.physical.write(
            tags, ts, fields, field_valid=field_valid, op=op,
        )

    def scan(self, *, ts_min=None, ts_max=None, field_names=None,
             matchers=None, fulltext=None) -> TableScanData:
        m = list(matchers) if matchers else []
        m.append((TABLE_ID_TAG, "eq", self._tid))
        names = (field_names if field_names is not None
                 else self.field_names)
        return self.physical.scan(
            ts_min=ts_min, ts_max=ts_max, field_names=names, matchers=m,
            fulltext=fulltext,
        )

    def scoped_sids(self, region) -> np.ndarray:
        """This table's sids on one physical region: an O(1) posting
        lookup on the __table_id tag through the secondary index —
        per-table scoping stays flat as logical tables multiply onto
        the shared region (engine.rs's tsid-prefix analog)."""
        return region.match_sids([(TABLE_ID_TAG, "eq", self._tid)])

    def flush(self):
        self.physical.flush()

    def truncate(self):
        # logical truncate: tombstone this table's rows only
        data = self.scan()
        if data.rows is None or len(data.rows) == 0:
            return
        rows = data.rows
        reg = data.registry
        # decode tag values for the DISTINCT matched series only —
        # registry-wide tag_values() gathers are O(total series) per
        # tag, which a shared physical region hosting a million
        # logical tables cannot afford per-table
        uniq, inv = np.unique(rows.sid, return_inverse=True)
        codes = reg.codes_matrix()
        tags = {}
        for t in self.physical.tag_names:
            i = reg.tag_names.index(t)
            d = reg.dicts[i]
            vals = np.asarray(
                [d.decode(int(c)) for c in codes[uniq, i]], dtype=object
            )
            tags[t] = vals[inv]
        self.physical.write(tags, rows.ts, {}, op=1)

    def row_count(self) -> int:
        return self.scan().num_rows


def ensure_physical_table(catalog, db: str) -> Table:
    t = catalog.maybe_table(db, PHYSICAL_TABLE)
    if t is not None:
        return t
    return catalog.create_table(
        db, PHYSICAL_TABLE, physical_schema(), engine="mito",
        if_not_exists=True,
    )


def widen_physical_for(catalog, db: str, physical: Table,
                       logical_schema: Schema):
    """Physical table gains any tag/field columns the logical table needs
    (the metric engine's add-columns-on-demand, engine/alter.rs)."""
    for c in logical_schema.columns:
        if c.is_time_index:
            continue
        existing = physical.schema.maybe_column(c.name)
        if existing is not None and (
            existing.semantic_type != c.semantic_type
        ):
            from greptimedb_tpu.errors import InvalidArgumentError

            raise InvalidArgumentError(
                f"column {c.name!r} already exists on the physical "
                f"metric table as a {existing.semantic_type.name}; the "
                f"logical table wants a {c.semantic_type.name} — rename "
                "the label/field"
            )
        if existing is None:
            catalog.alter_add_column(
                db, PHYSICAL_TABLE,
                ColumnSchema(
                    c.name,
                    ConcreteDataType.string() if c.is_tag else c.data_type,
                    c.semantic_type,
                ),
                if_not_exists=True,
            )
