from greptimedb_tpu.catalog.manager import CatalogManager, TableInfo
from greptimedb_tpu.catalog.table import Table, TableScanData

__all__ = ["CatalogManager", "TableInfo", "Table", "TableScanData"]
