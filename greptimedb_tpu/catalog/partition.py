"""PARTITION ON expression rules: write routing + query-time pruning.

Capability counterpart of the reference's multi-dimension partition rule
(/root/reference/src/partition/src/multi_dim.rs:37-74
MultiDimPartitionRule::find_region and src/partition/src/manager.rs:228
find_regions_by_filters): each region owns the rows satisfying its
expression over the partition columns; queries whose tag matchers pin the
partition columns scan only the owning regions.

Routing is first-match-wins over the expression list (the reference
requires the expressions to be exhaustive and disjoint; rows matching no
expression fall to the last region so ingestion never fails)."""

from __future__ import annotations

import numpy as np

from greptimedb_tpu.query.expr import Col, ColumnSource, eval_expr
from greptimedb_tpu.sql import ast as A


class _ValuesSource(ColumnSource):
    def __init__(self, values: dict[str, str]):
        self._values = values
        self.num_rows = 1

    def col(self, name: str) -> Col:
        if name not in self._values:
            from greptimedb_tpu.errors import ColumnNotFoundError

            raise ColumnNotFoundError(name)
        return Col(np.asarray([self._values[name]], dtype=object))


class PartitionRule:
    def __init__(self, columns: list[str], exprs: list[A.Expr],
                 expr_texts: list[str]):
        self.columns = list(columns)
        self.exprs = list(exprs)
        self.expr_texts = list(expr_texts)

    @property
    def num_regions(self) -> int:
        return max(len(self.exprs), 1)

    # ---- persistence ---------------------------------------------------
    def to_json(self) -> dict:
        return {"columns": self.columns, "exprs": self.expr_texts}

    @staticmethod
    def from_json(d: dict) -> "PartitionRule":
        from greptimedb_tpu.sql.parser import Parser

        exprs = [Parser(t).expr() for t in d["exprs"]]
        return PartitionRule(d["columns"], exprs, list(d["exprs"]))

    @staticmethod
    def from_ast(columns: list[str], exprs: list[A.Expr]) -> "PartitionRule":
        from greptimedb_tpu.query.expr import format_expr

        return PartitionRule(columns, exprs,
                             [format_expr(e) for e in exprs])

    # ---- routing -------------------------------------------------------
    def region_of(self, values: dict[str, str]) -> int:
        src = _ValuesSource(values)
        for i, e in enumerate(self.exprs):
            try:
                c = eval_expr(e, src)
            except Exception:
                continue
            if bool(np.asarray(c.values, bool)[0]) and bool(c.valid_mask[0]):
                return i
        return self.num_regions - 1

    def route_rows(self, tag_cols: dict[str, np.ndarray], n: int
                   ) -> np.ndarray:
        """Per-row region index; expression evaluation once per distinct
        partition-key combination."""
        cols = [
            np.asarray(tag_cols.get(c, np.full(n, "", object)), object)
            for c in self.columns
        ]
        if not cols:
            return np.zeros(n, np.int32)
        stacked = np.stack([c.astype(str) for c in cols], axis=1)
        uniq, inv = np.unique(stacked, axis=0, return_inverse=True)
        dest = np.empty(len(uniq), np.int32)
        for i, row in enumerate(uniq):
            dest[i] = self.region_of(dict(zip(self.columns, row)))
        return dest[np.ravel(inv)]

    # ---- pruning -------------------------------------------------------
    def prune(self, matchers: list[tuple[str, str, object]]
              ) -> list[int] | None:
        """Region indices that can satisfy the matchers, or None when the
        matchers don't pin every partition column with eq/in (conservative:
        scan everything)."""
        value_sets: dict[str, set] = {}
        for name, op, value in matchers or []:
            if name not in self.columns:
                continue
            if op == "eq":
                s = {value}
            elif op == "in":
                s = set(value)
            else:
                continue  # ne/re restrict further; never widen
            cur = value_sets.get(name)
            value_sets[name] = s if cur is None else (cur & s)
        if set(value_sets) != set(self.columns):
            return None
        combos = [{}]
        for c in self.columns:
            vals = value_sets[c]
            if not vals or len(combos) * len(vals) > 4096:
                return None if vals else []
            combos = [
                {**combo, c: v} for combo in combos for v in sorted(vals)
            ]
        return sorted({self.region_of(combo) for combo in combos})
