"""Catalog manager: databases -> tables, persisted as JSON metadata.

Capability counterpart of the reference's catalog + table-metadata layer
(/root/reference/src/catalog/src/kvbackend/, src/common/meta/src/key/): table
schemas (with TAG/FIELD/TIME INDEX semantics), table-id allocation, and the
table -> region mapping, persisted through the object store so a restart
recovers the full catalog and reopens every region (WAL replay included).
"""

from __future__ import annotations

import json

import time
from dataclasses import dataclass, field as dc_field

from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema, SemanticType
from greptimedb_tpu.datatypes.types import ConcreteDataType
from greptimedb_tpu.errors import (
    DatabaseNotFoundError,
    InvalidArgumentError,
    TableAlreadyExistsError,
    TableNotFoundError,
)
from greptimedb_tpu.catalog.table import Table
from greptimedb_tpu.storage.engine import TsdbEngine
from greptimedb_tpu.storage.region import RegionMetadata, RegionOptions

from greptimedb_tpu import concurrency

DEFAULT_CATALOG = "greptime"
DEFAULT_SCHEMA = "public"
CATALOG_PATH = "meta/catalog.json"

# region ids pack (table_id, region_seq) like the reference's RegionId
# (/root/reference/src/store-api/src/storage/descriptors.rs).
_REGION_SHIFT = 10


@dataclass
class TableInfo:
    table_id: int
    name: str
    database: str
    schema: Schema
    engine: str = "mito"
    options: dict = dc_field(default_factory=dict)
    num_regions: int = 1
    created_ms: int = 0
    partition: dict | None = None   # PartitionRule.to_json payload

    def region_ids(self) -> list[int]:
        return [
            (self.table_id << _REGION_SHIFT) | i for i in range(self.num_regions)
        ]

    # ---- json ---------------------------------------------------------
    def to_json(self) -> dict:
        return {
            "table_id": self.table_id,
            "name": self.name,
            "database": self.database,
            "engine": self.engine,
            "options": self.options,
            "num_regions": self.num_regions,
            "created_ms": self.created_ms,
            "partition": self.partition,
            "columns": [
                {
                    "name": c.name,
                    "type": c.data_type.name,
                    "semantic": int(c.semantic_type),
                    "nullable": c.nullable,
                    "default": c.default,
                    "fulltext": c.fulltext,
                    "inverted_index": c.inverted_index,
                }
                for c in self.schema.columns
            ],
            "schema_version": self.schema.version,
        }

    @staticmethod
    def from_json(d: dict) -> "TableInfo":
        cols = [
            ColumnSchema(
                name=c["name"],
                data_type=ConcreteDataType.from_name(c["type"]),
                semantic_type=SemanticType(c["semantic"]),
                nullable=c.get("nullable", True),
                default=c.get("default"),
                fulltext=c.get("fulltext", False),
                inverted_index=c.get("inverted_index", False),
            )
            for c in d["columns"]
        ]
        return TableInfo(
            table_id=d["table_id"],
            name=d["name"],
            database=d["database"],
            schema=Schema(cols, version=d.get("schema_version", 0)),
            engine=d.get("engine", "mito"),
            options=d.get("options", {}),
            num_regions=d.get("num_regions", 1),
            partition=d.get("partition"),
            created_ms=d.get("created_ms", 0),
        )


def append_mode_enabled(options: dict | None) -> bool:
    """THE append-mode predicate: every layer (region options, the
    ingest retry guard, the frontend statement-retry guard) must agree,
    or a table could get dedup regions while the write path refuses the
    dedup-safe retry (or worse, the inverse)."""
    return str((options or {}).get("append_mode", "")).lower() in (
        "true", "1",
    )


def validate_table_options(options: dict | None):
    """CREATE-boundary validation: parse_interval_ms carries signs
    now, and a negative TTL would compute a cutoff in the future and
    expire EVERYTHING. Runs only when a table is CREATED — the
    converter below stays lenient so a previously persisted catalog
    (whatever it holds) still opens."""
    from greptimedb_tpu.errors import InvalidArgumentError
    from greptimedb_tpu.sql.parser import parse_interval_ms

    for key in ("ttl", "compaction.twcs.time_window"):
        if key in (options or {}):
            if parse_interval_ms(str(options[key])) <= 0:
                raise InvalidArgumentError(
                    f"{key} must be positive: {options[key]!r}"
                )


def region_options_from_table(options: dict) -> RegionOptions:
    """SQL WITH(...) options -> region options (TTL, append_mode, merge_mode,
    compaction windows — the table-option surface of
    /root/reference/src/mito2/src/region/options.rs). Lenient: also the
    catalog REOPEN path, so non-positive persisted intervals disable
    the feature instead of failing the load."""
    from greptimedb_tpu.sql.parser import parse_interval_ms

    opts = RegionOptions()
    if "ttl" in options:
        ms = parse_interval_ms(str(options["ttl"]))
        if ms > 0:
            opts.ttl_ms = ms
    if append_mode_enabled(options):
        opts.append_mode = True
    if "merge_mode" in options:
        opts.merge_mode = str(options["merge_mode"])
    if "compaction.twcs.time_window" in options:
        ms = parse_interval_ms(
            str(options["compaction.twcs.time_window"])
        )
        if ms > 0:
            opts.compaction_window_ms = ms
    for key in ("compaction.twcs.trigger_file_num",
                "compaction.twcs.max_active_window_files"):
        # the reference's L0 trigger knob (twcs max_active_window_*
        # options); lenient on reopen like every other option here
        if key in options:
            try:
                n = int(str(options[key]))
            except ValueError:
                continue
            if n > 0:
                opts.compaction_trigger_files = n
    return opts


class _BrokenTable:
    """Placeholder for a table that failed to open: keeps the metadata
    alive while every data access raises the open error."""

    def __init__(self, info, error: Exception):
        self.info = info
        self._error = error

    @property
    def name(self):
        return self.info.name

    @property
    def schema(self):
        return self.info.schema

    def __getattr__(self, item):
        from greptimedb_tpu.errors import IllegalStateError

        raise IllegalStateError(
            f"table {self.info.name!r} failed to open: {self._error}"
        )


class CatalogManager:
    def __init__(self, engine: TsdbEngine):
        self.engine = engine
        self.store = engine.store
        self._lock = concurrency.RLock()
        self._databases: dict[str, dict[str, Table]] = {}
        self._views: dict[str, dict[str, str]] = {}  # db -> name -> SQL text
        self._next_table_id = 1024
        self._load()
        if DEFAULT_SCHEMA not in self._databases:
            self._databases[DEFAULT_SCHEMA] = {}
            self._persist()

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------
    def _load(self):
        if not self.store.exists(CATALOG_PATH):
            return
        doc = json.loads(self.store.read(CATALOG_PATH))
        self._next_table_id = doc.get("next_table_id", 1024)
        self._views = {
            db: dict(views) for db, views in doc.get("views", {}).items()
        }
        all_infos = {
            db_name: [TableInfo.from_json(t) for t in tables]
            for db_name, tables in doc.get("databases", {}).items()
        }
        # region-parallel startup: submit EVERY mito region across every
        # table to the engine's bounded recovery pool in one batch and
        # join, so N single-region tables recover concurrently too. The
        # per-table opens below then hit the registry; a failed open
        # re-raises there and lands in that table's _BrokenTable.
        batch = []
        for infos in all_infos.values():
            for info in infos:
                if info.engine == "mito":
                    batch.extend(self._region_metas(info))
        if batch:
            try:
                self.engine.open_regions(batch)
            except Exception as e:  # noqa: BLE001 - per-table isolation
                import logging

                logging.getLogger("greptimedb_tpu.catalog").warning(
                    "batch region open failed (isolating per table): %s",
                    e,
                )
        for db_name, infos in all_infos.items():
            db = self._databases.setdefault(db_name, {})
            # physical (mito) tables first: logical metric tables resolve
            # their shared physical table during open
            for info in sorted(infos, key=lambda i: i.engine == "metric"):
                try:
                    db[info.name] = self._open_table(info)
                except Exception as e:  # noqa: BLE001 - startup isolation
                    # one broken table (e.g. an external file that moved)
                    # must not take down the rest of the catalog; keep a
                    # placeholder so metadata persists and errors are
                    # per-table
                    import traceback

                    traceback.print_exc()
                    db[info.name] = _BrokenTable(info, e)

    def _persist(self):
        doc = {
            "next_table_id": self._next_table_id,
            # placeholder tables keep their info, so brokenness is not
            # silently dropped from the persisted catalog
            "databases": {
                db: [t.info.to_json() for t in tables.values()]
                for db, tables in self._databases.items()
            },
            "views": {db: dict(v) for db, v in self._views.items() if v},
        }
        self.store.write(CATALOG_PATH, json.dumps(doc).encode())

    def _region_metas(self, info: TableInfo) -> list[RegionMetadata]:
        opts = region_options_from_table(info.options)
        return [
            RegionMetadata(
                region_id=rid,
                table=info.name,
                tag_names=[c.name for c in info.schema.tag_columns],
                field_names=[c.name for c in info.schema.field_columns],
                ts_name=info.schema.time_index.name,
                options=opts,
                fulltext_fields=[
                    c.name for c in info.schema.field_columns
                    if getattr(c, "fulltext", False)
                ],
            )
            for rid in info.region_ids()
        ]

    def _open_table(self, info: TableInfo) -> Table:
        if info.engine == "metric":
            return self._open_metric_table(info)
        if info.engine == "file":
            from greptimedb_tpu.storage.file_engine import open_file_table

            return open_file_table(self, info)
        # multi-region tables open region-parallel on the engine's
        # bounded pool (already-open regions hit the registry)
        regions = self.engine.open_regions(self._region_metas(info))
        return Table(info, regions)

    # ------------------------------------------------------------------
    # databases
    # ------------------------------------------------------------------
    def create_database(self, name: str, *, if_not_exists: bool = False):
        with self._lock:
            if name in self._databases:
                if if_not_exists:
                    return
                raise InvalidArgumentError(f"database already exists: {name}")
            self._databases[name] = {}
            self._persist()

    def drop_database(self, name: str, *, if_exists: bool = False):
        with self._lock:
            if name not in self._databases:
                if if_exists:
                    return
                raise DatabaseNotFoundError(f"database not found: {name}")
            if name == DEFAULT_SCHEMA:
                raise InvalidArgumentError("cannot drop the public database")
            for tname in list(self._databases[name]):
                self.drop_table(name, tname)
            del self._databases[name]
            self._views.pop(name, None)
            self._persist()

    # ------------------------------------------------------------------
    # views (name -> stored SQL text; execution re-plans on every query,
    # the reference's view substitution in src/query/src/planner.rs)
    # ------------------------------------------------------------------
    def create_view(self, database: str, name: str, sql_text: str,
                    *, or_replace: bool = False):
        with self._lock:
            self._db(database)  # database must exist
            if name in self._databases.get(database, {}):
                raise InvalidArgumentError(
                    f"a table named {name!r} already exists"
                )
            views = self._views.setdefault(database, {})
            if name in views and not or_replace:
                raise InvalidArgumentError(f"view already exists: {name}")
            views[name] = sql_text
            self._persist()

    def drop_view(self, database: str, name: str, *, if_exists: bool = False):
        with self._lock:
            views = self._views.get(database, {})
            if name not in views:
                if if_exists:
                    return
                raise TableNotFoundError(f"view not found: {name}")
            del views[name]
            self._persist()

    def maybe_view(self, database: str, name: str) -> str | None:
        with self._lock:
            return self._views.get(database, {}).get(name)

    def view_names(self, database: str) -> list[str]:
        with self._lock:
            return sorted(self._views.get(database, {}))

    def database_names(self) -> list[str]:
        with self._lock:
            return sorted(self._databases)

    def has_database(self, name: str) -> bool:
        with self._lock:
            return name in self._databases

    # ------------------------------------------------------------------
    # tables
    # ------------------------------------------------------------------
    def create_table(
        self,
        database: str,
        name: str,
        schema: Schema,
        *,
        engine: str = "mito",
        options: dict | None = None,
        num_regions: int = 1,
        if_not_exists: bool = False,
        partition: dict | None = None,
    ) -> Table:
        validate_table_options(options)
        # GTS102/103: the standalone catalog persists the WHOLE catalog
        # doc (_persist) under its lock — mutate-then-write atomicity is
        # the consistency contract, and only DDL pays the (object-store)
        # write latency (wall-clock can cross the 1s hold threshold on a
        # saturated host). The dist catalog (per-key kv) does its wire
        # I/O outside the lock instead.
        with self._lock:  # gtlint: disable=GTS102,GTS103
            db = self._db(database)
            if name in self._views.get(database, {}):
                raise InvalidArgumentError(
                    f"a view named {name!r} already exists"
                )
            if name in db:
                if if_not_exists:
                    return db[name]
                raise TableAlreadyExistsError(f"table already exists: {name}")
            schema.time_index  # raises unless a TIME INDEX exists
            info = TableInfo(
                table_id=self._next_table_id,
                name=name,
                database=database,
                schema=schema,
                engine=engine,
                options=options or {},
                num_regions=max(1, num_regions),
                partition=partition,
                created_ms=int(time.time() * 1000),
            )
            self._next_table_id += 1
            table = self._open_table(info)
            db[name] = table
            self._persist()
            return table

    def _open_metric_table(self, info: TableInfo):
        """Logical metric-engine table: a view over the shared physical
        table (see metric_engine.py)."""
        from greptimedb_tpu import metric_engine as ME

        physical = ME.ensure_physical_table(self, info.database)
        ME.widen_physical_for(self, info.database, physical, info.schema)
        return ME.LogicalTable(info, physical)

    def drop_table(self, database: str, name: str, *, if_exists: bool = False):
        with self._lock:
            db = self._db(database)
            table = db.pop(name, None)
            if table is None:
                if if_exists:
                    return
                raise TableNotFoundError(f"table not found: {name}")
            for rid in table.info.region_ids():
                self.engine.drop_region(rid)
            self._persist()
        # release any HBM-resident query caches pinned to the table
        try:
            from greptimedb_tpu.promql import fast as _promql_fast

            _promql_fast.drop_table_entries(table)
        except ImportError:  # pragma: no cover - promql optional
            pass
        self._purge_result_caches(table)

    def _purge_result_caches(self, table):
        """Drop cached result payloads for a dropped table: a recreated
        table can reuse the table id and coincidentally match versions,
        so LRU aging alone is not enough. (Session-registry buffers are
        keyed per grid entry and released by the grid caches when they
        drop an entry — DeviceRangeCache._release /
        SelectorGridCache._release.)"""
        rc = getattr(self, "result_cache", None)
        if rc is not None:
            rc.purge_table(table.info.database, table.info.table_id)

    def table(self, database: str, name: str) -> Table:
        with self._lock:
            db = self._db(database)
            try:
                return db[name]
            except KeyError:
                raise TableNotFoundError(
                    f"table not found: {database}.{name}"
                ) from None

    def maybe_table(self, database: str, name: str) -> Table | None:
        with self._lock:
            return self._databases.get(database, {}).get(name)

    def table_names(self, database: str) -> list[str]:
        with self._lock:
            return sorted(self._db(database))

    def all_tables(self) -> list[Table]:
        with self._lock:
            return [
                t for db in self._databases.values() for t in db.values()
            ]

    # ------------------------------------------------------------------
    # alter
    # ------------------------------------------------------------------
    def alter_add_column(self, database: str, name: str, col: ColumnSchema,
                         *, if_not_exists: bool = False):
        """if_not_exists: protocol auto-widen mode — a same-semantic column
        is a no-op even when the inferred data type differs (the first
        writer's type wins; an int64/float64 inference race must not fail a
        whole ingest batch). Explicit SQL ALTER stays strict."""
        with self._lock:
            table = self.table(database, name)
            if col.semantic_type == SemanticType.TIMESTAMP:
                raise InvalidArgumentError("cannot add a TIME INDEX column")
            existing = table.info.schema.maybe_column(col.name)
            if existing is not None:
                if existing.semantic_type != col.semantic_type:
                    raise InvalidArgumentError(
                        f"column {col.name!r} already exists as a "
                        f"{existing.semantic_type.name} column"
                    )
                if if_not_exists or existing.data_type == col.data_type:
                    return
                raise InvalidArgumentError(
                    f"column {col.name!r} already exists as "
                    f"{existing.data_type.name}"
                )
            if table.info.engine == "metric":
                # logical metric table: the column must land on the
                # SHARED physical table (its own schema + regions) so it
                # persists across reopen — the metric engine's
                # add-columns-on-demand (ref src/metric-engine/src/
                # engine/alter.rs). Widen (and validate against the
                # physical schema) BEFORE touching the logical schema: a
                # semantic collision must leave the table unchanged, not
                # persist a column the physical side rejected.
                from greptimedb_tpu import metric_engine as ME

                physical = ME.ensure_physical_table(self, database)
                candidate = table.info.schema.with_column(col)
                ME.widen_physical_for(self, database, physical, candidate)
                table.info.schema = candidate
                self._persist()
                return
            table.info.schema = table.info.schema.with_column(col)
            if col.semantic_type == SemanticType.TAG:
                # existing series read "" for the new tag; sids stay stable
                for region in table.regions:
                    with region._lock:
                        region.series.add_tag(col.name)
                        region.meta.tag_names.append(col.name)
                self._persist()
                return
            for region in table.regions:
                with region._lock:
                    if col.name not in region.meta.field_names:
                        region.meta.field_names.append(col.name)
                        region.memtable.field_names.append(col.name)
                region.invalidate_scan_cache()
            self._persist()

    def alter_drop_column(self, database: str, name: str, col_name: str):
        with self._lock:
            table = self.table(database, name)
            col = table.info.schema.column(col_name)
            if not col.is_field:
                raise InvalidArgumentError(
                    "only FIELD columns can be dropped"
                )
            table.info.schema = table.info.schema.without_column(col_name)
            if table.info.engine == "metric":
                # logical drop only: the physical column is SHARED with
                # every other metric — touching the physical regions'
                # field lists would break ingest for all of them
                self._persist()
                return
            for region in table.regions:
                if col_name in region.meta.field_names:
                    region.meta.field_names.remove(col_name)
                if col_name in region.memtable.field_names:
                    region.memtable.field_names.remove(col_name)
                region.invalidate_scan_cache()
            self._persist()

    def rename_table(self, database: str, old: str, new: str):
        with self._lock:
            db = self._db(database)
            if new in db:
                raise TableAlreadyExistsError(f"table already exists: {new}")
            table = db.pop(old, None)
            if table is None:
                raise TableNotFoundError(f"table not found: {old}")
            table.info.name = new
            db[new] = table
            self._persist()

    # ------------------------------------------------------------------
    def _db(self, database: str) -> dict[str, Table]:
        try:
            return self._databases[database]
        except KeyError:
            raise DatabaseNotFoundError(
                f"database not found: {database}"
            ) from None
