"""Table abstraction: schema + N regions with hash partition routing.

Capability counterpart of the reference's `Table` trait + partition layer
(/root/reference/src/table/src/table.rs, src/partition/src/multi_dim.rs:37,
src/partition/src/splitter.rs): a table owns one or more storage regions;
writes are routed to regions by a stable hash of the tag tuple (the dense-sid
analog of the reference's partition-rule row split), scans fan out to every
region and merge into one table-level series space.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass

import numpy as np

from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.storage.memtable import OP_PUT, ColumnarRows, _concat_rows
from greptimedb_tpu.storage.region import Region
from greptimedb_tpu.storage.series import SeriesRegistry


@dataclass
class TableScanData:
    """Merged multi-region scan output in one table-level series space."""

    rows: ColumnarRows | None
    registry: SeriesRegistry
    field_names: list[str]

    @property
    def num_rows(self) -> int:
        return 0 if self.rows is None else len(self.rows)


def _route_rows(tag_cols: list[np.ndarray], n_rows: int, n_regions: int) -> np.ndarray:
    """Stable per-row region index from the tag tuple (crc32 of the joined
    tag strings, computed once per distinct combination).

    Empty tag values are EXCLUDED from the key: a series written before an
    ALTER ADD TAG reads "" for the new tag and must keep routing to the
    same region, or overwrite dedup and deletes would split across regions.
    Collisions between different series only affect placement, never
    identity."""
    if n_regions <= 1 or not tag_cols:
        return np.zeros(n_rows, dtype=np.int32)
    stacked = np.stack([c.astype(object) for c in tag_cols], axis=1)
    uniq, inv = np.unique(stacked.astype(str), axis=0, return_inverse=True)
    dest = np.empty(len(uniq), dtype=np.int32)
    for i, row in enumerate(uniq):
        key = "\x00".join(v for v in row if v != "")
        dest[i] = zlib.crc32(key.encode()) % n_regions
    return dest[np.ravel(inv)]


class Table:
    def __init__(self, info, regions: list[Region]):
        self.info = info
        self.regions = regions
        self.partition_rule = None
        part = getattr(info, "partition", None)
        if part:
            from greptimedb_tpu.catalog.partition import PartitionRule

            self.partition_rule = PartitionRule.from_json(part)

    @property
    def name(self) -> str:
        return self.info.name

    @property
    def schema(self) -> Schema:
        return self.info.schema

    @property
    def tag_names(self) -> list[str]:
        return [c.name for c in self.info.schema.tag_columns]

    @property
    def field_names(self) -> list[str]:
        return [c.name for c in self.info.schema.field_columns]

    @property
    def ts_name(self) -> str:
        return self.info.schema.time_index.name

    # ------------------------------------------------------------------
    # write path
    # ------------------------------------------------------------------
    def write(
        self,
        tag_columns: dict[str, np.ndarray],
        ts: np.ndarray,
        fields: dict[str, np.ndarray],
        *,
        field_valid: dict[str, np.ndarray] | None = None,
        op: int = OP_PUT,
        skip_wal: bool = False,
    ) -> int:
        """Route rows to regions by tag hash; returns rows written.

        skip_wal is the bulk-load path (restore/benchmark loads — the
        reference's bulk ingest part, src/mito2/src/memtable/bulk.rs):
        rows go straight to the memtable without durability."""
        n = len(ts)
        if n == 0:
            return 0
        ts = np.asarray(ts, np.int64)
        # normalize: every schema FIELD present with its proper dtype, so
        # regions never have to guess a fill dtype (string fields stay
        # object arrays end-to-end).
        fields = dict(fields)
        field_valid = dict(field_valid) if field_valid else {}
        if op == OP_PUT:
            for c in self.info.schema.field_columns:
                if c.name in fields:
                    continue
                if c.data_type.is_string():
                    fields[c.name] = np.full(n, "", object)
                else:
                    fields[c.name] = np.zeros(n, c.data_type.to_numpy())
                field_valid[c.name] = np.zeros(n, bool)
        tag_names = self.tag_names
        tag_cols = [np.asarray(tag_columns.get(t, np.full(n, "", object)),
                               object) for t in tag_names]
        if len(self.regions) == 1:
            self._dispatch_writes(
                [(0, dict(zip(tag_names, tag_cols)), ts, fields,
                  field_valid or None)],
                op=op, skip_wal=skip_wal,
            )
            return n
        if self.partition_rule is not None:
            dest = self.partition_rule.route_rows(
                dict(zip(tag_names, tag_cols)), n
            )
            dest = np.clip(dest, 0, len(self.regions) - 1)
        else:
            dest = _route_rows(tag_cols, n, len(self.regions))
        puts = []
        for r_idx in np.unique(dest):
            sel = dest == r_idx
            puts.append((
                int(r_idx),
                {t: c[sel] for t, c in zip(tag_names, tag_cols)},
                ts[sel],
                {k: v[sel] for k, v in fields.items()},
                (
                    {k: v[sel] for k, v in field_valid.items()}
                    if field_valid else None
                ),
            ))
        self._dispatch_writes(puts, op=op, skip_wal=skip_wal)
        return n

    def _dispatch_writes(self, puts, *, op: int, skip_wal: bool):
        """Apply routed row splits; remote tables override to batch all
        of one datanode's regions into a single RPC."""
        for r_idx, tag_columns, ts, fields, field_valid in puts:
            self.regions[r_idx].write(
                tag_columns, ts, fields, field_valid=field_valid, op=op,
                skip_wal=skip_wal,
            )

    def delete(self, tag_columns: dict[str, np.ndarray], ts: np.ndarray) -> int:
        from greptimedb_tpu.storage.memtable import OP_DELETE

        return self.write(tag_columns, ts, {}, op=OP_DELETE)

    # ------------------------------------------------------------------
    # scan path
    # ------------------------------------------------------------------
    def scan(
        self,
        *,
        ts_min: int | None = None,
        ts_max: int | None = None,
        field_names: list[str] | None = None,
        matchers: list[tuple[str, str, object]] | None = None,
        fulltext: list | None = None,
    ) -> TableScanData:
        """Fan out to regions, prune series by tag matchers, merge into one
        table-level sid space. Rows stay per-series time-sorted (series are
        region-disjoint, so concatenation preserves per-series order)."""
        names = field_names if field_names is not None else self.field_names
        from greptimedb_tpu import cancellation

        cancellation.checkpoint()
        if len(self.regions) == 1:
            region = self.regions[0]
            sids = None
            if matchers:
                sids = region.match_sids(matchers)
                if len(sids) == 0:
                    return TableScanData(None, region.series, names)
            res = region.scan(ts_min=ts_min, ts_max=ts_max,
                              field_names=names, sids=sids,
                              fulltext=fulltext)
            return TableScanData(res.rows, res.registry, names)

        from greptimedb_tpu.query import stats

        scan_regions = self.pruned_regions(matchers)
        stats.add("regions_scanned", len(scan_regions))
        merged = SeriesRegistry(self.tag_names)
        chunks: list[ColumnarRows] = []
        from greptimedb_tpu import cancellation

        for region in scan_regions:
            cancellation.checkpoint()
            sids = None
            if matchers:
                sids = region.match_sids(matchers)
                if len(sids) == 0:
                    continue
            res = region.scan(ts_min=ts_min, ts_max=ts_max,
                              field_names=names, sids=sids,
                              fulltext=fulltext)
            if res.rows is None or len(res.rows) == 0:
                continue
            # region sid -> table sid: intern every region series once
            reg = res.registry
            if reg.num_series:
                remap = merged.intern_rows(
                    [reg.tag_values(t) for t in self.tag_names]
                ) if self.tag_names else merged.intern_rows([])
                if self.tag_names:
                    rows = res.rows
                    rows.sid = remap[rows.sid]
            chunks.append(res.rows)
        if not chunks:
            return TableScanData(None, merged, names)
        rows = chunks[0] if len(chunks) == 1 else _concat_rows_full(chunks, names)
        return TableScanData(rows, merged, names)

    def pruned_regions(self, matchers) -> list:
        """Regions that can match `matchers` under the partition rule
        (all of them when unpartitioned / unprunable). The ONE pruning
        implementation shared by local scans, remote scans, and the
        distributed partial fan-out."""
        if self.partition_rule is None or not matchers:
            return self.regions
        keep = self.partition_rule.prune(matchers)
        if keep is None:
            return self.regions
        from greptimedb_tpu.query import stats

        out = [self.regions[i] for i in keep if i < len(self.regions)]
        stats.add("regions_pruned", len(self.regions) - len(out))
        return out

    def flush(self):
        for r in self.regions:
            r.flush()

    def truncate(self):
        for r in self.regions:
            r.truncate()

    def data_version(self) -> tuple:
        """Logical-data version across regions + schema; device caches
        compare this to decide reuse (see query/device_range.py)."""
        return (
            tuple(r.data_version for r in self.regions),
            tuple(self.schema.column_names),
            tuple(self.tag_names),
        )

    def physical_version(self) -> tuple:
        """data_version extended with each region's manifest version:
        additionally bumps on flush/compact/schema commits. The frontend
        result cache (query/result_cache.py) keys on THIS — the same
        conservative discipline as the datanode merged-scan cache."""
        return (
            tuple(r.physical_version for r in self.regions),
            tuple(self.schema.column_names),
            tuple(self.tag_names),
        )

    def row_count(self) -> int:
        """Approximate row count (memtable + SST rows, before dedup)."""
        total = 0
        for r in self.regions:
            total += r.memtable.rows
            total += sum(m.rows for m in r.manifest.state.ssts)
        return total


def _concat_rows_full(chunks: list[ColumnarRows], names: list[str]) -> ColumnarRows:
    return _concat_rows(chunks, names)
