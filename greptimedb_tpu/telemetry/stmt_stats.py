"""Statement statistics: pg_stat_statements for the TPU query path.

Capability counterpart of the reference's layer-2 `information_schema`
statistics surface (PAPER.md) and PostgreSQL's pg_stat_statements:
every executed statement is folded into a process-wide registry keyed
by a normalized statement FINGERPRINT — literals, timestamps and
IN-lists fold to `?`, multi-row VALUES lists collapse — so every poll
of a dashboard panel lands on ONE row, regardless of the concrete
window bounds each poll carries.

Per fingerprint the registry records: calls, errors by status code,
rows returned, latency + admission-queue-time histograms (p50/p99
derivable), the execution path (device | host | dist), the mesh
replicate-vs-shard decision, device compile vs program-cache hits,
upload/readback bytes (full vs since-cursor delta), session /
result-cache / dist-scan-cache hit attribution, shed + deadline
counts, and the LAST trace id as an exemplar linking the aggregate row
back into `/v1/traces` for one concrete execution.

Collection is contextvar-based like query/stats.py: execution sites
call `add()`/`note()` which are no-ops (one ContextVar.get) unless an
observation is active, so a disabled registry costs nothing on the hot
path. The registry itself is LRU-bounded: past `max_fingerprints` the
least-recently-seen row is MERGED into the `_other` row before the new
fingerprint is admitted, and the `gtpu_stmt_*` metric labels collapse
to `_other` past the (smaller) `metric_fingerprints` knob — Prometheus
series can never be evicted, so their cap is first-come like the sched
tenant labels.

Because the metrics self-export loop (telemetry/export.py) re-ingests
the registry's `gtpu_stmt_*` families, per-fingerprint statistics
become a queryable TIME SERIES in `greptime_metrics` for free:
`SELECT * FROM greptime_metrics.gtpu_stmt_calls_total` is the TSDB
dogfooding its own query history.
"""

from __future__ import annotations

import contextvars
import hashlib
import time
from collections import OrderedDict

from greptimedb_tpu import concurrency
from greptimedb_tpu.telemetry import metrics as _metrics
from greptimedb_tpu.telemetry.metrics import (
    global_registry,
    set_child_value as _set_counter,
)

# ---------------------------------------------------------------------------
# metrics — PULL-model: the gtpu_stmt_* families are published from
# the registry rows at SCRAPE time (a MetricsRegistry collector, like
# the memory accountant's gauges), so the statement hot path never
# touches a prometheus child lock. ADMIN reset folds each row's totals
# into a carried per-label base first, keeping every counter/histogram
# monotone across resets. Fingerprint label cardinality is capped —
# see _metric_fp_locked.
# ---------------------------------------------------------------------------

# latency/queue-time histogram bounds (ms) for the in-registry
# per-fingerprint histograms information_schema derives p50/p99 from;
# gtpu_stmt_latency_seconds exports the same bounds in seconds
_BUCKETS_MS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
)
# bucket lists carry one extra OVERFLOW slot past the last bound, so
# observations slower than 60s still count toward the percentiles
_N_BUCKETS = len(_BUCKETS_MS) + 1

_CALLS = global_registry.counter(
    "gtpu_stmt_calls_total",
    "statement executions per (db, fingerprint)",
    labels=("db", "fingerprint"),
)
_ERRORS = global_registry.counter(
    "gtpu_stmt_errors_total",
    "failed statement executions per (db, fingerprint, status code)",
    labels=("db", "fingerprint", "code"),
)
_LATENCY = global_registry.histogram(
    "gtpu_stmt_latency_seconds",
    "statement wall time per (db, fingerprint)",
    labels=("db", "fingerprint"),
    buckets=tuple(b / 1000.0 for b in _BUCKETS_MS),
)
_ROWS = global_registry.counter(
    "gtpu_stmt_rows_total",
    "result rows returned (or rows affected) per (db, fingerprint)",
    labels=("db", "fingerprint"),
)
_READBACK = global_registry.counter(
    "gtpu_stmt_readback_bytes_total",
    "device->host readback bytes per (db, fingerprint, mode)",
    labels=("db", "fingerprint", "mode"),
)
_TRACKED = global_registry.gauge(
    "gtpu_stmt_fingerprints",
    "distinct fingerprint rows currently tracked by the registry",
)

OTHER = "_other"


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------


class StmtFingerprint:
    """One statement's normalized identity: `fp` is the stable hex id,
    `text` the normalized statement (constants folded to `?`),
    `inner_fp` the fingerprint of the statement under an EXPLAIN
    [ANALYZE] wrapper (what EXPLAIN ANALYZE stamps, so the analyzed
    plan joins the PLAIN statement's statistics row)."""

    __slots__ = ("fp", "text", "inner_fp")

    def __init__(self, fp: str, text: str, inner_fp: str | None = None):
        self.fp = fp
        self.text = text
        self.inner_fp = inner_fp


def _normalize_tokens(toks) -> str:
    """Token list -> normalized statement text.

    - NUMBER and STRING literals (so timestamps, interval/RANGE window
      strings, tag values) fold to `?`
    - identifiers lowercase (quoted identifiers keep their case: they
      are case-sensitive)
    - a parenthesized list of only placeholders — an IN-list or a
      VALUES row — collapses to `(?)`, and consecutive `(?), (?), ...`
      row groups collapse to one `(?)` so a 1-row and a 10k-row batch
      INSERT share a fingerprint
    """
    from greptimedb_tpu.sql.lexer import Tok

    out: list[str] = []
    i, n = 0, len(toks)
    while i < n:
        t = toks[i]
        if t.kind in (Tok.NUMBER, Tok.STRING):
            # -? / +? : fold a sign that immediately precedes a folded
            # literal into the placeholder (…WHERE v > -5 and > 5 are
            # one shape)
            if (out and out[-1] in ("-", "+")
                    and (len(out) < 2 or out[-2] in (
                        "(", ",", "=", "<", ">", "<=", ">=", "<>",
                        "!=", "and", "or", "not", "in", "then", "else",
                        "when", "between", "select", "limit", "offset",
                        "values", "by",
                    ))):
                out.pop()
            out.append("?")
            i += 1
            continue
        if t.kind == Tok.IDENT:
            out.append(t.text.lower())
            i += 1
            continue
        if t.kind == Tok.QIDENT:
            out.append(f'"{t.text}"')
            i += 1
            continue
        if t.kind == Tok.OP and t.text == "(":
            # collapse (?, ?, ...) -> (?)
            j = i + 1
            only_ph = False
            while j < n:
                tj = toks[j]
                if tj.kind in (Tok.NUMBER, Tok.STRING):
                    only_ph = True
                    j += 1
                    continue
                if tj.kind == Tok.OP and tj.text in (",", "-", "+"):
                    j += 1
                    continue
                break
            if only_ph and j < n and toks[j].kind == Tok.OP \
                    and toks[j].text == ")":
                if len(out) >= 4 and out[-4:] == ["(", "?", ")", ","]:
                    # consecutive VALUES row groups: drop the repeat
                    out.pop()
                    i = j + 1
                    continue
                out.extend(["(", "?", ")"])
                i = j + 1
                continue
        out.append(t.text)
        i += 1
    return " ".join(out)


def _hash(text: str) -> str:
    return hashlib.blake2b(text.encode(), digest_size=8).hexdigest()


def _fingerprint_tokens(toks) -> StmtFingerprint | None:
    from greptimedb_tpu.sql.lexer import Tok

    toks = [t for t in toks if t.kind != Tok.EOF]
    if not toks:
        return None
    text = _normalize_tokens(toks)
    inner_fp = None
    if toks[0].kind == Tok.IDENT and toks[0].upper == "EXPLAIN":
        k = 1
        while (k < len(toks) and toks[k].kind == Tok.IDENT
               and toks[k].upper in ("ANALYZE", "VERBOSE")):
            k += 1
        if k < len(toks):
            inner_fp = _hash(_normalize_tokens(toks[k:]))
    return StmtFingerprint(_hash(text), text, inner_fp)


# raw-text -> fingerprints memo: a dashboard poll repeats the same SQL
# text, so the steady state is one dict lookup, not a re-tokenize.
# Oversized texts (giant batch INSERTs, each a distinct literal
# payload) are fingerprinted but NOT cached: they would pin megabytes
# of raw SQL per entry for inputs that never repeat
_FP_CACHE_MAX = 512
_FP_CACHE_TEXT_MAX = 8192
_fp_cache: OrderedDict[str, list] = OrderedDict()
_fp_cache_lock = concurrency.Lock()


def fingerprint_sql(sql: str) -> list[StmtFingerprint]:
    """Per-statement fingerprints of a (possibly multi-statement) SQL
    text, aligned with parse_sql's statement order. Unlexable text
    returns [] (the parser will raise its own typed error)."""
    with _fp_cache_lock:
        hit = _fp_cache.get(sql)
        if hit is not None:
            _fp_cache.move_to_end(sql)
            return hit
    from greptimedb_tpu.sql.lexer import Tok, tokenize

    try:
        toks = tokenize(sql)
    except Exception:  # noqa: BLE001 - parser owns syntax errors
        return []
    out: list[StmtFingerprint] = []
    cur: list = []
    for t in toks:
        if t.kind == Tok.OP and t.text == ";":
            fp = _fingerprint_tokens(cur)
            if fp is not None:
                out.append(fp)
            cur = []
        elif t.kind != Tok.EOF:
            cur.append(t)
    fp = _fingerprint_tokens(cur)
    if fp is not None:
        out.append(fp)
    if len(sql) <= _FP_CACHE_TEXT_MAX:
        with _fp_cache_lock:
            _fp_cache[sql] = out
            while len(_fp_cache) > _FP_CACHE_MAX:
                _fp_cache.popitem(last=False)
    return out


# ---------------------------------------------------------------------------
# per-statement observation (contextvar-scoped scratch)
# ---------------------------------------------------------------------------


class _Obs:
    __slots__ = ("fp", "text", "inner_fp", "db", "tenant", "channel",
                 "counters", "notes", "trace_id", "programs")

    def __init__(self, fp: StmtFingerprint, db: str, tenant: str,
                 channel: str, trace_id: str | None):
        self.fp = fp.fp
        self.text = fp.text
        self.inner_fp = fp.inner_fp
        self.db = db
        self.tenant = tenant
        self.channel = channel
        self.counters: dict[str, float] = {}
        self.notes: dict[str, str] = {}
        self.trace_id = trace_id
        # device-program registry ids this statement dispatched
        # (telemetry/device_programs.py; bounded — a statement shape
        # touches a handful of compiled programs)
        self.programs: list[str] | None = None

    def add(self, key: str, n: float = 1):
        self.counters[key] = self.counters.get(key, 0) + n

    def note(self, key: str, value: str):
        self.notes[key] = value


_current: contextvars.ContextVar[_Obs | None] = contextvars.ContextVar(
    "gtpu_stmt_obs", default=None
)
# the statement fingerprint execute_sql resolved for the statement it
# is about to execute (execute_statement has only the AST)
_pending_fp: contextvars.ContextVar[StmtFingerprint | None] = (
    contextvars.ContextVar("gtpu_stmt_fp", default=None)
)


def bind_fingerprint(fp: StmtFingerprint | None):
    return _pending_fp.set(fp)


def reset_fingerprint(token):
    _pending_fp.reset(token)


def active() -> _Obs | None:
    return _current.get()


def add(key: str, n: float = 1):
    obs = _current.get()
    if obs is not None:
        obs.add(key, n)


def note(key: str, value: str):
    obs = _current.get()
    if obs is not None:
        obs.note(key, value)


_MAX_OBS_PROGRAMS = 16


def note_program(prog_id: str):
    """Link the active statement observation to a device-program
    registry row (called by device_trace at the dispatch boundary)."""
    obs = _current.get()
    if obs is None:
        return
    progs = obs.programs
    if progs is None:
        progs = obs.programs = []
    if prog_id not in progs and len(progs) < _MAX_OBS_PROGRAMS:
        progs.append(prog_id)


def note_exec_path(path: str):
    """Executor path attribution ('device' | 'host:<reason>' |
    'dist:partial' ...) -> the row's device/host/dist triple."""
    obs = _current.get()
    if obs is None:
        return
    if path == "device":
        obs.note("exec_path", "device")
    elif path.startswith("dist"):
        obs.note("exec_path", "dist")
    else:
        obs.note("exec_path", "host")


def explain_fingerprint() -> str | None:
    """The fingerprint EXPLAIN ANALYZE stamps: the analyzed statement's
    own fingerprint (so the stamp joins the plain statement's row), or
    the active statement's fingerprint outside an EXPLAIN wrapper."""
    obs = _current.get()
    if obs is None:
        return None
    return obs.inner_fp or obs.fp


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------


class StmtStatsConfig:
    """`[stmt_stats]` options (config.py DEFAULTS documents each)."""

    __slots__ = ("enable", "max_fingerprints", "metric_fingerprints")

    def __init__(self, *, enable: bool = True,
                 max_fingerprints: int = 512,
                 metric_fingerprints: int = 64):
        self.enable = bool(enable)
        self.max_fingerprints = max(1, int(max_fingerprints))
        self.metric_fingerprints = max(0, int(metric_fingerprints))


# observation-counter key -> _Row attribute (fold_obs single pass)
_FOLD_ATTRS = {
    "rows": "rows_returned",
    "compile_first": "compile_count",
    "compile_cache_hit": "compile_cache_hits",
    "upload_bytes": "upload_bytes",
    "readback_full_bytes": "readback_full_bytes",
    "readback_delta_bytes": "readback_delta_bytes",
    "session_hits": "session_hits",
    "session_misses": "session_misses",
    "result_cache_hits": "result_cache_hits",
    "result_cache_misses": "result_cache_misses",
    "result_cache_bypass": "result_cache_bypass",
    "scan_cache_hits": "scan_cache_hits",
    "scan_cache_misses": "scan_cache_misses",
    "dist_datanodes": "datanodes",
    "dist_rpc_ms": "rpc_ms",
}


class _Row:
    """Aggregate statistics of one (db, fingerprint)."""

    __slots__ = (
        "fingerprint", "db", "tenant", "channel", "query",
        "calls", "errors", "rows_returned", "total_ms",
        "lat_buckets", "queue_ms", "queue_buckets",
        "path_device", "path_host", "path_dist", "mesh_decision",
        "compile_count", "compile_cache_hits",
        "upload_bytes", "readback_full_bytes", "readback_delta_bytes",
        "session_hits", "session_misses",
        "result_cache_hits", "result_cache_misses", "result_cache_bypass",
        "scan_cache_hits", "scan_cache_misses",
        "shed_count", "deadline_count", "datanodes", "rpc_ms",
        "last_trace_id", "first_seen_ms", "last_seen_ms",
        "metric_fp", "program_ids",
    )

    def __init__(self, fingerprint: str, db: str, tenant: str,
                 channel: str, query: str):
        self.fingerprint = fingerprint
        self.db = db
        self.tenant = tenant
        self.channel = channel
        self.query = query
        self.calls = 0
        self.errors: dict[int, int] = {}
        self.rows_returned = 0
        self.total_ms = 0.0
        self.lat_buckets = [0] * _N_BUCKETS
        self.queue_ms = 0.0
        self.queue_buckets = [0] * _N_BUCKETS
        self.path_device = 0
        self.path_host = 0
        self.path_dist = 0
        self.mesh_decision = ""
        self.compile_count = 0
        self.compile_cache_hits = 0
        self.upload_bytes = 0
        self.readback_full_bytes = 0
        self.readback_delta_bytes = 0
        self.session_hits = 0
        self.session_misses = 0
        self.result_cache_hits = 0
        self.result_cache_misses = 0
        self.result_cache_bypass = 0
        self.scan_cache_hits = 0
        self.scan_cache_misses = 0
        self.shed_count = 0
        self.deadline_count = 0
        self.datanodes = 0
        self.rpc_ms = 0.0
        self.last_trace_id = ""
        # device-program registry ids executions of this shape have
        # dispatched (joins information_schema.device_programs /
        # /debug/prof/device on the `program` column; bounded)
        self.program_ids: list[str] = []
        self.first_seen_ms = int(time.time() * 1000)
        self.last_seen_ms = self.first_seen_ms
        # the /metrics label this row publishes under (its own
        # fingerprint, or "_other" past the metric_fingerprints cap) —
        # decided once at row creation
        self.metric_fp = fingerprint

    # -- folding -------------------------------------------------------
    def fold_obs(self, obs: _Obs, elapsed_ms: float, error_code: int | None):
        self.calls += 1
        self.last_seen_ms = int(time.time() * 1000)
        self.total_ms += elapsed_ms
        _observe_buckets(self.lat_buckets, elapsed_ms)
        if error_code is not None:
            self.errors[error_code] = self.errors.get(error_code, 0) + 1
            if error_code in (6002, 6003):   # overloaded / queue timeout
                self.shed_count += 1
            elif error_code == 6004:         # deadline exceeded
                self.deadline_count += 1
        if obs.notes:
            path = obs.notes.get("exec_path")
            if path == "device":
                self.path_device += 1
            elif path == "dist":
                self.path_dist += 1
            elif path == "host":
                self.path_host += 1
            mesh = obs.notes.get("mesh_decision")
            if mesh:
                self.mesh_decision = mesh
        # one pass over the (small) observation counters instead of a
        # fixed probe per possible key — the hot path typically carries
        # 3-6 of them
        for k, v in obs.counters.items():
            attr = _FOLD_ATTRS.get(k)
            if attr is not None:
                setattr(self, attr, getattr(self, attr) + v)
            elif k == "queue_ms" and v:
                self.queue_ms += v
                _observe_buckets(self.queue_buckets, v)
        if obs.trace_id:
            self.last_trace_id = obs.trace_id
        if obs.programs:
            for pid in obs.programs:
                if (pid not in self.program_ids
                        and len(self.program_ids) < _MAX_OBS_PROGRAMS):
                    self.program_ids.append(pid)

    def fold_row(self, other: "_Row"):
        """Merge another row into this one (LRU eviction into _other)."""
        self.calls += other.calls
        for code, n in other.errors.items():
            self.errors[code] = self.errors.get(code, 0) + n
        self.rows_returned += other.rows_returned
        self.total_ms += other.total_ms
        self.queue_ms += other.queue_ms
        for i in range(_N_BUCKETS):
            self.lat_buckets[i] += other.lat_buckets[i]
            self.queue_buckets[i] += other.queue_buckets[i]
        self.path_device += other.path_device
        self.path_host += other.path_host
        self.path_dist += other.path_dist
        self.compile_count += other.compile_count
        self.compile_cache_hits += other.compile_cache_hits
        self.upload_bytes += other.upload_bytes
        self.readback_full_bytes += other.readback_full_bytes
        self.readback_delta_bytes += other.readback_delta_bytes
        self.session_hits += other.session_hits
        self.session_misses += other.session_misses
        self.result_cache_hits += other.result_cache_hits
        self.result_cache_misses += other.result_cache_misses
        self.result_cache_bypass += other.result_cache_bypass
        self.scan_cache_hits += other.scan_cache_hits
        self.scan_cache_misses += other.scan_cache_misses
        self.shed_count += other.shed_count
        self.deadline_count += other.deadline_count
        self.datanodes += other.datanodes
        self.rpc_ms += other.rpc_ms
        self.first_seen_ms = min(self.first_seen_ms, other.first_seen_ms)
        self.last_seen_ms = max(self.last_seen_ms, other.last_seen_ms)
        if other.last_trace_id:
            self.last_trace_id = other.last_trace_id
        for pid in other.program_ids:
            if (pid not in self.program_ids
                    and len(self.program_ids) < _MAX_OBS_PROGRAMS):
                self.program_ids.append(pid)

    # -- rendering -----------------------------------------------------
    def to_doc(self) -> dict:
        errors = sum(self.errors.values())
        exec_path = ""
        dominant = max(
            ("device", self.path_device), ("dist", self.path_dist),
            ("host", self.path_host), key=lambda kv: kv[1],
        )
        if dominant[1] > 0:
            exec_path = dominant[0]
        return {
            "fingerprint": self.fingerprint,
            "schema_name": self.db,
            "tenant": self.tenant,
            "channel": self.channel,
            "query": self.query,
            "calls": self.calls,
            "errors": errors,
            "errors_by_code": dict(sorted(self.errors.items())),
            "rows_returned": int(self.rows_returned),
            "total_ms": round(self.total_ms, 3),
            "mean_ms": round(self.total_ms / max(self.calls, 1), 3),
            "p50_ms": round(_quantile(self.lat_buckets, 0.50), 3),
            "p99_ms": round(_quantile(self.lat_buckets, 0.99), 3),
            "queue_total_ms": round(self.queue_ms, 3),
            "queue_p99_ms": round(_quantile(self.queue_buckets, 0.99), 3),
            "exec_path": exec_path,
            "mesh_decision": self.mesh_decision,
            "compile_count": int(self.compile_count),
            "compile_cache_hits": int(self.compile_cache_hits),
            "upload_bytes": int(self.upload_bytes),
            "readback_full_bytes": int(self.readback_full_bytes),
            "readback_delta_bytes": int(self.readback_delta_bytes),
            "session_hit_rate": _rate(self.session_hits,
                                      self.session_misses),
            "result_cache_hit_rate": _rate(
                self.result_cache_hits,
                self.result_cache_misses + self.result_cache_bypass,
            ),
            "scan_cache_hit_rate": _rate(self.scan_cache_hits,
                                         self.scan_cache_misses),
            "shed_count": self.shed_count,
            "deadline_count": self.deadline_count,
            "datanodes": int(self.datanodes),
            "rpc_ms": round(self.rpc_ms, 3),
            "last_trace_id": self.last_trace_id,
            "program_ids": list(self.program_ids),
            "first_seen_ms": self.first_seen_ms,
            "last_seen_ms": self.last_seen_ms,
        }


def _rate(hits: int, misses: int) -> float:
    total = hits + misses
    return round(hits / total, 4) if total else 0.0


def _observe_buckets(buckets: list[int], v_ms: float):
    # buckets are NON-cumulative (one increment per observation, with
    # the trailing overflow slot); _quantile accumulates
    _metrics.observe_bucket(buckets, _BUCKETS_MS, v_ms)


def _quantile(buckets: list[int], q: float) -> float:
    return _metrics.bucket_quantile(buckets, _BUCKETS_MS, q)


_ORDER_KEYS = frozenset({
    "calls", "errors", "rows_returned", "total_ms", "mean_ms", "p50_ms",
    "p99_ms", "queue_total_ms", "shed_count", "deadline_count",
    "readback_full_bytes", "readback_delta_bytes", "upload_bytes",
    "rpc_ms", "last_seen_ms",
})


class _MetricBase:
    """Carried totals for one (db, metric_fp) label: what ADMIN reset
    and LRU collapse fold a disappearing row into, so the exported
    prometheus series stay monotone while the queryable registry
    resets/collapses freely."""

    __slots__ = ("calls", "rows", "full", "delta", "total_ms",
                 "lat_buckets", "errors")

    def __init__(self):
        self.calls = 0
        self.rows = 0
        self.full = 0
        self.delta = 0
        self.total_ms = 0.0
        self.lat_buckets = [0] * _N_BUCKETS
        self.errors: dict[int, int] = {}

    def fold_row(self, row: "_Row"):
        self.calls += row.calls
        self.rows += int(row.rows_returned)
        self.full += int(row.readback_full_bytes)
        self.delta += int(row.readback_delta_bytes)
        self.total_ms += row.total_ms
        for i in range(_N_BUCKETS):
            self.lat_buckets[i] += row.lat_buckets[i]
        for code, n in row.errors.items():
            self.errors[code] = self.errors.get(code, 0) + n

    def fold_base(self, other: "_MetricBase"):
        self.calls += other.calls
        self.rows += other.rows
        self.full += other.full
        self.delta += other.delta
        self.total_ms += other.total_ms
        for i in range(_N_BUCKETS):
            self.lat_buckets[i] += other.lat_buckets[i]
        for code, n in other.errors.items():
            self.errors[code] = self.errors.get(code, 0) + n


class _Observation:
    """Context manager for one statement observation (class-based: the
    contextlib generator form costs measurable microseconds per
    statement on the warm-poll path)."""

    __slots__ = ("_reg", "_ctx", "_obs", "_token", "_t0")

    def __init__(self, registry: "StmtStatsRegistry", ctx):
        self._reg = registry
        self._ctx = ctx
        self._obs = None
        self._token = None
        self._t0 = 0.0

    def __enter__(self) -> _Obs | None:
        reg = self._reg
        fp = _pending_fp.get()
        if (not reg.config.enable or fp is None
                or _current.get() is not None):
            return None
        from greptimedb_tpu.telemetry import tracing  # cycle-safe lazy

        ctx = self._ctx
        db = getattr(ctx, "database", "") or "public"
        obs = _Obs(fp, db, getattr(ctx, "username", "") or db,
                   getattr(ctx, "channel", "") or "",
                   tracing.current_trace_id())
        self._obs = obs
        self._token = _current.set(obs)
        self._t0 = time.perf_counter()
        return obs

    def __exit__(self, exc_type, exc, tb):
        obs = self._obs
        if obs is None:
            return False
        code = None
        if exc is not None:
            code = getattr(getattr(exc, "status_code", None), "value",
                           None)
            if code is None:
                code = 1003  # INTERNAL: untyped failure
        _current.reset(self._token)
        self._reg._record(obs, (time.perf_counter() - self._t0)
                          * 1000.0, code)
        return False


class StmtStatsRegistry:
    """Process-wide registry; one per process (`global_stmt_stats`)."""

    def __init__(self, config: StmtStatsConfig | None = None):
        self.config = config or StmtStatsConfig()
        self._lock = concurrency.Lock()
        # serializes whole publish passes (snapshot + child writes):
        # two concurrent scrapes interleaving their writes could expose
        # a STALE aggregate after a newer one — a counter decrease
        self._publish_lock = concurrency.Lock()
        self._rows: OrderedDict[tuple[str, str], _Row] = OrderedDict()
        # fingerprints granted a real /metrics label (prometheus series
        # cannot be evicted, so this cap is first-come like the sched
        # tenant labels; later fingerprints export as "_other")
        self._metric_fps: set[str] = set()
        # carried per-(db, metric label) totals of rows that left the
        # registry (ADMIN reset / LRU collapse): published series must
        # stay monotone even though the queryable rows vanish
        self._metric_base: dict[tuple, _MetricBase] = {}
        # finished observations awaiting their fold. The statement hot
        # path only APPENDS here (one list op — folding touches a
        # 30-field row plus histogram lists, all cache-cold right
        # after a query, and costs ~20us in situ); every reader
        # (snapshot/publish/reset) drains first, and the bound forces
        # a synchronous drain so memory stays fixed under a reader
        # that never scrapes
        self._pending: list = []
        self.evicted_rows = 0

    # -- observation lifecycle ----------------------------------------
    def observe(self, ctx, kind: str) -> "_Observation":
        """Wrap one statement execution (hand-rolled context manager —
        the generator form costs real microseconds on a ~1ms
        statement). Enters as the observation, or None when disabled /
        no fingerprint was bound by execute_sql / an observation is
        already active (nested statement executions — EXECUTE of a
        prepared statement re-dispatching — fold into the OUTER one)."""
        return _Observation(self, ctx)

    _PENDING_MAX = 2048

    # -- folding ------------------------------------------------------
    def _record(self, obs: _Obs, elapsed_ms: float, code: int | None):
        """Queue one finished observation for its fold. The statement
        hot path ends at the append — folding runs when a reader
        drains (snapshot / scrape / reset) or the pending bound hits."""
        with self._lock:
            self._pending.append((obs, elapsed_ms, code))
            if len(self._pending) >= self._PENDING_MAX:
                self._drain_locked()

    def _drain_locked(self):
        for obs, elapsed_ms, code in self._pending:
            key = (obs.db, obs.fp)
            row = self._rows.get(key)
            if row is None:
                # make room INCLUDING the row about to be inserted
                # (collapsing may itself create a db's _other row, so
                # require net progress to terminate)
                while len(self._rows) >= self.config.max_fingerprints:
                    before = len(self._rows)
                    self._collapse_lru_locked()
                    if len(self._rows) >= before:
                        break  # only _other rows remain
                row = _Row(obs.fp, obs.db, obs.tenant, obs.channel,
                           obs.text)
                row.metric_fp = self._metric_fp_locked(obs.fp)
                self._rows[key] = row
            else:
                self._rows.move_to_end(key)
                # tenant/channel track the latest caller of the shape
                row.tenant = obs.tenant
                row.channel = obs.channel
            row.fold_obs(obs, elapsed_ms, code)
        self._pending.clear()

    def _collapse_lru_locked(self):
        """Evict the least-recently-seen row by MERGING it into the
        `_other` row (cardinality collapses, totals never vanish).
        The victim's published metric series freezes at its final
        totals (folded into the carried base under its OWN label —
        prometheus series must stay monotone); only the queryable
        registry row collapses into `_other`."""
        for key in self._rows:
            if key[1] != OTHER:
                victim = self._rows.pop(key)
                break
        else:
            return
        self._fold_base_locked(victim)
        okey = (victim.db, OTHER)
        other = self._rows.get(okey)
        if other is None:
            other = _Row(OTHER, victim.db, victim.tenant,
                         victim.channel, OTHER)
            self._rows[okey] = other
        else:
            self._rows.move_to_end(okey)
        other.fold_row(victim)
        self.evicted_rows += 1

    def _fold_base_locked(self, row: "_Row"):
        """Accumulate a disappearing row's totals into the carried
        metric base so its exported series never decreases."""
        key = (row.db, row.metric_fp)
        base = self._metric_base.get(key)
        if base is None:
            base = self._metric_base[key] = _MetricBase()
        base.fold_row(row)

    # -- scrape-time publishing ---------------------------------------
    def _publish_metrics(self):
        """MetricsRegistry collector: refresh every gtpu_stmt_* family
        from the registry rows + the carried bases. Registry `_other`
        ROWS are excluded — their content is already represented in
        the bases under the collapsed rows' own labels. The publish
        lock covers snapshot AND writes: publishes serialize, so each
        scrape exposes a consistent, never-older aggregate."""
        with self._publish_lock:
            self._publish_locked()

    def _publish_locked(self):
        with self._lock:
            self._drain_locked()
            agg: dict[tuple, _MetricBase] = {}
            for (db, fp), row in self._rows.items():
                if fp == OTHER:
                    continue
                key = (row.db, row.metric_fp)
                b = agg.get(key)
                if b is None:
                    b = agg[key] = _MetricBase()
                b.fold_row(row)
            for key, base in self._metric_base.items():
                b = agg.get(key)
                if b is None:
                    b = agg[key] = _MetricBase()
                b.fold_base(base)
            tracked = len(self._rows)
        _TRACKED.set(tracked)
        for (db, mfp), b in agg.items():
            _set_counter(_CALLS.labels(db, mfp), b.calls)
            _set_counter(_ROWS.labels(db, mfp), b.rows)
            _set_counter(_READBACK.labels(db, mfp, "full"), b.full)
            _set_counter(_READBACK.labels(db, mfp, "delta"), b.delta)
            for code, n in b.errors.items():
                _set_counter(_ERRORS.labels(db, mfp, str(code)), n)
            hist = _LATENCY.labels(db, mfp)
            with hist._lock:
                cum = 0
                # the exported histogram has len(_BUCKETS_MS) bounds;
                # the trailing OVERFLOW slot only reaches the +Inf
                # bucket, which the exposition derives from `count`
                for i in range(len(_BUCKETS_MS)):
                    cum += b.lat_buckets[i]
                    hist.counts[i] = cum
                hist.count = int(b.calls)
                hist.total = b.total_ms / 1000.0

    def _metric_fp_locked(self, fp: str) -> str:
        if fp in self._metric_fps:
            return fp
        if len(self._metric_fps) < self.config.metric_fingerprints:
            self._metric_fps.add(fp)
            return fp
        return OTHER

    # -- surfaces -----------------------------------------------------
    def snapshot(self, *, order_by: str = "total_ms",
                 limit: int = 0) -> list[dict]:
        if order_by not in _ORDER_KEYS:
            order_by = "total_ms"
        with self._lock:
            self._drain_locked()
            docs = [r.to_doc() for r in self._rows.values()]
        docs.sort(key=lambda d: d.get(order_by, 0), reverse=True)
        if limit > 0:
            docs = docs[:limit]
        return docs

    def reset(self) -> int:
        """ADMIN reset_statement_statistics(): drop every row (like
        pg_stat_statements_reset()). The prometheus counters are
        monotone by contract and keep counting: each dropped row's
        totals fold into the carried per-label base the scrape-time
        publisher adds back in."""
        with self._lock:
            self._drain_locked()
            n = len(self._rows)
            for (db, fp), row in self._rows.items():
                if fp != OTHER:
                    # _other rows' content is already in the base
                    # (folded at collapse time)
                    self._fold_base_locked(row)
            self._rows.clear()
            self.evicted_rows = 0
        _TRACKED.set(0)
        return n


global_stmt_stats = StmtStatsRegistry()
# scrape-time publisher: /metrics (and runtime_metrics, and the
# self-export loop) refresh the gtpu_stmt_* families from the registry
# rows on every render — zero prometheus work on the statement hot path
global_registry.register_collector(global_stmt_stats._publish_metrics)


def configure(options: dict | None) -> StmtStatsConfig:
    """Apply the `[stmt_stats]` TOML section to this process. The
    metric-label grant set re-derives under the new cap (already-
    exported prometheus series keep counting regardless)."""
    o = options or {}
    cfg = StmtStatsConfig(
        enable=o.get("enable", True),
        max_fingerprints=o.get("max_fingerprints", 512),
        metric_fingerprints=o.get("metric_fingerprints", 64),
    )
    with global_stmt_stats._lock:
        global_stmt_stats.config = cfg
        global_stmt_stats._metric_fps.clear()
    return cfg


def enabled() -> bool:
    return global_stmt_stats.config.enable
