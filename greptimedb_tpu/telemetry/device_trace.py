"""Device-time attribution for traces.

The BENCH_r03-r05 story is that ~1-30ms of database time rides on a
~90-280ms host<->device tunnel floor — but until now no single query
could SHOW which part it paid: XLA compilation (first call for a program
shape), device execution (dispatch + block_until_ready), or host<->
device transfer (uploads of masks/grids, result readback). This module
wraps the jit/shard_map CALL BOUNDARY in query/device_range.py,
query/reduce.py and promql/fast.py — always from HOST scope, never
inside a traced function (gtlint GT014 flags a span or metric call
inside device scope: it is a host-sync/recompile hazard).

Each wrapped call produces one `device.execute` span carrying:
- site: which kernel family ran (range / groupby / promql / topk / ...)
- compile: "first_call" (this process had not executed this static
  program shape before — the duration includes XLA compilation) or
  "cache_hit" (steady state)
- execute_ms: time to completion of the device computation
  (block_until_ready), excluding result readback
- upload_bytes / readback_bytes: host->device and device->host traffic
  attributable to this call
"""

from __future__ import annotations

import time

from greptimedb_tpu import concurrency
from greptimedb_tpu.telemetry import stmt_stats, tracing

# (site, static program key) shapes this process has already executed:
# membership decides first_call vs cache_hit attribution. Bounded the
# same way the jit caches are in practice (program shapes are few).
_SEEN_MAX = 4096
_seen: set = set()
_seen_lock = concurrency.Lock()


def note_compile(site: str, key) -> str:
    """Record one execution of (site, key); returns the compile
    attribution for THIS call."""
    k = (site, key)
    with _seen_lock:
        if k in _seen:
            return "cache_hit"
        if len(_seen) >= _SEEN_MAX:
            _seen.clear()  # rare; worst case a few re-labelled firsts
        _seen.add(k)
        return "first_call"


class device_call:
    """`with device_trace.device_call("range", key=spec) as d:` — wraps
    one jit/shard_map invocation. The span duration covers dispatch +
    execute + readback; call `d.executed()` right after
    block_until_ready so execute time splits from readback, and
    `d.transfer(nbytes, "upload"|"readback")` for tunnel traffic."""

    __slots__ = ("_cm", "_span", "_mono0", "site", "_stmt")

    def __init__(self, site: str, *, key=None, **attrs):
        self.site = site
        # skip the compile-memo lookup entirely when NEITHER a trace
        # nor a statement observation is active: the memo only feeds
        # attribution, and the bare hot path must stay zero-cost
        self._stmt = stmt_stats.active() is not None
        traced = tracing.enabled() and tracing.current_span() is not None
        if traced or self._stmt:
            comp = note_compile(site, key)
            if self._stmt:
                # per-statement compile-vs-program-cache attribution:
                # a repeatedly polled fingerprint shows compile=1 /
                # cache_hit=N-1 in statement_statistics
                stmt_stats.add("compile_first" if comp == "first_call"
                               else "compile_cache_hit")
        if traced:
            self._cm = tracing.child_span(
                "device.execute", site=site, compile=comp, **attrs,
            )
        else:
            self._cm = tracing.child_span("device.execute")
        self._span = None
        self._mono0 = 0.0

    def __enter__(self) -> "device_call":
        self._span = self._cm.__enter__()
        self._mono0 = time.monotonic()
        return self

    def executed(self):
        """Mark the device computation complete (call right after
        block_until_ready); the remainder of the span is readback."""
        self._span.attributes["execute_ms"] = round(
            (time.monotonic() - self._mono0) * 1000.0, 3
        )

    def transfer(self, nbytes: int, direction: str = "readback"):
        key = f"{direction}_bytes"
        attrs = self._span.attributes
        attrs[key] = int(attrs.get(key, 0)) + int(nbytes)
        if self._stmt and direction == "upload":
            # readback bytes are attributed (full vs delta) at the one
            # blessed crossing in query/readback.py; uploads only here
            stmt_stats.add("upload_bytes", int(nbytes))

    def __exit__(self, exc_type, exc, tb):
        sp = self._span
        if sp is not None and sp.trace_id:
            # per-query device-bytes attribution: the HBM pinned by the
            # registered device pools at the moment this call finished
            # (telemetry/memory.py ledger), so every device.* span on a
            # trace shows what the chip was holding when it ran
            from greptimedb_tpu.telemetry import memory as _memory

            acct = _memory.global_accountant
            if acct.enabled:
                # TTL-cached: a burst of traced device calls must not
                # take every pool's lock per span
                sp.attributes["device_pool_bytes"] = (
                    acct.device_bytes_cached()
                )
        return self._cm.__exit__(exc_type, exc, tb)
