"""Device-time attribution for traces + the program-profiler boundary.

The BENCH_r03-r05 story is that ~1-30ms of database time rides on a
~90-280ms host<->device tunnel floor — but until now no single query
could SHOW which part it paid: XLA compilation (first call for a program
shape), device execution (dispatch + block_until_ready), or host<->
device transfer (uploads of masks/grids, result readback). This module
wraps the jit/shard_map CALL BOUNDARY in query/device_range.py,
query/reduce.py, promql/fast.py, storage/device_merge.py and
flow/device_state.py — always from HOST scope, never inside a traced
function (gtlint GT014 flags a span or metric call inside device scope:
it is a host-sync/recompile hazard; GT018 flags a jit-produced callable
invoked OUTSIDE a device_call scope: an untracked dispatch).

Each wrapped call produces one `device.execute` span carrying:
- site: which kernel family ran (range / groupby / promql / topk / ...)
- compile: "first_call" (this process had not executed this static
  program shape before — the duration includes XLA compilation) or
  "cache_hit" (steady state)
- execute_ms: time to completion of the device computation
  (block_until_ready), excluding result readback
- upload_bytes / readback_bytes: host->device and device->host traffic
  attributable to this call
- program + roofline attribution (telemetry/device_programs.py): the
  program-registry id, and — once the program's XLA cost analysis has
  run — flops, bound=compute|memory and this call's achieved GFLOP/s
  / %-of-peak

Dispatching THROUGH `device_call.run(fn, *args, **kw)` additionally
folds the call into the process-wide device-program registry
(telemetry/device_programs.py): calls, compile/execute timing, transfer
bytes, and the argument shape specs the lazy XLA cost analysis lowers
against. A session hit that skips the dispatch keeps its span but does
NOT count as a program call — the registry describes real dispatches.
"""

from __future__ import annotations

import time

from greptimedb_tpu import concurrency
from greptimedb_tpu.telemetry import stmt_stats, tracing

# (site, static program key) shapes this process has already executed:
# membership decides first_call vs cache_hit attribution. Bounded the
# same way the jit caches are in practice (program shapes are few).
_SEEN_MAX = 4096
_seen: set = set()
_seen_lock = concurrency.Lock()


def note_compile(site: str, key) -> str:
    """Record one execution of (site, key); returns the compile
    attribution for THIS call."""
    k = (site, key)
    with _seen_lock:
        if k in _seen:
            return "cache_hit"
        if len(_seen) >= _SEEN_MAX:
            _seen.clear()  # rare; worst case a few re-labelled firsts
        _seen.add(k)
        return "first_call"


class device_call:
    """`with device_trace.device_call("range", key=spec) as d:` — wraps
    one jit/shard_map invocation. The span duration covers dispatch +
    execute + readback; dispatch the program via `d.run(fn, *args,
    **kw)` so it registers with the device-program profiler, call
    `d.executed()` right after block_until_ready so execute time splits
    from readback (pass `dispatch_only=True` when the caller
    deliberately does not block — async flow applies), and
    `d.transfer(nbytes, "upload"|"readback")` for tunnel traffic."""

    __slots__ = ("_cm", "_span", "_mono0", "site", "_stmt", "key",
                 "_rec", "_first", "_run_t0", "_exec_ms", "_up", "_rb",
                 "_dispatch_only", "collective", "comm_bytes")

    def __init__(self, site: str, *, key=None, collective: bool = False,
                 comm_bytes: int = 0, **attrs):
        self.site = site
        self.key = key
        # collective-time attribution (kernel programs with declared
        # inter-chip copies): rides the span AND the program row, so
        # bench multichip can report communication share per mesh size
        self.collective = bool(collective)
        self.comm_bytes = int(comm_bytes)
        if self.collective:
            attrs = dict(attrs)
            attrs["collective"] = True
            attrs["comm_bytes"] = self.comm_bytes
        self._rec = None
        self._first = False
        self._run_t0 = 0.0
        self._exec_ms = None
        self._up = 0
        self._rb = 0
        self._dispatch_only = False
        # skip the compile-memo lookup entirely when NEITHER a trace
        # nor a statement observation is active: the memo only feeds
        # attribution, and the bare hot path must stay zero-cost
        self._stmt = stmt_stats.active() is not None
        traced = tracing.enabled() and tracing.current_span() is not None
        if traced or self._stmt:
            comp = note_compile(site, key)
            if self._stmt:
                # per-statement compile-vs-program-cache attribution:
                # a repeatedly polled fingerprint shows compile=1 /
                # cache_hit=N-1 in statement_statistics
                stmt_stats.add("compile_first" if comp == "first_call"
                               else "compile_cache_hit")
        if traced:
            self._cm = tracing.child_span(
                "device.execute", site=site, compile=comp, **attrs,
            )
        else:
            self._cm = tracing.child_span("device.execute")
        self._span = None
        self._mono0 = 0.0

    def __enter__(self) -> "device_call":
        self._span = self._cm.__enter__()
        self._mono0 = time.monotonic()
        return self

    def run(self, fn, *args, **kw):
        """Dispatch the program. Registers (site, key) with the
        device-program registry — first dispatch captures the argument
        shape specs for the lazy XLA cost analysis — and anchors the
        execute timer at the dispatch, so session lookups before it
        never count as device time."""
        from greptimedb_tpu.telemetry import device_programs

        reg = device_programs.global_programs
        if reg.config.enable:
            prep = reg.prepare(self.site, self.key, fn, args, kw)
            if prep is not None:
                self._rec, self._first = prep
        self._run_t0 = time.monotonic()
        return fn(*args, **kw)

    def executed(self, *, dispatch_only: bool = False):
        """Mark the device computation complete (call right after
        block_until_ready); the remainder of the span is readback.
        dispatch_only=True records that the caller did NOT block — the
        timing covers dispatch, not the computation — so the profiler
        suppresses achieved-rate claims for this program."""
        now = time.monotonic()
        self._exec_ms = (now - (self._run_t0 or self._mono0)) * 1000.0
        self._dispatch_only = dispatch_only
        self._span.attributes["execute_ms"] = round(
            (now - self._mono0) * 1000.0, 3
        )

    def transfer(self, nbytes: int, direction: str = "readback"):
        nbytes = int(nbytes)
        if direction == "upload":
            self._up += nbytes
        else:
            self._rb += nbytes
        key = f"{direction}_bytes"
        attrs = self._span.attributes
        attrs[key] = int(attrs.get(key, 0)) + nbytes
        if self._stmt and direction == "upload":
            # readback bytes are attributed (full vs delta) at the one
            # blessed crossing in query/readback.py; uploads only here
            stmt_stats.add("upload_bytes", int(nbytes))

    def _fold_program(self, sp, rec, *, dispatched: bool):
        """Fold the dispatch into the program registry (when one
        happened) + attach the program / roofline attribution to the
        span, EXPLAIN ANALYZE stats and the statement observation.
        A no-dispatch path (session hit) attributes without folding —
        and without per-call achieved rates, since no compute ran."""
        from greptimedb_tpu.telemetry import device_programs

        reg = device_programs.global_programs
        if dispatched:
            reg.finish(rec, execute_ms=self._exec_ms,
                       upload=self._up, readback=self._rb,
                       dispatch_only=self._dispatch_only,
                       run_start=self._run_t0 or None,
                       collective=self.collective,
                       comm_bytes=self.comm_bytes)
        if self._stmt:
            # program-registry link: the statement_statistics row lists
            # the program ids its executions used (dispatched, or
            # served from the program's session buffer)
            stmt_stats.note_program(rec.prog_id)
        roof = None
        if rec.analysis == "ok":
            pf, pb, _plat, _src = reg.peaks()
            bound, _pct = rec.roofline(pf, pb)
            gflops = gbps = pct = 0.0
            if (dispatched and self._exec_ms and self._exec_ms > 0
                    and not self._dispatch_only and not self._first):
                s = self._exec_ms / 1000.0
                gflops = rec.flops / s / 1e9
                gbps = rec.bytes_accessed / s / 1e9
                if bound == "compute":
                    pct = gflops / (pf * 1e3) * 100.0
                elif bound == "memory":
                    pct = gbps / pb * 100.0
            roof = (bound, gflops, gbps, pct)
        traced = sp is not None and sp.trace_id
        if traced:
            sp.attributes["program"] = rec.prog_id
            if roof is not None:
                sp.attributes["flops"] = rec.flops
                if roof[0]:
                    sp.attributes["roofline_bound"] = roof[0]
                    if dispatched:
                        sp.attributes["pct_of_peak"] = round(roof[3], 3)
                if dispatched:
                    sp.attributes["achieved_gflops"] = round(roof[1], 3)
        from greptimedb_tpu.query import stats as qstats

        if qstats.active() is not None:
            qstats.note(f"device_program_{self.site}", rec.prog_id)
            if roof is not None and roof[0]:
                if dispatched:
                    qstats.note(
                        f"roofline_{self.site}",
                        f"{roof[0]}-bound {roof[3]:.1f}% of peak "
                        f"({roof[1]:.1f} GFLOP/s, {roof[2]:.1f} GB/s)",
                    )
                else:
                    # steady-state row numbers: this call served from
                    # the session buffer, no program ran
                    _bound, row_pct = rec.roofline(pf, pb)
                    g, b = rec.achieved()
                    qstats.note(
                        f"roofline_{self.site}",
                        f"{roof[0]}-bound {row_pct:.1f}% of peak at "
                        f"p50 ({g:.1f} GFLOP/s, {b:.1f} GB/s; served "
                        "from the session buffer)",
                    )

    def __exit__(self, exc_type, exc, tb):
        sp = self._span
        rec = self._rec
        dispatched = rec is not None
        if rec is None:
            # no dispatch happened (session hit): when someone is
            # watching (trace / statement stats / EXPLAIN ANALYZE),
            # attribute the program row read-only — the warm steady
            # state must not lose the program link
            watching = self._stmt or (sp is not None and sp.trace_id)
            if not watching:
                from greptimedb_tpu.query import stats as qstats

                watching = qstats.active() is not None
            if watching:
                from greptimedb_tpu.telemetry import device_programs

                rec = device_programs.global_programs.lookup(
                    self.site, self.key
                )
        if rec is not None:
            self._fold_program(sp, rec, dispatched=dispatched)
        if sp is not None and sp.trace_id:
            # per-query device-bytes attribution: the HBM pinned by the
            # registered device pools at the moment this call finished
            # (telemetry/memory.py ledger), so every device.* span on a
            # trace shows what the chip was holding when it ran
            from greptimedb_tpu.telemetry import memory as _memory

            acct = _memory.global_accountant
            if acct.enabled:
                # TTL-cached: a burst of traced device calls must not
                # take every pool's lock per span
                sp.attributes["device_pool_bytes"] = (
                    acct.device_bytes_cached()
                )
        return self._cm.__exit__(exc_type, exc, tb)
