"""Metrics self-export: periodically import the node's own metrics into
the TSDB (or push them to a remote-write endpoint).

Capability counterpart of the reference's ExportMetricsTask
(/root/reference/src/servers/src/export_metrics.rs:81-191): every
`interval_s` the global registry is scraped in-process and written
through the same per-metric table path Prometheus remote write uses, so
`select * from greptime_http_requests_total` works on the node itself.
"""

from __future__ import annotations

import re
import threading

import time

from greptimedb_tpu.telemetry.metrics import global_registry

from greptimedb_tpu import concurrency

_LINE = re.compile(
    r"^(?P<name>[A-Za-z_:][A-Za-z0-9_:]*)"
    # quote-aware label block: a '}' inside a quoted label value (e.g.
    # path="a}b") must not terminate the block early
    r'(?:\{(?P<labels>(?:[^"}]|"(?:[^"\\]|\\.)*")*)\})?\s+(?P<value>[^\s]+)$'
)
_LABEL = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def scrape_registry(now_ms: int | None = None,
                    extra_labels: dict | None = None) -> list:
    """Render the global registry and parse it into remote-write-shaped
    series: [(labels-with-__name__, [(value, ts_ms)])]. `extra_labels`
    (e.g. {"node": ..., "role": ...}) stamp every series WITHOUT
    overriding a label the metric already carries — two roles exporting
    into one greptime_metrics database must never collide into one
    series."""
    now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
    series = []
    for line in global_registry.render().splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = {"__name__": m.group("name")}
        if m.group("labels"):
            for lk, lv in _LABEL.findall(m.group("labels")):
                labels[lk] = lv.replace('\\"', '"').replace("\\\\", "\\")
        if extra_labels:
            for lk, lv in extra_labels.items():
                if lv:
                    labels.setdefault(lk, str(lv))
        series.append((labels, [(value, now_ms)]))
    return series


class ExportMetricsTask:
    """Background self-import loop. `instance` is a Standalone (or any
    object with the catalog/_notify_flows surface apply_series needs)."""

    def __init__(self, instance, *, db: str = "greptime_metrics",
                 interval_s: float = 30.0, node: str | None = None,
                 role: str | None = None):
        self.instance = instance
        self.db = db
        self.interval_s = max(1.0, float(interval_s))
        # node/role identity labels stamped on every re-ingested
        # series. None = resolve from the instance AT TICK TIME (the
        # dialable address may bind after this task is constructed).
        self.node = node
        self.role = role
        self._stop = concurrency.Event()
        self._thread: threading.Thread | None = None
        self.runs = 0
        self.samples_written = 0
        self.failures = 0
        self._last_error: str | None = None

    def _identity_labels(self) -> dict:
        node = self.node
        if node is None:
            node = getattr(self.instance, "node_addr", "") or ""
        role = self.role
        if role is None:
            role = getattr(self.instance, "node_role", "") or ""
        return {"node": node, "role": role}

    def start(self):
        self.instance.catalog.create_database(self.db, if_not_exists=True)
        # one immediate tick BEFORE the interval loop: the first
        # samples land at startup, not a full interval_s later (an
        # operator querying greptime_metrics right after boot sees
        # data; the loop thread then keeps the cadence). A failing
        # first tick must not abort startup — it counts like a loop
        # failure and the loop retries.
        self._safe_tick()
        self._thread = concurrency.Thread(
            target=self._loop, daemon=True, name="export-metrics"
        )
        self._thread.start()
        return self

    def tick(self):
        """One scrape+import cycle (also called by the loop). Duration
        lands on the greptime_export_metrics_duration_seconds histogram
        so a slow scrape/import (large registry, slow storage) is
        visible before it starts eating the interval."""
        import time as _time

        from greptimedb_tpu.servers.prom_store import apply_series

        t0 = _time.perf_counter()
        try:
            series = scrape_registry(
                extra_labels=self._identity_labels()
            )
            if series:
                self.samples_written += apply_series(
                    self.instance, series, db=self.db
                )
            self.runs += 1
        finally:
            global_registry.histogram(
                "greptime_export_metrics_duration_seconds",
                "wall time of one metrics self-export tick",
            ).observe(_time.perf_counter() - t0)

    def _safe_tick(self):
        import logging

        try:
            self.tick()
        except Exception as e:  # export must never take the node down,
            # but persistent failures need a trace: log each distinct
            # error once and count every failure in the registry
            self.failures += 1
            global_registry.counter(
                "greptime_export_metrics_failures_total",
                "metrics self-export tick failures",
            ).inc()
            msg = f"{type(e).__name__}: {e}"
            if msg != self._last_error:
                self._last_error = msg
                logging.getLogger("greptimedb_tpu.export").warning(
                    "metrics self-export failing: %s", msg
                )

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self._safe_tick()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
