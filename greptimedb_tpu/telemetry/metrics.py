"""Prometheus-style in-process metrics.

Capability counterpart of the reference's per-crate Prometheus registries
(/root/reference/src/*/src/metrics.rs + the /metrics endpoint,
src/servers/src/metrics_handler.rs): counters, gauges, histograms with
labels, rendered in the text exposition format.
"""

from __future__ import annotations

import time

from greptimedb_tpu import concurrency

class _Metric:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...]):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._children: dict[tuple, object] = {}
        self._lock = concurrency.Lock()

    def labels(self, *values: str):
        key = tuple(str(v) for v in values)
        if len(key) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected {len(self.label_names)} labels"
            )
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
            return child

    def _snapshot(self) -> list[tuple[tuple, object]]:
        with self._lock:
            return list(self._children.items())

    def _default(self):
        return self.labels()


def _fmt_labels(names, values) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{v}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _CounterChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = concurrency.Lock()

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount


class Counter(_Metric):
    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0):
        self._default().inc(amount)

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        for key, c in self._snapshot():
            out.append(
                f"{self.name}{_fmt_labels(self.label_names, key)} {c.value}"
            )
        return out


class _GaugeChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = concurrency.Lock()

    def set(self, v: float):
        with self._lock:
            self.value = float(v)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)


class Gauge(_Metric):
    def _new_child(self):
        return _GaugeChild()

    def set(self, v: float):
        self._default().set(v)

    def inc(self, amount: float = 1.0):
        self._default().inc(amount)

    def dec(self, amount: float = 1.0):
        self._default().dec(amount)

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        for key, c in self._snapshot():
            out.append(
                f"{self.name}{_fmt_labels(self.label_names, key)} {c.value}"
            )
        return out


_DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0,
)


class _HistogramChild:
    __slots__ = ("buckets", "counts", "total", "count", "_lock")

    def __init__(self, buckets):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.total = 0.0
        self.count = 0
        self._lock = concurrency.Lock()

    def observe(self, v: float):
        with self._lock:
            self.total += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1

    def time(self):
        return _Timer(self)


class _Timer:
    def __init__(self, child):
        self.child = child

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.child.observe(time.perf_counter() - self.t0)


class Histogram(_Metric):
    def __init__(self, name, help_, label_names=(), buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(buckets)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float):
        self._default().observe(v)

    def time(self):
        return self._default().time()

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        for key, c in self._snapshot():
            # observe() increments every bucket with v <= bound, so counts
            # are already cumulative as the exposition format requires
            for b, n in zip(self.buckets, c.counts):
                lab = _fmt_labels(
                    self.label_names + ("le",), key + (repr(float(b)),)
                )
                out.append(f"{self.name}_bucket{lab} {n}")
            lab = _fmt_labels(self.label_names + ("le",), key + ("+Inf",))
            out.append(f"{self.name}_bucket{lab} {c.count}")
            out.append(
                f"{self.name}_sum{_fmt_labels(self.label_names, key)} "
                f"{c.total}"
            )
            out.append(
                f"{self.name}_count{_fmt_labels(self.label_names, key)} "
                f"{c.count}"
            )
        return out


class MetricsRegistry:
    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = concurrency.Lock()

    def counter(self, name, help_="", labels=()) -> Counter:
        return self._get(name, lambda: Counter(name, help_, tuple(labels)))

    def gauge(self, name, help_="", labels=()) -> Gauge:
        return self._get(name, lambda: Gauge(name, help_, tuple(labels)))

    def histogram(self, name, help_="", labels=(),
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._get(
            name, lambda: Histogram(name, help_, tuple(labels), buckets)
        )

    def _get(self, name, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            return m

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


global_registry = MetricsRegistry()
