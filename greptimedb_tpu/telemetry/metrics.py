"""Prometheus-style in-process metrics.

Capability counterpart of the reference's per-crate Prometheus registries
(/root/reference/src/*/src/metrics.rs + the /metrics endpoint,
src/servers/src/metrics_handler.rs): counters, gauges, histograms with
labels, rendered in the text exposition format.
"""

from __future__ import annotations

import time

from greptimedb_tpu import concurrency


class MetricRegistrationError(TypeError):
    """A metric name was re-registered as a different type or with a
    different label set. The registry is get-or-create by name, so the
    second registration used to silently return the FIRST metric — and
    the caller's `.labels(...)` then raised (or mislabelled) far from
    the actual bug. Raised at registration time instead, naming both
    schemas."""


class _Metric:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...]):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._children: dict[tuple, object] = {}
        self._lock = concurrency.Lock()

    def labels(self, *values: str):
        key = tuple(str(v) for v in values)
        if len(key) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected {len(self.label_names)} labels"
            )
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
            return child

    def _snapshot(self) -> list[tuple[tuple, object]]:
        with self._lock:
            return list(self._children.items())

    def _default(self):
        return self.labels()


def _fmt_labels(names, values) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{v}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _CounterChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = concurrency.Lock()

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount


class Counter(_Metric):
    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0):
        self._default().inc(amount)

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        for key, c in self._snapshot():
            out.append(
                f"{self.name}{_fmt_labels(self.label_names, key)} {c.value}"
            )
        return out


class _GaugeChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = concurrency.Lock()

    def set(self, v: float):
        with self._lock:
            self.value = float(v)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)


class Gauge(_Metric):
    def _new_child(self):
        return _GaugeChild()

    def set(self, v: float):
        self._default().set(v)

    def inc(self, amount: float = 1.0):
        self._default().inc(amount)

    def dec(self, amount: float = 1.0):
        self._default().dec(amount)

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        for key, c in self._snapshot():
            out.append(
                f"{self.name}{_fmt_labels(self.label_names, key)} {c.value}"
            )
        return out


_DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0,
)


class _HistogramChild:
    __slots__ = ("buckets", "counts", "total", "count", "_lock")

    def __init__(self, buckets):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.total = 0.0
        self.count = 0
        self._lock = concurrency.Lock()

    def observe(self, v: float):
        with self._lock:
            self.total += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1

    def time(self):
        return _Timer(self)


class _Timer:
    def __init__(self, child):
        self.child = child

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.child.observe(time.perf_counter() - self.t0)


class Histogram(_Metric):
    def __init__(self, name, help_, label_names=(), buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(buckets)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float):
        self._default().observe(v)

    def time(self):
        return self._default().time()

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        for key, c in self._snapshot():
            # read counts/total/count under the child lock: a scrape
            # racing observe() must never see a half-applied observation
            # (low buckets bumped, high buckets not yet — a non-monotone
            # cumulative family — or sum/count disagreeing with +Inf)
            with c._lock:
                counts = list(c.counts)
                total = c.total
                count = c.count
            # observe() increments every bucket with v <= bound, so counts
            # are already cumulative as the exposition format requires
            for b, n in zip(self.buckets, counts):
                lab = _fmt_labels(
                    self.label_names + ("le",), key + (repr(float(b)),)
                )
                out.append(f"{self.name}_bucket{lab} {n}")
            lab = _fmt_labels(self.label_names + ("le",), key + ("+Inf",))
            out.append(f"{self.name}_bucket{lab} {count}")
            out.append(
                f"{self.name}_sum{_fmt_labels(self.label_names, key)} "
                f"{total}"
            )
            out.append(
                f"{self.name}_count{_fmt_labels(self.label_names, key)} "
                f"{count}"
            )
        return out


def observe_bucket(buckets: list, bounds: tuple, v: float):
    """Non-cumulative bucket observe for registry-local histograms
    (stmt_stats latency/queue, device-program execute): one increment
    per observation, with a trailing OVERFLOW slot past the last bound
    so slow outliers still count toward the percentiles. `buckets`
    must be len(bounds) + 1."""
    for i, b in enumerate(bounds):
        if v <= b:
            buckets[i] += 1
            return
    buckets[-1] += 1


def bucket_quantile(buckets: list, bounds: tuple, q: float) -> float:
    """Linear-interpolated quantile over observe_bucket counts; the
    overflow slot reports at the last bound (a floor — the registries
    do not track the true maximum)."""
    total = sum(buckets)
    if total == 0:
        return 0.0
    target = q * total
    cum = 0
    prev = 0.0
    for i, b in enumerate(bounds):
        n = buckets[i]
        if n and cum + n >= target:
            return prev + (b - prev) * ((target - cum) / n)
        cum += n
        prev = b
    return bounds[-1]


def set_child_value(child, value: float):
    """Pull-model publisher helper: overwrite a counter/gauge child's
    value under its lock (scrape-time publishers — stmt_stats, the
    device-program profiler — refresh exported families from their
    registries instead of incrementing on the hot path)."""
    with child._lock:
        child.value = float(value)


class MetricsRegistry:
    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = concurrency.Lock()
        # scrape-time callbacks (run at the START of render, outside the
        # registry lock): pull-model publishers — the memory accountant
        # refreshes its per-pool gauges here so /metrics always shows
        # current pool state without a background thread
        self._collectors: list = []

    def counter(self, name, help_="", labels=()) -> Counter:
        return self._get(name, Counter, tuple(labels),
                         lambda: Counter(name, help_, tuple(labels)))

    def gauge(self, name, help_="", labels=()) -> Gauge:
        return self._get(name, Gauge, tuple(labels),
                         lambda: Gauge(name, help_, tuple(labels)))

    def histogram(self, name, help_="", labels=(),
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._get(
            name, Histogram, tuple(labels),
            lambda: Histogram(name, help_, tuple(labels), buckets)
        )

    def _get(self, name, cls, label_names, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
                return m
        # conflict checks OUTSIDE the lock (pure reads of immutable
        # registration-time attributes)
        if type(m) is not cls:
            raise MetricRegistrationError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, re-registered as {cls.__name__}"
            )
        if m.label_names != label_names:
            raise MetricRegistrationError(
                f"metric {name!r} already registered with labels "
                f"{m.label_names!r}, re-registered with {label_names!r}"
                " — use MetricsRegistry.get(name) for lookups"
            )
        return m

    def get(self, name) -> _Metric:
        """Look up an existing metric WITHOUT declaring its schema
        (bench/test readers that only consume values). KeyError when
        the metric has not been registered by its owning module yet."""
        with self._lock:
            m = self._metrics.get(name)
        if m is None:
            raise KeyError(f"metric {name!r} is not registered")
        return m

    def register_collector(self, fn) -> None:
        """Add a scrape-time callback invoked before every render()."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def render(self) -> str:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - a broken publisher
                # must never take /metrics down with it
                import logging

                logging.getLogger("greptimedb_tpu.metrics").debug(
                    "metrics collector failed: %s", e
                )
        with self._lock:
            metrics = list(self._metrics.values())
        lines = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


global_registry = MetricsRegistry()
