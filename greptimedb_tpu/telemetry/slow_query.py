"""Slow-query logging.

Capability counterpart of the reference's StatementStatistics slow-query
support (/root/reference/src/cmd/src/standalone.rs:570 wiring + the
[logging.slow_query] config section): statements slower than the
threshold are logged and kept in a bounded ring surfaced through
`information_schema.slow_queries`.
"""

from __future__ import annotations

import logging
import random

import time
from collections import deque

from greptimedb_tpu import concurrency

logger = logging.getLogger("greptimedb_tpu.slow_query")


class SlowQueryLog:
    def __init__(self, *, enable: bool = True, threshold_s: float = 5.0,
                 sample_ratio: float = 1.0, capacity: int = 256):
        self.enable = enable
        self.threshold_s = float(threshold_s)
        self.sample_ratio = min(1.0, max(0.0, float(sample_ratio)))
        self._ring: deque = deque(maxlen=max(1, capacity))
        self._lock = concurrency.Lock()
        self.total_recorded = 0

    def maybe_record(self, sql: str, elapsed_s: float, *, db: str = "",
                     channel: str = "", trace_id: str | None = None,
                     fingerprint: str = ""):
        """Record one slow statement. `elapsed_s` MUST come from the
        monotonic clock (time.monotonic()/perf_counter deltas, never
        time.time() arithmetic — gtlint GT011); ts_ms below is an
        epoch-ms display timestamp only. `trace_id` links the entry to
        its trace in /v1/traces + information_schema.traces;
        `fingerprint` (the batch's first statement) joins it to its
        aggregate `information_schema.statement_statistics` row."""
        if not self.enable or elapsed_s < self.threshold_s:
            return
        if self.sample_ratio < 1.0 and random.random() > self.sample_ratio:
            return
        entry = {
            "ts_ms": int(time.time() * 1000),
            "cost_ms": round(elapsed_s * 1000.0, 3),
            "threshold_ms": round(self.threshold_s * 1000.0, 3),
            "query": sql[:4096],
            "schema": db,
            "channel": channel,
            "trace_id": trace_id or "",
            "fingerprint": fingerprint or "",
        }
        with self._lock:
            self._ring.append(entry)
            self.total_recorded += 1
        logger.warning(
            "slow query (%.1f ms > %.0f ms) [%s] trace=%s: %s",
            entry["cost_ms"], entry["threshold_ms"], db,
            entry["trace_id"] or "-", entry["query"],
        )

    def entries(self) -> list[dict]:
        with self._lock:
            return list(self._ring)
