"""Device program profiler: the XLA cost registry behind every
``device_call``.

The north star is "as fast as the hardware allows", but tracing (PR 8),
memory (PR 10) and statement statistics (PR 13) all attribute per-query
— the compiled XLA programs that actually burn the device time stayed
anonymous. This module is the process-wide registry every program
dispatched through ``telemetry/device_trace.device_call.run`` folds
into, one row per compiled program (site + static program key):

- per-call stats: calls, compile_ms (wall time of the process's FIRST
  execution, which includes XLA compilation), cumulative execute_ms and
  p50/p99 from a bucketed histogram, upload/readback bytes;
- XLA analysis (lazy, on first surface consult): ``Lowered.
  cost_analysis()`` flops + bytes accessed, and ``Compiled.
  memory_analysis()`` temp/output/argument bytes. Argument SHAPES are
  captured at first dispatch (jax.ShapeDtypeStruct — no device buffers
  pinned) so the analysis re-lowers the exact program without holding
  live data;
- roofline attribution: operational intensity I = flops / bytes
  accessed compared against the machine balance peak_flops / peak_bw
  classifies each program ``bound=compute|memory``; achieved GFLOP/s
  and HBM GB/s derive from the p50 execute time, and %-of-peak is the
  achieved fraction of the BOUNDING resource. Peaks come from the
  ``[profiling]`` knobs; on a TPU backend they default to v5e
  single-chip numbers, on CPU runs the registry reports achieved-only
  (no verdict) unless peaks are configured explicitly.

Surfaces: ``information_schema.device_programs``, ``/debug/prof/device``
(text + ?format=json, top-N by cumulative device time),
``gtpu_device_program_*`` pull-model metrics (published from the rows
at scrape time), roofline attrs on ``device.execute`` spans and EXPLAIN
ANALYZE, ``ADMIN reset_device_profiler()``, and a per-statement
``program_ids`` link from every statement_statistics row to the
programs it dispatched. Unlike the gtpu_stmt_* families (carried-base
monotone), ADMIN reset here resets the exported series too — the
3-surface agreement contract (information_schema == /debug/prof/device
== gtpu_device_program_*) is exact at every scrape, and Prometheus
consumers treat the drop as an ordinary counter reset.

On-demand trace capture (``/debug/prof/device/trace?seconds=``) wraps
``jax.profiler.start_trace``/``stop_trace`` and writes a TensorBoard/
perfetto-loadable trace under ``[profiling] trace_dir``.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import time
from collections import OrderedDict

from greptimedb_tpu import concurrency
from greptimedb_tpu.telemetry import metrics
from greptimedb_tpu.telemetry.metrics import (
    global_registry,
    set_child_value as _set_value,
)

# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

# v5e single-chip roofline peaks (Google Cloud TPU v5e system
# architecture docs): 197 TFLOP/s bf16 MXU peak, 819 GB/s HBM
# bandwidth. Used when the backend is a TPU and the [profiling] knobs
# leave a peak at 0 (= auto); every other platform reports
# achieved-only unless both peaks are configured explicitly.
V5E_PEAK_TFLOPS = 197.0
V5E_PEAK_HBM_GBPS = 819.0


class ProfilingConfig:
    """`[profiling]` options (config.py DEFAULTS documents each)."""

    __slots__ = ("enable", "max_programs", "metric_programs",
                 "peak_tflops", "peak_hbm_gbps", "analysis",
                 "trace_dir")

    def __init__(self, *, enable: bool = True, max_programs: int = 256,
                 metric_programs: int = 128,
                 peak_tflops: float = 0.0, peak_hbm_gbps: float = 0.0,
                 analysis: bool = True, trace_dir: str = ""):
        self.enable = bool(enable)
        self.max_programs = max(1, int(max_programs))
        # /metrics label cap: prometheus series can never be evicted,
        # so real (site, program) labels are granted FIRST-COME (like
        # stmt_stats' metric_fingerprints); later programs export
        # under program="_other"
        self.metric_programs = max(0, int(metric_programs))
        self.peak_tflops = float(peak_tflops or 0.0)
        self.peak_hbm_gbps = float(peak_hbm_gbps or 0.0)
        self.analysis = bool(analysis)
        self.trace_dir = str(trace_dir or "")


# ---------------------------------------------------------------------------
# metrics — PULL-model like gtpu_stmt_*: families publish from the
# registry rows at scrape time via a MetricsRegistry collector, so the
# dispatch hot path never touches a prometheus child lock. Label
# cardinality is bounded by [profiling] max_programs (LRU rows collapse
# into a per-site "_other" row). ADMIN reset zeroes the exported
# series (an ordinary prometheus counter reset) so all three surfaces
# stay exactly equal.
# ---------------------------------------------------------------------------

_M_CALLS = global_registry.counter(
    "gtpu_device_program_calls_total",
    "device program dispatches per (site, program)",
    labels=("site", "program"),
)
_M_EXEC = global_registry.counter(
    "gtpu_device_program_execute_ms_total",
    "cumulative steady-state execute ms per (site, program) "
    "(excludes the first call, whose wall time is compile_ms)",
    labels=("site", "program"),
)
_M_UPLOAD = global_registry.counter(
    "gtpu_device_program_upload_bytes_total",
    "host->device bytes uploaded by dispatches of (site, program)",
    labels=("site", "program"),
)
_M_READBACK = global_registry.counter(
    "gtpu_device_program_readback_bytes_total",
    "device->host bytes read back by dispatches of (site, program)",
    labels=("site", "program"),
)
_M_COMM = global_registry.counter(
    "gtpu_device_program_comm_bytes_total",
    "declared inter-chip bytes moved by collective kernel dispatches "
    "of (site, program)",
    labels=("site", "program"),
)
_M_COMPILE = global_registry.gauge(
    "gtpu_device_program_compile_ms",
    "wall time of the first execution (includes XLA compilation)",
    labels=("site", "program"),
)
_M_P50 = global_registry.gauge(
    "gtpu_device_program_execute_p50_ms",
    "p50 steady-state execute ms per (site, program)",
    labels=("site", "program"),
)
_M_P99 = global_registry.gauge(
    "gtpu_device_program_execute_p99_ms",
    "p99 steady-state execute ms per (site, program)",
    labels=("site", "program"),
)
_M_FLOPS = global_registry.gauge(
    "gtpu_device_program_flops",
    "per-call FLOPs from XLA cost_analysis (0 until analyzed)",
    labels=("site", "program"),
)
_M_BYTES = global_registry.gauge(
    "gtpu_device_program_bytes_accessed",
    "per-call HBM bytes accessed from XLA cost_analysis",
    labels=("site", "program"),
)
_M_GFLOPS = global_registry.gauge(
    "gtpu_device_program_achieved_gflops",
    "achieved GFLOP/s at the p50 execute time",
    labels=("site", "program"),
)
_M_GBPS = global_registry.gauge(
    "gtpu_device_program_achieved_hbm_gbps",
    "achieved HBM GB/s at the p50 execute time",
    labels=("site", "program"),
)
_M_PCT = global_registry.gauge(
    "gtpu_device_program_pct_of_peak",
    "achieved fraction of the roofline-bounding resource (percent; "
    "0 when peaks are unknown on this platform)",
    labels=("site", "program"),
)
_M_COUNT = global_registry.gauge(
    "gtpu_device_programs",
    "distinct program rows currently tracked by the profiler",
)

OTHER = "_other"

# execute-time histogram bounds (ms) for the per-row p50/p99; one
# OVERFLOW slot past the last bound like stmt_stats' buckets
_EXEC_BUCKETS_MS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
)
_N_BUCKETS = len(_EXEC_BUCKETS_MS) + 1


def _observe(buckets: list[int], v_ms: float):
    metrics.observe_bucket(buckets, _EXEC_BUCKETS_MS, v_ms)


def _quantile(buckets: list[int], q: float) -> float:
    return metrics.bucket_quantile(buckets, _EXEC_BUCKETS_MS, q)


def _platform() -> str:
    """The active jax backend platform, WITHOUT forcing jax to
    initialize: a process that never dispatched a program must be able
    to scrape /metrics without paying a backend bring-up."""
    import sys

    if "jax" not in sys.modules:
        return "none"
    try:
        import jax

        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 - no usable backend
        return "none"


def _prog_id(site: str, key) -> str:
    return hashlib.blake2b(
        repr((site, key)).encode(), digest_size=6
    ).hexdigest()


def _arg_spec(a):
    """Shape/dtype skeleton of one program argument: concrete arrays
    (device or host) reduce to jax.ShapeDtypeStruct so the captured
    spec pins no device memory; static values pass through unchanged."""
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is not None and dtype is not None:
        import jax

        return jax.ShapeDtypeStruct(tuple(shape), dtype)
    return a


class _Program:
    """One compiled program's aggregate row."""

    __slots__ = (
        "site", "prog_id", "key_text", "calls", "compile_ms",
        "execute_ms_total", "exec_buckets", "upload_bytes",
        "readback_bytes", "dispatch_only", "errors",
        "first_seen_ms", "last_seen_ms",
        "analysis", "analysis_error", "flops", "bytes_accessed",
        "temp_bytes", "output_bytes", "argument_bytes",
        "aot_compile_ms", "_spec", "_compile_done", "metric_prog",
        "collective", "comm_bytes",
    )

    def __init__(self, site: str, prog_id: str, key_text: str):
        self.site = site
        self.prog_id = prog_id
        self.key_text = key_text
        self.calls = 0
        self.compile_ms: float | None = None
        self.execute_ms_total = 0.0
        self.exec_buckets = [0] * _N_BUCKETS
        self.upload_bytes = 0
        self.readback_bytes = 0
        # True when at least one fold timed only the DISPATCH (the
        # caller did not block_until_ready — flow apply): achieved
        # rates would overstate, so they are suppressed for the row
        self.dispatch_only = False
        self.errors = 0
        self.first_seen_ms = int(time.time() * 1000)
        self.last_seen_ms = self.first_seen_ms
        self.analysis = "pending"      # pending | ok | failed | off
        self.analysis_error = ""
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.temp_bytes = 0
        self.output_bytes = 0
        self.argument_bytes = 0
        self.aot_compile_ms = 0.0
        # collective kernel programs (Pallas ring/merge paths) declare
        # their inter-chip copy sizes per dispatch; cumulative here so
        # communication share per program is computable from the row
        self.collective = False
        self.comm_bytes = 0
        self._spec = None              # (fn, arg specs, kw specs)
        # monotonic instant the compile call finished: dispatches that
        # STARTED before it blocked on the shared XLA compile and are
        # not steady-state samples
        self._compile_done: float | None = None
        # the /metrics label this row publishes under (its own id, or
        # "_other" past the metric_programs first-come cap) — decided
        # once at row creation
        self.metric_prog = prog_id

    # -- folding -------------------------------------------------------
    def fold_call(self, execute_ms: float | None, upload: int,
                  readback: int, *, dispatch_only: bool,
                  run_start: float | None = None,
                  collective: bool = False, comm_bytes: int = 0):
        self.calls += 1
        self.last_seen_ms = int(time.time() * 1000)
        self.upload_bytes += upload
        self.readback_bytes += readback
        if collective:
            self.collective = True
        self.comm_bytes += int(comm_bytes)
        if execute_ms is None:
            # failed dispatch: if it was the compile attempt,
            # compile_ms stays None and the NEXT successful call (which
            # pays the compile) records it
            self.errors += 1
            return
        if self.compile_ms is None:
            # the first SUCCESSFUL execution's wall time is dominated
            # by XLA compilation (or the persistent-cache load); keep
            # it out of the steady-state percentiles
            self.compile_ms = execute_ms
            self._compile_done = time.monotonic()
            return
        if (run_start is not None and self._compile_done is not None
                and run_start < self._compile_done):
            # concurrent cold dispatch: it blocked on the creator's
            # shared XLA compile, so its wall time would poison the
            # steady-state percentiles (calls/bytes still counted)
            return
        if dispatch_only:
            self.dispatch_only = True
        self.execute_ms_total += execute_ms
        _observe(self.exec_buckets, execute_ms)

    def fold_row(self, other: "_Program"):
        """Merge an LRU-evicted row into this (_other) one."""
        self.calls += other.calls
        self.errors += other.errors
        self.execute_ms_total += other.execute_ms_total
        for i in range(_N_BUCKETS):
            self.exec_buckets[i] += other.exec_buckets[i]
        self.upload_bytes += other.upload_bytes
        self.readback_bytes += other.readback_bytes
        self.collective = self.collective or other.collective
        self.comm_bytes += other.comm_bytes
        self.dispatch_only = self.dispatch_only or other.dispatch_only
        if other.compile_ms:
            self.compile_ms = (self.compile_ms or 0.0) + other.compile_ms
        self.first_seen_ms = min(self.first_seen_ms, other.first_seen_ms)
        self.last_seen_ms = max(self.last_seen_ms, other.last_seen_ms)

    # -- derived -------------------------------------------------------
    def exec_p50_ms(self) -> float:
        return _quantile(self.exec_buckets, 0.50)

    def exec_p99_ms(self) -> float:
        return _quantile(self.exec_buckets, 0.99)

    def device_ms(self) -> float:
        return (self.compile_ms or 0.0) + self.execute_ms_total

    def achieved(self) -> tuple[float, float]:
        """(GFLOP/s, HBM GB/s) at the p50 execute time; (0, 0) until
        the program is analyzed, has steady-state samples, and its
        timing covers the completed computation (not dispatch-only)."""
        p50 = self.exec_p50_ms()
        if (self.analysis != "ok" or p50 <= 0.0 or self.dispatch_only
                or sum(self.exec_buckets) == 0):
            return 0.0, 0.0
        s = p50 / 1000.0
        return self.flops / s / 1e9, self.bytes_accessed / s / 1e9

    def roofline(self, peak_tflops: float, peak_hbm_gbps: float
                 ) -> tuple[str, float]:
        """(bound, pct_of_peak). bound classifies by operational
        intensity vs the machine balance (static — no timing needed);
        pct is achieved/peak for the bounding resource, 0.0 when
        unmeasurable. ("", 0.0) when unanalyzed or peaks unknown."""
        if (self.analysis != "ok" or peak_tflops <= 0
                or peak_hbm_gbps <= 0 or self.bytes_accessed <= 0):
            return "", 0.0
        intensity = self.flops / self.bytes_accessed  # FLOP / byte
        balance = (peak_tflops * 1e12) / (peak_hbm_gbps * 1e9)
        bound = "compute" if intensity >= balance else "memory"
        gflops, gbps = self.achieved()
        if bound == "compute":
            pct = gflops / (peak_tflops * 1e3) * 100.0
        else:
            pct = gbps / peak_hbm_gbps * 100.0
        return bound, pct

    def to_doc(self, peak_tflops: float, peak_hbm_gbps: float) -> dict:
        gflops, gbps = self.achieved()
        bound, pct = self.roofline(peak_tflops, peak_hbm_gbps)
        return {
            "site": self.site,
            "program": self.prog_id,
            "key": self.key_text,
            "calls": self.calls,
            "errors": self.errors,
            "compile_ms": round(self.compile_ms or 0.0, 3),
            "execute_ms_total": round(self.execute_ms_total, 3),
            "execute_p50_ms": round(self.exec_p50_ms(), 3),
            "execute_p99_ms": round(self.exec_p99_ms(), 3),
            "device_ms_total": round(self.device_ms(), 3),
            "upload_bytes": int(self.upload_bytes),
            "readback_bytes": int(self.readback_bytes),
            "collective": self.collective,
            "comm_bytes": int(self.comm_bytes),
            "dispatch_only": self.dispatch_only,
            "analysis": self.analysis,
            "analysis_error": self.analysis_error,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "temp_bytes": int(self.temp_bytes),
            "output_bytes": int(self.output_bytes),
            "argument_bytes": int(self.argument_bytes),
            "aot_compile_ms": round(self.aot_compile_ms, 3),
            "achieved_gflops": round(gflops, 3),
            "achieved_hbm_gbps": round(gbps, 3),
            "bound": bound,
            "pct_of_peak": round(pct, 3),
            "first_seen_ms": self.first_seen_ms,
            "last_seen_ms": self.last_seen_ms,
        }


class DeviceProgramRegistry:
    """Process-wide registry; one per process (``global_programs``)."""

    def __init__(self, config: ProfilingConfig | None = None):
        self.config = config or ProfilingConfig()
        self._lock = concurrency.Lock()
        self._rows: OrderedDict[tuple, _Program] = OrderedDict()
        # serializes the lazy AOT analysis passes (lower + compile can
        # take seconds for a big fused program; two surfaces consulting
        # at once must not both pay it)
        self._analysis_lock = concurrency.Lock()
        # serializes whole publish passes (snapshot + child writes):
        # two concurrent scrapes interleaving their writes could
        # expose a STALE aggregate after a newer one — a counter
        # decrease to Prometheus (same contract as stmt_stats'
        # publish lock)
        self._publish_lock = concurrency.Lock()
        # labels this process has published, so a scrape after ADMIN
        # reset (or LRU collapse) zeroes vanished series instead of
        # leaving them frozen at stale values
        self._published: set[tuple[str, str]] = set()
        # program ids granted a real /metrics label (first-come,
        # bounded by metric_programs — exported series can never be
        # evicted, so churn past the cap exports as "_other")
        self._metric_progs: set[str] = set()
        self.evicted_rows = 0

    # -- dispatch-side hot path ---------------------------------------
    def prepare(self, site: str, key, fn, args, kwargs
                ) -> tuple[_Program, bool] | None:
        """Called by device_call.run just before the dispatch. Returns
        (row, is_first_dispatch) or None when disabled. On the first
        dispatch of a program the argument shape/dtype specs are
        captured (no device buffers pinned) for the lazy analysis."""
        if not self.config.enable:
            return None
        if key is None:
            # keyless dispatch: the callable IS the identity (process-
            # local, like the jit cache itself)
            key = repr(fn)
        try:
            hkey = (site, key)
            hash(hkey)
        except TypeError:
            key = repr(key)
            hkey = (site, key)
        with self._lock:
            row = self._rows.get(hkey)
            if row is not None:
                self._rows.move_to_end(hkey)
                return row, False
            # make room INCLUDING the row about to be inserted; a
            # collapse that merely CREATED a db's _other row has not
            # shrunk anything yet, so keep collapsing until the bound
            # holds or only _other rows remain
            while len(self._rows) >= self.config.max_programs:
                if not self._collapse_lru_locked():
                    break  # only _other rows remain
            key_text = repr(key)
            if len(key_text) > 160:
                key_text = key_text[:157] + "..."
            row = _Program(site, _prog_id(site, key), key_text)
            row.metric_prog = self._metric_prog_locked(row.prog_id)
            self._rows[hkey] = row
        if self.config.analysis:
            try:
                import jax

                specs = jax.tree_util.tree_map(_arg_spec, (args, kwargs))
                row._spec = (fn, specs[0], specs[1])
            except Exception:  # noqa: BLE001 - spec capture is
                # best-effort; the row still folds per-call stats
                row.analysis = "failed"
                row.analysis_error = "argument spec capture failed"
        else:
            row.analysis = "off"
        return row, True

    def lookup(self, site: str, key) -> _Program | None:
        """Read-only row lookup for ATTRIBUTION on no-dispatch paths
        (session hits keep their device.execute span and EXPLAIN
        ANALYZE notes, but do not count a call). Never creates a row."""
        if not self.config.enable:
            return None
        if key is None:
            return None
        try:
            hkey = (site, key)
            hash(hkey)
        except TypeError:
            hkey = (site, repr(key))
        with self._lock:
            row = self._rows.get(hkey)
            if row is not None:
                # a session-served program is HOT: refresh its LRU
                # recency so the steady-state rows are the last to
                # collapse into _other, not the first
                self._rows.move_to_end(hkey)
            return row

    def finish(self, row: _Program, *,
               execute_ms: float | None, upload: int, readback: int,
               dispatch_only: bool = False,
               run_start: float | None = None,
               collective: bool = False, comm_bytes: int = 0):
        with self._lock:
            row.fold_call(execute_ms, upload, readback,
                          dispatch_only=dispatch_only,
                          run_start=run_start,
                          collective=collective, comm_bytes=comm_bytes)

    def _metric_prog_locked(self, prog_id: str) -> str:
        if prog_id in self._metric_progs:
            return prog_id
        if len(self._metric_progs) < self.config.metric_programs:
            self._metric_progs.add(prog_id)
            return prog_id
        return OTHER

    def _collapse_lru_locked(self) -> bool:
        """Merge the least-recently-dispatched row into its site's
        _other row. Returns False when only _other rows remain."""
        for hkey in self._rows:
            if self._rows[hkey].prog_id != OTHER:
                victim = self._rows.pop(hkey)
                break
        else:
            return False
        okey = (victim.site, OTHER)
        other = self._rows.get(okey)
        if other is None:
            other = _Program(victim.site, OTHER, OTHER)
            other.analysis = "off"
            other.metric_prog = OTHER
            self._rows[okey] = other
        else:
            self._rows.move_to_end(okey)
        other.fold_row(victim)
        self.evicted_rows += 1
        return True

    # -- peaks ---------------------------------------------------------
    def peaks(self) -> tuple[float, float, str, str]:
        """(peak_tflops, peak_hbm_gbps, platform, source). Peaks are 0
        when unknown (achieved-only reporting)."""
        pf = self.config.peak_tflops
        pb = self.config.peak_hbm_gbps
        plat = _platform()
        if pf > 0 and pb > 0:
            return pf, pb, plat, "configured"
        if plat == "tpu":
            return (pf if pf > 0 else V5E_PEAK_TFLOPS,
                    pb if pb > 0 else V5E_PEAK_HBM_GBPS,
                    plat, "v5e_default")
        return 0.0, 0.0, plat, "achieved_only"

    # -- lazy XLA analysis ---------------------------------------------
    def analyze_pending(self):
        """Run the XLA cost/memory analysis for every row that still
        carries its captured spec. Triggered by the consulting surfaces
        (information_schema / /debug/prof/device / snapshot), NEVER by
        the /metrics publisher — a plain scrape must not pay an AOT
        compile. One pass per program per process; artifacts are
        dropped as soon as the numbers are extracted."""
        if not self.config.analysis:
            return
        with self._lock:
            pending = [r for r in self._rows.values()
                       if r.analysis == "pending" and r._spec is not None]
        if not pending:
            return
        # contract: the analysis lock serializes whole AOT passes
        # (lower + XLA compile, potentially seconds); it is never taken
        # on the dispatch hot path and never nests another lock
        with self._analysis_lock:  # gtlint: disable=GTS103
            for row in pending:
                if row.analysis == "pending":
                    self._analyze_row(row)

    def _analyze_row(self, row: _Program):
        fn, arg_specs, kw_specs = row._spec
        try:
            lowered = fn.lower(*arg_specs, **kw_specs)
            cost = lowered.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            cost = cost or {}
            row.flops = float(cost.get("flops", 0.0) or 0.0)
            row.bytes_accessed = float(
                cost.get("bytes accessed", 0.0) or 0.0
            )
            t0 = time.perf_counter()
            compiled = lowered.compile()
            row.aot_compile_ms = (time.perf_counter() - t0) * 1000.0
            mem = compiled.memory_analysis()
            if mem is not None:
                row.temp_bytes = int(
                    getattr(mem, "temp_size_in_bytes", 0) or 0
                )
                row.output_bytes = int(
                    getattr(mem, "output_size_in_bytes", 0) or 0
                )
                row.argument_bytes = int(
                    getattr(mem, "argument_size_in_bytes", 0) or 0
                )
        except Exception as e:  # noqa: BLE001 - analysis is additive:
            # a program that cannot re-lower still folds call stats
            row.analysis = "failed"
            row.analysis_error = f"{type(e).__name__}: {e}"[:200]
        else:
            row.analysis = "ok"
        finally:
            row._spec = None

    # -- surfaces ------------------------------------------------------
    def snapshot(self, *, top: int = 0, analyze: bool = True
                 ) -> list[dict]:
        """Row docs ordered by cumulative device time (compile +
        execute), top-N bounded when top > 0. Triggers the lazy XLA
        analysis unless analyze=False."""
        if analyze:
            self.analyze_pending()
        pf, pb, _plat, _src = self.peaks()
        with self._lock:
            docs = [r.to_doc(pf, pb) for r in self._rows.values()]
        docs.sort(key=lambda d: d["device_ms_total"], reverse=True)
        if top > 0:
            docs = docs[:top]
        return docs

    def report(self, *, top: int = 20) -> dict:
        pf, pb, plat, src = self.peaks()
        with self._lock:
            total = len(self._rows)
        return {
            "platform": plat,
            "peak_tflops": pf,
            "peak_hbm_gbps": pb,
            "peak_source": src,
            "programs_tracked": total,
            "evicted_rows": self.evicted_rows,
            "programs": self.snapshot(top=top),
        }

    def reset(self) -> int:
        """ADMIN reset_device_profiler(): drop every row. The exported
        gtpu_device_program_* series zero at the next scrape (a plain
        prometheus counter reset) so all three surfaces stay equal."""
        with self._lock:
            n = len(self._rows)
            self._rows.clear()
            self.evicted_rows = 0
        return n

    # -- scrape-time publisher ----------------------------------------
    def _publish_metrics(self):
        """MetricsRegistry collector: refresh every
        gtpu_device_program_* family from the rows. Does NOT trigger
        the AOT analysis (a scrape stays cheap); analysis-derived
        gauges publish once a consulting surface has computed them.
        The publish lock covers snapshot AND writes: publishes
        serialize, so each scrape exposes a consistent, never-older
        aggregate (and the _published bookkeeping can't race)."""
        with self._publish_lock:
            self._publish_locked()

    def _publish_locked(self):
        pf, pb, _plat, _src = self.peaks()
        with self._lock:
            rows = [(r.to_doc(pf, pb), r.metric_prog)
                    for r in self._rows.values()]
            n_rows = len(rows)
        # aggregate by the EXPORTED label: past the metric_programs
        # first-come cap, churned programs share the per-site "_other"
        # label (counters sum; the per-program gauges publish only for
        # labels backed by their own row) — the exported series set
        # stays bounded no matter how many program shapes a
        # long-running server mints
        agg: dict[tuple[str, str], dict] = {}
        for d, mp in rows:
            lab = (d["site"], mp)
            a = agg.get(lab)
            if a is None:
                a = agg[lab] = {"calls": 0, "exec": 0.0, "up": 0,
                                "rb": 0, "comm": 0, "doc": None}
            a["calls"] += d["calls"]
            a["exec"] += d["execute_ms_total"]
            a["up"] += d["upload_bytes"]
            a["rb"] += d["readback_bytes"]
            a["comm"] += d["comm_bytes"]
            if mp == d["program"]:
                a["doc"] = d
        live: set[tuple[str, str]] = set()
        for lab, a in agg.items():
            live.add(lab)
            _set_value(_M_CALLS.labels(*lab), a["calls"])
            _set_value(_M_EXEC.labels(*lab), a["exec"])
            _set_value(_M_UPLOAD.labels(*lab), a["up"])
            _set_value(_M_READBACK.labels(*lab), a["rb"])
            _set_value(_M_COMM.labels(*lab), a["comm"])
            d = a["doc"]
            if d is None:
                # an over-cap aggregate label: per-program gauges are
                # meaningless for a mixed bucket
                d = {"compile_ms": 0.0, "execute_p50_ms": 0.0,
                     "execute_p99_ms": 0.0, "flops": 0.0,
                     "bytes_accessed": 0.0, "achieved_gflops": 0.0,
                     "achieved_hbm_gbps": 0.0, "pct_of_peak": 0.0}
            _M_COMPILE.labels(*lab).set(d["compile_ms"])
            _M_P50.labels(*lab).set(d["execute_p50_ms"])
            _M_P99.labels(*lab).set(d["execute_p99_ms"])
            _M_FLOPS.labels(*lab).set(d["flops"])
            _M_BYTES.labels(*lab).set(d["bytes_accessed"])
            _M_GFLOPS.labels(*lab).set(d["achieved_gflops"])
            _M_GBPS.labels(*lab).set(d["achieved_hbm_gbps"])
            _M_PCT.labels(*lab).set(d["pct_of_peak"])
        for lab in self._published - live:
            # vanished rows (ADMIN reset / LRU collapse): zero, don't
            # freeze — the surfaces must agree at every scrape
            for fam in (_M_CALLS, _M_EXEC, _M_UPLOAD, _M_READBACK,
                        _M_COMM):
                _set_value(fam.labels(*lab), 0)
            for fam in (_M_COMPILE, _M_P50, _M_P99, _M_FLOPS, _M_BYTES,
                        _M_GFLOPS, _M_GBPS, _M_PCT):
                fam.labels(*lab).set(0.0)
        self._published = live
        _M_COUNT.set(n_rows)


def render_text(doc: dict) -> str:
    """Human face of /debug/prof/device: top-N by device time."""
    out = [
        f"device programs: {doc['programs_tracked']} tracked "
        f"({doc['evicted_rows']} collapsed), platform "
        f"{doc['platform']}",
    ]
    if doc["peak_tflops"] > 0:
        out.append(
            f"roofline peaks [{doc['peak_source']}]: "
            f"{doc['peak_tflops']:g} TFLOP/s, "
            f"{doc['peak_hbm_gbps']:g} GB/s HBM"
        )
    else:
        out.append("roofline peaks: unknown (achieved-only; set "
                   "[profiling] peak_tflops / peak_hbm_gbps)")
    hdr = (f"{'site':<16} {'program':<13} {'calls':>7} "
           f"{'compile':>9} {'p50ms':>9} {'p99ms':>9} {'GFLOP/s':>9} "
           f"{'GB/s':>8} {'%peak':>6} {'bound':<7}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for d in doc["programs"]:
        pct = f"{d['pct_of_peak']:.1f}" if d["bound"] else "-"
        bound = d["bound"] or ("dispatch" if d["dispatch_only"]
                               else d["analysis"])
        out.append(
            f"{d['site']:<16.16} {d['program']:<13.13} "
            f"{d['calls']:>7} {d['compile_ms']:>9.1f} "
            f"{d['execute_p50_ms']:>9.3f} {d['execute_p99_ms']:>9.3f} "
            f"{d['achieved_gflops']:>9.2f} "
            f"{d['achieved_hbm_gbps']:>8.2f} {pct:>6} {bound:<7}"
        )
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# on-demand trace capture (jax.profiler)
# ---------------------------------------------------------------------------


class CaptureBusyError(RuntimeError):
    """A trace capture is already in progress in this process."""


_capture_seq = itertools.count(1)
_capture_lock = concurrency.Lock()
_capture_active = False


def capture_trace(seconds: float, out_dir: str | None = None) -> dict:
    """Capture `seconds` of device activity via jax.profiler into a
    TensorBoard/perfetto-loadable trace directory. One capture at a
    time per process (CaptureBusyError otherwise)."""
    global _capture_active

    seconds = float(seconds)
    if not (0.0 < seconds <= 60.0):
        raise ValueError("seconds must be in (0, 60]")
    import tempfile

    base = (out_dir or global_programs.config.trace_dir
            or os.path.join(tempfile.gettempdir(), "gtpu_device_traces"))
    with _capture_lock:
        if _capture_active:
            raise CaptureBusyError("a trace capture is already running")
        _capture_active = True
    try:
        # dir creation AFTER the busy check: a 409'd caller must not
        # litter trace_dir with empty capture directories
        path = os.path.join(
            base, f"capture_{os.getpid()}_{next(_capture_seq)}"
        )
        os.makedirs(path, exist_ok=True)
        import jax

        jax.profiler.start_trace(path)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
    finally:
        with _capture_lock:
            _capture_active = False
    files = []
    for root, _dirs, names in os.walk(path):
        for name in names:
            files.append(os.path.relpath(os.path.join(root, name), path))
    return {
        "trace_dir": path,
        "seconds": seconds,
        "files": sorted(files),
    }


# ---------------------------------------------------------------------------
# process-wide instance + wiring
# ---------------------------------------------------------------------------

global_programs = DeviceProgramRegistry()
# scrape-time publisher: /metrics (and runtime_metrics, and the
# self-export loop) refresh the gtpu_device_program_* families from the
# registry rows on every render — zero prometheus work at dispatch
global_registry.register_collector(global_programs._publish_metrics)


def configure(options: dict | None) -> ProfilingConfig:
    """Apply the `[profiling]` TOML section to this process."""
    o = options or {}
    cfg = ProfilingConfig(
        enable=o.get("enable", True),
        max_programs=o.get("max_programs", 256),
        metric_programs=o.get("metric_programs", 128),
        peak_tflops=o.get("peak_tflops", 0.0),
        peak_hbm_gbps=o.get("peak_hbm_gbps", 0.0),
        analysis=o.get("analysis", True),
        trace_dir=o.get("trace_dir", ""),
    )
    with global_programs._lock:
        global_programs.config = cfg
        # the label grant set re-derives under the new cap (already-
        # exported series keep counting regardless)
        global_programs._metric_progs.clear()
    return cfg


def enabled() -> bool:
    return global_programs.config.enable
