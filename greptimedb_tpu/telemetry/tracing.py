"""Distributed-tracing spans with device-time attribution.

Capability counterpart of the reference's tracing stack
(/root/reference/src/common/telemetry/src/logging.rs:22-67 tracing
subscriber + OTLP export, src/common/telemetry/src/tracing_context.rs
W3C context propagation): timed spans carrying a trace id, parent links
via a context var (so nested spans form a tree across threads when the
context is passed), inbound `traceparent` parsing on every wire the
system speaks (HTTP header, Flight ticket field, DoPut app_metadata),
and an in-memory ring of finished traces served by the HTTP API
(/v1/traces) + `information_schema.traces` for inspection without an
external collector.

Cross-process stitching: a datanode executing a shipped partial plan
collects the spans it produced (`export_spans`) and ships them back in
the Arrow response metadata (`gtdb:spans`); the frontend ingests them
(`ingest_spans`) so ONE trace in its ring covers the whole distributed
query — frontend sched/plan/fan-out spans and per-datanode scan/device
spans under a shared trace_id.

Sampling is TAIL-BASED: every span records while in flight, and the
keep/drop decision happens when the process-local root span finishes —
error traces, slow traces (>= slow_ms) and explicitly marked traces
(`mark_keep`) are ALWAYS kept; the rest keep with probability
`sample_ratio`. `[tracing]` TOML knobs: enable, sample_ratio, capacity
(trace ring size, 0 = unbounded — bench.py refuses that), slow_ms.

Timestamps: `start_ms` is epoch milliseconds (display/correlation);
durations are computed on the MONOTONIC clock (an NTP slew must never
produce negative or absurd span durations — gtlint GT011).
"""

from __future__ import annotations

import contextlib
import contextvars
import random
import secrets

import time
from dataclasses import dataclass, field

from greptimedb_tpu import concurrency

_current_span: contextvars.ContextVar["Span | None"] = (
    contextvars.ContextVar("gtpu_span", default=None)
)

# finished spans additionally append here when a collector is active
# (export_spans) — the cross-process export used by dist/merge.py and
# the EXPLAIN ANALYZE span-tree rendering
_collector: contextvars.ContextVar["list | None"] = (
    contextvars.ContextVar("gtpu_span_collector", default=None)
)

_MAX_TRACES = 256
_MAX_EXPORT_SPANS = 128


class TracingConfig:
    """`[tracing]` options (config.py DEFAULTS documents each knob)."""

    __slots__ = ("enabled", "sample_ratio", "capacity", "slow_ms")

    def __init__(self, *, enable: bool = True, sample_ratio: float = 1.0,
                 capacity: int = _MAX_TRACES,
                 slow_ms: float = 5000.0):
        self.enabled = bool(enable)
        self.sample_ratio = min(1.0, max(0.0, float(sample_ratio)))
        self.capacity = int(capacity)
        self.slow_ms = float(slow_ms)


_config = TracingConfig()


def configure(options: dict | None):
    """Apply the `[tracing]` TOML section to this process."""
    global _config
    o = options or {}
    _config = TracingConfig(
        enable=o.get("enable", True),
        sample_ratio=o.get("sample_ratio", 1.0),
        capacity=o.get("capacity", _MAX_TRACES),
        slow_ms=o.get("slow_ms", 5000.0),
    )
    global_traces.set_cap(_config.capacity)
    return _config


def enabled() -> bool:
    return _config.enabled


def ring_unbounded() -> bool:
    """True when the trace ring has no capacity bound (capacity <= 0):
    a misconfiguration bench.py refuses to measure under."""
    return global_traces.cap <= 0


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: str | None
    name: str
    start_ms: float
    end_ms: float | None = None
    attributes: dict = field(default_factory=dict)
    # True ONLY on the placeholder parent start_remote builds from a
    # traceparent: a span whose parent carries this flag is this
    # process's LOCAL ROOT for the tail-sampling decision (the flag
    # deliberately does not propagate to descendants — a child exit
    # must never roll the sampling dice while the root is in flight)
    remote: bool = False

    def to_json(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ms": round(self.start_ms, 3),
            "duration_ms": (
                None if self.end_ms is None
                else round(self.end_ms - self.start_ms, 3)
            ),
            # copied: a reader may serialize while __exit__ mutates
            "attributes": dict(self.attributes),
        }


class _TraceStore:
    """Bounded ring of traces (newest kept). Spans record at START so
    /v1/traces shows in-flight work; the tail-sampling decision at the
    local root's finish either confirms the trace or drops it."""

    def __init__(self, cap: int = _MAX_TRACES):
        self._lock = concurrency.Lock()
        self._spans: dict[str, list[Span]] = {}
        self._order: list[str] = []
        self._kept: set[str] = set()
        # local roots currently in flight per trace (a client may send
        # one traceparent on several concurrent requests): a sampled-
        # out sibling must never drop a trace another root is still
        # writing — the LAST root out makes the final drop decision
        self._active: dict[str, int] = {}
        self.cap = cap
        self.evicted_traces = 0
        from greptimedb_tpu.telemetry import memory as _memory

        _memory.register_pool(
            "trace_ring", "host", self, stats=_TraceStore._mem_stats
        )

    # a client/proxy bug resending one traceparent forever must not
    # grow a single trace unboundedly
    MAX_SPANS_PER_TRACE = 512

    # flat per-span host-byte estimate for the memory accountant (a
    # Span dataclass + ids + a small attribute dict; exact accounting
    # would walk every attribute on every scrape)
    SPAN_EST_BYTES = 512

    def _mem_stats(self) -> dict:
        with self._lock:
            n_spans = sum(len(s) for s in self._spans.values())
            return {
                "bytes": n_spans * self.SPAN_EST_BYTES,
                "entries": n_spans,
                "max_entries": max(self.cap, 0)
                * self.MAX_SPANS_PER_TRACE,
                "evictions": self.evicted_traces,
            }

    def set_cap(self, cap: int):
        with self._lock:
            self.cap = int(cap)
            self._evict_locked()

    def _evict_locked(self):
        if self.cap <= 0:
            return  # unbounded (bench.py refuses to run like this)
        while len(self._order) > self.cap:
            victim = self._order.pop(0)
            self._spans.pop(victim, None)
            self._kept.discard(victim)
            self.evicted_traces += 1

    def record(self, span: Span):
        with self._lock:
            if span.trace_id not in self._spans:
                self._spans[span.trace_id] = []
                self._order.append(span.trace_id)
                self._evict_locked()
            spans = self._spans[span.trace_id]
            if len(spans) < self.MAX_SPANS_PER_TRACE:
                spans.append(span)

    def enter_root(self, trace_id: str):
        with self._lock:
            self._active[trace_id] = self._active.get(trace_id, 0) + 1

    def decide(self, root: Span):
        """Tail-sampling decision at a local root's finish: error spans
        anywhere in the trace, slow roots, and marked traces always
        keep; otherwise keep with probability sample_ratio. A drop only
        happens when NO other local root of the trace is in flight."""
        tid = root.trace_id
        with self._lock:
            remaining = self._active.get(tid, 1) - 1
            if remaining > 0:
                self._active[tid] = remaining
            else:
                self._active.pop(tid, None)
            if tid in self._kept:
                return
            spans = self._spans.get(tid)
            if spans is None:
                return
            keep = False
            for s in spans:
                if "error" in s.attributes or s.attributes.get("keep"):
                    keep = True
                    break
            if not keep and root.end_ms is not None and (
                    root.end_ms - root.start_ms) >= _config.slow_ms:
                keep = True
            if not keep:
                ratio = _config.sample_ratio
                keep = ratio >= 1.0 or random.random() < ratio
            if keep:
                self._kept.add(tid)
            elif remaining <= 0:
                # last root out and nothing remarkable: drop. With
                # siblings still writing, defer — the last one decides
                # over the COMPLETE span set (an error recorded later
                # must still be able to keep the trace).
                self._spans.pop(tid, None)
                self._kept.discard(tid)
                try:
                    self._order.remove(tid)
                except ValueError:
                    pass

    def ingest(self, span_dicts: list, limit: int = _MAX_EXPORT_SPANS):
        """Record spans exported by ANOTHER process (gtdb:spans
        metadata) into this ring so the stitched trace lives in one
        place. No sampling decision — the local root's decision covers
        the whole trace."""
        for doc in span_dicts[:limit]:
            try:
                dur = doc.get("duration_ms")
                start = float(doc.get("start_ms") or 0.0)
                self.record(Span(
                    trace_id=str(doc["trace_id"]),
                    span_id=str(doc.get("span_id") or ""),
                    parent_id=doc.get("parent_id"),
                    name=str(doc.get("name") or "remote"),
                    start_ms=start,
                    end_ms=None if dur is None else start + float(dur),
                    attributes=dict(doc.get("attributes") or {}),
                ))
            except (KeyError, TypeError, ValueError):
                continue  # a malformed remote span must not kill a query

    def traces(self, limit: int = 50) -> list[dict]:
        with self._lock:
            out = []
            for tid in reversed(self._order[-limit:]):
                spans = self._spans.get(tid, [])
                out.append({
                    "trace_id": tid,
                    "spans": [s.to_json() for s in spans],
                })
            return out

    def trace(self, trace_id: str) -> list[dict]:
        with self._lock:
            return [s.to_json() for s in self._spans.get(trace_id, [])]

    def clear(self):
        with self._lock:
            self._spans.clear()
            self._order.clear()
            self._kept.clear()
            self._active.clear()


global_traces = _TraceStore()


# span/trace ids need uniqueness, not cryptographic strength — and
# they are on the hot path of every traced statement. A per-process
# PRNG seeded from the CSPRNG is ~20x faster than secrets.token_hex
# (single C call; the GIL makes getrandbits atomic in CPython).
_idgen = random.Random(secrets.randbits(64))


def _new_id(nbytes: int) -> str:
    return f"{_idgen.getrandbits(nbytes * 8):0{nbytes * 2}x}"


class span:
    """Context manager: `with tracing.span("query.plan", sql=...)`.
    Nests under the current span; starts a new trace at the root."""

    __slots__ = ("name", "attributes", "_parent", "_span", "_token",
                 "_mono0", "_local_root")

    def __init__(self, name: str, _parent: Span | None = None,
                 **attributes):
        self.name = name
        self.attributes = attributes
        self._parent = _parent
        self._span: Span | None = None
        self._token = None
        self._mono0 = 0.0
        self._local_root = False

    def __enter__(self) -> Span:
        if not _config.enabled:
            # inert span: no context, no ring, no ids — zero footprint
            self._span = Span("", "", None, self.name, 0.0,
                              attributes=dict(self.attributes))
            return self._span
        parent = (self._parent if self._parent is not None
                  else _current_span.get())
        self._local_root = parent is None or parent.remote
        self._span = Span(
            trace_id=(parent.trace_id if parent else _new_id(16)),
            span_id=_new_id(8),
            parent_id=parent.span_id if parent else None,
            name=self.name,
            # epoch-ms START timestamp for display/correlation; the
            # duration below comes from the monotonic clock (GT011)
            start_ms=time.time() * 1000.0,
            attributes=dict(self.attributes),
        )
        self._mono0 = time.monotonic()
        self._token = _current_span.set(self._span)
        if self._local_root:
            global_traces.enter_root(self._span.trace_id)
        # recorded at START: /v1/traces shows in-flight spans (duration
        # null) and a span is never missing just because its exit races
        # a reader; __exit__ finalizes the same object in place
        global_traces.record(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb):
        sp = self._span
        if self._token is None:
            return False  # disabled at __enter__ time
        sp.end_ms = sp.start_ms + (time.monotonic() - self._mono0) * 1000.0
        if exc is not None:
            sp.attributes["error"] = f"{type(exc).__name__}: {exc}"
        _current_span.reset(self._token)
        self._token = None
        col = _collector.get()
        if col is not None and len(col) < _MAX_EXPORT_SPANS:
            col.append(sp)
        if self._local_root:
            # this process's outermost span: tail-sampling decision
            global_traces.decide(sp)
        return False


class _noop_span:
    """Context manager yielding an inert Span (attribute writes land
    nowhere); the zero-cost path for child_span with no active trace."""

    __slots__ = ("_span",)

    def __init__(self, name: str, attributes: dict):
        self._span = Span("", "", None, name, 0.0,
                          attributes=dict(attributes))

    def __enter__(self) -> Span:
        return self._span

    def __exit__(self, exc_type, exc, tb):
        return False


def child_span(name: str, _parent: Span | None = None, **attributes):
    """A span ONLY when it can join an existing trace: hot-path
    internals (WAL append, flush, scans, device calls) use this so
    background work with no request context never floods the ring with
    single-span root traces."""
    if not _config.enabled:
        return _noop_span(name, attributes)
    parent = _parent if _parent is not None else _current_span.get()
    if parent is None or not parent.trace_id:
        # no trace to join (or an inert parent from a disabled scope)
        return _noop_span(name, attributes)
    return span(name, _parent=parent, **attributes)


def event_span(name: str, duration_ms: float, **attributes):
    """Record an already-measured stage as a completed child span (the
    dist-query stage clock and recovery stage recorder re-publish the
    SAME numbers they export as gtpu_*_stage_ms metrics, so traces and
    metrics agree). No-op outside an active trace."""
    if not _config.enabled:
        return
    parent = _current_span.get()
    if parent is None:
        return
    now = time.time() * 1000.0
    dur = max(float(duration_ms), 0.0)
    sp = Span(
        trace_id=parent.trace_id, span_id=_new_id(8),
        parent_id=parent.span_id, name=name,
        start_ms=now - dur, end_ms=now,
        attributes=dict(attributes),
    )
    global_traces.record(sp)
    col = _collector.get()
    if col is not None and len(col) < _MAX_EXPORT_SPANS:
        col.append(sp)


def current_span() -> Span | None:
    return _current_span.get()


def current_trace_id() -> str | None:
    sp = _current_span.get()
    return sp.trace_id if sp and sp.trace_id else None


def set_attr(**attributes):
    """Attach attributes to the current span (e.g. the mesh planner's
    replicate-vs-shard decision); no-op outside a span."""
    sp = _current_span.get()
    if sp is not None:
        sp.attributes.update(attributes)


def mark_keep():
    """Force-keep the current trace through tail sampling (shed and
    deadline-expired queries stay inspectable at any sample_ratio)."""
    sp = _current_span.get()
    if sp is not None:
        sp.attributes["keep"] = True


def traceparent() -> str | None:
    """W3C `traceparent` of the current span — what every outbound wire
    (Flight ticket field, DoPut app_metadata, HTTP header) carries so
    the receiving process parents its spans under ours."""
    sp = _current_span.get()
    if sp is None or not sp.trace_id:
        return None
    return f"00-{sp.trace_id}-{sp.span_id}-01"


import re as _re

# strict W3C form: lowercase hex only. The ids are CLIENT-controlled
# and get spliced into hand-built ticket JSON (dist_query.py) and
# stripped by a lowercase-hex regex on the datanode (merge.py) — a
# looser accept here would let a quote-bearing "trace id" corrupt
# tickets or an uppercase one churn the datanode decode memo.
_TRACEPARENT_RE = _re.compile(
    r"00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}\Z"
)


def start_remote(traceparent: str | None, name: str, **attributes):
    """Span continuing a W3C `traceparent: 00-<trace>-<parent>-<flags>`
    header when present and well-formed (strict lowercase hex); a
    fresh root otherwise. Either way the span is this process's local
    root for the tail-sampling decision."""
    parent = None
    if traceparent:
        m = _TRACEPARENT_RE.match(traceparent.strip())
        if m and m.group(1) != "0" * 32:
            parent = Span(
                trace_id=m.group(1), span_id=m.group(2),
                parent_id=None, name="remote-parent", start_ms=0.0,
                remote=True,
            )
    return span(name, _parent=parent, **attributes)


@contextlib.contextmanager
def export_spans():
    """Collect every span FINISHED inside this context (the list the
    datanode ships back as `gtdb:spans`, and EXPLAIN ANALYZE renders
    inline). Yields the live list; read it after the block."""
    spans: list[Span] = []
    token = _collector.set(spans)
    try:
        yield spans
    finally:
        _collector.reset(token)


def ingest_spans(span_dicts: list | None):
    """Record spans exported by another process into the local ring."""
    if span_dicts:
        global_traces.ingest(span_dicts)


def render_tree(span_dicts: list[dict]) -> list[str]:
    """Indented parent->child rendering of one trace's span dicts (the
    EXPLAIN ANALYZE inline view). Spans whose parent is not in the set
    (remote parents) render as roots; children sort by start time."""
    by_id = {s["span_id"]: s for s in span_dicts if s.get("span_id")}
    children: dict[str | None, list[dict]] = {}
    roots: list[dict] = []
    for s in span_dicts:
        pid = s.get("parent_id")
        if pid in by_id and pid != s.get("span_id"):
            children.setdefault(pid, []).append(s)
        else:
            roots.append(s)

    def fmt(s: dict) -> str:
        dur = s.get("duration_ms")
        dur_s = "..." if dur is None else f"{dur:.3f}ms"
        attrs = {
            k: v for k, v in (s.get("attributes") or {}).items()
            if k != "keep"
        }
        extra = ""
        if attrs:
            inner = ", ".join(f"{k}={v}" for k, v in sorted(
                attrs.items(), key=lambda kv: kv[0]
            ))
            extra = f" {{{inner}}}"
        return f"{s['name']} {dur_s}{extra}"

    lines: list[str] = []

    def walk(s: dict, depth: int):
        lines.append("  " * depth + fmt(s))
        for c in sorted(children.get(s.get("span_id"), []),
                        key=lambda x: x.get("start_ms") or 0.0):
            walk(c, depth + 1)

    for r in sorted(roots, key=lambda x: x.get("start_ms") or 0.0):
        walk(r, 0)
    return lines
