"""Distributed-tracing spans.

Capability counterpart of the reference's tracing stack
(/root/reference/src/common/telemetry/src/logging.rs:22-67 tracing
subscriber + OTLP export, src/common/telemetry/src/tracing_context.rs
W3C context propagation): timed spans carrying a trace id, parent links
via a context var (so nested spans form a tree across threads when the
context is passed), inbound `traceparent` header parsing, and an
in-memory ring of finished traces served by the HTTP API (/v1/traces)
for inspection without an external collector.
"""

from __future__ import annotations

import contextvars
import secrets

import time
from dataclasses import dataclass, field

from greptimedb_tpu import concurrency

_current_span: contextvars.ContextVar["Span | None"] = (
    contextvars.ContextVar("gtpu_span", default=None)
)

_MAX_TRACES = 256


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: str | None
    name: str
    start_ms: float
    end_ms: float | None = None
    attributes: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ms": round(self.start_ms, 3),
            "duration_ms": (
                None if self.end_ms is None
                else round(self.end_ms - self.start_ms, 3)
            ),
            # copied: a reader may serialize while __exit__ mutates
            "attributes": dict(self.attributes),
        }


class _TraceStore:
    """Bounded ring of finished traces (newest kept)."""

    def __init__(self, cap: int = _MAX_TRACES):
        self._lock = concurrency.Lock()
        self._spans: dict[str, list[Span]] = {}
        self._order: list[str] = []
        self.cap = cap

    # a client/proxy bug resending one traceparent forever must not
    # grow a single trace unboundedly
    MAX_SPANS_PER_TRACE = 512

    def record(self, span: Span):
        with self._lock:
            if span.trace_id not in self._spans:
                self._spans[span.trace_id] = []
                self._order.append(span.trace_id)
                while len(self._order) > self.cap:
                    victim = self._order.pop(0)
                    self._spans.pop(victim, None)
            spans = self._spans[span.trace_id]
            if len(spans) < self.MAX_SPANS_PER_TRACE:
                spans.append(span)

    def traces(self, limit: int = 50) -> list[dict]:
        with self._lock:
            out = []
            for tid in reversed(self._order[-limit:]):
                spans = self._spans.get(tid, [])
                out.append({
                    "trace_id": tid,
                    "spans": [s.to_json() for s in spans],
                })
            return out

    def trace(self, trace_id: str) -> list[dict]:
        with self._lock:
            return [s.to_json() for s in self._spans.get(trace_id, [])]

    def clear(self):
        with self._lock:
            self._spans.clear()
            self._order.clear()


global_traces = _TraceStore()


def _new_id(nbytes: int) -> str:
    return secrets.token_hex(nbytes)


class span:
    """Context manager: `with tracing.span("query.plan", sql=...)`.
    Nests under the current span; starts a new trace at the root."""

    def __init__(self, name: str, _parent: Span | None = None,
                 **attributes):
        self.name = name
        self.attributes = attributes
        self._parent = _parent
        self._span: Span | None = None
        self._token = None

    def __enter__(self) -> Span:
        parent = (self._parent if self._parent is not None
                  else _current_span.get())
        self._span = Span(
            trace_id=(parent.trace_id if parent else _new_id(16)),
            span_id=_new_id(8),
            parent_id=parent.span_id if parent else None,
            name=self.name,
            start_ms=time.time() * 1000.0,
            attributes=dict(self.attributes),
        )
        self._token = _current_span.set(self._span)
        # recorded at START: /v1/traces shows in-flight spans (duration
        # null) and a span is never missing just because its exit races
        # a reader; __exit__ finalizes the same object in place
        global_traces.record(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb):
        sp = self._span
        sp.end_ms = time.time() * 1000.0
        if exc is not None:
            sp.attributes["error"] = f"{type(exc).__name__}: {exc}"
        _current_span.reset(self._token)
        return False


def current_trace_id() -> str | None:
    sp = _current_span.get()
    return sp.trace_id if sp else None


def start_remote(traceparent: str | None, name: str, **attributes):
    """Span continuing a W3C `traceparent: 00-<trace>-<parent>-<flags>`
    header when present; a fresh root otherwise."""
    parent = None
    if traceparent:
        parts = traceparent.strip().split("-")
        if len(parts) == 4 and len(parts[1]) == 32 and len(parts[2]) == 16:
            parent = Span(
                trace_id=parts[1], span_id=parts[2], parent_id=None,
                name="remote-parent", start_ms=0.0,
            )
    return span(name, _parent=parent, **attributes)
