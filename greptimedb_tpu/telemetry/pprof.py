"""On-demand CPU and heap profiling behind HTTP debug routes.

Capability counterpart of the reference's pprof integration
(/root/reference/src/common/pprof/src/nix.rs — pprof-rs sampling CPU
profiler behind /debug/prof/cpu, src/servers/src/http/pprof.rs) and the
jemalloc heap dumps (/root/reference/src/common/mem-prof/, http/mem_prof.rs).

CPU: a sampling profiler over `sys._current_frames()` — the Python analog
of a SIGPROF sampler. Output is collapsed-stack (flamegraph) text or an
aggregated self/total report. Heap: tracemalloc snapshots with top
allocation sites.
"""

from __future__ import annotations

import sys
import threading

import time
from collections import Counter

from greptimedb_tpu import concurrency

def sample_cpu(seconds: float = 1.0, hz: int = 99,
               *, skip_threads: tuple[str, ...] = ("pprof-sampler",)
               ) -> Counter:
    """Sample all thread stacks for `seconds` at `hz`. Returns a Counter
    of collapsed stacks ('outer;inner;leaf' -> samples)."""
    seconds = max(0.01, min(float(seconds), 60.0))
    hz = max(1, min(int(hz), 1000))
    interval = 1.0 / hz
    stacks: Counter = Counter()
    names = {}

    def loop():
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            for tid, frame in sys._current_frames().items():
                name = names.get(tid)
                if name is None:
                    name = "thread"
                    for t in threading.enumerate():
                        if t.ident == tid:
                            name = t.name
                            break
                    names[tid] = name
                if name in skip_threads:
                    continue
                parts = []
                f = frame
                depth = 0
                while f is not None and depth < 128:
                    code = f.f_code
                    parts.append(
                        f"{code.co_name} "
                        f"({code.co_filename.rsplit('/', 1)[-1]}:"
                        f"{f.f_lineno})"
                    )
                    f = f.f_back
                    depth += 1
                parts.reverse()
                stacks[name + ";" + ";".join(parts)] += 1
            time.sleep(interval)

    t = concurrency.Thread(target=loop, name="pprof-sampler", daemon=True)
    t.start()
    t.join(seconds + 5.0)
    return stacks


def render_collapsed(stacks: Counter) -> str:
    """flamegraph.pl / speedscope-compatible collapsed stack lines."""
    return "\n".join(
        f"{stack} {count}" for stack, count in stacks.most_common()
    ) + ("\n" if stacks else "")


def render_report(stacks: Counter, top: int = 40) -> str:
    """Aggregated self-time report (like `pprof -top`)."""
    total = sum(stacks.values())
    self_c: Counter = Counter()
    total_c: Counter = Counter()
    for stack, n in stacks.items():
        frames = stack.split(";")[1:]  # drop the thread name
        if not frames:
            continue
        self_c[frames[-1]] += n
        for fr in set(frames):
            total_c[fr] += n
    lines = [f"samples: {total}", "",
             f"{'self':>8} {'self%':>7} {'total%':>7}  function"]
    for fn, n in self_c.most_common(top):
        lines.append(
            f"{n:>8} {100.0 * n / max(total, 1):>6.1f}% "
            f"{100.0 * total_c[fn] / max(total, 1):>6.1f}%  {fn}"
        )
    return "\n".join(lines) + "\n"


# ----------------------------------------------------------------------
# heap profiling (tracemalloc)
# ----------------------------------------------------------------------

_tracemalloc_lock = concurrency.Lock()

def mem_profile(top: int = 30) -> str:
    """Top heap allocation sites. Starts tracemalloc on first use (the
    first call reports allocations made after it — like enabling jemalloc
    profiling at runtime)."""
    import tracemalloc

    with _tracemalloc_lock:
        if not tracemalloc.is_tracing():
            tracemalloc.start(8)
            return (
                "tracemalloc started; allocations are now being tracked.\n"
                "Request this endpoint again to see a snapshot.\n"
            )
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")
    current, peak = tracemalloc.get_traced_memory()
    lines = [
        f"traced current={current / 1e6:.1f}MB peak={peak / 1e6:.1f}MB",
        "", f"{'bytes':>12} {'count':>8}  site",
    ]
    for st in stats[:max(1, min(int(top), 200))]:
        frame = st.traceback[0]
        lines.append(
            f"{st.size:>12} {st.count:>8}  "
            f"{frame.filename.rsplit('/', 1)[-1]}:{frame.lineno}"
        )
    return "\n".join(lines) + "\n"
