"""On-demand CPU and heap profiling behind HTTP debug routes.

Capability counterpart of the reference's pprof integration
(/root/reference/src/common/pprof/src/nix.rs — pprof-rs sampling CPU
profiler behind /debug/prof/cpu, src/servers/src/http/pprof.rs) and the
jemalloc heap dumps (/root/reference/src/common/mem-prof/, http/mem_prof.rs).

CPU: a sampling profiler over `sys._current_frames()` — the Python analog
of a SIGPROF sampler. Output is collapsed-stack (flamegraph) text or an
aggregated self/total report. Heap: tracemalloc snapshots with top
allocation sites.
"""

from __future__ import annotations

import sys
import threading

import time
from collections import Counter

from greptimedb_tpu import concurrency

def sample_cpu(seconds: float = 1.0, hz: int = 99,
               *, skip_threads: tuple[str, ...] = ("pprof-sampler",)
               ) -> Counter:
    """Sample all thread stacks for `seconds` at `hz`. Returns a Counter
    of collapsed stacks ('outer;inner;leaf' -> samples)."""
    seconds = max(0.01, min(float(seconds), 60.0))
    hz = max(1, min(int(hz), 1000))
    interval = 1.0 / hz
    stacks: Counter = Counter()
    names = {}

    def loop():
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            for tid, frame in sys._current_frames().items():
                name = names.get(tid)
                if name is None:
                    name = "thread"
                    for t in threading.enumerate():
                        if t.ident == tid:
                            name = t.name
                            break
                    names[tid] = name
                if name in skip_threads:
                    continue
                parts = []
                f = frame
                depth = 0
                while f is not None and depth < 128:
                    code = f.f_code
                    parts.append(
                        f"{code.co_name} "
                        f"({code.co_filename.rsplit('/', 1)[-1]}:"
                        f"{f.f_lineno})"
                    )
                    f = f.f_back
                    depth += 1
                parts.reverse()
                stacks[name + ";" + ";".join(parts)] += 1
            time.sleep(interval)

    t = concurrency.Thread(target=loop, name="pprof-sampler", daemon=True)
    t.start()
    t.join(seconds + 5.0)
    return stacks


def render_collapsed(stacks: Counter) -> str:
    """flamegraph.pl / speedscope-compatible collapsed stack lines."""
    return "\n".join(
        f"{stack} {count}" for stack, count in stacks.most_common()
    ) + ("\n" if stacks else "")


def render_speedscope(stacks: Counter, name: str = "cpu") -> str:
    """Speedscope file-format JSON (https://www.speedscope.app) from
    collapsed stacks: one `sampled` profile aggregating every thread,
    weights = sample counts. Drag the response onto speedscope (or
    `speedscope profile.json`) for an interactive flamegraph."""
    import json

    frame_index: dict[str, int] = {}
    frames: list[dict] = []
    samples: list[list[int]] = []
    weights: list[int] = []
    for stack, count in stacks.most_common():
        parts = stack.split(";")  # parts[0] is the thread name
        idxs = []
        for fr in parts:
            i = frame_index.get(fr)
            if i is None:
                i = frame_index[fr] = len(frames)
                frames.append({"name": fr})
            idxs.append(i)
        samples.append(idxs)
        weights.append(count)
    total = sum(weights)
    return json.dumps({
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [{
            "type": "sampled",
            "name": name,
            "unit": "none",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        }],
        "exporter": "greptimedb-tpu pprof",
    })


def render_report(stacks: Counter, top: int = 40) -> str:
    """Aggregated self-time report (like `pprof -top`)."""
    total = sum(stacks.values())
    self_c: Counter = Counter()
    total_c: Counter = Counter()
    for stack, n in stacks.items():
        frames = stack.split(";")[1:]  # drop the thread name
        if not frames:
            continue
        self_c[frames[-1]] += n
        for fr in set(frames):
            total_c[fr] += n
    lines = [f"samples: {total}", "",
             f"{'self':>8} {'self%':>7} {'total%':>7}  function"]
    for fn, n in self_c.most_common(top):
        lines.append(
            f"{n:>8} {100.0 * n / max(total, 1):>6.1f}% "
            f"{100.0 * total_c[fn] / max(total, 1):>6.1f}%  {fn}"
        )
    return "\n".join(lines) + "\n"


# ----------------------------------------------------------------------
# heap profiling (tracemalloc)
# ----------------------------------------------------------------------

_tracemalloc_lock = concurrency.Lock()
# previous snapshot for the ?diff=1 mode: growth since the LAST
# mem_profile call (either mode updates it), so two diff requests
# bracket exactly the interval between them
_last_snapshot = None


def mem_profile(top: int = 30, diff: bool = False) -> str:
    """Top heap allocation sites. Starts tracemalloc on first use (the
    first call reports allocations made after it — like enabling jemalloc
    profiling at runtime).

    diff=True reports top allocation-site GROWTH since the previous
    snapshot instead of absolute bytes — the mode that finds a slow
    host-side leak that absolute top-N hides under steady large
    allocations."""
    global _last_snapshot
    import tracemalloc

    with _tracemalloc_lock:
        if not tracemalloc.is_tracing():
            tracemalloc.start(8)
            return (
                "tracemalloc started; allocations are now being tracked.\n"
                "Request this endpoint again to see a snapshot.\n"
            )
        snap = tracemalloc.take_snapshot()
        prev, _last_snapshot = _last_snapshot, snap
    top = max(1, min(int(top), 200))
    current, peak = tracemalloc.get_traced_memory()
    head = f"traced current={current / 1e6:.1f}MB peak={peak / 1e6:.1f}MB"
    if diff:
        if prev is None:
            return (
                head + "\nno previous snapshot; request again to see "
                "allocation-site growth since this one.\n"
            )
        stats = snap.compare_to(prev, "lineno")
        lines = [
            head, "",
            f"{'growth':>12} {'count+':>8}  site (since previous "
            "snapshot)",
        ]
        shown = 0
        # compare_to sorts by ABS(size_diff): a large deallocation can
        # rank above every real growth site, so skip non-positive
        # entries instead of stopping at the first one
        for st in stats:
            if st.size_diff <= 0:
                continue
            if shown >= top:
                break
            frame = st.traceback[0]
            lines.append(
                f"{st.size_diff:>+12} {st.count_diff:>+8}  "
                f"{frame.filename.rsplit('/', 1)[-1]}:{frame.lineno}"
            )
            shown += 1
        if shown == 0:
            lines.append("(no allocation-site growth since the "
                         "previous snapshot)")
        return "\n".join(lines) + "\n"
    stats = snap.statistics("lineno")
    lines = [head, "", f"{'bytes':>12} {'count':>8}  site"]
    for st in stats[:top]:
        frame = st.traceback[0]
        lines.append(
            f"{st.size:>12} {st.count:>8}  "
            f"{frame.filename.rsplit('/', 1)[-1]}:{frame.lineno}"
        )
    return "\n".join(lines) + "\n"
