"""Process-wide memory accountant: one ledger over every byte-budgeted
pool.

The device-resident result path made HBM a contended long-lived
resource: session result buffers, range cell-state grids and PromQL
selector grids all pin device memory across queries, next to host-side
byte pools (merged-scan cache, result cache, page cache, trace ring,
ingest queues) — and each pool was a silo with its own budget. This
module is the arbiter the tf.data design (PAPERS.md) argues for: every
pool registers here with an owner tag and reports
bytes/entries/budget/hits/evictions through ONE interface, the way the
reference exposes jemalloc heap accounting behind /debug/prof.

Three capabilities on top of registration:

- **unified surfaces** — `gtpu_mem_{bytes,entries,budget_bytes,
  evictions_total}{pool,tier=device|host}` refresh on every /metrics
  scrape (a registry collector, no background thread), mirrored by
  `information_schema.memory_pools` and `/debug/prof/hbm`;

- **device live-buffer census** — owner-tagged buffers enumerated by
  each device pool are reconciled against `jax.live_arrays()`:
  `gtpu_mem_unaccounted_device_bytes` is the residue no pool claims, an
  always-on detector for exactly the stranded-buffer leak class that
  was previously only found by manual code reading;

- **cross-pool pressure** — a global `[memory] device_budget_bytes`
  watermark below the sum of individual pool budgets is enforced by
  demand-driven proportional eviction: a device pool calls
  `note_device_bytes()` after growing (OUTSIDE its own lock — eviction
  re-enters other pools), and the accountant asks each evictable pool
  to shed its proportional share of the overage. Three independent
  LRUs can no longer jointly exceed HBM with no arbiter.

Registrations hold the pool through a weakref: a GC'd pool (a closed
test instance) silently drops out of the ledger, so no unregister
plumbing is needed and pool names aggregate across live instances.
"""

from __future__ import annotations

import time
import weakref

from dataclasses import dataclass

from greptimedb_tpu.telemetry.metrics import global_registry

from greptimedb_tpu import concurrency

_BYTES = global_registry.gauge(
    "gtpu_mem_bytes",
    "bytes held per registered memory pool", ("pool", "tier"),
)
_ENTRIES = global_registry.gauge(
    "gtpu_mem_entries",
    "entries held per registered memory pool", ("pool", "tier"),
)
_BUDGET = global_registry.gauge(
    "gtpu_mem_budget_bytes",
    "configured byte budget per registered memory pool (0 = entry- or "
    "row-bounded)", ("pool", "tier"),
)
_EVICTIONS = global_registry.counter(
    "gtpu_mem_evictions_total",
    "entries evicted per registered memory pool (budget, staleness or "
    "cross-pool pressure)", ("pool", "tier"),
)
_CROSS_EVICTED = global_registry.counter(
    "gtpu_mem_cross_pool_evicted_bytes_total",
    "device bytes evicted by the global [memory] device_budget_bytes "
    "watermark, per shedding pool", ("pool",),
)
_DEVICE_LIVE = global_registry.gauge(
    "gtpu_mem_device_live_bytes",
    "bytes of all live device arrays (jax.live_arrays census)",
)
_DEVICE_ACCOUNTED = global_registry.gauge(
    "gtpu_mem_device_accounted_bytes",
    "census bytes owned by a registered device pool",
)
_UNACCOUNTED = global_registry.gauge(
    "gtpu_mem_unaccounted_device_bytes",
    "live device bytes no registered pool claims — the leak gauge",
)


@dataclass
class PoolStats:
    """One pool's aggregated snapshot (summed across live instances of
    the same registered name)."""

    name: str
    tier: str                 # "device" | "host"
    bytes: int = 0
    entries: int = 0
    budget_bytes: int = 0
    max_entries: int = 0      # 0 = no entry cap
    hits: int = 0
    misses: int = 0
    evictions: int = 0
    instances: int = 0

    def to_doc(self) -> dict:
        return {
            "pool": self.name, "tier": self.tier,
            "bytes": int(self.bytes), "entries": int(self.entries),
            "budget_bytes": int(self.budget_bytes),
            "max_entries": int(self.max_entries),
            "hits": int(self.hits), "misses": int(self.misses),
            "evictions": int(self.evictions),
            "instances": int(self.instances),
        }


class _Registration:
    __slots__ = ("name", "tier", "ref", "stats_fn", "evict_fn",
                 "buffers_fn", "last_evictions")

    def __init__(self, name, tier, ref, stats_fn, evict_fn, buffers_fn):
        self.name = name
        self.tier = tier
        self.ref = ref
        self.stats_fn = stats_fn
        self.evict_fn = evict_fn
        self.buffers_fn = buffers_fn
        # per-INSTANCE published-evictions baseline: deltas keyed on the
        # aggregate would stall behind a dead instance's high-water mark
        self.last_evictions = 0


def iter_device_arrays(obj, _depth: int = 0):
    """Best-effort walk of nested containers for jax device arrays —
    pools whose derived caches hold tuples/dicts of device inputs
    (promql match/group/win caches) enumerate them for the census
    without knowing their exact shape."""
    if _depth > 4 or obj is None:
        return
    import jax

    if isinstance(obj, jax.Array):
        yield obj
        return
    if isinstance(obj, dict):
        for v in obj.values():
            yield from iter_device_arrays(v, _depth + 1)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            yield from iter_device_arrays(v, _depth + 1)


class MemoryAccountant:
    """The process-wide pool ledger. One instance (`global_accountant`)
    serves every pool in the process."""

    def __init__(self):
        self._lock = concurrency.Lock()
        self._regs: list[_Registration] = []
        self.enabled = True
        # 0 = no global watermark: per-pool budgets only
        self.device_budget_bytes = 0
        # refresh the census gauges on every /metrics render
        self.census_on_scrape = True
        # (name, tier) keys whose gauges this accountant has published:
        # a pool whose last instance died must have its gauges zeroed,
        # not frozen at the final value
        self._published: set = set()
        # serializes enforcement: taken NON-blocking, so (a) an
        # eviction triggered by enforcement can never recursively
        # re-enforce on the same thread (a plain Lock is
        # non-reentrant), and (b) two threads that both notice the
        # same overage do not each run a full sweep and jointly shed
        # twice the required bytes
        self._enforce_lock = concurrency.Lock()
        # (monotonic, bytes) TTL cache for device_bytes_cached(): span
        # attribution reads this per traced device call and must not
        # take every pool's lock each time
        self._dev_bytes_cache = (-1e18, 0)

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    def register_pool(self, name: str, tier: str, pool, *, stats,
                      evict=None, buffers=None) -> None:
        """Register one pool instance.

        - `stats(pool) -> dict` with any of bytes/entries/budget_bytes/
          max_entries/hits/misses/evictions (missing keys default 0);
        - `evict(pool, target_bytes) -> freed_bytes` (device pools that
          participate in cross-pool pressure eviction);
        - `buffers(pool) -> iterable of (array, owner_tag)` (device
          pools; feeds the live-buffer census).
        """
        if tier not in ("device", "host"):
            raise ValueError(f"tier must be device|host, got {tier!r}")
        reg = _Registration(name, tier, weakref.ref(pool), stats, evict,
                            buffers)
        with self._lock:
            self._regs.append(reg)

    def _live(self) -> list[tuple[_Registration, object]]:
        with self._lock:
            regs = list(self._regs)
        out = []
        dead = []
        for r in regs:
            p = r.ref()
            if p is None:
                dead.append(r)
            else:
                out.append((r, p))
        if dead:
            with self._lock:
                self._regs = [r for r in self._regs if r not in dead]
        return out

    # ------------------------------------------------------------------
    # snapshots + publication
    # ------------------------------------------------------------------
    def snapshot(self) -> list[PoolStats]:
        """Per-pool aggregated stats, summed across live instances of
        each registered name, sorted device-first then by name."""
        agg: dict[tuple, PoolStats] = {}
        for reg, pool in self._live():
            try:
                doc = reg.stats_fn(pool) or {}
            except Exception:  # noqa: BLE001 - a pool mid-teardown must
                # not break the whole ledger
                continue
            key = (reg.tier, reg.name)
            st = agg.get(key)
            if st is None:
                st = agg[key] = PoolStats(name=reg.name, tier=reg.tier)
            st.bytes += int(doc.get("bytes", 0))
            st.entries += int(doc.get("entries", 0))
            st.budget_bytes += int(doc.get("budget_bytes", 0))
            st.max_entries += int(doc.get("max_entries", 0))
            st.hits += int(doc.get("hits", 0))
            st.misses += int(doc.get("misses", 0))
            st.evictions += int(doc.get("evictions", 0))
            st.instances += 1
        return [
            agg[k] for k in sorted(
                agg, key=lambda k: (k[0] != "device", k[1])
            )
        ]

    def device_bytes(self) -> int:
        """Total bytes reported by device-tier pools (the number the
        global watermark is enforced against)."""
        total = 0
        for reg, pool in self._live():
            if reg.tier != "device":
                continue
            try:
                total += int((reg.stats_fn(pool) or {}).get("bytes", 0))
            except Exception:  # noqa: BLE001
                continue
        self._dev_bytes_cache = (time.monotonic(), total)
        return total

    def device_bytes_cached(self, max_age_s: float = 0.5) -> int:
        """device_bytes() behind a short TTL: per-span attribution on
        the traced hot path reads this, so a burst of device calls
        takes the pool locks once per TTL window, not once per call."""
        ts, val = self._dev_bytes_cache
        if time.monotonic() - ts <= max_age_s:
            return val
        return self.device_bytes()

    def publish(self) -> None:
        """Refresh the gtpu_mem_* families from current pool state
        (called by the registry collector on every scrape)."""
        if not self.enabled:
            return
        rows = []
        for reg, pool in self._live():
            try:
                doc = reg.stats_fn(pool) or {}
            except Exception:  # noqa: BLE001 - a pool mid-teardown
                continue
            rows.append((reg, doc))
        agg: dict[tuple, list] = {}
        for reg, doc in rows:
            a = agg.setdefault((reg.name, reg.tier), [0, 0, 0])
            a[0] += int(doc.get("bytes", 0))
            a[1] += int(doc.get("entries", 0))
            a[2] += int(doc.get("budget_bytes", 0))
        # delta bookkeeping under the accountant lock: two concurrent
        # scrapes reading the same stale baseline would both inc() the
        # counter with the full delta and inflate it forever. Baselines
        # are per-REGISTRATION: a dead instance's count dies with it
        # instead of masking the survivors' evictions behind the old
        # aggregate high-water mark.
        with self._lock:
            for reg, doc in rows:
                ev = int(doc.get("evictions", 0))
                if ev > reg.last_evictions:
                    _EVICTIONS.labels(reg.name, reg.tier).inc(
                        ev - reg.last_evictions
                    )
                reg.last_evictions = max(reg.last_evictions, ev)
            # a pool whose last instance was GC'd must report zero, not
            # freeze at its final published value
            for key in list(self._published):
                if key not in agg:
                    _BYTES.labels(*key).set(0.0)
                    _ENTRIES.labels(*key).set(0.0)
                    _BUDGET.labels(*key).set(0.0)
                    self._published.discard(key)
            for key, (b, e, bu) in agg.items():
                _BYTES.labels(*key).set(float(b))
                _ENTRIES.labels(*key).set(float(e))
                _BUDGET.labels(*key).set(float(bu))
                self._published.add(key)

    # ------------------------------------------------------------------
    # device live-buffer census
    # ------------------------------------------------------------------
    def census(self, top: int = 0) -> dict:
        """Reconcile owner-tagged pool buffers against
        jax.live_arrays(). Returns {live_bytes, accounted_bytes,
        unaccounted_bytes, unaccounted_count, pools: {name: bytes},
        top: [{bytes, owner, shape, dtype}]} and refreshes the census
        gauges. `top` > 0 additionally ranks the largest live buffers
        with their owner attribution."""
        # id -> (arr, owner): the array reference is PINNED here for
        # the duration of the census — a concurrent eviction freeing an
        # enumerated buffer would otherwise let CPython reuse its id
        # for an unrelated (possibly genuinely leaked) array, which
        # would then be misattributed as accounted
        owned: dict[int, tuple] = {}
        per_pool: dict[str, int] = {}
        for reg, pool in self._live():
            if reg.tier != "device" or reg.buffers_fn is None:
                continue
            try:
                bufs = list(reg.buffers_fn(pool))
            except Exception:  # noqa: BLE001 - census is best-effort
                continue
            per_pool.setdefault(reg.name, 0)
            for item in bufs:
                arr, owner = (item if isinstance(item, tuple)
                              else (item, reg.name))
                if arr is None or id(arr) in owned:
                    continue
                owned[id(arr)] = (arr, owner)
                per_pool[reg.name] += int(getattr(arr, "nbytes", 0))
        live_bytes = 0
        accounted = 0
        unaccounted = 0
        unacc_count = 0
        ranked: list[tuple[int, str, str, str]] = []
        try:
            import jax

            arrays = jax.live_arrays()
        except Exception:  # noqa: BLE001 - no jax backend: census empty
            arrays = []
        for a in arrays:
            try:
                if a.is_deleted():
                    continue
                nb = int(a.nbytes)
            except Exception:  # noqa: BLE001 - donated/poisoned array
                continue
            live_bytes += nb
            ent = owned.get(id(a))
            if ent is None:
                unaccounted += nb
                unacc_count += 1
            else:
                accounted += nb
            if top > 0:
                ranked.append((
                    nb, ent[1] if ent is not None else "(unaccounted)",
                    str(getattr(a, "shape", "?")),
                    str(getattr(a, "dtype", "?")),
                ))
        _DEVICE_LIVE.set(float(live_bytes))
        _DEVICE_ACCOUNTED.set(float(accounted))
        _UNACCOUNTED.set(float(unaccounted))
        out = {
            "live_bytes": live_bytes,
            "accounted_bytes": accounted,
            "unaccounted_bytes": unaccounted,
            "unaccounted_count": unacc_count,
            "pools": per_pool,
        }
        if top > 0:
            ranked.sort(key=lambda r: -r[0])
            out["top"] = [
                {"bytes": nb, "owner": ow, "shape": sh, "dtype": dt}
                for nb, ow, sh, dt in ranked[:top]
            ]
        return out

    # ------------------------------------------------------------------
    # cross-pool pressure
    # ------------------------------------------------------------------
    def note_device_bytes(self) -> int:
        """Device pools call this after growing, OUTSIDE their own lock
        (enforcement re-enters pools through their evict callbacks).
        Near-free when no global watermark is configured."""
        if not self.enabled or self.device_budget_bytes <= 0:
            return 0
        return self.enforce_device_budget()

    def enforce_device_budget(self) -> int:
        """Demand-driven proportional eviction: while total device pool
        bytes (evictable or not — a non-evictable pool's residency
        still consumes HBM) exceed the watermark, each evictable pool
        sheds its byte-share of the overage (largest pools first); a
        residual overage (a pool that could not free) falls through to
        a greedy second pass. Returns bytes freed."""
        budget = self.device_budget_bytes
        if budget <= 0:
            return 0
        if not self._enforce_lock.acquire(blocking=False):
            # another thread (or this one, re-entered through an evict
            # callback) is already sweeping the same overage
            return 0
        try:
            freed_total = 0
            for greedy in (False, True):
                evictable = []
                total = 0
                ev_total = 0
                for reg, pool in self._live():
                    if reg.tier != "device":
                        continue
                    try:
                        b = int(
                            (reg.stats_fn(pool) or {}).get("bytes", 0)
                        )
                    except Exception:  # noqa: BLE001
                        continue
                    total += b
                    if reg.evict_fn is not None and b > 0:
                        evictable.append((reg, pool, b))
                        ev_total += b
                overage = total - budget
                if overage <= 0 or not evictable:
                    return freed_total
                evictable.sort(key=lambda t: -t[2])
                for reg, pool, b in evictable:
                    if overage <= 0:
                        break
                    target = (min(b, overage) if greedy
                              else min(b, -(-overage * b // ev_total)))
                    try:
                        got = int(reg.evict_fn(pool, target) or 0)
                    except Exception:  # noqa: BLE001 - one pool's
                        # failure must not stop the sweep
                        got = 0
                    if got > 0:
                        _CROSS_EVICTED.labels(reg.name).inc(got)
                        freed_total += got
                        if greedy:
                            overage -= got
            return freed_total
        finally:
            self._enforce_lock.release()


global_accountant = MemoryAccountant()


def register_pool(name: str, tier: str, pool, *, stats, evict=None,
                  buffers=None) -> None:
    """Module-level convenience over the process-wide accountant."""
    global_accountant.register_pool(
        name, tier, pool, stats=stats, evict=evict, buffers=buffers
    )


def note_device_bytes() -> int:
    return global_accountant.note_device_bytes()


def configure(options: dict | None) -> None:
    """Apply the `[memory]` TOML section to this process."""
    o = options or {}
    acct = global_accountant
    acct.enabled = bool(o.get("enable", True))
    acct.device_budget_bytes = int(o.get("device_budget_bytes", 0))
    acct.census_on_scrape = bool(o.get("census_on_scrape", True))
    if acct.enabled and acct.device_budget_bytes > 0:
        # a watermark configured below current residency applies now,
        # not at the next put
        acct.enforce_device_budget()


def hbm_report(top: int = 10) -> dict:
    """The /debug/prof/hbm document: per-pool stats (device pools also
    carry their census-enumerated bytes), the live-buffer census with
    unaccounted residue, and the top-N live buffers by size with owner/
    shape/dtype attribution."""
    acct = global_accountant
    census = acct.census(top=top)
    pools = []
    for st in acct.snapshot():
        doc = st.to_doc()
        if st.tier == "device":
            doc["census_bytes"] = int(
                census["pools"].get(st.name, 0)
            )
        pools.append(doc)
    return {
        "pools": pools,
        "device_budget_bytes": acct.device_budget_bytes,
        "census": {
            "live_bytes": census["live_bytes"],
            "accounted_bytes": census["accounted_bytes"],
            "unaccounted_bytes": census["unaccounted_bytes"],
            "unaccounted_count": census["unaccounted_count"],
        },
        "top_buffers": census.get("top", []),
    }


def render_hbm_text(doc: dict) -> str:
    """Plain-text rendering of hbm_report (the default /debug/prof/hbm
    response, beside the CPU/heap text routes)."""
    lines = []
    c = doc["census"]
    budget = doc.get("device_budget_bytes", 0)
    lines.append(
        f"device census: live={c['live_bytes']} "
        f"accounted={c['accounted_bytes']} "
        f"unaccounted={c['unaccounted_bytes']} "
        f"({c['unaccounted_count']} buffers)"
    )
    lines.append(
        "global device budget: "
        + (f"{budget}" if budget > 0 else "(none)")
    )
    for tier in ("device", "host"):
        rows = [p for p in doc["pools"] if p["tier"] == tier]
        lines.append("")
        lines.append(f"{tier} pools:")
        lines.append(
            f"{'pool':<18} {'bytes':>14} {'census':>14} {'entries':>10} "
            f"{'budget':>14} {'hits':>10} {'miss':>10} {'evict':>8}"
        )
        for p in rows:
            census_col = (str(p.get("census_bytes", ""))
                          if tier == "device" else "-")
            lines.append(
                f"{p['pool']:<18} {p['bytes']:>14} {census_col:>14} "
                f"{p['entries']:>10} {p['budget_bytes']:>14} "
                f"{p['hits']:>10} {p['misses']:>10} {p['evictions']:>8}"
            )
    tops = doc.get("top_buffers", [])
    if tops:
        lines.append("")
        lines.append("top live buffers:")
        lines.append(f"{'bytes':>14}  {'shape':<20} {'dtype':<10} owner")
        for b in tops:
            lines.append(
                f"{b['bytes']:>14}  {b['shape']:<20} {b['dtype']:<10} "
                f"{b['owner']}"
            )
    return "\n".join(lines) + "\n"


def _scrape_collect() -> None:
    acct = global_accountant
    if not acct.enabled:
        return
    acct.publish()
    if acct.census_on_scrape:
        acct.census()


global_registry.register_collector(_scrape_collect)
