"""Anonymous usage reporting (disabled by default).

Capability counterpart of the reference's greptimedb-telemetry crate
(/root/reference/src/common/greptimedb-telemetry/src/lib.rs:29-34): a
persisted random install uuid + a small JSON payload (version, os,
arch, mode, node counts) POSTed to a configurable endpoint every
`interval_s`. Nothing is sent unless explicitly enabled.
"""

from __future__ import annotations

import json
import os
import platform
import threading

import uuid

from greptimedb_tpu.version import __version__

from greptimedb_tpu import concurrency

UUID_FILE_NAME = ".greptimedb-telemetry-uuid"


def install_uuid(data_home: str) -> str:
    """Stable random id persisted in the data home (never derived from
    any host identity)."""
    path = os.path.join(data_home, UUID_FILE_NAME)
    try:
        with open(path) as f:
            val = f.read().strip()
        if val:
            return val
    except OSError:
        pass
    val = str(uuid.uuid4())
    os.makedirs(data_home, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(val)
    os.replace(tmp, path)
    return val


def build_payload(data_home: str, *, mode: str = "standalone",
                  nodes: int = 1) -> dict:
    return {
        "uuid": install_uuid(data_home),
        "version": __version__,
        "os": platform.system().lower(),
        "arch": platform.machine(),
        "mode": mode,
        "nodes": nodes,
    }


class TelemetryTask:
    """Background reporter. `endpoint` is an http(s) URL; a report that
    fails is dropped silently (reporting must never affect the node)."""

    def __init__(self, data_home: str, *, endpoint: str,
                 interval_s: float = 1800.0, mode: str = "standalone",
                 nodes: int = 1):
        self.data_home = data_home
        self.endpoint = endpoint
        self.interval_s = max(1.0, float(interval_s))
        self.mode = mode
        self.nodes = nodes
        self.reports_sent = 0
        self._stop = concurrency.Event()
        self._thread: threading.Thread | None = None

    def start(self):
        self._thread = concurrency.Thread(
            target=self._loop, daemon=True, name="telemetry-report"
        )
        self._thread.start()
        return self

    def report_once(self) -> bool:
        import urllib.request

        try:
            # payload build included: install_uuid touches the data home
            # and an unwritable disk must not kill the reporter thread
            body = json.dumps(build_payload(
                self.data_home, mode=self.mode, nodes=self.nodes
            )).encode()
            req = urllib.request.Request(
                self.endpoint, data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10):
                pass
            self.reports_sent += 1
            return True
        except Exception:
            return False

    def _loop(self):
        # first report shortly after start, like the reference
        if not self._stop.wait(5.0):
            self.report_once()
        while not self._stop.wait(self.interval_s):
            self.report_once()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
