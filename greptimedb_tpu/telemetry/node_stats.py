"""Per-node telemetry payloads for the fleet observability plane.

Two local-only builders live here (no network I/O — gtlint GT019
enforces that the scrape/heartbeat paths can never hang a node):

- `build_node_stats(inst)` — the compact node-stats document every role
  (datanode / flownode / frontend / standalone) attaches to its metasrv
  heartbeat: role, addr, version, uptime, region count, WAL/compaction
  backlog, memory-pool bytes per tier (the PR 10 accountant), ingest +
  query rate counters and resident device bytes. The metasrv keeps a
  bounded per-node ring of these samples next to its phi-accrual
  verdict (meta/metasrv.py), and `information_schema.cluster_node_stats`
  is the SQL face of that ring.

- `deep_health(inst)` — the `/health?deep=1` readiness probe: per-role
  checks (engine open, WAL/data dir appendable, object store reachable,
  device dispatch OK, metasrv heartbeat fresh), each timed and isolated
  so one failing subsystem degrades the verdict instead of erroring the
  probe. `/v1/cluster/health` aggregates this JSON across the fleet.
"""

from __future__ import annotations

import logging
import os
import time

from greptimedb_tpu.version import __version__

_log = logging.getLogger("greptimedb_tpu.telemetry.node_stats")

# process birth, pinned at import: uptime is monotonic-derived (GT011 —
# wall clock is for data timestamps, not intervals); start_ms is the
# epoch-ms constructor form for display
_START_MONOTONIC = time.monotonic()
_START_EPOCH_MS = int(time.time() * 1000)


def process_uptime_s() -> float:
    return time.monotonic() - _START_MONOTONIC


def _registry_total(name: str) -> float:
    """Sum of every label child of a registered counter/gauge; 0.0 when
    the owning module has not registered it yet (role never imported
    it). Pure in-process reads — never blocks."""
    from greptimedb_tpu.telemetry.metrics import global_registry

    try:
        metric = global_registry.get(name)
    except KeyError:
        return 0.0
    return float(sum(c.value for _k, c in metric._snapshot()))


def build_node_stats(inst) -> dict:
    """The heartbeat-carried node-stats payload. Compact (one small
    JSON object), cheap (in-memory registry/accountant reads only) and
    bounded (no network, no device sync) — it rides EVERY heartbeat."""
    from greptimedb_tpu.telemetry import memory as _memory

    role = getattr(inst, "node_role", "standalone")
    doc = {
        "role": role,
        "addr": getattr(inst, "node_addr", "") or "",
        "version": __version__,
        "start_ms": _START_EPOCH_MS,
        "uptime_s": round(process_uptime_s(), 3),
        "regions": 0,
        "wal_backlog_rows": 0,
        "memtable_bytes": 0,
        "sst_count": 0,
        "sst_bytes": 0,
        "compaction_backlog": 0,
        "mem_host_bytes": 0,
        "mem_device_bytes": 0,
        "device_live_bytes": 0,
        "ingest_rows_total": 0.0,
        "queries_total": 0.0,
        "flows": 0,
    }
    engine = getattr(inst, "engine", None)
    if engine is not None:
        try:
            regions = engine.regions()
            doc["regions"] = len(regions)
            # rows still only in the memtable = what a restart would
            # replay from the WAL; manifest state is in memory
            doc["wal_backlog_rows"] = int(
                sum(r.memtable.rows for r in regions)
            )
            doc["memtable_bytes"] = int(
                sum(r.memtable.bytes for r in regions)
            )
            doc["sst_count"] = int(
                sum(len(r.manifest.state.ssts) for r in regions)
            )
            doc["sst_bytes"] = int(sum(
                m.size_bytes for r in regions
                for m in r.manifest.state.ssts
            ))
        except Exception as e:  # noqa: BLE001 - engine mid-teardown:
            # the payload ships partial rather than failing liveness
            _log.debug("node-stats engine read failed: %s", e)
    acct = _memory.global_accountant
    try:
        for st in acct.snapshot():
            if st.tier == "device":
                doc["mem_device_bytes"] += int(st.bytes)
            else:
                doc["mem_host_bytes"] += int(st.bytes)
            if st.name == "compaction":
                # in-flight merge jobs on the bounded scheduler pool
                doc["compaction_backlog"] = int(st.entries)
        doc["device_live_bytes"] = int(acct.device_bytes_cached())
    except Exception as e:  # noqa: BLE001 - accountant is advisory here
        _log.debug("node-stats accountant read failed: %s", e)
    # rate counters: whichever of the role's surfaces registered them
    doc["ingest_rows_total"] = (
        _registry_total("gtpu_ingest_rows_total")
        + _registry_total("greptime_servers_ingest_rows_total")
    )
    doc["queries_total"] = _registry_total("gtpu_sched_admitted_total")
    flows = getattr(inst, "flows", None)
    if flows is not None:
        try:
            doc["flows"] = len(flows.flow_infos())
        except Exception as e:  # noqa: BLE001 - flows mid-teardown
            _log.debug("node-stats flow read failed: %s", e)
    return doc


# ----------------------------------------------------------------------
# deep health
# ----------------------------------------------------------------------

# device dispatch probe result is cached: the readiness probe may be
# polled aggressively and a jit dispatch per poll would be waste
_DEVICE_PROBE_TTL_S = 60.0
_device_probe: tuple[float, bool, str] = (-1e18, False, "never ran")


def _check(fn) -> dict:
    t0 = time.perf_counter()
    try:
        ok, detail = fn()
    except Exception as e:  # noqa: BLE001 - a probe failure IS the result
        ok, detail = False, f"{type(e).__name__}: {e}"
    out = {"ok": bool(ok),
           "ms": round((time.perf_counter() - t0) * 1000.0, 2)}
    if detail:
        out["detail"] = str(detail)
    return out


def _probe_device() -> tuple[bool, str]:
    global _device_probe
    now = time.monotonic()
    ts, ok, detail = _device_probe
    if now - ts <= _DEVICE_PROBE_TTL_S:
        return ok, detail
    try:
        import jax
        import jax.numpy as jnp

        n = len(jax.devices())
        v = jnp.add(1, 1)
        v.block_until_ready()
        ok, detail = True, f"{n} device(s)"
    except Exception as e:  # noqa: BLE001 - no backend / poisoned chip
        ok, detail = False, f"{type(e).__name__}: {e}"
    _device_probe = (now, ok, detail)
    return ok, detail


def deep_health(inst) -> dict:
    """Per-role readiness: every check runs (one failure never hides
    another), each is timed, and the aggregate verdict is `ok` only
    when all of them pass. Local probes only — the fleet aggregation
    (`/v1/cluster/health`) fans this out with its own bounds."""
    role = getattr(inst, "node_role", "standalone")
    checks: dict[str, dict] = {}

    engine = getattr(inst, "engine", None)
    if engine is not None:
        def engine_open():
            regions = engine.regions()
            return True, f"{len(regions)} region(s) open"

        checks["engine"] = _check(engine_open)

        def data_appendable():
            # a real (tiny) write probe: WAL segments and manifests
            # live under data_root, so an unwritable/full volume fails
            # here before it fails an ingest
            root = engine.config.data_root
            os.makedirs(root, exist_ok=True)
            probe = os.path.join(root, ".health_probe")
            with open(probe, "w") as f:
                f.write("ok")
            os.remove(probe)
            return True, root

        checks["wal_appendable"] = _check(data_appendable)

        store = getattr(engine, "store", None)
        if store is not None:
            def store_reachable():
                # bounded metadata round trip against the object store
                # (the recovery/compaction read path dies first when
                # this is broken)
                store.exists("__health_probe__")
                return True, type(store).__name__

            checks["object_store"] = _check(store_reachable)

    checks["device"] = _check(_probe_device)

    meta = getattr(inst, "meta", None)
    if meta is not None:
        # dist roles: the metasrv lease/heartbeat channel. The
        # heartbeat loop stamps its last success (fleet.start_heartbeat)
        # — a fresh stamp proves the channel without a network probe;
        # without one (no loop running) probe the metasrv directly,
        # bounded by the MetaClient timeout.
        def metasrv_held():
            at = getattr(inst, "fleet_heartbeat_at", None)
            if at is not None:
                from greptimedb_tpu.dist import fleet

                # freshness bound scales with the CONFIGURED cadence
                # (a 15s heartbeat interval must not read as degraded
                # between perfectly healthy beats)
                bound = max(
                    10.0,
                    3.0 * fleet.config()["heartbeat_interval_s"],
                )
                age = time.monotonic() - at
                return age < bound, f"last heartbeat {age:.1f}s ago"
            meta._get("/health")
            return True, "metasrv reachable"

        checks["metasrv_lease"] = _check(metasrv_held)

    flows = getattr(inst, "flows", None)
    if flows is not None:
        def flows_live():
            return True, f"{len(flows.flow_infos())} flow(s)"

        checks["flows"] = _check(flows_live)

    ok = all(c["ok"] for c in checks.values())
    return {
        "status": "ok" if ok else "degraded",
        "role": role,
        "addr": getattr(inst, "node_addr", "") or "",
        "version": __version__,
        "uptime_s": round(process_uptime_s(), 3),
        "checks": checks,
    }
