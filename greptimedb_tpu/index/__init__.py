"""Secondary tag-index dataplane (ROADMAP item 5, reference src/index).

- tag_index: per-registry inverted index — tag-value -> sid postings
  over the dictionary-coded label plane, version-validated, with a
  memoized per-matcher-set sid cache. `match_sids(registry, matchers)`
  is the one entry point every scan path routes through.
- device_plane: the label plane kept HBM-resident so PromQL/SQL
  matcher masks are computed on device (ok-tables move, series don't).
"""

from greptimedb_tpu.index.tag_index import (  # noqa: F401
    TagIndex,
    configure,
    device_plane_enabled,
    enabled,
    index_for,
    match_mask,
    match_sids,
    matcher_key,
)
from greptimedb_tpu.index import device_plane  # noqa: F401
