"""HBM-resident dictionary-coded label plane: device matcher masks.

The PromQL/SQL device paths need an (S_pad,) bool mask per matcher set.
The host path computes it over the numpy label plane and uploads
S_pad bytes per DISTINCT matcher set; at 10M series that is a 10MB
tunnel transfer before the first fused program runs. This module keeps
the label plane itself resident in HBM — the (S_pad, num_tags) int32
code matrix, sharded over the series axis like every other grid — and
computes masks on device: per query, only the per-DISTINCT-VALUE
ok-tables move (kilobytes), the gather+AND runs where the data already
lives, and the result feeds the fused programs without a host round
trip (HiFrames' columnar-pipeline locality argument, PAPERS.md).

Padded rows (sid >= num_series) carry a per-column sentinel code whose
ok-table entry is always False, so the mask is padded-False by
construction. Ok-tables are padded to powers of two to bound jit
recompiles as dictionaries grow.

Planes are version-validated against the registry (like the postings in
tag_index.py) and registered with the memory accountant as a device
pool — census-enumerable buffers, LRU eviction under cross-pool HBM
pressure.
"""

from __future__ import annotations

import functools
from collections import OrderedDict

import numpy as np

from greptimedb_tpu import concurrency
from greptimedb_tpu.storage.series import missing_tag_ok, ok_codes_for

_MAX_PLANES = 8
_MAX_MASKS = 128

_LOCK = concurrency.Lock()
_PLANES: "OrderedDict[tuple, _Plane]" = OrderedDict()
_POOL_REGISTERED = False
_HITS = 0
_MISSES = 0
_EVICTIONS = 0


class _Plane:
    __slots__ = ("registry_ref", "version", "s_pad", "num_series",
                 "dev_codes", "nbytes", "mask_cache", "tag_names")

    def __init__(self, registry, version, s_pad, dev_codes, nbytes):
        import weakref

        self.registry_ref = weakref.ref(registry)
        self.version = version
        self.s_pad = s_pad
        self.num_series = registry.num_series
        self.dev_codes = dev_codes      # (s_pad, k) int32 device
        self.nbytes = nbytes
        self.tag_names = list(registry.tag_names)
        # matcher key -> (dev mask, any_match) — same shape the promql
        # per-entry match_cache stores, computed on device here
        self.mask_cache: OrderedDict = OrderedDict()


def _pow2(n: int) -> int:
    p = 8
    while p < n:
        p <<= 1
    return p


@functools.lru_cache(maxsize=64)
def _mask_prog(ncols: int):
    """jit'd gather+AND over `ncols` referenced tag columns: each
    column's codes index its ok-table; the mask is the conjunction."""
    import jax
    import jax.numpy as jnp

    def f(cols, oks):
        m = None
        for c, ok in zip(cols, oks):
            t = jnp.take(ok, c, axis=0)
            m = t if m is None else (m & t)
        return m

    return jax.jit(f)


def _sharding(mesh):
    if mesh is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P

    from greptimedb_tpu.parallel.mesh import AXIS_SHARD

    return NamedSharding(mesh, P(AXIS_SHARD, None))


def _get_plane(registry, s_pad: int, mesh) -> _Plane | None:
    global _HITS, _MISSES
    version = registry.version
    key = (id(registry), s_pad, id(mesh) if mesh is not None else None)
    with _LOCK:
        p = _PLANES.get(key)
        if (p is not None and p.version == version
                and p.registry_ref() is registry):
            _PLANES.move_to_end(key)
            _HITS += 1
            return p
        _MISSES += 1
    import jax
    import jax.numpy as jnp

    codes = registry.codes_matrix()
    n, k = codes.shape
    if k == 0 or s_pad < n:
        return None
    plane = np.empty((s_pad, k), dtype=np.int32)
    plane[:n] = codes
    # padded rows get each column's sentinel code (== dict size); the
    # ok-tables below always hold False there, so padded rows never match
    for i in range(k):
        plane[n:, i] = len(registry.dicts[i])
    sh = _sharding(mesh)
    dev = (jax.device_put(plane, sh) if sh is not None
           else jnp.asarray(plane))
    p = _Plane(registry, version, s_pad, dev, int(plane.nbytes))
    with _LOCK:
        old = _PLANES.get(key)
        _PLANES[key] = p
        _PLANES.move_to_end(key)
        while len(_PLANES) > _MAX_PLANES:
            _PLANES.popitem(last=False)
        del old
    _ensure_pool()
    from greptimedb_tpu.telemetry import memory as _memory

    _memory.note_device_bytes()
    return p


def matcher_mask_dev(registry, matchers, s_pad: int, mesh=None,
                     num_series: int | None = None):
    """((s_pad,) bool device mask, any_match) for a matcher set, or
    None when the device plane can't serve it (disabled, tagless
    registry, or a constant matcher set with no indexable column —
    callers fall back to the host mask + upload path). `num_series` is
    the caller's view of the series count: a plane built over a
    registry that has since grown past it would mark rows the caller
    considers padding, so the mismatch falls back too."""
    from greptimedb_tpu.index import tag_index

    if not tag_index.device_plane_enabled():
        return None
    p = _get_plane(registry, s_pad, mesh)
    if p is None:
        return None
    if num_series is not None and p.num_series != num_series:
        return None
    key = tag_index.matcher_key(matchers)
    with _LOCK:
        hit = p.mask_cache.get(key)
        if hit is not None:
            p.mask_cache.move_to_end(key)
            return hit
    import jax.numpy as jnp

    cols: list[int] = []
    oks: list[np.ndarray] = []
    for name, op, value in matchers:
        if name not in p.tag_names:
            if not missing_tag_ok(op, value):
                zero = jnp.zeros(s_pad, dtype=bool)
                out = (zero, False)
                break
            continue
        i = p.tag_names.index(name)
        d = registry.dicts[i]
        vals = np.asarray(list(d.values), dtype=object)
        ok = ok_codes_for(vals, op, value)
        # pow2-padded with a False sentinel tail: padded plane rows
        # (code == len(d)) and future codes both read False
        padded = np.zeros(_pow2(len(ok) + 1), dtype=bool)
        padded[: len(ok)] = ok
        cols.append(i)
        oks.append(padded)
    else:
        if not cols:
            return None  # constant-true set: host path pads correctly
        prog = _mask_prog(len(cols))
        dev = prog(
            tuple(p.dev_codes[:, i] for i in cols),
            tuple(jnp.asarray(ok) for ok in oks),
        )
        out = (dev, bool(dev.any()))
    with _LOCK:
        p.mask_cache[key] = out
        p.mask_cache.move_to_end(key)
        while len(p.mask_cache) > _MAX_MASKS:
            p.mask_cache.popitem(last=False)
    return out


def invalidate() -> None:
    with _LOCK:
        _PLANES.clear()


# ---------------------------------------------------------------------
# memory accountant surface (device tier)
# ---------------------------------------------------------------------
class _PlanePool:
    def stats(self) -> dict:
        from greptimedb_tpu.telemetry.memory import iter_device_arrays

        with _LOCK:
            total = 0
            for p in _PLANES.values():
                total += int(p.dev_codes.nbytes)
                for v in list(p.mask_cache.values()):
                    for arr in iter_device_arrays(v):
                        total += int(arr.nbytes)
            return {
                "bytes": total, "entries": len(_PLANES),
                "budget_bytes": 0, "hits": _HITS, "misses": _MISSES,
                "evictions": _EVICTIONS,
            }

    def evict_bytes(self, target: int) -> int:
        global _EVICTIONS
        freed = 0
        with _LOCK:
            while _PLANES and freed < target:
                _, p = _PLANES.popitem(last=False)
                freed += int(p.dev_codes.nbytes)
                for v in list(p.mask_cache.values()):
                    from greptimedb_tpu.telemetry.memory import (
                        iter_device_arrays,
                    )

                    for arr in iter_device_arrays(v):
                        freed += int(arr.nbytes)
                _EVICTIONS += 1
        return freed

    def buffers(self):
        from greptimedb_tpu.telemetry.memory import iter_device_arrays

        out = []
        with _LOCK:
            for p in _PLANES.values():
                out.append((p.dev_codes, "tag_index:plane"))
                for v in list(p.mask_cache.values()):
                    for arr in iter_device_arrays(v):
                        out.append((arr, "tag_index:mask"))
        return out


_POOL = _PlanePool()


def _ensure_pool() -> None:
    global _POOL_REGISTERED
    with _LOCK:
        if _POOL_REGISTERED:
            return
        _POOL_REGISTERED = True
    from greptimedb_tpu.telemetry import memory as _memory

    _memory.register_pool(
        "tag_index_plane", "device", _POOL,
        stats=_PlanePool.stats, evict=_PlanePool.evict_bytes,
        buffers=_PlanePool.buffers,
    )
