"""Per-region secondary tag index: tag-value -> sid postings.

The capability analog of the reference's inverted index appliers
(src/index + the puffin blobs mito2 attaches to SSTs): instead of a
separate on-disk index format, the postings are derived from the
dictionary-coded label plane the series registry already maintains —
per tag column, a CSR (offsets, order) pair where order is the stable
argsort of that column's codes, so the sids for one tag value are a
contiguous ascending slice.

Matcher evaluation splits into two domains:

- `eq`/`in` matchers resolve a value to its dictionary code (O(1) hash
  lookup) and read the posting slice — no per-series work at all.
- `re`/`nre`/`ne`/`nin` matchers evaluate once per DISTINCT value
  (series.ok_codes_for — the same code match_mask broadcasts through),
  then expand the accepting codes through the postings. String/regex
  cost scales with value cardinality, not series cardinality.

The most selective matcher (estimated from posting lengths) seeds the
candidate set; the rest filter candidates by indexing their ok-tables
with the candidates' codes — O(|candidates|) int work per matcher.

Maintenance is incremental and version-validated like the scan cache:
sids are dense and append-only, so postings built at registry version v
cover a sid PREFIX; series registered since are evaluated directly
(O(delta)) until the delta crosses `rebuild_threshold` and the CSR is
rebuilt. ALTER ADD TAG (column-count change) always rebuilds. Matched
sid sets are memoized per canonical matcher key, keyed on the registry
version (an eq lookup repeated across a dashboard poll costs one dict
hit).
"""

from __future__ import annotations

import weakref
from collections import OrderedDict

import numpy as np

from greptimedb_tpu import concurrency
from greptimedb_tpu.storage.series import missing_tag_ok, ok_codes_for

_CFG = {
    "enable": True,
    # device-resident label plane (index/device_plane.py)
    "device_plane": True,
    # per-index memoized (matcher-set -> sids) entries
    "result_cache_entries": 256,
    # series registered since the last CSR build before a rebuild;
    # below it the delta tail is evaluated directly per lookup
    "rebuild_threshold": 4096,
}


def configure(section: dict | None) -> None:
    """Apply the [index] config section (config.DEFAULTS['index'])."""
    for k, v in (section or {}).items():
        if k in _CFG:
            _CFG[k] = v
    if not _CFG["device_plane"] or not _CFG["enable"]:
        from greptimedb_tpu.index import device_plane

        device_plane.invalidate()


def enabled() -> bool:
    return bool(_CFG["enable"])


def device_plane_enabled() -> bool:
    return bool(_CFG["enable"]) and bool(_CFG["device_plane"])


def matcher_key(matchers) -> tuple:
    """Canonical hashable key for a matcher set: compiled regexes fold
    to their pattern string, list values to tuples. Order-sensitive
    (matcher sets arrive in plan order, which is stable per statement
    fingerprint)."""
    out = []
    for name, op, value in matchers:
        if op in ("re", "nre"):
            v = getattr(value, "pattern", value)
        elif isinstance(value, (list, tuple, set, frozenset)):
            v = tuple(sorted(str(x) for x in value))
        else:
            v = value
        out.append((name, op, v))
    return tuple(out)


def _expand_csr(offsets: np.ndarray, order: np.ndarray,
                codes: np.ndarray) -> np.ndarray:
    """Gather the concatenated posting slices for `codes` (vectorized
    multi-slice CSR expand — no per-code Python loop)."""
    if len(codes) == 0:
        return np.zeros(0, dtype=np.int32)
    starts = offsets[codes]
    lens = offsets[codes + 1] - starts
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int32)
    pos = np.repeat(starts - (np.cumsum(lens) - lens), lens)
    return order[pos + np.arange(total, dtype=np.int64)]


class TagIndex:
    """Secondary index over one SeriesRegistry (see module docstring)."""

    def __init__(self, registry):
        self._reg = registry
        self._lock = concurrency.Lock()
        self._built_version = -1
        self._built_rows = 0
        self._built_tags = 0
        # per tag column: (offsets int64 (nvals+1,), order int32) over
        # the first _built_rows sids
        self._postings: list[tuple[np.ndarray, np.ndarray]] = []
        self._results: OrderedDict[tuple, tuple[int, np.ndarray]] = (
            OrderedDict()
        )
        self._hits = 0
        self._misses = 0
        self._builds = 0
        _track(self)

    # -- maintenance ---------------------------------------------------
    def _ensure_built(self, codes: np.ndarray, version: int) -> int:
        """Bring postings up to date for the (n, k) snapshot `codes`;
        returns the prefix length the CSR covers. Caller holds no lock —
        builds race benignly (last writer wins, both are correct)."""
        n, k = codes.shape
        if version == self._built_version and k == self._built_tags:
            return self._built_rows
        if (k == self._built_tags and self._built_rows <= n
                and n - self._built_rows <= int(_CFG["rebuild_threshold"])):
            # delta tail small: validate the version without rebuilding
            # (lookups evaluate sids >= _built_rows directly)
            self._built_version = version
            return self._built_rows
        dicts = self._reg.dicts
        postings = []
        for i in range(k):
            col = codes[:, i]
            nvals = max(len(dicts[i]) if i < len(dicts) else 0,
                        int(col.max()) + 1 if n else 0)
            counts = np.bincount(col, minlength=nvals)
            offsets = np.zeros(len(counts) + 1, dtype=np.int64)
            np.cumsum(counts, out=offsets[1:])
            # stable argsort keeps original (ascending-sid) order within
            # each code, so every posting slice is already sorted
            order = np.argsort(col, kind="stable").astype(np.int32)
            postings.append((offsets, order))
        with self._lock:
            self._postings = postings
            self._built_rows = n
            self._built_tags = k
            self._built_version = version
            self._builds += 1
        return n

    # -- lookup --------------------------------------------------------
    def match_sids(self, matchers) -> np.ndarray:
        """Sids satisfying all matchers, ascending int32 — bit-identical
        to SeriesRegistry.match_sids by construction (same ok-code
        tables, broadcast through postings instead of the full plane)."""
        from greptimedb_tpu.query import stats

        reg = self._reg
        version = reg.version
        key = matcher_key(matchers)
        with self._lock:
            hit = self._results.get(key)
            if hit is not None and hit[0] == version:
                self._results.move_to_end(key)
                self._hits += 1
                _count_lookup("cache")
                stats.add("index_lookups", 1)
                return hit[1]
            self._misses += 1
        sids = self._eval(matchers, version)
        with self._lock:
            self._results[key] = (version, sids)
            self._results.move_to_end(key)
            cap = int(_CFG["result_cache_entries"])
            while len(self._results) > max(cap, 1):
                self._results.popitem(last=False)
        _count_lookup("postings")
        stats.add("index_lookups", 1)
        return sids

    def match_mask(self, matchers) -> np.ndarray:
        """(num_series,) bool mask via the index (postings expanded back
        into a dense mask — what the device plane ok-tables mirror)."""
        n = self._reg.num_series
        mask = np.zeros(n, dtype=bool)
        sids = self.match_sids(matchers)
        mask[sids[sids < n]] = True
        return mask

    def _eval(self, matchers, version: int) -> np.ndarray:
        reg = self._reg
        codes = reg.codes_matrix()
        n, k = codes.shape
        empty = np.zeros(0, dtype=np.int32)
        if n == 0:
            return empty
        tag_names = reg.tag_names
        dicts = reg.dicts
        # dictionary-domain pass: one ok-table per matcher
        cols: list[int] = []
        oks: list[np.ndarray] = []
        for name, op, value in matchers:
            if name not in tag_names:
                if not missing_tag_ok(op, value):
                    return empty
                continue  # constant-true: no constraint
            i = tag_names.index(name)
            vals = np.asarray(list(dicts[i].values), dtype=object)
            ok = ok_codes_for(vals, op, value)
            if not ok.any():
                return empty
            cols.append(i)
            oks.append(ok)
        if not cols:
            return np.arange(n, dtype=np.int32)
        built = self._ensure_built(codes, version)
        postings = self._postings
        # seed candidates from the most selective matcher (estimated
        # from posting lengths over the built prefix)
        seed = 0
        if built and postings:
            best = None
            for j, (i, ok) in enumerate(zip(cols, oks)):
                offsets, _ = postings[i]
                nv = min(len(ok), len(offsets) - 1)
                est = int(
                    (offsets[1:nv + 1] - offsets[:nv])[ok[:nv]].sum()
                )
                if best is None or est < best:
                    best, seed = est, j
            offsets, order = postings[cols[seed]]
            ok = oks[seed]
            nv = min(len(ok), len(offsets) - 1)
            cs = np.flatnonzero(ok[:nv]).astype(np.int64)
            cand = _expand_csr(offsets, order, cs)
            if len(cs) > 1:
                # each posting slice is ascending; a multi-code union
                # needs one merge sort to restore global sid order
                cand = np.sort(cand)
        else:
            cand = np.arange(built, dtype=np.int32)
        # remaining matchers filter candidates through their ok-tables
        for j, (i, ok) in enumerate(zip(cols, oks)):
            if built and postings and j == seed:
                continue
            if len(cand) == 0:
                break
            c = codes[cand, i]
            safe = np.minimum(c, len(ok) - 1)
            cand = cand[ok[safe] & (c < len(ok))]
        # delta tail (sids registered since the CSR build): direct
        # evaluation over O(delta) rows
        if built < n:
            keep = np.ones(n - built, dtype=bool)
            for i, ok in zip(cols, oks):
                c = codes[built:, i]
                safe = np.minimum(c, len(ok) - 1)
                keep &= ok[safe] & (c < len(ok))
            tail = (np.flatnonzero(keep) + built).astype(np.int32)
            if len(tail):
                cand = np.concatenate([cand.astype(np.int32), tail])
        return np.ascontiguousarray(cand, dtype=np.int32)

    # -- observability -------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "built_rows": self._built_rows,
                "built_version": self._built_version,
                "builds": self._builds,
                "hits": self._hits,
                "misses": self._misses,
                "cached_results": len(self._results),
                "bytes": self.nbytes(),
            }

    def nbytes(self) -> int:
        total = 0
        for offsets, order in self._postings:
            total += int(offsets.nbytes) + int(order.nbytes)
        for _, sids in self._results.values():
            total += int(sids.nbytes)
        return total


# ---------------------------------------------------------------------
# registry -> index association + host memory-pool accounting
# ---------------------------------------------------------------------
_INDEXES: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_INDEXES_LOCK = concurrency.Lock()
# separate from _INDEXES_LOCK: TagIndex.__init__ runs under it (via
# index_for) and _track must not re-acquire the same non-reentrant lock
_POOL_LOCK = concurrency.Lock()
_POOL_REGISTERED = False
_LIVE: "weakref.WeakSet[TagIndex]" = weakref.WeakSet()


class _IndexPool:
    """Accountant surface over every live TagIndex (host tier)."""

    def stats(self) -> dict:
        total = entries = hits = misses = 0
        for ix in list(_LIVE):
            s = ix.stats()
            total += s["bytes"]
            entries += s["cached_results"]
            hits += s["hits"]
            misses += s["misses"]
        return {
            "bytes": total, "entries": entries, "budget_bytes": 0,
            "hits": hits, "misses": misses, "evictions": 0,
        }


_POOL = _IndexPool()


def _track(ix: TagIndex) -> None:
    global _POOL_REGISTERED
    _LIVE.add(ix)
    with _POOL_LOCK:
        if _POOL_REGISTERED:
            return
        _POOL_REGISTERED = True
    from greptimedb_tpu.telemetry import memory as _memory

    _memory.register_pool(
        "tag_index", "host", _POOL, stats=_IndexPool.stats,
    )


def _count_lookup(path: str) -> None:
    from greptimedb_tpu.telemetry.metrics import global_registry

    global_registry.counter(
        "gtpu_index_lookups_total",
        "Secondary tag-index matcher lookups by path "
        "(cache | postings | host)",
        labels=("path",),
    ).labels(path).inc()


def count_pruned(*, row_groups: int = 0, bytes_: int = 0,
                 scope: str = "row_group") -> None:
    """Record scan data skipped by sid-range/sid-index pruning, in the
    per-query ExecStats (EXPLAIN ANALYZE) and the process counters.
    scope: "row_group" (footer sid-index) | "sst" (manifest sid range)."""
    from greptimedb_tpu.query import stats
    from greptimedb_tpu.telemetry.metrics import global_registry

    if row_groups:
        stats.add("index_pruned_row_groups", row_groups)
        global_registry.counter(
            "gtpu_index_pruned_row_groups_total",
            "Row groups skipped by the secondary-index sid pruning",
        ).inc(row_groups)
    if bytes_:
        stats.add("index_pruned_bytes", bytes_)
        global_registry.counter(
            "gtpu_index_pruned_bytes_total",
            "Bytes skipped by secondary-index sid pruning "
            "(sst = whole files via the manifest sid range, "
            "row_group = Parquet row groups via the footer sid index)",
            labels=("scope",),
        ).labels(scope).inc(bytes_)


def index_for(registry) -> TagIndex:
    """The TagIndex for a registry (one per registry, weakly held — a
    region swapping its registry on replay/restore drops the old index
    with it)."""
    with _INDEXES_LOCK:
        ix = _INDEXES.get(registry)
        if ix is None:
            ix = TagIndex(registry)
            _INDEXES[registry] = ix
        return ix


def match_sids(registry, matchers) -> np.ndarray:
    """Route a matcher lookup through the secondary index when enabled;
    the registry's full-plane compare is the fallback (and the oracle
    the index tests equate against)."""
    if not matchers:
        return np.arange(registry.num_series, dtype=np.int32)
    if not _CFG["enable"]:
        _count_lookup("host")
        return registry.match_sids(matchers)
    return index_for(registry).match_sids(matchers)


def match_mask(registry, matchers) -> np.ndarray:
    """Dense bool mask counterpart of match_sids (PromQL grid path)."""
    if not matchers:
        return np.ones(registry.num_series, dtype=bool)
    if not _CFG["enable"]:
        _count_lookup("host")
        return registry.match_mask(matchers)
    return index_for(registry).match_mask(matchers)
