"""JSON / geo / network scalar functions (VERDICT row 20)."""

import math

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone


@pytest.fixture()
def inst(tmp_path):
    s = Standalone(str(tmp_path / "data"))
    yield s
    s.close()


@pytest.fixture()
def jt(inst):
    inst.sql(
        "CREATE TABLE jt (doc STRING, ts TIMESTAMP TIME INDEX)"
    )
    inst.sql(
        'INSERT INTO jt (doc, ts) VALUES '
        '(\'{"a": {"b": 7}, "tags": ["x", "y"], "ok": true, "pi": 3.5}\', 1), '
        "('not json', 2), "
        '(\'{"a": {}}\', 3)'
    )
    return inst


def test_json_get(jt):
    r = jt.sql("SELECT json_get_int(doc, '$.a.b') FROM jt ORDER BY ts")
    rows = r.rows()
    assert rows[0][0] == 7 and rows[1][0] is None and rows[2][0] is None
    r = jt.sql("SELECT json_get_string(doc, '$.tags[1]') FROM jt "
               "WHERE ts = 1")
    assert r.rows()[0][0] == "y"
    r = jt.sql("SELECT json_get_bool(doc, 'ok'), "
               "json_get_float(doc, 'pi') FROM jt WHERE ts = 1")
    assert r.rows()[0] == [True, 3.5]


def test_json_predicates(jt):
    r = jt.sql("SELECT json_path_exists(doc, '$.a.b'), "
               "json_is_object(doc) FROM jt ORDER BY ts")
    rows = r.rows()
    assert rows[0] == [True, True]
    assert rows[1] == [False, False]
    assert rows[2] == [False, True]


def test_json_in_where(jt):
    r = jt.sql("SELECT ts FROM jt WHERE json_get_int(doc, '$.a.b') = 7")
    assert [row[0] for row in r.rows()] == [1]


def test_geo_functions(inst):
    inst.sql("CREATE TABLE gt (lat DOUBLE, lon DOUBLE, "
             "ts TIMESTAMP TIME INDEX)")
    # San Francisco and New York
    inst.sql("INSERT INTO gt (lat, lon, ts) VALUES "
             "(37.7749, -122.4194, 1), (40.7128, -74.0060, 2)")
    r = inst.sql("SELECT st_distance(lat, lon, 40.7128, -74.0060) "
                 "FROM gt ORDER BY ts")
    d = float(r.rows()[0][0])
    assert abs(d - 4_129_000) < 15_000   # ~4129 km great-circle
    assert float(r.rows()[1][0]) == 0.0

    r = inst.sql("SELECT geohash(lat, lon, 6) FROM gt ORDER BY ts")
    assert r.rows()[0][0].startswith("9q8yy")   # SF geohash prefix

    r = inst.sql("SELECT st_point(lat, lon) FROM gt WHERE ts = 2")
    assert r.rows()[0][0] == "POINT(-74.006 40.7128)"

    # cell bucketing groups nearby points to the same id
    r = inst.sql("SELECT h3_latlng_to_cell(lat, lon, 8) FROM gt "
                 "ORDER BY ts")
    ids = [row[0] for row in r.rows()]
    assert ids[0] != ids[1] and all(isinstance(i, int) for i in ids)


def test_net_functions(inst):
    inst.sql("CREATE TABLE nt (ip STRING, ts TIMESTAMP TIME INDEX)")
    inst.sql("INSERT INTO nt (ip, ts) VALUES ('10.0.0.1', 1), "
             "('192.168.1.5', 2), ('garbage', 3)")
    r = inst.sql("SELECT ipv4_string_to_num(ip) FROM nt ORDER BY ts")
    rows = [row[0] for row in r.rows()]
    assert rows[0] == 10 * 2**24 + 1 and rows[2] is None
    r = inst.sql("SELECT ipv4_num_to_string(167772161) FROM nt LIMIT 1")
    assert r.rows()[0][0] == "10.0.0.1"
    r = inst.sql("SELECT ts FROM nt WHERE ipv4_in_range(ip, "
                 "'192.168.0.0/16')")
    assert [row[0] for row in r.rows()] == [2]
