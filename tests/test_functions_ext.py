"""JSON / geo / network scalar functions (VERDICT row 20)."""

import math

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone


@pytest.fixture()
def inst(tmp_path):
    s = Standalone(str(tmp_path / "data"))
    yield s
    s.close()


@pytest.fixture()
def jt(inst):
    inst.sql(
        "CREATE TABLE jt (doc STRING, ts TIMESTAMP TIME INDEX)"
    )
    inst.sql(
        'INSERT INTO jt (doc, ts) VALUES '
        '(\'{"a": {"b": 7}, "tags": ["x", "y"], "ok": true, "pi": 3.5}\', 1), '
        "('not json', 2), "
        '(\'{"a": {}}\', 3)'
    )
    return inst


def test_json_get(jt):
    r = jt.sql("SELECT json_get_int(doc, '$.a.b') FROM jt ORDER BY ts")
    rows = r.rows()
    assert rows[0][0] == 7 and rows[1][0] is None and rows[2][0] is None
    r = jt.sql("SELECT json_get_string(doc, '$.tags[1]') FROM jt "
               "WHERE ts = 1")
    assert r.rows()[0][0] == "y"
    r = jt.sql("SELECT json_get_bool(doc, 'ok'), "
               "json_get_float(doc, 'pi') FROM jt WHERE ts = 1")
    assert r.rows()[0] == [True, 3.5]


def test_json_predicates(jt):
    r = jt.sql("SELECT json_path_exists(doc, '$.a.b'), "
               "json_is_object(doc) FROM jt ORDER BY ts")
    rows = r.rows()
    assert rows[0] == [True, True]
    assert rows[1] == [False, False]
    assert rows[2] == [False, True]


def test_json_in_where(jt):
    r = jt.sql("SELECT ts FROM jt WHERE json_get_int(doc, '$.a.b') = 7")
    assert [row[0] for row in r.rows()] == [1]


def test_geo_functions(inst):
    inst.sql("CREATE TABLE gt (lat DOUBLE, lon DOUBLE, "
             "ts TIMESTAMP TIME INDEX)")
    # San Francisco and New York
    inst.sql("INSERT INTO gt (lat, lon, ts) VALUES "
             "(37.7749, -122.4194, 1), (40.7128, -74.0060, 2)")
    r = inst.sql("SELECT st_distance(lat, lon, 40.7128, -74.0060) "
                 "FROM gt ORDER BY ts")
    d = float(r.rows()[0][0])
    assert abs(d - 4_129_000) < 15_000   # ~4129 km great-circle
    assert float(r.rows()[1][0]) == 0.0

    r = inst.sql("SELECT geohash(lat, lon, 6) FROM gt ORDER BY ts")
    assert r.rows()[0][0].startswith("9q8yy")   # SF geohash prefix

    r = inst.sql("SELECT st_point(lat, lon) FROM gt WHERE ts = 2")
    assert r.rows()[0][0] == "POINT(-74.006 40.7128)"

    # cell bucketing groups nearby points to the same id
    r = inst.sql("SELECT h3_latlng_to_cell(lat, lon, 8) FROM gt "
                 "ORDER BY ts")
    ids = [row[0] for row in r.rows()]
    assert ids[0] != ids[1] and all(isinstance(i, int) for i in ids)


def test_net_functions(inst):
    inst.sql("CREATE TABLE nt (ip STRING, ts TIMESTAMP TIME INDEX)")
    inst.sql("INSERT INTO nt (ip, ts) VALUES ('10.0.0.1', 1), "
             "('192.168.1.5', 2), ('garbage', 3)")
    r = inst.sql("SELECT ipv4_string_to_num(ip) FROM nt ORDER BY ts")
    rows = [row[0] for row in r.rows()]
    assert rows[0] == 10 * 2**24 + 1 and rows[2] is None
    r = inst.sql("SELECT ipv4_num_to_string(167772161) FROM nt LIMIT 1")
    assert r.rows()[0][0] == "10.0.0.1"
    r = inst.sql("SELECT ts FROM nt WHERE ipv4_in_range(ip, "
                 "'192.168.0.0/16')")
    assert [row[0] for row in r.rows()] == [2]


# ----------------------------------------------------------------------
# signed intervals (ADVICE r5: date_add(ts, INTERVAL '-1 month') must
# subtract, not add)
# ----------------------------------------------------------------------

def test_parse_interval_ms_signed():
    from greptimedb_tpu.sql.parser import parse_interval_ms

    assert parse_interval_ms("-90 minutes") == -5_400_000
    assert parse_interval_ms("-1h") == -3_600_000
    assert parse_interval_ms("1 day -1 hour") == 82_800_000
    # space-separated sign must not silently drop
    assert parse_interval_ms("- 1 day") == -86_400_000


def test_interval_months_signed():
    from greptimedb_tpu.query.functions import _interval_months
    from greptimedb_tpu.sql import ast as A

    def months(raw):
        return _interval_months(A.IntervalLit(0, raw))

    assert months("-1 month") == -1
    assert months("-2 years") == -24
    assert months("1 year -1 month") == 11
    assert months("- 1 month") == -1  # space-separated sign
    assert months("-1 day") is None  # fixed-span path, not calendar


def test_date_add_negative_month_over_table(inst):
    inst.sql("CREATE TABLE sd (ts TIMESTAMP TIME INDEX, v DOUBLE)")
    # 2024-03-31: minus 1 month clamps to 2024-02-29 (leap year)
    inst.sql("INSERT INTO sd VALUES (1711843200000, 1.0)")
    r = inst.sql("SELECT date_add(ts, INTERVAL '-1 month') FROM sd")
    assert r.rows()[0][0] == 1709164800000
    # date_sub of a negative interval ADDS
    r = inst.sql("SELECT date_sub(ts, INTERVAL '-1 month') FROM sd")
    assert r.rows()[0][0] == 1714435200000  # 2024-04-30 (clamped)


def test_negative_range_interval_rejected(inst):
    from greptimedb_tpu.errors import InvalidSyntaxError

    inst.sql("CREATE TABLE nr (ts TIMESTAMP TIME INDEX, v DOUBLE)")
    with pytest.raises(InvalidSyntaxError):
        inst.sql("SELECT ts, avg(v) RANGE '-1h' FROM nr ALIGN '1h'")
    with pytest.raises(InvalidSyntaxError):
        inst.sql("SELECT ts, avg(v) RANGE '1h' FROM nr ALIGN '-1h'")


# ----------------------------------------------------------------------
# integer SUM overflow detection (ADVICE r5: raise, don't wrap)
# ----------------------------------------------------------------------

def test_sum_bigint_overflow_raises(inst):
    from greptimedb_tpu.errors import ArithmeticOverflowError

    inst.sql("CREATE TABLE so (ts TIMESTAMP TIME INDEX, n BIGINT)")
    big = 2**63 - 1
    inst.sql(f"INSERT INTO so VALUES (1, {big}), (2, {big})")
    with pytest.raises(ArithmeticOverflowError, match="overflows"):
        inst.sql("SELECT sum(n) FROM so")


def test_sum_uint64_above_int63_raises_not_wraps(inst):
    from greptimedb_tpu.errors import ArithmeticOverflowError

    inst.sql("CREATE TABLE su (ts TIMESTAMP TIME INDEX, "
             "n BIGINT UNSIGNED)")
    inst.sql(f"INSERT INTO su VALUES (1, {2**63 - 1}), (2, 100)")
    # the old path wrapped the int64 accumulator silently
    with pytest.raises(ArithmeticOverflowError):
        inst.sql("SELECT sum(n) FROM su")


def test_reduce_uint64_value_above_int63_raises():
    """A single uint64 value above 2^63 used to mis-cast negative via
    .astype(int64); the exact path must raise instead."""
    from greptimedb_tpu.errors import ArithmeticOverflowError
    from greptimedb_tpu.query.reduce import _host_reduce

    vals = np.asarray([2**63 + 10, 5], np.uint64)
    valid = np.ones(2, bool)
    gid = np.zeros(2, np.int64)
    with pytest.raises(ArithmeticOverflowError):
        _host_reduce("sum", vals, valid, gid, 1, None)
    # big-but-representable uint64 sums stay exact
    vals = np.asarray([2**62, 2**61], np.uint64)
    out, present = _host_reduce("sum", vals, valid, gid, 1, None)
    assert int(out[0]) == 2**62 + 2**61 and bool(present[0])


def test_sum_bigint_exact_above_2_53(inst):
    """Sums past float53 but inside int64 must stay exact (the safety
    bound falls back to exact big-int accumulation, not a raise)."""
    inst.sql("CREATE TABLE se (ts TIMESTAMP TIME INDEX, n BIGINT, "
             "g STRING PRIMARY KEY)")
    a = 2**62
    inst.sql(f"INSERT INTO se (ts, g, n) VALUES (1, 'x', {a}), "
             f"(2, 'x', 1), (3, 'y', -5)")
    r = inst.sql("SELECT g, sum(n) FROM se GROUP BY g ORDER BY g")
    assert r.rows() == [["x", a + 1], ["y", -5]]


def test_negative_ttl_and_window_rejected(inst):
    """Signed interval parsing must not let a negative TTL through —
    it would compute a cutoff in the future and expire everything."""
    from greptimedb_tpu.errors import GreptimeError

    with pytest.raises(GreptimeError, match="positive"):
        inst.sql("CREATE TABLE nt1 (ts TIMESTAMP TIME INDEX, v DOUBLE) "
                 "WITH (ttl = '-1 day')")
    with pytest.raises(GreptimeError, match="positive"):
        inst.sql("CREATE TABLE nt2 (ts TIMESTAMP TIME INDEX, v DOUBLE) "
                 "WITH ('compaction.twcs.time_window' = '-1h')")
