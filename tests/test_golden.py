"""Sqlness-style golden runner.

Capability counterpart of the reference's sqlness harness
(/root/reference/tests/runner/src/env.rs:68-133 + tests/cases/standalone/
common/): each `tests/golden/*.sql` file is a sequence of statements; a
statement followed by a `----` block asserts the formatted result. Cases
port the behavior covered by the reference's common sqlness suites
(select, join, cte, view, order_by, ...) onto this engine's dialect.

Format:
    -- comment
    CREATE TABLE t (...);          <- executed, result ignored
    SELECT ...;
    ----
    col1|col2
    v11|v12
    <blank line ends the block>
An expected block of `ERROR` asserts the statement raises.
"""

import math
import pathlib

import pytest

from greptimedb_tpu.instance import Standalone

GOLDEN_DIR = pathlib.Path(__file__).parent / "golden"


def _fmt_value(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if v == int(v) and abs(v) < 1e15:
            return f"{v:.1f}"
        return f"{v:.6g}"
    return str(v)


def format_result(res) -> list[str]:
    lines = ["|".join(res.names)]
    for row in res.rows():
        # multi-line cells (SHOW CREATE TABLE) expand to file lines so
        # expected blocks stay diffable
        lines.extend("|".join(_fmt_value(v) for v in row).split("\n"))
    return lines


def parse_cases(text: str):
    """Yields (statement, expected_lines | None, line_no)."""
    lines = text.split("\n")
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        if not line or line.startswith("--"):
            i += 1
            continue
        # accumulate statement until ';'
        start = i
        stmt_lines = []
        while i < len(lines):
            stmt_lines.append(lines[i])
            if lines[i].rstrip().endswith(";"):
                break
            i += 1
        stmt = "\n".join(stmt_lines).strip().rstrip(";")
        i += 1
        expected = None
        if i < len(lines) and lines[i].strip() == "----":
            i += 1
            expected = []
            while i < len(lines) and lines[i].strip() != "":
                expected.append(lines[i].rstrip())
                i += 1
        yield stmt, expected, start + 1


def golden_files():
    return sorted(GOLDEN_DIR.glob("*.sql"))


# Cases whose semantics are legitimately standalone-only (the
# reference's flow sqlness cases also run only under standalone/common/;
# wire-topology flows are covered by tests/test_dist_processes.py).
DIST_SKIP: dict[str, str] = {
    "alter_flow_interaction":
        "flows need a flownode process in the wire topology",
}


def _run_case(inst, path):
    from greptimedb_tpu.session import QueryContext

    ctx = QueryContext()  # one session per case file, like sqlness
    for stmt, expected, line_no in parse_cases(path.read_text()):
        if expected and expected[0].startswith("ERROR"):
            # `ERROR` or `ERROR <<detail for the reader>>`: asserts the
            # statement raises (detail text is documentation only — the
            # exact message may differ between topologies)
            with pytest.raises(Exception):
                inst.sql(stmt, ctx)
            continue
        try:
            res = inst.sql(stmt, ctx)
        except Exception as e:
            raise AssertionError(
                f"{path.name}:{line_no}: {stmt!r} failed: {e}"
            ) from e
        if expected is None:
            continue
        got = format_result(res)
        assert got == expected, (
            f"{path.name}:{line_no}:\n{stmt}\n"
            f"expected:\n" + "\n".join(expected)
            + "\ngot:\n" + "\n".join(got)
        )


@pytest.mark.parametrize(
    "path", golden_files(), ids=lambda p: p.stem,
)
def test_golden(path, tmp_path):
    inst = Standalone(str(tmp_path / "data"))
    try:
        _run_case(inst, path)
    finally:
        inst.close()


@pytest.mark.parametrize(
    "path", golden_files(), ids=lambda p: p.stem,
)
def test_golden_dist(path, tmp_path):
    """Every golden case re-run against a wire topology: metasrv +
    2 datanode Flight servers + a DistInstance frontend over real
    sockets — the reference's tests/cases/distributed/ tier
    (/root/reference/tests/runner/src/env.rs:68-133)."""
    if path.stem in DIST_SKIP:
        pytest.skip(DIST_SKIP[path.stem])
    pytest.importorskip("pyarrow.flight")
    from tests.test_dist_cluster import DistHarness

    h = DistHarness(tmp_path, n_datanodes=2)
    try:
        _run_case(h.frontend, path)
    finally:
        h.close()


def test_golden_dir_has_cases():
    assert len(golden_files()) >= 5, "golden suite missing"
