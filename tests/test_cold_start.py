"""Restart snapshots for the device grid cache (VERDICT r2 task #10):
a rebuilt instance restores HBM grids from the persisted snapshot
instead of rescanning SSTs, and stale snapshots are rejected."""

import time

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.query import device_range as DR
from greptimedb_tpu.query.executor import QueryEngine

Q = ("SELECT ts, host, avg(u) RANGE '10s', last_value(u) RANGE '10s' "
     "FROM cpu ALIGN '10s' BY (host) ORDER BY ts, host")


def _mk(tmp_path, rng):
    inst = Standalone(str(tmp_path), prefer_device=True, warm_start=False)
    inst.execute_sql(
        "create table cpu (ts timestamp time index, host string primary "
        "key, u double)"
    )
    tab = inst.catalog.table("public", "cpu")
    n_hosts, t = 8, 200
    ts = np.tile(np.arange(t) * 1000, n_hosts).astype(np.int64)
    hosts = np.repeat([f"h{i}" for i in range(n_hosts)], t).astype(object)
    u = rng.random(n_hosts * t) * 100
    tab.write({"host": hosts}, ts, {"u": u})
    return inst


def _wait_snapshot(inst, timeout=15.0):
    region = inst.catalog.table("public", "cpu").regions[0]
    deadline = time.time() + timeout
    while time.time() < deadline:
        if region.store.list(f"{region.prefix}/{DR._SNAP_DIRNAME}/"):
            return True
        time.sleep(0.05)
    return False


def test_snapshot_restores_without_rescan(tmp_path, rng, monkeypatch):
    inst = _mk(tmp_path, rng)
    r1 = inst.sql(Q)
    assert inst.query_engine.last_exec_path == "device"
    assert _wait_snapshot(inst), "snapshot never persisted"
    inst.close()

    inst2 = Standalone(str(tmp_path), prefer_device=True, warm_start=False)

    def _no_build(*a, **k):  # restored entries must NOT trigger a rescan
        raise AssertionError("build_entry called despite a live snapshot")

    monkeypatch.setattr(DR, "build_entry", _no_build)
    r2 = inst2.sql(Q)
    assert inst2.query_engine.last_exec_path == "device"
    assert r1.rows() == r2.rows()
    inst2.close()


def test_stale_snapshot_rejected_and_rebuilt(tmp_path, rng):
    inst = _mk(tmp_path, rng)
    inst.sql(Q)
    assert _wait_snapshot(inst)
    # new write AFTER the snapshot: version moves on
    inst.sql("insert into cpu (ts, host, u) values (500000, 'h0', 42.0)")
    inst.close()

    inst2 = Standalone(str(tmp_path), prefer_device=True, warm_start=False)
    r = inst2.sql(Q)
    assert inst2.query_engine.last_exec_path == "device"
    # the stale file must be gone (deleted at load) or replaced
    vals = {row[1]: row for row in r.rows() if row[0] == 500000}
    assert float(vals["h0"][2]) == 42.0  # new row visible: not stale data
    inst2.close()


def test_warm_start_thread_restores(tmp_path, rng):
    inst = _mk(tmp_path, rng)
    inst.sql(Q)
    assert _wait_snapshot(inst)
    inst.close()

    inst2 = Standalone(str(tmp_path), prefer_device=True, warm_start=True)
    deadline = time.time() + 15
    while time.time() < deadline:
        if inst2.query_engine.range_cache._entries:
            break
        time.sleep(0.05)
    assert inst2.query_engine.range_cache._entries, "warm start idle"
    inst2.close()


def test_program_specs_persist_and_precompile(tmp_path, rng):
    """The first query's static jit spec persists next to the snapshot;
    warm_from_snapshots precompiles it so the first query after restart
    pays steady-state latency (VERDICT r3 cold-start task)."""
    inst = _mk(tmp_path, rng)
    inst.sql(Q)
    assert _wait_snapshot(inst)
    region = inst.catalog.table("public", "cpu").regions[0]
    entry = next(iter(inst.query_engine.range_cache._entries.values()))
    spec_path = DR._program_specs_path(entry, region)
    deadline = time.time() + 10
    while time.time() < deadline and not region.store.exists(spec_path):
        time.sleep(0.05)
    assert region.store.exists(spec_path), "program specs never persisted"
    inst.close()

    inst2 = Standalone(str(tmp_path), prefer_device=True,
                       warm_start=False)
    n = DR.warm_from_snapshots(inst2.query_engine, inst2.catalog)
    assert n == 1
    entry2 = next(iter(inst2.query_engine.range_cache._entries.values()))
    assert entry2.program_specs, "warm did not precompile any program"
    precompiled = set(entry2.program_specs)
    # the first query must HIT a precompiled spec: the set must not grow
    r = inst2.sql(Q)
    assert inst2.query_engine.last_exec_path == "device"
    assert set(entry2.program_specs) == precompiled, (
        "first query built a NEW spec — precompile missed it"
    )
    assert r.num_rows > 0
    inst2.close()


def test_bench_emit_ordering():
    """Every auditable metric must sit in the FINAL output block, in
    tail-priority order, with the headline last (VERDICT r3 weak #5)."""
    import importlib.util
    import json
    import os

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..",
                              "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    lines = [
        json.dumps({"metric": "tsbs_ingest_skip_wal_rows_per_s",
                    "value": 1}),
        json.dumps({"metric": "tsbs_ingest_wal_rows_per_s", "value": 2}),
        json.dumps({"metric": "tsbs_lastpoint_sql_ms", "value": 3}),
        json.dumps({"metric": "tsbs_single_groupby_1_1_1_sql_ms",
                    "value": 4}),
        json.dumps({"metric": "tsbs_groupby_orderby_limit_sql_ms",
                    "value": 5}),
        json.dumps({"metric": "promql_1m_series_range_p50_ms",
                    "value": 6}),
        json.dumps({"metric": "tsbs_double_groupby_all_sql_ms",
                    "value": 7}),
    ]
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        bench._emit_ordered(
            lines, json.dumps({"metric": "cold_start_first_query_ms",
                               "value": 8})
        )
    out = [json.loads(x) for x in buf.getvalue().splitlines()]
    metrics = [d["metric"] for d in out]
    assert metrics[-1] == "tsbs_double_groupby_all_sql_ms"
    assert metrics[-2] == "cold_start_first_query_ms"
    # every audit-critical metric present in the test input sits in the
    # final block, directly before cold-start + headline
    present = [m for m in bench._TAIL_PRIORITY if m in metrics]
    tail = set(metrics[-(len(present) + 2):])
    for m in present:
        assert m in tail, m
    # shape metrics precede them
    assert metrics[0] == "tsbs_single_groupby_1_1_1_sql_ms"
