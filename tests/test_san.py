"""gtsan (cooperative concurrency sanitizer) fixtures.

Every detector has a deterministic positive fixture that never
actually deadlocks or hangs the test process, and a negative fixture
(correctly ordered locks, joined threads, shut-down pools) that stays
clean.  The off path is pinned: with the sanitizer disabled the
concurrency facade returns raw stdlib objects — no wrapper frames.
"""

from __future__ import annotations

import threading
import time

import pytest

from greptimedb_tpu import concurrency as C
from greptimedb_tpu.tools import san

pytest_plugins = ["pytester"]


@pytest.fixture()
def scope():
    s = san.enable(san.SanConfig(hold_time_ms=60.0))
    yield s
    san.disable(s)


def _run_threads(*fns):
    """Run each fn on its own (sequential) thread so lock orders are
    observed per-thread without any real contention."""
    for fn in fns:
        t = C.Thread(target=fn, daemon=True)
        t.start()
        t.join(10)
        assert not t.is_alive()


def rules_of(scope):
    return [f["rule"] for f in scope.snapshot_findings()]


# ---------------------------------------------------------------------------
# off path: raw stdlib objects, zero wrapper frames
# ---------------------------------------------------------------------------

def test_facade_off_returns_raw_stdlib_objects():
    if san.enabled():
        pytest.skip("sanitizer is enabled suite-wide (GTPU_SAN=1); "
                    "the off path is covered by the plain tier-1 run")
    assert type(C.Lock()) is type(threading.Lock())
    assert type(C.RLock()) is type(threading.RLock())
    assert type(C.Condition()) is threading.Condition
    assert type(C.Event()) is threading.Event
    assert type(C.Thread(target=lambda: None)) is threading.Thread
    from concurrent.futures import ThreadPoolExecutor

    pool = C.ThreadPoolExecutor(max_workers=1)
    try:
        assert type(pool) is ThreadPoolExecutor
    finally:
        pool.shutdown()


def test_facade_on_returns_wrappers_and_restores():
    was_on = san.enabled()
    s = san.enable()
    try:
        from greptimedb_tpu.tools.san.wrappers import SanLock

        assert isinstance(C.Lock(), SanLock)
    finally:
        san.disable(s)
    if was_on:
        # an outer suite-wide scope (GTPU_SAN=1) remains active
        assert san.enabled()
    else:
        assert type(C.Lock()) is type(threading.Lock())


# ---------------------------------------------------------------------------
# GTS101 lock-order cycles
# ---------------------------------------------------------------------------

def test_abba_cycle_detected_with_both_stacks(scope):
    A = C.Lock(name="A")
    B = C.Lock(name="B")

    def ab():
        with A:
            with B:
                pass

    def ba():
        with B:
            with A:
                pass

    _run_threads(ab, ba)
    cycles = [f for f in scope.snapshot_findings()
              if f["rule"] == "GTS101"]
    assert len(cycles) == 1
    msg = cycles[0]["message"]
    assert "Lock(A)" in msg and "Lock(B)" in msg
    # BOTH acquisition stacks, ABBA style: this thread's and the
    # reverse direction recorded earlier
    assert "in ba" in msg and "in ab" in msg
    assert msg.count("acquired") >= 2
    # the report anchors at a real source location in THIS file
    assert cycles[0]["path"].endswith("test_san.py")
    assert cycles[0]["line"] > 0


def test_three_lock_cycle_detected(scope):
    A, B, X = (C.Lock(name="A3"), C.Lock(name="B3"), C.Lock(name="C3"))

    def ab():
        with A:
            with B:
                pass

    def bc():
        with B:
            with X:
                pass

    def ca():
        with X:
            with A:
                pass

    _run_threads(ab, bc, ca)
    cycles = [f for f in scope.snapshot_findings()
              if f["rule"] == "GTS101"]
    assert len(cycles) == 1
    assert all(k in cycles[0]["message"]
               for k in ("Lock(A3)", "Lock(B3)", "Lock(C3)"))


def test_consistent_order_and_reentrant_rlock_stay_clean(scope):
    A = C.Lock(name="An")
    B = C.Lock(name="Bn")
    R = C.RLock(name="Rn")

    def ordered():
        for _ in range(3):
            with A:
                with B:
                    pass

    def reentrant():
        with R:
            with R:     # same lock re-entered: not a cycle edge
                pass

    _run_threads(ordered, reentrant, ordered)
    assert rules_of(scope) == []


# ---------------------------------------------------------------------------
# GTS102 blocking under lock
# ---------------------------------------------------------------------------

def test_sleep_under_lock_flagged_and_anchored_at_acquisition(scope):
    L = C.Lock(name="SleepLock")
    with L:
        time.sleep(0.005)
    hits = [f for f in scope.snapshot_findings()
            if f["rule"] == "GTS102"]
    assert len(hits) == 1
    assert "time.sleep" in hits[0]["message"]
    assert "SleepLock" in hits[0]["message"]
    assert hits[0]["path"].endswith("test_san.py")


def test_cv_wait_holding_other_lock_flagged_own_lock_exempt(scope):
    other = C.Lock(name="Other")
    cv = C.Condition(name="CV")

    # waiting on your own condvar releases it: clean
    with cv:
        cv.wait(0.01)
    assert rules_of(scope) == []

    # waiting while ANOTHER lock is held blocks its waiters
    with other:
        with cv:
            cv.wait(0.01)
    hits = [f for f in scope.snapshot_findings()
            if f["rule"] == "GTS102"]
    assert len(hits) == 1
    assert "Other" in hits[0]["message"]


def test_event_wait_and_short_sleep_negatives(scope):
    ev = C.Event()
    ev.set()
    L = C.Lock(name="NegL")
    with L:
        ev.wait(0.0005)      # under sleep_min_s: yield-style, clean
        time.sleep(0.0001)
    time.sleep(0.005)        # no lock held: clean
    assert rules_of(scope) == []


# ---------------------------------------------------------------------------
# GTS103 hold time
# ---------------------------------------------------------------------------

def test_hold_time_threshold(scope):
    L = C.Lock(name="Slow")
    with L:
        t0 = time.monotonic()
        while time.monotonic() - t0 < 0.1:   # spin: no blocking call
            pass
    hits = [f for f in scope.snapshot_findings()
            if f["rule"] == "GTS103"]
    assert len(hits) == 1
    assert "Slow" in hits[0]["message"]

    # a fast critical section stays clean
    with C.Lock(name="Fast"):
        pass
    assert len([f for f in scope.snapshot_findings()
                if f["rule"] == "GTS103"]) == 1


# ---------------------------------------------------------------------------
# GTS104/GTS105 lifecycle leaks
# ---------------------------------------------------------------------------

def test_leaked_thread_and_pool_detected_then_cleared(scope):
    token = scope.lifecycle_token()
    release = threading.Event()
    t = C.Thread(target=release.wait)        # non-daemon, unjoined
    t.start()
    pool = C.ThreadPoolExecutor(max_workers=1)
    leaks = scope.leak_findings(token, record=False)
    assert sorted(f["rule"] for f in leaks) == ["GTS104", "GTS105"]
    assert all(f["path"].endswith("test_san.py") for f in leaks)

    release.set()
    t.join()
    pool.shutdown()
    assert scope.leak_findings(token, record=False) == []


def test_daemon_joined_and_shared_are_not_leaks(scope):
    token = scope.lifecycle_token()
    d = C.Thread(target=lambda: time.sleep(0.01), daemon=True)
    d.start()                                # daemon: exempt
    j = C.Thread(target=lambda: None)
    j.start()
    j.join()                                 # joined: exempt
    with C.ThreadPoolExecutor(max_workers=1) as pool:
        pool.submit(lambda: None).result()   # ctx manager: shutdown
    shared = C.ThreadPoolExecutor(max_workers=1, shared=True)
    try:
        assert scope.leak_findings(token, record=False) == []
    finally:
        shared.shutdown()
        d.join(5)


# ---------------------------------------------------------------------------
# pytest plugin: leaking tests FAIL
# ---------------------------------------------------------------------------

_PLUGIN = "greptimedb_tpu.tools.san.pytest_plugin"


def test_plugin_fails_leaked_thread_test(pytester):
    pytester.makepyfile("""
        import threading

        from greptimedb_tpu import concurrency as C

        release = threading.Event()

        def test_leaks_a_thread():
            t = C.Thread(target=release.wait,
                         name="leaky-fixture-thread")
            t.start()

        def test_cleanup():
            release.set()
    """)
    result = pytester.runpytest_inprocess("-p", _PLUGIN, "-q")
    outcomes = result.parseoutcomes()
    assert outcomes.get("errors", 0) >= 1
    result.stdout.fnmatch_lines(["*GTS104*leaky-fixture-thread*"])


def test_plugin_fails_unshutdown_pool_test(pytester):
    pytester.makepyfile("""
        from greptimedb_tpu import concurrency as C

        def test_leaks_a_pool():
            pool = C.ThreadPoolExecutor(max_workers=1)
            pool.submit(lambda: None).result()
    """)
    result = pytester.runpytest_inprocess("-p", _PLUGIN, "-q")
    assert result.parseoutcomes().get("errors", 0) >= 1
    result.stdout.fnmatch_lines(["*GTS105*"])


def test_plugin_clean_suite_passes_and_reports_clean(pytester):
    pytester.makepyfile("""
        from greptimedb_tpu import concurrency as C

        def test_tidy():
            t = C.Thread(target=lambda: None)
            t.start()
            t.join()
            with C.ThreadPoolExecutor(max_workers=1) as pool:
                pool.submit(lambda: None).result()
            with C.Lock(name="x"):
                pass
    """)
    result = pytester.runpytest_inprocess("-p", _PLUGIN, "-q")
    result.assert_outcomes(passed=1)
    assert result.ret == 0
    result.stdout.fnmatch_lines(["*gtsan: clean*"])


def test_plugin_session_fails_on_cycle_findings(pytester):
    pytester.makepyfile("""
        from greptimedb_tpu import concurrency as C

        def test_abba():
            A = C.Lock(name="pA")
            B = C.Lock(name="pB")

            def ab():
                with A:
                    with B: pass

            def ba():
                with B:
                    with A: pass

            for fn in (ab, ba):
                t = C.Thread(target=fn, daemon=True)
                t.start(); t.join()
    """)
    result = pytester.runpytest_inprocess("-p", _PLUGIN, "-q")
    result.assert_outcomes(passed=1)     # the test itself passes...
    assert result.ret == 1               # ...the session does not
    result.stdout.fnmatch_lines(["*GTS101*"])


# ---------------------------------------------------------------------------
# suppression + baseline round-trip (shared gtlint machinery)
# ---------------------------------------------------------------------------

def _fake_finding(path, line):
    return {"rule": "GTS102", "path": str(path), "line": line, "col": 0,
            "message": "blocking call time.sleep(1) while holding X"}


def test_suppression_comment_covers_san_finding(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text(
        "def f(lock):\n"
        "    with lock:  # gtlint: disable=GTS102\n"
        "        pass\n"
    )
    doc = san.result_doc([_fake_finding(src, 2)], baseline_path=None)
    assert doc["clean"]
    assert doc["counts"]["suppressed"] == 1
    # the wrong id does NOT cover
    src.write_text(
        "def f(lock):\n"
        "    with lock:  # gtlint: disable=GTS101\n"
        "        pass\n"
    )
    doc = san.result_doc([_fake_finding(src, 2)], baseline_path=None)
    assert not doc["clean"]
    assert doc["counts"]["new"] == 1


def test_baseline_round_trip_and_stale(tmp_path):
    from greptimedb_tpu.tools.lint import Baseline

    src = tmp_path / "mod.py"
    src.write_text("def f(lock):\n    with lock:\n        pass\n")
    finding = _fake_finding(src, 2)

    base_path = tmp_path / "san_baseline.json"
    Baseline([{"rule": "GTS102", "path": str(src), "line": 2,
               "text": "with lock:"}]).save(str(base_path))
    doc = san.result_doc([finding], baseline_path=str(base_path))
    assert doc["clean"]
    assert doc["counts"]["baselined"] == 1

    # violation gone -> the entry is stale and fails the run
    doc = san.result_doc([], baseline_path=str(base_path))
    assert not doc["clean"]
    assert doc["counts"]["stale_baseline"] == 1


def test_checked_in_san_baseline_is_empty():
    from greptimedb_tpu.tools.lint import Baseline
    from greptimedb_tpu.tools.san.report import DEFAULT_BASELINE

    assert Baseline.load(DEFAULT_BASELINE).entries == []


# ---------------------------------------------------------------------------
# CLI driver
# ---------------------------------------------------------------------------

def test_san_cli_reports_child_findings_and_exit_code(tmp_path):
    import subprocess
    import sys

    demo = tmp_path / "abba.py"
    demo.write_text(
        "from greptimedb_tpu import concurrency as C\n"
        "A = C.Lock(name='cliA')\n"
        "B = C.Lock(name='cliB')\n"
        "def ab():\n"
        "    with A:\n"
        "        with B: pass\n"
        "def ba():\n"
        "    with B:\n"
        "        with A: pass\n"
        "for fn in (ab, ba):\n"
        "    t = C.Thread(target=fn, daemon=True)\n"
        "    t.start(); t.join()\n"
    )
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "PYTHONPATH": repo, "JAX_PLATFORMS": "cpu"}
    env.pop("GTPU_SAN", None)
    p = subprocess.run(
        [sys.executable, "-m", "greptimedb_tpu.tools.san",
         "--no-baseline", "--", sys.executable, str(demo)],
        capture_output=True, text=True, cwd=repo, env=env, timeout=120,
    )
    assert p.returncode == 1, p.stdout + p.stderr
    assert "GTS101" in p.stdout
    assert "cliA" in p.stdout and "cliB" in p.stdout

    # a clean child exits 0
    clean = tmp_path / "clean.py"
    clean.write_text(
        "from greptimedb_tpu import concurrency as C\n"
        "with C.Lock(name='only'):\n"
        "    pass\n"
    )
    p = subprocess.run(
        [sys.executable, "-m", "greptimedb_tpu.tools.san",
         "--no-baseline", "--", sys.executable, str(clean)],
        capture_output=True, text=True, cwd=repo, env=env, timeout=120,
    )
    assert p.returncode == 0, p.stdout + p.stderr


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
