"""Unified memory observability (telemetry/memory.py): the pool
ledger, the device live-buffer census, cross-pool pressure eviction,
/debug/prof/hbm + information_schema.memory_pools, and the strict
metric-registration contract (telemetry/metrics.py).
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.servers.http import HttpServer
from greptimedb_tpu.telemetry import memory
from greptimedb_tpu.telemetry.memory import MemoryAccountant
from greptimedb_tpu.telemetry.metrics import (
    MetricRegistrationError,
    MetricsRegistry,
    global_registry,
)


class FakePool:
    """Minimal accountant client: a dict of jax buffers with an LRU
    evict."""

    def __init__(self, budget=1 << 20):
        self.entries = {}
        self.budget = budget
        self.evictions = 0

    def put(self, key, arr):
        self.entries[key] = arr
        memory.note_device_bytes()

    def stats(self):
        return {
            "bytes": sum(a.nbytes for a in self.entries.values()),
            "entries": len(self.entries),
            "budget_bytes": self.budget,
            "evictions": self.evictions,
        }

    def evict(self, target):
        freed = 0
        while freed < target and self.entries:
            _, a = self.entries.popitem()
            freed += a.nbytes
            self.evictions += 1
        return freed

    def buffers(self):
        return [(a, f"fake:{k}") for k, a in self.entries.items()]


def _jnp_buf(n_floats):
    import jax.numpy as jnp

    return jnp.zeros((n_floats,), jnp.float32)


# ---------------------------------------------------------------------
# accountant core
# ---------------------------------------------------------------------

def test_registration_aggregates_instances_and_drops_dead():
    acct = MemoryAccountant()
    a, b = FakePool(), FakePool()
    for p in (a, b):
        acct.register_pool("fake", "device", p, stats=FakePool.stats,
                           evict=FakePool.evict,
                           buffers=FakePool.buffers)
    a.entries["x"] = _jnp_buf(16)
    b.entries["y"] = _jnp_buf(8)
    snap = {s.name: s for s in acct.snapshot()}
    assert snap["fake"].instances == 2
    assert snap["fake"].bytes == 16 * 4 + 8 * 4
    assert snap["fake"].entries == 2
    # a GC'd pool silently leaves the ledger
    del b, p
    import gc

    gc.collect()
    snap = {s.name: s for s in acct.snapshot()}
    assert snap["fake"].instances == 1
    assert snap["fake"].bytes == 64


def test_census_attributes_owned_and_flags_unaccounted():
    acct = MemoryAccountant()
    pool = FakePool()
    acct.register_pool("owned", "device", pool, stats=FakePool.stats,
                       buffers=FakePool.buffers)
    owned = _jnp_buf(1024)
    pool.entries["g"] = owned
    leak = _jnp_buf(512)   # held only by this frame: no owner
    c0 = acct.census(top=50)
    assert c0["pools"]["owned"] == owned.nbytes
    assert c0["unaccounted_bytes"] >= leak.nbytes
    owners = {t["owner"] for t in c0["top"]}
    assert "fake:g" in owners
    # adopting the leak moves it from unaccounted to accounted
    pool.entries["adopted"] = leak
    c1 = acct.census()
    assert c1["unaccounted_bytes"] <= c0["unaccounted_bytes"] - leak.nbytes
    assert c1["accounted_bytes"] >= c0["accounted_bytes"] + leak.nbytes


def test_cross_pool_eviction_proportional_to_bytes():
    acct = MemoryAccountant()
    big, small = FakePool(), FakePool()
    acct.register_pool("big", "device", big, stats=FakePool.stats,
                       evict=FakePool.evict, buffers=FakePool.buffers)
    acct.register_pool("small", "device", small, stats=FakePool.stats,
                       evict=FakePool.evict, buffers=FakePool.buffers)
    for i in range(8):
        big.entries[i] = _jnp_buf(1024)     # 32 KiB total
    small.entries[0] = _jnp_buf(1024)       # 4 KiB
    total = 9 * 4096
    acct.device_budget_bytes = total - 6000  # ~6 KB overage
    freed = acct.enforce_device_budget()
    assert freed >= 6000
    assert acct.device_bytes() <= acct.device_budget_bytes
    # the big pool sheds more than the small one (proportional)
    assert big.evictions >= small.evictions
    assert big.evictions >= 1


def test_budget_unset_is_free_and_greedy_pass_covers_stuck_pools():
    acct = MemoryAccountant()
    stuck, ok = FakePool(), FakePool()

    def no_evict(pool, target):
        return 0

    acct.register_pool("stuck", "device", stuck, stats=FakePool.stats,
                       evict=no_evict)
    acct.register_pool("ok", "device", ok, stats=FakePool.stats,
                       evict=FakePool.evict)
    stuck.entries["a"] = _jnp_buf(1024)
    ok.entries["b"] = _jnp_buf(1024)
    assert acct.note_device_bytes() == 0      # no watermark configured
    acct.device_budget_bytes = 4096           # one buffer must go
    acct.enforce_device_budget()
    # the stuck pool freed nothing; the greedy second pass took the
    # whole overage out of the evictable pool
    assert not ok.entries
    assert stuck.entries


def test_eviction_delta_survives_instance_death():
    import gc

    acct = MemoryAccountant()
    a, b = FakePool(), FakePool()
    for p in (a, b):
        acct.register_pool("t_evd", "device", p, stats=FakePool.stats)
    counter = global_registry.counter(
        "gtpu_mem_evictions_total",
        "entries evicted per registered memory pool (budget, staleness "
        "or cross-pool pressure)", ("pool", "tier"),
    ).labels("t_evd", "device")
    a.evictions = 100
    b.evictions = 5
    acct.publish()
    v0 = counter.value
    # instance A dies; B keeps evicting — the counter must keep
    # advancing, not stall behind A's dead high-water mark
    del a, p
    gc.collect()
    b.evictions += 50
    acct.publish()
    assert counter.value == v0 + 50


def test_publish_zeroes_gauges_of_dead_pools():
    import gc

    acct = MemoryAccountant()
    pool = FakePool()
    acct.register_pool("t_dead_pool", "host", pool,
                       stats=FakePool.stats)
    pool.entries["x"] = _jnp_buf(256)
    acct.publish()
    gauge = global_registry.get("gtpu_mem_bytes").labels(
        "t_dead_pool", "host"
    )
    assert gauge.value == 1024.0
    del pool
    gc.collect()
    acct.publish()
    # freed memory must not keep reporting as held forever
    assert gauge.value == 0.0


def test_configure_applies_budget_immediately():
    acct = memory.global_accountant
    saved = (acct.enabled, acct.device_budget_bytes,
             acct.census_on_scrape)
    pool = FakePool()
    acct.register_pool("cfg_pool", "device", pool,
                       stats=FakePool.stats, evict=FakePool.evict)
    pool.entries["a"] = _jnp_buf(4096)
    pool.entries["b"] = _jnp_buf(4096)
    base = acct.device_bytes()
    try:
        memory.configure({"device_budget_bytes": base - 8192})
        assert acct.device_bytes() <= base - 8192
        assert pool.evictions >= 1
    finally:
        acct.enabled, acct.device_budget_bytes, acct.census_on_scrape = \
            saved


# ---------------------------------------------------------------------
# real pools end to end
# ---------------------------------------------------------------------

@pytest.fixture()
def inst(tmp_path):
    inst = Standalone(str(tmp_path / "data"), prefer_device=True,
                      warm_start=False)
    yield inst
    inst.close()


@pytest.fixture()
def server(inst):
    srv = HttpServer(inst, port=0).start()
    yield srv
    srv.stop()


def _get(srv, path):
    url = f"http://127.0.0.1:{srv.port}{path}"
    with urllib.request.urlopen(url, timeout=120) as r:
        return r.status, r.read().decode()


def _seed_device_table(inst, name="mt", hosts=4, cells=600):
    inst.execute_sql(
        f"create table {name} (ts timestamp time index, "
        "h string primary key, v double)"
    )
    t = inst.catalog.table("public", name)
    rng = np.random.default_rng(7)
    ts = np.tile(np.arange(cells, dtype=np.int64) * 1000, hosts)
    hs = np.repeat(
        np.asarray([f"h{i}" for i in range(hosts)], object), cells
    )
    t.write({"h": hs}, ts, {"v": rng.random(len(ts))}, skip_wal=True)
    return t


def _run_range(inst, name="mt"):
    out = inst.execute_sql(
        f"SELECT ts, avg(v) RANGE '1m' FROM {name} ALIGN '1m' BY ()"
    )
    assert inst.query_engine.last_exec_path == "device"
    return out


def test_hbm_route_reports_every_pool_and_census_sums(inst, server):
    _seed_device_table(inst)
    _run_range(inst)
    status, body = _get(server, "/debug/prof/hbm?format=json&top=8")
    assert status == 200
    doc = json.loads(body)
    pools = {p["pool"]: p for p in doc["pools"]}
    # the pools this workload exercises all report
    for name in ("range_grid", "sessions", "result_cache",
                 "trace_ring"):
        assert name in pools, sorted(pools)
    rg = pools["range_grid"]
    assert rg["tier"] == "device" and rg["bytes"] > 0
    assert rg["budget_bytes"] > 0
    # acceptance: per-pool census bytes sum to the census accounted
    # total (every owner-tagged buffer is claimed by exactly one pool)
    device_census_sum = sum(
        p.get("census_bytes", 0) for p in doc["pools"]
        if p["tier"] == "device"
    )
    assert device_census_sum == doc["census"]["accounted_bytes"]
    # and each device pool's REPORTED bytes equal its census bytes:
    # derived per-query inputs (query_memo gid/mask, promql match/
    # group/win caches) count in stats, not just in the census — the
    # watermark sees every resident byte
    for p in doc["pools"]:
        if p["tier"] == "device":
            assert p["bytes"] == p["census_bytes"], p
    assert doc["census"]["live_bytes"] == (
        doc["census"]["accounted_bytes"]
        + doc["census"]["unaccounted_bytes"]
    )
    # top buffers carry owner/shape/dtype attribution
    assert doc["top_buffers"]
    top = doc["top_buffers"][0]
    assert top["owner"].startswith(("range:", "sessions:", "promql:",
                                    "warm_precompile:"))
    assert "shape" in top and "dtype" in top
    # text rendering serves the same report
    status, text = _get(server, "/debug/prof/hbm")
    assert status == 200
    assert "device census:" in text and "range_grid" in text


def test_memory_pools_table_matches_hbm_report(inst, server):
    _seed_device_table(inst)
    _run_range(inst)
    res = inst.sql(
        "select pool, tier, bytes, census_bytes, budget_bytes "
        "from information_schema.memory_pools order by pool"
    )
    rows = {r[0]: r for r in res.rows()}
    assert "range_grid" in rows and "sessions" in rows
    doc = json.loads(_get(server, "/debug/prof/hbm?format=json")[1])
    hbm = {p["pool"]: p for p in doc["pools"]}
    # SQL table and /debug/prof/hbm read the same ledger
    for name, row in rows.items():
        assert row[1] == hbm[name]["tier"]
    # WHERE works (it goes through the normal planner)
    res = inst.sql(
        "select count(*) from information_schema.memory_pools "
        "where tier = 'device'"
    )
    assert res.rows()[0][0] >= 2


def test_gtpu_mem_metrics_render_and_unaccounted_gauge(inst, server):
    _seed_device_table(inst)
    _run_range(inst)
    status, text = _get(server, "/metrics")
    assert status == 200
    assert 'gtpu_mem_bytes{pool="range_grid",tier="device"}' in text
    assert 'gtpu_mem_budget_bytes{pool="sessions",tier="device"}' in text
    assert "gtpu_mem_unaccounted_device_bytes" in text
    assert "gtpu_mem_device_live_bytes" in text
    # runtime_metrics mirrors the same families
    res = inst.sql(
        "select count(*) from information_schema.runtime_metrics "
        "where metric_name = 'gtpu_mem_bytes'"
    )
    assert res.rows()[0][0] >= 2


def test_global_watermark_evicts_across_real_pools(inst):
    """A [memory] device_budget_bytes below the sum of the individual
    pool budgets is enforced by cross-pool eviction on the put path."""
    acct = memory.global_accountant
    saved = acct.device_budget_bytes
    _seed_device_table(inst, "wt1")
    _seed_device_table(inst, "wt2")
    _run_range(inst, "wt1")
    _run_range(inst, "wt2")
    base = acct.device_bytes()
    assert base > 0
    cross0 = _cross_evicted_total()
    try:
        # watermark below current residency (and far below the 4GiB +
        # 1GiB individual budgets): enforcement applies at configure,
        # and every later put re-checks
        memory.configure({"device_budget_bytes": max(base // 2, 4096)})
        assert acct.device_bytes() <= acct.device_budget_bytes
        assert _cross_evicted_total() > cross0
        # the evicted grid rebuilds on the next query and the budget
        # still holds afterwards — steady state under pressure
        _run_range(inst, "wt1")
        assert acct.device_bytes() <= acct.device_budget_bytes
    finally:
        acct.device_budget_bytes = saved


def _cross_evicted_total() -> float:
    m = global_registry.get("gtpu_mem_cross_pool_evicted_bytes_total")
    return sum(c.value for _k, c in m._snapshot())


def test_session_strand_would_be_visible_as_unaccounted(inst):
    """The leak class PR 9's reviews caught by hand: a device buffer
    that loses its owner shows up in gtpu_mem_unaccounted_device_bytes
    instead of hiding."""
    _seed_device_table(inst)
    _run_range(inst)
    c0 = memory.global_accountant.census()
    # simulate a strand: pull a buffer out of the session registry but
    # keep it alive (exactly what a purge-less eviction used to do)
    from greptimedb_tpu.query.sessions import global_sessions

    with global_sessions._lock:
        key = next(iter(global_sessions._entries))
        stranded = global_sessions._entries[key][1]
        global_sessions._drop_locked(key)
    c1 = memory.global_accountant.census()
    assert c1["unaccounted_bytes"] >= (
        c0["unaccounted_bytes"] + stranded.nbytes
    )
    del stranded


def test_device_span_carries_pool_bytes_attribution(inst):
    from greptimedb_tpu.telemetry import tracing

    _seed_device_table(inst)
    _run_range(inst)
    dev_spans = [
        s for tr in tracing.global_traces.traces(limit=50)
        for s in tr["spans"] if s["name"] == "device.execute"
    ]
    assert dev_spans, "no device.execute span recorded"
    attrs = dev_spans[-1]["attributes"]
    assert attrs.get("device_pool_bytes", 0) > 0


# ---------------------------------------------------------------------
# strict metric registration (satellite: MetricsRegistry._get)
# ---------------------------------------------------------------------

def test_metric_reregistration_type_conflict_raises():
    reg = MetricsRegistry()
    reg.counter("m_total", "help")
    with pytest.raises(MetricRegistrationError) as ei:
        reg.gauge("m_total", "help")
    assert "Counter" in str(ei.value) and "Gauge" in str(ei.value)


def test_metric_reregistration_label_conflict_raises():
    reg = MetricsRegistry()
    reg.counter("m2_total", "help", labels=("mode",))
    with pytest.raises(MetricRegistrationError) as ei:
        reg.counter("m2_total", "help")
    assert "mode" in str(ei.value)
    # identical re-registration stays get-or-create
    again = reg.counter("m2_total", "different help", labels=("mode",))
    again.labels("full").inc()
    assert again.labels("full").value == 1.0


def test_metric_get_is_schema_free_lookup():
    reg = MetricsRegistry()
    with pytest.raises(KeyError):
        reg.get("absent_total")
    c = reg.counter("present_total", "h", labels=("x",))
    assert reg.get("present_total") is c


# ---------------------------------------------------------------------
# /metrics under concurrent label churn (satellite: test coverage)
# ---------------------------------------------------------------------

def test_metrics_render_survives_concurrent_label_churn(inst, server):
    """Many threads creating labelled children and observing histograms
    mid-scrape: every scrape through the real HTTP endpoint must parse,
    keep each family contiguous under one HELP/TYPE header, and show
    monotone cumulative histogram buckets with count == +Inf."""
    stop = threading.Event()
    churn_c = global_registry.counter(
        "gtpu_test_churn_total", "churn", labels=("worker", "step")
    )
    churn_h = global_registry.histogram(
        "gtpu_test_churn_seconds", "churn", labels=("worker",)
    )
    errors = []

    def churner(wid):
        import time

        i = 0
        while not stop.is_set():
            churn_c.labels(str(wid), str(i % 97)).inc()
            churn_h.labels(str(wid)).observe((i % 13) / 1000.0)
            i += 1
            if i % 50 == 0:
                # yield: hot-spinning on the 1-core CI box would starve
                # the HTTP server thread serving the scrape
                time.sleep(0.001)

    threads = [
        threading.Thread(target=churner, args=(w,), daemon=True)
        for w in range(3)
    ]
    for t in threads:
        t.start()
    try:
        for _ in range(10):
            status, text = _get(server, "/metrics")
            assert status == 200
            try:
                _assert_exposition_consistent(text)
            except AssertionError as e:
                errors.append(str(e))
                break
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not errors, errors[0]


def _assert_exposition_consistent(text: str):
    seen_families = set()
    current = None
    buckets: dict[str, list] = {}
    counts: dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("# HELP "):
            fam = line.split()[2]
            assert fam not in seen_families, f"family {fam} torn apart"
            seen_families.add(fam)
            current = fam
            continue
        if line.startswith("# TYPE "):
            assert line.split()[2] == current, "TYPE without its HELP"
            continue
        if not line:
            continue
        name = line.split("{")[0].split(" ")[0]
        assert current is not None and name.startswith(current), (
            f"sample {name} outside its family block"
        )
        if name.endswith("_bucket"):
            series = line.rsplit(",le=", 1)[0]
            buckets.setdefault(series, []).append(
                float(line.rsplit(" ", 1)[1])
            )
        elif name.endswith("_count"):
            counts[line.rsplit(" ", 1)[0]] = float(
                line.rsplit(" ", 1)[1]
            )
    for series, vals in buckets.items():
        assert vals == sorted(vals), (
            f"non-monotone cumulative buckets for {series}: {vals}"
        )
        cname = series.replace("_bucket{", "_count{") + "}"
        if cname in counts:
            # the count may have advanced between the bucket lines and
            # the count line of the SAME scrape only if a new
            # observation landed in between; both were read under the
            # child lock, so they must agree exactly
            assert vals[-1] == counts[cname], (
                f"+Inf bucket != count for {series}"
            )


# ---------------------------------------------------------------------
# ExportMetricsTask failure path (satellite: test coverage)
# ---------------------------------------------------------------------

def test_export_metrics_failure_path(inst, server, caplog,
                                     monkeypatch):
    """The REAL background loop under a failing sink: the failures
    counter increments (visible through the real HTTP endpoint), the
    identical repeated error logs exactly once, the thread survives,
    and a recovered sink resumes importing samples."""
    import logging
    import time

    from greptimedb_tpu.servers import prom_store
    from greptimedb_tpu.telemetry.export import ExportMetricsTask

    boom = {"on": True}
    real_apply = prom_store.apply_series

    def flaky_apply(instance, series, db="x"):
        if boom["on"]:
            raise RuntimeError("sink unavailable")
        return real_apply(instance, series, db=db)

    monkeypatch.setattr(prom_store, "apply_series", flaky_apply)
    task = ExportMetricsTask(inst, db="t_export")
    task.interval_s = 0.05  # the ctor clamps; the loop reads the attr
    with caplog.at_level(logging.WARNING,
                         logger="greptimedb_tpu.export"):
        task.start()
        try:
            deadline = time.monotonic() + 20
            while task.failures < 3 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert task.failures >= 3, "loop died on the first failure"
            assert task._thread.is_alive()
            same_error_logs = [
                r for r in caplog.records
                if "sink unavailable" in r.getMessage()
            ]
            assert len(same_error_logs) == 1, (
                "identical consecutive errors must log once, got "
                f"{len(same_error_logs)}"
            )
            _status, text = _get(server, "/metrics")
            val = [
                line for line in text.splitlines() if line.startswith(
                    "greptime_export_metrics_failures_total "
                )
            ]
            assert val and float(val[0].split()[-1]) >= 3
            # recovery: the surviving loop imports samples again
            boom["on"] = False
            deadline = time.monotonic() + 20
            while (task.samples_written == 0
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert task.samples_written > 0
            assert inst.catalog.table_names("t_export")
        finally:
            task.stop()
