"""Test configuration: force an 8-virtual-device CPU platform.

The driver validates multi-chip sharding the same way (see
__graft_entry__.dryrun_multichip). The axon site hook pins
jax_platforms="axon,cpu"; overriding the config (not just the env var) is
required to get CPU here.
"""

import os

# GTPU_SAN=1 turns every run into a race/deadlock audit: the gtsan
# plugin enables the concurrency sanitizer before test modules import
# the package, fails tests that leak threads/pools, and reports
# lock-order cycles + blocking-under-lock at session end
if (os.environ.get("GTPU_SAN") or "").strip().lower() in (
        "1", "true", "on", "yes"):
    pytest_plugins = ["greptimedb_tpu.tools.san.pytest_plugin"]

os.environ.setdefault("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# f64 available so golden tests can check semantics at Prometheus precision;
# the engine's device path stays explicitly f32/int32.
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running stress tests (tier-1 runs -m 'not slow')",
    )


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 CPU devices, got {devs}"
    return devs


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def _build_native():
    """Best-effort build of the C extensions so the native-parity tests
    run instead of skipping (loaders fall back to Python when absent)."""
    import glob
    import pathlib
    import subprocess

    native = pathlib.Path(__file__).parent.parent / "greptimedb_tpu" / "native"
    if glob.glob(str(native / "_lineproto*.so")):
        return
    try:
        subprocess.run(
            ["make", "-C", str(native)],
            check=False, capture_output=True, timeout=120,
        )
    except Exception:
        pass


_build_native()
