"""SQL queries executing multi-device: the database itself on the mesh.

The device RANGE path shards its cell-state grids over the series axis of
an 8-device mesh (conftest forces 8 virtual CPU devices); XLA inserts the
cross-shard collectives for the group folds. Capability counterpart of the
reference's distributed merge-scan
(/root/reference/src/query/src/dist_plan/merge_scan.rs:124,
src/partition/src/multi_dim.rs:37) with the Flight gather replaced by ICI
collectives.
"""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.parallel import mesh as M
from greptimedb_tpu.query.executor import QueryEngine
from greptimedb_tpu.query.planner import plan_select
from greptimedb_tpu.sql.parser import parse_sql


FLAGSHIP = (
    "SELECT ts, host, avg(u) RANGE '1m', max(v) RANGE '1m', "
    "last_value(u) RANGE '1m' FROM cpu ALIGN '1m' BY (host) "
    "ORDER BY ts, host"
)


@pytest.fixture
def inst(tmp_path, rng, devices):
    i = Standalone(str(tmp_path))
    i.execute_sql(
        "create table cpu (ts timestamp time index, host string primary key,"
        " u double, v double)"
    )
    tab = i.catalog.table("public", "cpu")
    n_hosts, t = 24, 240
    ts = np.tile(np.arange(t) * 10_000, n_hosts).astype(np.int64)
    hosts = np.repeat([f"h{i:02d}" for i in range(n_hosts)], t).astype(object)
    tab.write(
        {"host": hosts}, ts,
        {"u": rng.random(n_hosts * t) * 100, "v": rng.random(n_hosts * t)},
    )
    yield i
    i.close()


def _run(engine, inst, sql):
    stmt = parse_sql(sql)[0]
    plan, table = inst.plan(stmt, __import__(
        "greptimedb_tpu.session", fromlist=["QueryContext"]
    ).QueryContext())
    return engine.execute(plan, table)


def _compare(ra, rb):
    assert ra.num_rows == rb.num_rows
    for i in range(len(ra.names)):
        a, b = ra.cols[i].values, rb.cols[i].values
        if a.dtype == object:
            assert (a == b).all()
        else:
            np.testing.assert_allclose(
                np.asarray(a, float), np.asarray(b, float),
                rtol=2e-4, atol=1e-3, err_msg=ra.names[i],
            )


def test_sql_on_8device_mesh_matches_single(inst, devices):
    mesh = M.make_mesh(devices)  # 8-way series sharding
    e1 = QueryEngine(prefer_device=True)
    em = QueryEngine(prefer_device=True, mesh=mesh)
    r1 = _run(e1, inst, FLAGSHIP)
    assert e1.last_exec_path == "device"
    rm = _run(em, inst, FLAGSHIP)
    assert em.last_exec_path == "device"
    # grids actually live sharded over the mesh
    entry = next(iter(em.range_cache._entries.values()))
    sharding = entry.nrow.sharding
    assert getattr(sharding, "mesh", None) is not None
    assert len(entry.nrow.devices()) == 8
    _compare(r1, rm)


def test_sql_on_mesh_global_group(inst, devices):
    mesh = M.make_mesh(devices)
    em = QueryEngine(prefer_device=True, mesh=mesh)
    q = ("SELECT ts, avg(u) RANGE '2m', count(*) RANGE '2m' FROM cpu "
         "ALIGN '1m' BY () ORDER BY ts")
    eh = QueryEngine(prefer_device=False)
    _compare(_run(eh, inst, q), _run(em, inst, q))
    assert em.last_exec_path == "device"


def test_cluster_sql_on_mesh(tmp_path, rng, devices):
    """The full distributed shape: multi-region Cluster table, query
    planned from SQL, executed on the 8-device mesh."""
    from greptimedb_tpu.cluster import Cluster
    from greptimedb_tpu.datatypes.schema import (
        ColumnSchema, Schema, SemanticType,
    )
    from greptimedb_tpu.datatypes.types import ConcreteDataType as T

    cluster = Cluster(str(tmp_path), n_datanodes=3)
    schema = Schema([
        ColumnSchema("ts", T.timestamp_millisecond(),
                     SemanticType.TIMESTAMP, nullable=False),
        ColumnSchema("host", T.string(), SemanticType.TAG, nullable=False),
        ColumnSchema("u", T.float64(), SemanticType.FIELD),
    ])
    table = cluster.create_table("public", "cpu", schema, num_regions=3)
    n_hosts, t = 16, 120
    ts = np.tile(np.arange(t) * 10_000, n_hosts).astype(np.int64)
    hosts = np.repeat(
        [f"h{i:02d}" for i in range(n_hosts)], t
    ).astype(object)
    table.write({"host": hosts}, ts, {"u": rng.random(n_hosts * t) * 100})
    # rows really are spread over the datanodes
    dist = cluster.region_distribution()
    assert sum(1 for rids in dist.values() if rids) == 3

    stmt = parse_sql(FLAGSHIP.replace(", max(v) RANGE '1m'", "")
                     .replace(", last_value(u) RANGE '1m'", ""))[0]
    plan = plan_select(stmt, ts_name="ts", tag_names=["host"],
                       all_columns=["ts", "host", "u"])
    eh = QueryEngine(prefer_device=False)
    rh = eh.execute(plan, cluster.table("public", "cpu"))
    em = QueryEngine(prefer_device=True, mesh=M.make_mesh(devices))
    rm = em.execute(plan, cluster.table("public", "cpu"))
    assert em.last_exec_path == "device"
    _compare(rh, rm)
    cluster.shutdown()


def test_groupby_on_8device_mesh_matches_host(inst, devices):
    """Plain GROUP BY: the fused reduce program runs row-sharded over
    the mesh (VERDICT r3 task #2); results must equal the host path."""
    mesh = M.make_mesh(devices)
    em = QueryEngine(prefer_device=True, mesh=mesh)
    eh = QueryEngine(prefer_device=False)
    q = ("SELECT host, count(u), sum(u), avg(u), min(v), max(v), "
         "stddev_samp(u) FROM cpu GROUP BY host ORDER BY host")
    rh = _run(eh, inst, q)
    rm = _run(em, inst, q)
    assert em.last_exec_path == "device"
    _compare(rh, rm)


def test_promql_fast_on_8device_mesh_matches_host(tmp_path, rng, devices):
    """PromQL sum by (dc)(rate(...)): the selector-grid fast path runs
    series-sharded over the mesh; equality vs the single-device path."""
    from greptimedb_tpu.parallel import mesh as M2
    from greptimedb_tpu.promql import fast as F
    from greptimedb_tpu.promql.engine import PromEngine

    def build(home, mesh):
        rng = np.random.default_rng(7)  # identical data in both builds
        i = Standalone(str(home), prefer_device=True, mesh=mesh,
                       warm_start=False)
        i.execute_sql(
            "create table http_requests (ts timestamp time index, "
            "host string primary key, dc string primary key, "
            "greptime_value double)"
        )
        tab = i.catalog.table("public", "http_requests")
        n_hosts, t = 24, 120
        ts = np.tile(np.arange(t) * 10_000, n_hosts).astype(np.int64)
        hosts = np.repeat(
            [f"h{k:02d}" for k in range(n_hosts)], t
        ).astype(object)
        dcs = np.repeat(
            [f"dc{k % 3}" for k in range(n_hosts)], t
        ).astype(object)
        vals = np.cumsum(rng.random(n_hosts * t), 0)
        tab.write({"host": hosts, "dc": dcs}, ts,
                  {"greptime_value": vals})
        return i

    F.invalidate_cache()
    mesh = M2.make_mesh(devices)
    i1 = build(tmp_path / "a", None)
    im = build(tmp_path / "b", mesh)
    q = "sum by (dc) (rate(http_requests[2m]))"
    t0, t1 = 0, 119 * 10_000
    try:
        r1, _ = PromEngine(i1).query_range(q, t0, t1, 60_000)
        F.invalidate_cache()
        rm, _ = PromEngine(im).query_range(q, t0, t1, 60_000)
        # the grid really is sharded over 8 devices
        entry = next(iter(F._CACHE._entries.values()))
        assert entry.mesh is mesh
        assert len(entry.vals.devices()) == 8
        assert [frozenset(lb.items()) for lb in r1.labels] == \
               [frozenset(lb.items()) for lb in rm.labels]
        np.testing.assert_allclose(
            np.where(r1.present, r1.values, 0.0),
            np.where(rm.present, rm.values, 0.0),
            rtol=2e-4, atol=1e-3,
        )
        assert (r1.present == rm.present).all()
    finally:
        F.invalidate_cache()
        i1.close()
        im.close()
