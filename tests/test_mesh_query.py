"""SQL queries executing multi-device: the database itself on the mesh.

The device RANGE path shards its cell-state grids over the series axis of
an 8-device mesh (conftest forces 8 virtual CPU devices); XLA inserts the
cross-shard collectives for the group folds. Capability counterpart of the
reference's distributed merge-scan
(/root/reference/src/query/src/dist_plan/merge_scan.rs:124,
src/partition/src/multi_dim.rs:37) with the Flight gather replaced by ICI
collectives.
"""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.parallel import mesh as M
from greptimedb_tpu.query.executor import QueryEngine
from greptimedb_tpu.query.planner import plan_select
from greptimedb_tpu.sql.parser import parse_sql


FLAGSHIP = (
    "SELECT ts, host, avg(u) RANGE '1m', max(v) RANGE '1m', "
    "last_value(u) RANGE '1m' FROM cpu ALIGN '1m' BY (host) "
    "ORDER BY ts, host"
)

# test grids are tiny; force the replicate-vs-shard planner to shard so
# the mesh programs actually run (prod defaults gate on 4096 series)
FORCE_SHARD = M.MeshOptions(shard_min_series=1, shard_min_rows=1)


@pytest.fixture
def inst(tmp_path, rng, devices):
    i = Standalone(str(tmp_path))
    i.execute_sql(
        "create table cpu (ts timestamp time index, host string primary key,"
        " u double, v double)"
    )
    tab = i.catalog.table("public", "cpu")
    n_hosts, t = 24, 240
    ts = np.tile(np.arange(t) * 10_000, n_hosts).astype(np.int64)
    hosts = np.repeat([f"h{i:02d}" for i in range(n_hosts)], t).astype(object)
    tab.write(
        {"host": hosts}, ts,
        {"u": rng.random(n_hosts * t) * 100, "v": rng.random(n_hosts * t)},
    )
    yield i
    i.close()


def _run(engine, inst, sql):
    stmt = parse_sql(sql)[0]
    plan, table = inst.plan(stmt, __import__(
        "greptimedb_tpu.session", fromlist=["QueryContext"]
    ).QueryContext())
    return engine.execute(plan, table)


def _compare(ra, rb):
    assert ra.num_rows == rb.num_rows
    for i in range(len(ra.names)):
        a, b = ra.cols[i].values, rb.cols[i].values
        if a.dtype == object:
            assert (a == b).all()
        else:
            np.testing.assert_allclose(
                np.asarray(a, float), np.asarray(b, float),
                rtol=2e-4, atol=1e-3, err_msg=ra.names[i],
            )


def test_sql_on_8device_mesh_matches_single(inst, devices):
    mesh = M.make_mesh(devices)  # 8-way series sharding
    e1 = QueryEngine(prefer_device=True)
    em = QueryEngine(prefer_device=True, mesh=mesh, mesh_opts=FORCE_SHARD)
    r1 = _run(e1, inst, FLAGSHIP)
    assert e1.last_exec_path == "device"
    rm = _run(em, inst, FLAGSHIP)
    assert em.last_exec_path == "device"
    # grids actually live sharded over the mesh
    entry = next(iter(em.range_cache._entries.values()))
    sharding = entry.nrow.sharding
    assert getattr(sharding, "mesh", None) is not None
    assert len(entry.nrow.devices()) == 8
    _compare(r1, rm)


def test_sql_on_mesh_global_group(inst, devices):
    mesh = M.make_mesh(devices)
    em = QueryEngine(prefer_device=True, mesh=mesh, mesh_opts=FORCE_SHARD)
    q = ("SELECT ts, avg(u) RANGE '2m', count(*) RANGE '2m' FROM cpu "
         "ALIGN '1m' BY () ORDER BY ts")
    eh = QueryEngine(prefer_device=False)
    _compare(_run(eh, inst, q), _run(em, inst, q))
    assert em.last_exec_path == "device"


def test_cluster_sql_on_mesh(tmp_path, rng, devices):
    """The full distributed shape: multi-region Cluster table, query
    planned from SQL, executed on the 8-device mesh."""
    from greptimedb_tpu.cluster import Cluster
    from greptimedb_tpu.datatypes.schema import (
        ColumnSchema, Schema, SemanticType,
    )
    from greptimedb_tpu.datatypes.types import ConcreteDataType as T

    cluster = Cluster(str(tmp_path), n_datanodes=3)
    schema = Schema([
        ColumnSchema("ts", T.timestamp_millisecond(),
                     SemanticType.TIMESTAMP, nullable=False),
        ColumnSchema("host", T.string(), SemanticType.TAG, nullable=False),
        ColumnSchema("u", T.float64(), SemanticType.FIELD),
    ])
    table = cluster.create_table("public", "cpu", schema, num_regions=3)
    n_hosts, t = 16, 120
    ts = np.tile(np.arange(t) * 10_000, n_hosts).astype(np.int64)
    hosts = np.repeat(
        [f"h{i:02d}" for i in range(n_hosts)], t
    ).astype(object)
    table.write({"host": hosts}, ts, {"u": rng.random(n_hosts * t) * 100})
    # rows really are spread over the datanodes
    dist = cluster.region_distribution()
    assert sum(1 for rids in dist.values() if rids) == 3

    stmt = parse_sql(FLAGSHIP.replace(", max(v) RANGE '1m'", "")
                     .replace(", last_value(u) RANGE '1m'", ""))[0]
    plan = plan_select(stmt, ts_name="ts", tag_names=["host"],
                       all_columns=["ts", "host", "u"])
    eh = QueryEngine(prefer_device=False)
    rh = eh.execute(plan, cluster.table("public", "cpu"))
    em = QueryEngine(prefer_device=True, mesh=M.make_mesh(devices),
                     mesh_opts=FORCE_SHARD)
    rm = em.execute(plan, cluster.table("public", "cpu"))
    assert em.last_exec_path == "device"
    _compare(rh, rm)
    cluster.shutdown()


def test_groupby_on_8device_mesh_matches_host(inst, devices):
    """Plain GROUP BY: the fused reduce program runs row-sharded over
    the mesh (VERDICT r3 task #2); results must equal the host path."""
    mesh = M.make_mesh(devices)
    em = QueryEngine(prefer_device=True, mesh=mesh, mesh_opts=FORCE_SHARD)
    eh = QueryEngine(prefer_device=False)
    q = ("SELECT host, count(u), sum(u), avg(u), min(v), max(v), "
         "stddev_samp(u) FROM cpu GROUP BY host ORDER BY host")
    rh = _run(eh, inst, q)
    rm = _run(em, inst, q)
    assert em.last_exec_path == "device"
    _compare(rh, rm)


def test_promql_fast_on_8device_mesh_matches_host(tmp_path, rng, devices):
    """PromQL sum by (dc)(rate(...)): the selector-grid fast path runs
    series-sharded over the mesh; equality vs the single-device path."""
    from greptimedb_tpu.parallel import mesh as M2
    from greptimedb_tpu.promql import fast as F
    from greptimedb_tpu.promql.engine import PromEngine

    def build(home, mesh):
        rng = np.random.default_rng(7)  # identical data in both builds
        i = Standalone(str(home), prefer_device=True, mesh=mesh,
                       mesh_opts=None if mesh is None else FORCE_SHARD,
                       warm_start=False)
        i.execute_sql(
            "create table http_requests (ts timestamp time index, "
            "host string primary key, dc string primary key, "
            "greptime_value double)"
        )
        tab = i.catalog.table("public", "http_requests")
        n_hosts, t = 24, 120
        ts = np.tile(np.arange(t) * 10_000, n_hosts).astype(np.int64)
        hosts = np.repeat(
            [f"h{k:02d}" for k in range(n_hosts)], t
        ).astype(object)
        dcs = np.repeat(
            [f"dc{k % 3}" for k in range(n_hosts)], t
        ).astype(object)
        vals = np.cumsum(rng.random(n_hosts * t), 0)
        tab.write({"host": hosts, "dc": dcs}, ts,
                  {"greptime_value": vals})
        return i

    F.invalidate_cache()
    mesh = M2.make_mesh(devices)
    i1 = build(tmp_path / "a", None)
    im = build(tmp_path / "b", mesh)
    q = "sum by (dc) (rate(http_requests[2m]))"
    t0, t1 = 0, 119 * 10_000
    try:
        r1, _ = PromEngine(i1).query_range(q, t0, t1, 60_000)
        F.invalidate_cache()
        rm, _ = PromEngine(im).query_range(q, t0, t1, 60_000)
        # the grid really is sharded over 8 devices
        entry = next(iter(F._CACHE._entries.values()))
        assert entry.mesh is mesh
        assert len(entry.vals.devices()) == 8
        assert [frozenset(lb.items()) for lb in r1.labels] == \
               [frozenset(lb.items()) for lb in rm.labels]
        np.testing.assert_allclose(
            np.where(r1.present, r1.values, 0.0),
            np.where(rm.present, rm.values, 0.0),
            rtol=2e-4, atol=1e-3,
        )
        assert (r1.present == rm.present).all()
    finally:
        F.invalidate_cache()
        i1.close()
        im.close()


# ----------------------------------------------------------------------
# replicate-vs-shard planner + observability (ISSUE 7)
# ----------------------------------------------------------------------


def test_planner_replicate_vs_shard_decisions(devices):
    """decide_mesh_execution: large grids shard, small ones replicate,
    non-decomposable aggregates force replicate, and a missing mesh is
    always replicate."""
    from greptimedb_tpu.query.planner import decide_mesh_execution

    mesh = M.make_mesh(devices)
    opts = M.MeshOptions()  # prod defaults: 4096 series / 256k rows

    d = decide_mesh_execution(mesh, kind="range", series=100_000,
                              ops=("sum", "mean"), opts=opts)
    assert d.shard and d.reason == "large_grid" and d.devices == 8

    d = decide_mesh_execution(mesh, kind="range", series=64,
                              ops=("sum",), opts=opts)
    assert not d.shard and d.reason == "small_grid"

    d = decide_mesh_execution(mesh, kind="aggregate", rows=1_000_000,
                              ops=("count", "max"), opts=opts)
    assert d.shard and d.reason == "large_rowset"

    d = decide_mesh_execution(mesh, kind="aggregate", rows=500,
                              ops=("count",), opts=opts)
    assert not d.shard and d.reason == "small_rowset"

    # median is not decomposable: the whole query runs replicated
    d = decide_mesh_execution(mesh, kind="aggregate", rows=1_000_000,
                              ops=("median",), opts=opts)
    assert not d.shard and d.reason == "non_decomposable:median"

    d = decide_mesh_execution(None, kind="range", series=1_000_000)
    assert not d.shard and d.reason == "no_mesh"


def test_planner_decision_through_query_path(inst, devices):
    """The live query path consults the planner: with prod thresholds a
    24-series grid replicates (single-device placement); with forced
    thresholds the same query shards over 8 devices."""
    from greptimedb_tpu.query import stats as qstats

    mesh = M.make_mesh(devices)
    q = ("SELECT ts, host, avg(u) RANGE '1m' FROM cpu ALIGN '1m' "
         "BY (host) ORDER BY ts, host")

    e_def = QueryEngine(prefer_device=True, mesh=mesh,
                        mesh_opts=M.MeshOptions())
    with qstats.collect() as st:
        _run(e_def, inst, q)
    assert st.notes["mesh_decision_range"] == "replicate(small_grid)"
    entry = next(iter(e_def.range_cache._entries.values()))
    assert entry.mesh is None

    e_force = QueryEngine(prefer_device=True, mesh=mesh,
                          mesh_opts=FORCE_SHARD)
    with qstats.collect() as st:
        _run(e_force, inst, q)
    assert st.notes["mesh_decision_range"] == "shard(large_grid)"
    assert st.counters["mesh_devices"] == 8
    entry = next(iter(e_force.range_cache._entries.values()))
    assert entry.mesh is mesh


def test_mesh_metrics_and_explain_analyze(tmp_path, rng):
    """gtpu_mesh_* must render in /metrics AND runtime_metrics, and
    EXPLAIN ANALYZE must carry the replicate-vs-shard decision. Uses the
    full [mesh]-config lifecycle (configure() from TOML-shaped knobs)."""
    import urllib.request

    from greptimedb_tpu.servers.http import HttpServer

    M.reset_for_tests()
    try:
        opts = M.mesh_options_from({
            "enabled": True, "shard_min_series": 1, "shard_min_rows": 1,
        })
        mesh = M.configure(opts)
        assert mesh is not None and M.shard_count(mesh) == 8
        inst = Standalone(str(tmp_path), mesh=mesh, mesh_opts=opts,
                          prefer_device=True)
        inst.execute_sql(
            "create table cpu (ts timestamp time index, host string "
            "primary key, u double)"
        )
        tab = inst.catalog.table("public", "cpu")
        n_hosts, t = 16, 240
        ts = np.tile(np.arange(t) * 10_000, n_hosts).astype(np.int64)
        hosts = np.repeat(
            [f"h{i:02d}" for i in range(n_hosts)], t
        ).astype(object)
        tab.write({"host": hosts}, ts, {"u": rng.random(n_hosts * t)})
        r = inst.sql(
            "EXPLAIN ANALYZE SELECT ts, host, avg(u) RANGE '1m' FROM cpu "
            "ALIGN '1m' BY (host) ORDER BY ts, host"
        )
        text = "\n".join(row[0] for row in r.rows())
        assert "mesh_decision_range: shard(large_grid)" in text
        assert "mesh_devices: 8" in text
        srv = HttpServer(inst, port=0).start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=30
            ) as resp:
                body = resp.read().decode()
            assert "gtpu_mesh_devices 8" in body
            assert ('gtpu_mesh_queries_total{kind="range",mode="shard",'
                    'reason="large_grid"}') in body
        finally:
            srv.stop()
        res = inst.sql("select metric_name from "
                       "information_schema.runtime_metrics")
        names = list(res.column("metric_name").values)
        assert "gtpu_mesh_devices" in names
        assert "gtpu_mesh_queries_total" in names
        inst.close()
    finally:
        M.reset_for_tests()


def test_rows_preceding_window_on_global_mesh(tmp_path, rng, monkeypatch,
                                              devices):
    """ROWS k PRECEDING frames run the halo shard_map program when the
    process-wide mesh is configured, matching the host baseline within
    the documented ~ulp tolerance; exact counts stay exact."""
    from greptimedb_tpu.query import stats as qstats
    from greptimedb_tpu.query import window_fns as W

    M.reset_for_tests()
    try:
        mesh = M.configure(M.MeshOptions(enabled=True, shard_min_rows=1))
        assert mesh is not None and mesh.shape[M.AXIS_SHARD] == 8
        monkeypatch.setattr(W, "DEVICE_THRESHOLD", 100)
        inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                          warm_start=False)
        try:
            inst.execute_sql(
                "create table w (ts timestamp time index, g string "
                "primary key, v double)"
            )
            tab = inst.catalog.table("public", "w")
            n = 4000
            ts = np.tile(np.arange(n // 4) * 1000, 4).astype(np.int64)
            gs = np.repeat(
                [f"g{i}" for i in range(4)], n // 4
            ).astype(object)
            tab.write({"g": gs}, ts, {"v": rng.random(n) * 100})
            q = ("select g, ts, sum(v) over (partition by g order by ts "
                 "rows between 5 preceding and current row) as s, "
                 "count(v) over (partition by g order by ts "
                 "rows between 5 preceding and current row) as c "
                 "from w order by g, ts")
            with qstats.collect() as st:
                dev = inst.sql(q).rows()
            assert st.notes.get("exec_path_window") == "device_mesh"
            # host baseline: with the global mesh dropped the same
            # query must run the host path
            M.reset_for_tests()
            with qstats.collect() as st2:
                host = inst.sql(q).rows()
            assert st2.notes.get("exec_path_window") != "device_mesh"
            assert len(host) == len(dev) == n
            for h, d in zip(host, dev):
                assert h[0] == d[0] and h[1] == d[1]
                np.testing.assert_allclose(d[2], h[2], rtol=1e-9)
                assert h[3] == d[3]
        finally:
            inst.close()
    finally:
        M.reset_for_tests()
