"""SQL queries executing multi-device: the database itself on the mesh.

The device RANGE path shards its cell-state grids over the series axis of
an 8-device mesh (conftest forces 8 virtual CPU devices); XLA inserts the
cross-shard collectives for the group folds. Capability counterpart of the
reference's distributed merge-scan
(/root/reference/src/query/src/dist_plan/merge_scan.rs:124,
src/partition/src/multi_dim.rs:37) with the Flight gather replaced by ICI
collectives.
"""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.parallel import mesh as M
from greptimedb_tpu.query.executor import QueryEngine
from greptimedb_tpu.query.planner import plan_select
from greptimedb_tpu.sql.parser import parse_sql


FLAGSHIP = (
    "SELECT ts, host, avg(u) RANGE '1m', max(v) RANGE '1m', "
    "last_value(u) RANGE '1m' FROM cpu ALIGN '1m' BY (host) "
    "ORDER BY ts, host"
)


@pytest.fixture
def inst(tmp_path, rng, devices):
    i = Standalone(str(tmp_path))
    i.execute_sql(
        "create table cpu (ts timestamp time index, host string primary key,"
        " u double, v double)"
    )
    tab = i.catalog.table("public", "cpu")
    n_hosts, t = 24, 240
    ts = np.tile(np.arange(t) * 10_000, n_hosts).astype(np.int64)
    hosts = np.repeat([f"h{i:02d}" for i in range(n_hosts)], t).astype(object)
    tab.write(
        {"host": hosts}, ts,
        {"u": rng.random(n_hosts * t) * 100, "v": rng.random(n_hosts * t)},
    )
    yield i
    i.close()


def _run(engine, inst, sql):
    stmt = parse_sql(sql)[0]
    plan, table = inst.plan(stmt, __import__(
        "greptimedb_tpu.session", fromlist=["QueryContext"]
    ).QueryContext())
    return engine.execute(plan, table)


def _compare(ra, rb):
    assert ra.num_rows == rb.num_rows
    for i in range(len(ra.names)):
        a, b = ra.cols[i].values, rb.cols[i].values
        if a.dtype == object:
            assert (a == b).all()
        else:
            np.testing.assert_allclose(
                np.asarray(a, float), np.asarray(b, float),
                rtol=2e-4, atol=1e-3, err_msg=ra.names[i],
            )


def test_sql_on_8device_mesh_matches_single(inst, devices):
    mesh = M.make_mesh(devices)  # 8-way series sharding
    e1 = QueryEngine(prefer_device=True)
    em = QueryEngine(prefer_device=True, mesh=mesh)
    r1 = _run(e1, inst, FLAGSHIP)
    assert e1.last_exec_path == "device"
    rm = _run(em, inst, FLAGSHIP)
    assert em.last_exec_path == "device"
    # grids actually live sharded over the mesh
    entry = next(iter(em.range_cache._entries.values()))
    sharding = entry.nrow.sharding
    assert getattr(sharding, "mesh", None) is not None
    assert len(entry.nrow.devices()) == 8
    _compare(r1, rm)


def test_sql_on_mesh_global_group(inst, devices):
    mesh = M.make_mesh(devices)
    em = QueryEngine(prefer_device=True, mesh=mesh)
    q = ("SELECT ts, avg(u) RANGE '2m', count(*) RANGE '2m' FROM cpu "
         "ALIGN '1m' BY () ORDER BY ts")
    eh = QueryEngine(prefer_device=False)
    _compare(_run(eh, inst, q), _run(em, inst, q))
    assert em.last_exec_path == "device"


def test_cluster_sql_on_mesh(tmp_path, rng, devices):
    """The full distributed shape: multi-region Cluster table, query
    planned from SQL, executed on the 8-device mesh."""
    from greptimedb_tpu.cluster import Cluster
    from greptimedb_tpu.datatypes.schema import (
        ColumnSchema, Schema, SemanticType,
    )
    from greptimedb_tpu.datatypes.types import ConcreteDataType as T

    cluster = Cluster(str(tmp_path), n_datanodes=3)
    schema = Schema([
        ColumnSchema("ts", T.timestamp_millisecond(),
                     SemanticType.TIMESTAMP, nullable=False),
        ColumnSchema("host", T.string(), SemanticType.TAG, nullable=False),
        ColumnSchema("u", T.float64(), SemanticType.FIELD),
    ])
    table = cluster.create_table("public", "cpu", schema, num_regions=3)
    n_hosts, t = 16, 120
    ts = np.tile(np.arange(t) * 10_000, n_hosts).astype(np.int64)
    hosts = np.repeat(
        [f"h{i:02d}" for i in range(n_hosts)], t
    ).astype(object)
    table.write({"host": hosts}, ts, {"u": rng.random(n_hosts * t) * 100})
    # rows really are spread over the datanodes
    dist = cluster.region_distribution()
    assert sum(1 for rids in dist.values() if rids) == 3

    stmt = parse_sql(FLAGSHIP.replace(", max(v) RANGE '1m'", "")
                     .replace(", last_value(u) RANGE '1m'", ""))[0]
    plan = plan_select(stmt, ts_name="ts", tag_names=["host"],
                       all_columns=["ts", "host", "u"])
    eh = QueryEngine(prefer_device=False)
    rh = eh.execute(plan, cluster.table("public", "cpu"))
    em = QueryEngine(prefer_device=True, mesh=M.make_mesh(devices))
    rm = em.execute(plan, cluster.table("public", "cpu"))
    assert em.last_exec_path == "device"
    _compare(rh, rm)
    cluster.shutdown()
