"""Layered config + role-process topology (VERDICT coverage rows 1/30:
CLI role processes, option layering; row 6: a real frontend->datanode
data plane over Flight)."""

import json
import urllib.request

import numpy as np
import pytest

from greptimedb_tpu.config import load_options
from greptimedb_tpu.instance import Standalone


# ----------------------------------------------------------------------
# config layering
# ----------------------------------------------------------------------

def test_config_layering(tmp_path):
    cfg = tmp_path / "cfg.toml"
    cfg.write_text(
        'data_home = "/from/toml"\n'
        "[http]\naddr = \"0.0.0.0:9000\"\n"
        "[flow]\ntick_interval_s = 9.5\n"
    )
    env = {
        "GREPTIMEDB_TPU__HTTP__ADDR": "1.2.3.4:8000",
        "GREPTIMEDB_TPU__WAL__SYNC": "true",
        "GREPTIMEDB_TPU__ENGINE__BACKGROUND_INTERVAL_S": "2.5",
    }
    opts = load_options(
        "standalone", config_file=str(cfg), env=env,
        cli_overrides={"http.addr": "127.0.0.1:7000",
                       "mysql.addr": None},   # unset flag: no masking
    )
    # precedence: cli > env > toml > defaults
    assert opts.get("http.addr") == "127.0.0.1:7000"
    assert opts.get("wal.sync") is True
    assert opts.get("engine.background_interval_s") == 2.5
    assert opts.get("data_home") == "/from/toml"
    assert opts.get("flow.tick_interval_s") == 9.5
    assert opts.get("mysql.addr") == "127.0.0.1:4002"  # default kept


def test_config_role_scoped_env_wins():
    env = {
        "GREPTIMEDB_TPU__HTTP__ADDR": "generic:1",
        "GREPTIMEDB_TPU_DATANODE__HTTP__ADDR": "scoped:2",
    }
    opts = load_options("datanode", env=env)
    assert opts.get("http.addr") == "scoped:2"
    assert load_options("frontend", env=env).get("http.addr") == "generic:1"


def test_config_list_env_parse():
    env = {"GREPTIMEDB_TPU__FRONTEND__DATANODE_ADDRS":
           "[\"127.0.0.1:4001\", \"127.0.0.1:5001\"]"}
    opts = load_options("frontend", env=env)
    assert opts.get("frontend.datanode_addrs") == [
        "127.0.0.1:4001", "127.0.0.1:5001",
    ]


# ----------------------------------------------------------------------
# role topology: metasrv + datanode(flight) + frontend(remote)
# ----------------------------------------------------------------------

flight = pytest.importorskip("pyarrow.flight")


@pytest.fixture()
def datanode(tmp_path):
    from greptimedb_tpu.servers.flight import FlightFrontend

    inst = Standalone(str(tmp_path / "dn"))
    f = FlightFrontend(inst, port=0).start()
    yield inst, f
    f.close()
    inst.close()


def test_frontend_forwards_sql_over_flight(datanode):
    from greptimedb_tpu.servers.remote import RemoteInstance

    _, f = datanode
    fe = RemoteInstance([f"127.0.0.1:{f.server.port}"])
    out = fe.execute_sql(
        "CREATE TABLE rt (host STRING, v DOUBLE, ts TIMESTAMP TIME "
        "INDEX, PRIMARY KEY (host))"
    )[-1]
    assert out.result is None
    out = fe.execute_sql(
        "INSERT INTO rt (host, v, ts) VALUES ('a', 1.5, 1000), "
        "('b', 2.5, 2000)"
    )[-1]
    assert out.affected_rows == 2
    res = fe.sql("SELECT host, v FROM rt ORDER BY host")
    assert [list(r) for r in res.rows()] == [["a", 1.5], ["b", 2.5]]
    # errors surface as GreptimeError, not gRPC internals
    from greptimedb_tpu.errors import GreptimeError

    with pytest.raises(GreptimeError):
        fe.sql("SELECT broken FROM missing")
    fe.close()


def test_frontend_database_context(datanode):
    from greptimedb_tpu.servers.remote import RemoteInstance
    from greptimedb_tpu.session import QueryContext

    inst, f = datanode
    inst.sql("CREATE DATABASE fdb")
    inst.sql("CREATE TABLE fdb.t (v DOUBLE, ts TIMESTAMP TIME INDEX)")
    inst.sql("INSERT INTO fdb.t (v, ts) VALUES (4.5, 10)")
    fe = RemoteInstance([f"127.0.0.1:{f.server.port}"])
    res = fe.sql("SELECT v FROM t", QueryContext(database="fdb"))
    assert float(res.cols[0].values[0]) == 4.5
    assert fe.catalog.has_database("fdb")
    assert not fe.catalog.has_database("nope")
    fe.close()


def test_frontend_mysql_protocol_through_datanode(datanode):
    from greptimedb_tpu.servers.mysql import MySqlServer
    from greptimedb_tpu.servers.remote import RemoteInstance

    import sys
    sys.path.insert(0, "tests")
    from test_wire_protocols import MiniMySqlClient

    _, f = datanode
    fe = RemoteInstance([f"127.0.0.1:{f.server.port}"])
    srv = MySqlServer(fe, port=0).start()
    try:
        c = MiniMySqlClient(srv.port)
        c.query("CREATE TABLE mt (v DOUBLE, ts TIMESTAMP TIME INDEX)")
        c.query("INSERT INTO mt (v, ts) VALUES (7.5, 1000)")
        _, rows = c.query("SELECT v FROM mt")
        assert rows == [["7.5"]]
        c.close()
    finally:
        srv.close()
        fe.close()


def test_metasrv_http_service(tmp_path):
    from greptimedb_tpu.servers.meta_http import MetasrvServer

    srv = MetasrvServer(port=0, data_home=str(tmp_path)).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"

        def post(path, doc):
            req = urllib.request.Request(
                base + path, data=json.dumps(doc).encode(),
                headers={"Content-Type": "application/json"},
            )
            return json.loads(urllib.request.urlopen(req, timeout=5)
                              .read())

        post("/register", {"node_id": 1})
        hb = post("/heartbeat", {"node_id": 1,
                                 "region_stats": {"7": {"rows": 10}}})
        # first heartbeat grants the node its lease
        assert {i["type"] for i in hb["instructions"]} <= {"grant_lease"}
        # kv with CAS
        assert post("/kv", {"op": "cas", "key": "k", "expect": None,
                            "value": "v1"})["success"]
        assert not post("/kv", {"op": "cas", "key": "k", "expect": None,
                                "value": "v2"})["success"]
        assert post("/kv", {"op": "get", "key": "k"})["value"] == "v1"
        got = json.loads(urllib.request.urlopen(
            base + "/routes", timeout=5
        ).read())
        assert isinstance(got, dict)
    finally:
        srv.close()


def test_cli_role_parsers():
    """Every role's start command parses with the layered flags."""
    from greptimedb_tpu import cli

    ap = cli.build_parser()
    for role in cli.ROLES:
        args = ap.parse_args([
            role, "start", "--data-home", "/tmp/x",
            "--http-addr", "127.0.0.1:0", "--mysql-addr", "",
            "--postgres-addr", "", "--flight-addr", "127.0.0.1:0",
            "--metasrv-addr", "127.0.0.1:4010",
            "--datanode-addrs", "a:1,b:2", "--node-id", "7",
            "--no-flows",
        ])
        assert args.role == role and args.cmd == "start"
        assert args.data_home == "/tmp/x"
        assert args.node_id == 7 and args.no_flows
    args = ap.parse_args(["cli", "--data-home", "/tmp/y"])
    assert args.role == "cli" and args.data_home == "/tmp/y"
