"""Flush-time fulltext index in puffin sidecars (VERDICT rows 23/24):
matches() queries skip row groups whose term index can't contain a hit,
with exact residual filtering on the survivors."""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.query import stats
from greptimedb_tpu.storage.puffin import PuffinReader, PuffinWriter


def test_puffin_container_roundtrip():
    w = PuffinWriter()
    w.add_blob("type-a", b"hello", {"column": "c1"})
    w.add_blob("type-a", b"world", {"column": "c2"})
    w.add_blob("type-b", b"x" * 100)
    data = w.finish()
    r = PuffinReader(data)
    assert len(r.blobs) == 3
    b = r.find("type-a", column="c2")
    assert r.read(b) == b"world"
    assert r.find("type-a", column="zz") is None
    with pytest.raises(ValueError):
        PuffinReader(b"garbage")


@pytest.fixture()
def inst(tmp_path):
    s = Standalone(str(tmp_path / "data"))
    yield s
    s.close()


def _mk_logs(inst, n_per_group=2000):
    # append_mode: the log-table shape — no dedup, so value-based
    # row-group pruning is sound
    inst.sql(
        "CREATE TABLE logs (host STRING, msg STRING FULLTEXT, "
        "ts TIMESTAMP TIME INDEX, PRIMARY KEY (host)) "
        "WITH (append_mode = 'true')"
    )
    table = inst.catalog.table("public", "logs")
    region = table.regions[0]
    assert region.meta.fulltext_fields == ["msg"]
    # three batches flushed as one SST with small row groups: group 0
    # has "error timeout", group 1 "warning slow", group 2 "info ok"
    msgs = (["disk error timeout on raid"] * n_per_group
            + ["warning slow query path"] * n_per_group
            + ["info everything ok"] * n_per_group)
    n = len(msgs)
    ts = np.arange(n, dtype=np.int64) * 1000
    hosts = np.asarray([f"h{i % 7}" for i in range(n)], object)
    table.write({"host": hosts}, ts,
                {"msg": np.asarray(msgs, object)})
    from greptimedb_tpu.storage import sst as S

    orig = S.write_sst

    def small_groups(*a, **k):
        k["row_group_rows"] = n_per_group
        return orig(*a, **k)

    S.write_sst = small_groups
    try:
        region.flush()
    finally:
        S.write_sst = orig
    meta = region.manifest.state.ssts[0]
    assert meta.fulltext, "sidecar missing"
    assert region.store.exists(S.sidecar_path(meta.path))
    return inst


def test_fulltext_prunes_row_groups(inst):
    _mk_logs(inst)
    with stats.collect() as st:
        r = inst.sql("SELECT count(*) FROM logs "
                     "WHERE matches(msg, 'error AND timeout')")
    assert int(r.rows()[0][0]) == 2000
    doc = st.to_dict() if hasattr(st, "to_dict") else dict(st.__dict__)
    # only 1 of 3 row groups decoded
    flat = str(doc)
    assert "'row_groups_read': 1" in flat or '"row_groups_read": 1' in flat


def test_fulltext_term_absent_skips_sst(inst):
    _mk_logs(inst)
    r = inst.sql("SELECT count(*) FROM logs "
                 "WHERE matches(msg, 'nonexistentterm')")
    assert int(r.rows()[0][0]) == 0


def test_fulltext_or_still_correct(inst):
    _mk_logs(inst)
    # OR has no single required term -> no pruning, results still exact
    r = inst.sql("SELECT count(*) FROM logs "
                 "WHERE matches(msg, 'timeout OR slow')")
    assert int(r.rows()[0][0]) == 4000
    # NOT semantics untouched
    r = inst.sql("SELECT count(*) FROM logs "
                 "WHERE matches(msg, 'NOT error')")
    assert int(r.rows()[0][0]) == 4000


def test_fulltext_phrase_edges_not_overpruned(inst):
    _mk_logs(inst)
    # '"disk err"' substring-matches "disk error ..." rows; the edge
    # word "err" must NOT be used for pruning (it's not a whole token)
    r = inst.sql("SELECT count(*) FROM logs "
                 "WHERE matches(msg, '\"disk err\"')")
    assert int(r.rows()[0][0]) == 2000


def test_no_pruning_under_dedup_overwrites(inst):
    """Last-write-wins tables must NOT index-prune: an overwrite whose
    new text lacks the term would resurrect the shadowed old row."""
    inst.sql(
        "CREATE TABLE ow (host STRING, msg STRING FULLTEXT, "
        "ts TIMESTAMP TIME INDEX, PRIMARY KEY (host))"
    )
    table = inst.catalog.table("public", "ow")
    region = table.regions[0]
    inst.sql("INSERT INTO ow (host, msg, ts) VALUES "
             "('a', 'fatal error in disk', 1000)")
    region.flush()
    inst.sql("INSERT INTO ow (host, msg, ts) VALUES "
             "('a', 'all fine now', 1000)")    # overwrite same (host,ts)
    region.flush()
    r = inst.sql("SELECT count(*) FROM ow WHERE matches(msg, 'error')")
    assert int(r.rows()[0][0]) == 0   # the old version must stay dead


def test_fulltext_survives_restart_and_truncate(inst):
    _mk_logs(inst)
    root = str(inst.engine.config.data_root)
    inst.close()
    inst2 = Standalone(root)
    try:
        r = inst2.sql("SELECT count(*) FROM logs "
                      "WHERE matches(msg, 'slow AND query')")
        assert int(r.rows()[0][0]) == 2000
        table = inst2.catalog.table("public", "logs")
        region = table.regions[0]
        from greptimedb_tpu.storage.sst import sidecar_path

        paths = [m.path for m in region.manifest.state.ssts]
        inst2.sql("TRUNCATE TABLE logs")
        for p in paths:
            assert not region.store.exists(sidecar_path(p))
    finally:
        inst2.close()
