"""Admission control + deadline scheduling (greptimedb_tpu/sched/).

Tier-1 gate for the overload surface: typed shedding (429/503 class
errors, never a hang), per-tenant isolation (an over-quota tenant is
shed while an in-quota tenant on the same instance completes), deadline
propagation through cooperative checkpoints and the distributed
fan-out, `gtpu_sched_*` observability in /metrics and
information_schema, and the queued/running split in SHOW PROCESSLIST.
"""

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from greptimedb_tpu.errors import (
    QueryDeadlineExceededError,
    QueryOverloadedError,
    QueryQueueTimeoutError,
)
from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.sched import (
    AdmissionController,
    Deadline,
    SchedulerConfig,
    tenant_of,
)
from greptimedb_tpu.session import QueryContext


@pytest.fixture()
def inst(tmp_path):
    inst = Standalone(str(tmp_path / "data"), prefer_device=False,
                      warm_start=False)
    yield inst
    inst.close()


# ---------------------------------------------------------------------
# controller unit behavior
# ---------------------------------------------------------------------

def test_tenant_identity():
    assert tenant_of(QueryContext()) == "public"
    assert tenant_of(QueryContext(database="metrics")) == "metrics"
    assert tenant_of(QueryContext(username="alice",
                                  database="metrics")) == "alice"


def test_qps_quota_sheds_typed():
    c = AdmissionController(SchedulerConfig(tenant_qps=1.0,
                                            tenant_burst=1.0))
    ctx = QueryContext(username="noisy")
    with c.admit(ctx):
        pass
    with pytest.raises(QueryOverloadedError):
        with c.admit(ctx):
            pass
    # tokens refill at qps: after a second one passes again
    time.sleep(1.05)
    with c.admit(ctx):
        pass


def test_per_tenant_quota_isolation():
    """The over-quota tenant sheds; another tenant on the SAME
    controller is untouched."""
    c = AdmissionController(SchedulerConfig(
        tenants={"noisy": {"qps": 1.0, "burst": 1.0}},
    ))
    with c.admit(QueryContext(username="noisy")):
        pass
    with pytest.raises(QueryOverloadedError):
        with c.admit(QueryContext(username="noisy")):
            pass
    for _ in range(5):   # unlimited tenant: never shed
        with c.admit(QueryContext(username="quiet")):
            pass


def test_queue_timeout_and_queue_full_shed_typed():
    c = AdmissionController(SchedulerConfig(
        max_concurrency=1, queue_depth=1, queue_timeout_s=0.2,
    ))
    hold = c.admit(QueryContext())
    hold.__enter__()
    try:
        results = {}

        def attempt(name, delay):
            time.sleep(delay)
            try:
                with c.admit(QueryContext(username=name)):
                    results[name] = "admitted"
            except Exception as e:  # noqa: BLE001 - recorded
                results[name] = type(e).__name__

        ts = [threading.Thread(target=attempt, args=("waiter", 0.0)),
              threading.Thread(target=attempt, args=("spill", 0.05))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(5)
        # first queues then times out (503 class); second finds the
        # bounded queue full and sheds immediately (429 class)
        assert results["waiter"] == "QueryQueueTimeoutError"
        assert results["spill"] == "QueryOverloadedError"
    finally:
        hold.__exit__(None, None, None)


def test_queue_knobs_zero_mean_unlimited():
    """queue_depth=0 / queue_timeout_s=0 follow the same 0=unlimited
    convention as every other limit knob: an unbounded queue never
    sheds queue_full, and no SLO means the waiter holds on until a
    slot frees (or its deadline lapses)."""
    c = AdmissionController(SchedulerConfig(
        max_concurrency=1, queue_depth=0, queue_timeout_s=0.0,
    ))
    hold = c.admit(QueryContext())
    hold.__enter__()
    outcomes = []
    lock = threading.Lock()

    def attempt():
        try:
            with c.admit(QueryContext()):
                with lock:
                    outcomes.append("admitted")
        except Exception as e:  # noqa: BLE001 - recorded
            with lock:
                outcomes.append(type(e).__name__)

    ts = [threading.Thread(target=attempt) for _ in range(3)]
    for t in ts:
        t.start()
    time.sleep(0.3)   # well past a 0-valued SLO misread as 0 seconds
    assert outcomes == [] and c.snapshot()["queued"] == 3
    hold.__exit__(None, None, None)
    for t in ts:
        t.join(10)
    assert outcomes == ["admitted"] * 3


def test_tenant_state_stays_bounded_under_name_rotation():
    """The tenant string is client-controlled (HTTP db param): a storm
    rotating names must not grow per-tenant state without bound."""
    from greptimedb_tpu.sched import admission

    c = AdmissionController(SchedulerConfig(tenant_qps=100.0,
                                            tenant_burst=100.0))
    n = admission._TENANT_STATE_MAX + 64
    for i in range(n):
        with c.admit(tenant=f"t{i}"):
            pass
    assert len(c._buckets) <= admission._TENANT_STATE_MAX
    # unconfigured tenants share ONE limits object (nothing cached)
    assert c.config._limits_cache == {}
    assert c.config.limits("t0") is c.config.limits("t999999")


def test_slot_handover_wakes_waiter():
    c = AdmissionController(SchedulerConfig(max_concurrency=1,
                                            queue_timeout_s=5.0))
    hold = c.admit(QueryContext())
    hold.__enter__()
    admitted = threading.Event()

    def waiter():
        with c.admit(QueryContext()):
            admitted.set()

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    assert not admitted.is_set()
    hold.__exit__(None, None, None)
    t.join(5)
    assert admitted.is_set()
    snap = c.snapshot()
    assert snap["running"] == 0 and snap["queued"] == 0


def test_priority_orders_the_queue():
    """Two tenants queued behind a held slot: the lower-priority
    number is admitted first regardless of arrival order."""
    c = AdmissionController(SchedulerConfig(
        max_concurrency=1, queue_timeout_s=5.0,
        tenants={"fast": {"priority": 1}, "slow": {"priority": 200}},
    ))
    hold = c.admit(QueryContext())
    hold.__enter__()
    order = []
    lock = threading.Lock()

    def run(name):
        with c.admit(QueryContext(username=name)):
            with lock:
                order.append(name)
            time.sleep(0.05)

    t_slow = threading.Thread(target=run, args=("slow",))
    t_slow.start()
    time.sleep(0.05)   # slow is queued first
    t_fast = threading.Thread(target=run, args=("fast",))
    t_fast.start()
    time.sleep(0.05)
    hold.__exit__(None, None, None)
    t_slow.join(5)
    t_fast.join(5)
    assert order == ["fast", "slow"]


def test_nested_admission_rides_parent_slot():
    """A statement executed INSIDE an admitted statement (prepared
    EXECUTE, COPY's inner SELECT) must not deadlock on its own
    tenant's concurrency limit."""
    c = AdmissionController(SchedulerConfig(max_concurrency=1,
                                            queue_timeout_s=0.1))
    with c.admit(QueryContext()):
        with c.admit(QueryContext()):   # would deadlock if counted
            pass
    assert c.snapshot()["running"] == 0


def test_deadline_expires_in_queue():
    c = AdmissionController(SchedulerConfig(
        max_concurrency=1, queue_timeout_s=10.0,
    ))
    hold = c.admit(QueryContext())
    hold.__enter__()
    try:
        result = {}

        def attempt():
            # separate thread: the same-thread re-entrancy guard would
            # otherwise treat this as a nested statement
            t0 = time.monotonic()
            try:
                with c.admit(QueryContext(), timeout_s=0.2):
                    result["outcome"] = "admitted"
            except Exception as e:  # noqa: BLE001 - recorded
                result["outcome"] = type(e).__name__
            result["elapsed"] = time.monotonic() - t0

        t = threading.Thread(target=attempt)
        t.start()
        t.join(10)
        assert result["outcome"] == "QueryDeadlineExceededError"
        assert result["elapsed"] < 5.0   # bounded by the deadline SLO
    finally:
        hold.__exit__(None, None, None)


def test_deadline_checkpoint_raises_typed():
    from greptimedb_tpu import cancellation
    from greptimedb_tpu.sched import deadline as dl

    token = dl.bind(Deadline(0.01))
    try:
        time.sleep(0.02)
        with pytest.raises(QueryDeadlineExceededError):
            cancellation.checkpoint()
    finally:
        dl.reset(token)
    cancellation.checkpoint()   # unbound again: no-op


def test_call_timeout_caps_remaining():
    from greptimedb_tpu.sched import deadline as dl

    assert dl.call_timeout() is None
    assert dl.call_timeout(5.0) == 5.0
    token = dl.bind(Deadline(100.0))
    try:
        assert dl.call_timeout(5.0) == 5.0
        assert 99.0 < dl.call_timeout() <= 100.0
    finally:
        dl.reset(token)


# ---------------------------------------------------------------------
# instance integration
# ---------------------------------------------------------------------

def _seed(inst, rows=64):
    inst.sql("create table cpu (ts timestamp time index, host string "
             "primary key, v double)")
    vals = ", ".join(
        f"('h{i % 8}', {1_700_000_000_000 + i * 1000}, {float(i)})"
        for i in range(rows)
    )
    inst.execute_sql(f"insert into cpu (host, ts, v) values {vals}")


def test_over_quota_tenant_shed_while_in_quota_completes(inst):
    """THE tier-1 isolation gate: same instance, one tenant over its
    qps quota gets the typed 429-class error, the other completes."""
    _seed(inst)
    inst.scheduler = AdmissionController(SchedulerConfig(
        tenants={"noisy": {"qps": 1.0, "burst": 1.0}},
    ))
    noisy = QueryContext(username="noisy")
    quiet = QueryContext(username="quiet")
    assert inst.sql("select count(*) from cpu",
                    noisy).cols[0].values[0] == 64
    with pytest.raises(QueryOverloadedError):
        inst.sql("select count(*) from cpu", noisy)
    # the in-quota tenant is untouched, repeatedly
    for _ in range(3):
        assert inst.sql("select count(*) from cpu",
                        quiet).cols[0].values[0] == 64


def test_statement_deadline_bounds_query(inst):
    _seed(inst)
    ctx = QueryContext()
    ctx.extensions["deadline_s"] = 1e-9   # expires before any scan
    with pytest.raises(QueryDeadlineExceededError):
        inst.sql("select count(*) from cpu", ctx)
    # control-plane statements bypass admission even with the hint
    assert inst.sql("show tables", ctx).num_rows == 1


def test_max_execution_time_session_variable(inst):
    """SET max_execution_time=<ms> (the MySQL-compatible knob) feeds
    the per-statement deadline resolution."""
    ctx = QueryContext()
    inst.execute_sql("set max_execution_time = 250", ctx)
    adm = inst.scheduler.admit(ctx)
    assert adm._resolve_timeout() == pytest.approx(0.25)
    # an explicit per-request hint (HTTP ?timeout=) wins over it
    ctx.extensions["deadline_s"] = 2.0
    assert inst.scheduler.admit(ctx)._resolve_timeout() == 2.0


def test_show_processlist_has_state_column(inst):
    res = inst.sql("SHOW PROCESSLIST")
    assert "State" in res.names
    assert "Running" in list(res.column("State").values)


def test_sched_metrics_render_in_metrics_and_information_schema(inst):
    """gtpu_sched_* must surface through BOTH observability paths."""
    from greptimedb_tpu.servers.http import HttpServer

    _seed(inst, rows=8)
    inst.scheduler = AdmissionController(SchedulerConfig(
        tenants={"noisy": {"qps": 1.0, "burst": 1.0}},
    ))
    noisy = QueryContext(username="noisy")
    inst.sql("select count(*) from cpu", noisy)
    with pytest.raises(QueryOverloadedError):
        inst.sql("select count(*) from cpu", noisy)
    srv = HttpServer(inst, port=0).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=30
        ) as r:
            body = r.read().decode()
        assert 'gtpu_sched_admitted_total{tenant="noisy"}' in body
        assert ('gtpu_sched_shed_total{tenant="noisy",reason="qps"}'
                in body)
        assert "gtpu_sched_queue_depth" in body
        assert "gtpu_sched_running" in body
        assert "gtpu_sched_queue_time_seconds_bucket" in body
    finally:
        srv.stop()
    res = inst.sql("select metric_name, value, labels from "
                   "information_schema.runtime_metrics")
    names = list(res.column("metric_name").values)
    assert "gtpu_sched_admitted_total" in names
    assert "gtpu_sched_shed_total" in names


def test_http_surface_maps_shed_to_429_and_deadline_to_503(inst):
    from greptimedb_tpu.servers.http import HttpServer

    _seed(inst, rows=8)
    inst.scheduler = AdmissionController(SchedulerConfig(
        tenants={"public": {"qps": 1.0, "burst": 1.0}},
    ))
    srv = HttpServer(inst, port=0).start()
    base = f"http://127.0.0.1:{srv.port}"

    def sql(q, extra=""):
        return urllib.request.urlopen(
            f"{base}/v1/sql?sql={urllib.parse.quote(q)}{extra}",
            data=b"", timeout=30,
        )

    try:
        with sql("select count(*) from cpu") as r:
            assert r.status == 200
        with pytest.raises(urllib.error.HTTPError) as ei:
            sql("select count(*) from cpu")
        assert ei.value.code == 429
        body = json.loads(ei.value.read())
        assert "quota" in body["error"]
        # deadline via ?timeout= maps to 503 after the bucket refills
        time.sleep(1.1)
        with pytest.raises(urllib.error.HTTPError) as ei:
            sql("select count(*) from cpu", "&timeout=0.000000001")
        assert ei.value.code == 503
        # non-finite / non-positive timeouts are client errors, not
        # never-expiring (nan) or instantly-failing (inf RPC budget)
        # deadlines
        for bad in ("nan", "inf", "-1", "0", "bogus"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                sql("select 1", f"&timeout={bad}")
            assert ei.value.code == 400, bad
    finally:
        srv.stop()


# ---------------------------------------------------------------------
# distributed propagation (in-process wire topology)
# ---------------------------------------------------------------------

def _dist_harness(tmp_path, n=2):
    pytest.importorskip("pyarrow.flight")
    from tests.test_dist_cluster import DistHarness

    return DistHarness(tmp_path, n_datanodes=n)


def _dist_seed(frontend, rows=60):
    frontend.execute_sql(
        "create table cpu (ts timestamp time index, host string "
        "primary key, v double) with (num_regions = 3)"
    )
    vals = ", ".join(
        f"('h{i % 6}', {1_700_000_000_000 + i * 1000}, {float(i)})"
        for i in range(rows)
    )
    frontend.execute_sql(f"insert into cpu (host, ts, v) values {vals}")


def test_deadline_bounds_distributed_query_typed(tmp_path):
    """An expired per-statement deadline against the wire topology
    fails with the TYPED error, bounded — never a hang (the mid-flight
    blackhole propagation case lives in tests/test_chaos.py)."""
    h = _dist_harness(tmp_path)
    try:
        _dist_seed(h.frontend)
        ctx = QueryContext()
        res = h.frontend.sql("select count(*) from cpu", ctx)
        assert res.cols[0].values[0] == 60
        ctx.extensions["deadline_s"] = 1e-9
        t0 = time.monotonic()
        with pytest.raises(QueryDeadlineExceededError):
            h.frontend.sql("select count(*) from cpu", ctx)
        assert time.monotonic() - t0 < 10.0
    finally:
        h.close()


def test_partial_result_when_datanode_dies(tmp_path):
    """[scheduler] allow_partial_results: killing one datanode mid-
    stream degrades a decomposable aggregate to a typed partial result
    (partial=true + missing-region count) instead of failing."""
    h = _dist_harness(tmp_path, n=2)
    try:
        _dist_seed(h.frontend)
        h.frontend.scheduler = AdmissionController(SchedulerConfig(
            allow_partial_results=True, default_deadline_s=30.0,
        ))
        full = h.frontend.sql("select sum(v) from cpu")
        assert float(full.cols[0].values[0]) == float(sum(range(60)))
        assert not getattr(full, "partial", False)
        h.stop_datanode(0)
        res = h.frontend.sql("select sum(v) from cpu")
        assert getattr(res, "partial", False) is True
        assert res.missing_regions >= 1
        # the surviving regions' sum is a strict subset
        assert float(res.cols[0].values[0]) < float(sum(range(60)))
    finally:
        h.close()
