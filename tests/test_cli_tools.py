"""CLI export/import round trip (reference: src/cmd/src/cli/export.rs,
import.rs)."""

import numpy as np

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.tools import export_data, import_data


def _seed(data_home: str):
    inst = Standalone(data_home, prefer_device=False, warm_start=False)
    inst.execute_sql(
        "create table cpu (ts timestamp time index, host string primary "
        "key, usage double)"
    )
    inst.catalog.table("public", "cpu").write(
        {"host": np.asarray(["a", "b", "a"], object)},
        np.asarray([1000, 1000, 2000], np.int64),
        {"usage": np.asarray([1.0, 2.0, 3.0])},
    )
    inst.execute_sql("create database metrics")
    inst.execute_sql(
        "create table m (ts timestamp time index, v double)",
        __import__("greptimedb_tpu.session",
                   fromlist=["QueryContext"]).QueryContext(
            database="metrics"),
    )
    inst.execute_sql("create view top_cpu as select host, usage from cpu")
    inst.close()


def test_export_import_roundtrip(tmp_path):
    src_home = str(tmp_path / "src")
    out = str(tmp_path / "dump")
    dst_home = str(tmp_path / "dst")
    _seed(src_home)

    report = export_data(src_home, out)
    assert report["public"]["tables"] == 1
    assert report["public"]["rows"] == 3
    assert (tmp_path / "dump" / "public" / "create_tables.sql").exists()
    assert (tmp_path / "dump" / "public" / "cpu.parquet").exists()
    assert (tmp_path / "dump" / "metrics" / "create_tables.sql").exists()

    report = import_data(dst_home, out)
    assert report["public"]["rows"] == 3

    inst = Standalone(dst_home, prefer_device=False, warm_start=False)
    try:
        r = inst.sql("select host, usage from cpu order by ts, host")
        assert list(r.cols[0].values) == ["a", "b", "a"]
        assert list(r.cols[1].values) == [1.0, 2.0, 3.0]
        # schema made it over: tags/time index survive
        r = inst.sql("show columns from cpu")
        by_name = dict(zip(r.cols[0].values, r.cols[3].values))
        assert by_name["host"] == "PRI"
        # the view was recreated
        r = inst.sql("select count(usage) from top_cpu")
        assert r.cols[0].values[0] == 3
        # second database present (schema-only table)
        assert "m" in inst.catalog.table_names("metrics")
    finally:
        inst.close()


def test_export_schema_only(tmp_path):
    src_home = str(tmp_path / "src")
    out = str(tmp_path / "dump")
    _seed(src_home)
    report = export_data(src_home, out, target="schema")
    assert report["public"]["rows"] == 0
    assert not (tmp_path / "dump" / "public" / "cpu.parquet").exists()


def test_export_single_database(tmp_path):
    src_home = str(tmp_path / "src")
    out = str(tmp_path / "dump")
    _seed(src_home)
    report = export_data(src_home, out, database="metrics")
    assert list(report) == ["metrics"]
    assert not (tmp_path / "dump" / "public").exists()


def test_cli_entrypoints(tmp_path, capsys):
    from greptimedb_tpu.cli import main

    src_home = str(tmp_path / "src")
    _seed(src_home)
    rc = main(["cli", "export", "--data-home", src_home,
               "--output-dir", str(tmp_path / "dump")])
    assert rc == 0
    assert "exported public" in capsys.readouterr().out
    rc = main(["cli", "import", "--data-home", str(tmp_path / "dst"),
               "--input-dir", str(tmp_path / "dump")])
    assert rc == 0
    assert "imported public" in capsys.readouterr().out
