"""Pipelined wire-ingest dataplane (greptimedb_tpu/ingest/).

Covers the PR-1 contract: coalescer thresholds, bounded-queue
backpressure surfacing IngestOverloadedError with bounded frontend
memory, typed errors across the Flight boundary, the region-not-found
route-refresh retry, and crash-mid-stream dedup-idempotent replay.
"""

import threading
import time

import numpy as np
import pytest

pytest.importorskip("pyarrow.flight")

from greptimedb_tpu.dist.client import DatanodeClient, MetaClient
from greptimedb_tpu.dist.frontend import DistInstance
from greptimedb_tpu.dist.region_server import RegionServer
from greptimedb_tpu.errors import (
    FlowNotFoundError,
    IngestOverloadedError,
    RegionNotFoundError,
)
from greptimedb_tpu.ingest import (
    AdaptiveDelay,
    IngestConfig,
    IngestEntry,
    IngestPipeline,
    WriteTicket,
    coalesce_entries,
)
from greptimedb_tpu.ingest.sender import DatanodeSender
from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.servers.flight import FlightFrontend
from greptimedb_tpu.servers.meta_http import MetasrvServer
from greptimedb_tpu.storage.engine import EngineConfig


# ----------------------------------------------------------------------
# unit: coalescer
# ----------------------------------------------------------------------

def _entry(rid=1, n=3, base_ts=0, client=None, op=0, skip_wal=False,
           valid=None, ticket=None):
    return IngestEntry(
        region_id=rid, client=client,
        tag_columns={"host": np.asarray([f"h{i}" for i in range(n)],
                                        object)},
        ts=np.arange(base_ts, base_ts + n, dtype=np.int64),
        fields={"v": np.arange(n, dtype=np.float64)},
        field_valid=valid, op=op, skip_wal=skip_wal, ticket=ticket,
    )


def test_coalesce_merges_same_region_in_order():
    t1, t2 = WriteTicket(), WriteTicket()
    out = coalesce_entries([
        _entry(rid=1, n=2, base_ts=0, ticket=t1),
        _entry(rid=1, n=3, base_ts=100, ticket=t2),
    ])
    assert len(out) == 1
    m = out[0]
    assert m.rows == 5
    # order preserved: the second submit's rows stay LATER
    assert list(m.ts) == [0, 1, 100, 101, 102]
    assert m.tickets == [t1, t2]


def test_coalesce_keeps_incompatible_entries_apart():
    out = coalesce_entries([
        _entry(rid=1), _entry(rid=2),             # different region
        _entry(rid=1, op=1),                      # different op
        _entry(rid=1, skip_wal=True),             # different durability
    ])
    assert len(out) == 4


def test_coalesce_merges_partial_validity():
    v = {"v": np.asarray([True, False, True])}
    out = coalesce_entries([
        _entry(rid=1, n=3),            # fully valid (no mask)
        _entry(rid=1, n=3, valid=v),
    ])
    assert len(out) == 1
    mask = out[0].field_valid["v"]
    assert list(mask) == [True, True, True, True, False, True]


def test_adaptive_delay_widens_and_narrows():
    d = AdaptiveDelay(max_delay_s=0.008)
    assert d.current_s == 0.0
    d.note_flush(10, target_rows=1000)   # undersized flush: widen
    first = d.current_s
    assert first > 0
    for _ in range(20):
        d.note_flush(10, target_rows=1000)
    assert d.current_s == 0.008          # capped at max
    d.note_flush(5000, target_rows=1000)  # at-target: narrow
    assert d.current_s < 0.008
    for _ in range(20):
        d.note_flush(5000, target_rows=1000)
    assert d.current_s == 0.0            # back to zero added latency


def test_write_ticket_timeout_raises_unknown_outcome():
    """An unacked ticket times out as the unavailable (unknown-outcome)
    error, NOT the retry-inviting IngestOverloadedError — the group may
    still apply when the datanode recovers."""
    from greptimedb_tpu.errors import DatanodeUnavailableError

    t = WriteTicket()
    t.add_parts(1)
    with pytest.raises(DatanodeUnavailableError):
        t.wait(0.05)
    t.part_done()
    assert t.wait(0.05) == []


# ----------------------------------------------------------------------
# unit: sender backpressure (transport stubbed out)
# ----------------------------------------------------------------------

class _FakeClient:
    addr = "stub:0"

    def close(self):
        pass


def test_sender_backpressure_bounds_queue_and_sheds(monkeypatch):
    release = threading.Event()
    shipped = []

    def stalled_ship(self, taken):
        shipped.append(sum(e.rows for e in taken))
        release.wait(10.0)

    monkeypatch.setattr(DatanodeSender, "_ship", stalled_ship)
    cfg = IngestConfig(queue_max_rows=10, block_timeout_s=0.1)
    sender = DatanodeSender(_FakeClient(), cfg)
    try:
        sender.submit(_entry(n=8))   # worker takes it, stalls in _ship
        deadline = time.monotonic() + 5
        while not shipped and time.monotonic() < deadline:
            time.sleep(0.01)
        sender.submit(_entry(n=8))   # queued (queue empty, oversized ok)
        t0 = time.monotonic()
        with pytest.raises(IngestOverloadedError):
            sender.submit(_entry(n=8))   # over budget: block then shed
        assert time.monotonic() - t0 >= 0.09
        # frontend memory stays bounded by the queue budget
        assert sender._queued_rows <= cfg.queue_max_rows
    finally:
        release.set()
        sender.close(drain_timeout=0.1)


# ----------------------------------------------------------------------
# wire harness
# ----------------------------------------------------------------------

class MiniCluster:
    def __init__(self, tmp_path, n=2, *, store=None, wal_backend="fs",
                 ingest_options=None):
        self.tmp_path = tmp_path
        self.store = store
        self.wal_backend = wal_backend
        self.meta = MetasrvServer(
            addr="127.0.0.1", port=0, data_home=str(tmp_path / "meta")
        ).start()
        self.meta_addr = f"127.0.0.1:{self.meta.port}"
        self.datanodes = {}
        for i in range(n):
            self.start_datanode(i)
        self.frontend = DistInstance(
            str(tmp_path / "fe"), self.meta_addr, prefer_device=False,
            ingest_options=ingest_options,
        )

    def start_datanode(self, i):
        home = str(self.tmp_path / f"dn{i}")
        inst = Standalone(
            engine_config=EngineConfig(data_root=home,
                                       enable_background=False,
                                       wal_backend=self.wal_backend),
            prefer_device=False, warm_start=False, store=self.store,
        )
        inst.region_server = RegionServer(inst.engine, home)
        fs = FlightFrontend(inst, port=0).start()
        MetaClient(self.meta_addr).register(
            i, f"127.0.0.1:{fs.server.port}"
        )
        self.datanodes[i] = (inst, fs)
        return inst, fs

    def stop_datanode(self, i):
        inst, fs = self.datanodes.pop(i)
        fs.close()
        inst.close()

    def close(self):
        self.frontend.close()
        for i in list(self.datanodes):
            self.stop_datanode(i)
        self.meta.close()


@pytest.fixture()
def cluster(tmp_path):
    c = MiniCluster(tmp_path)
    yield c
    c.close()


def _seed_table(fe, name="t", regions=2):
    fe.execute_sql(
        f"create table {name} (ts timestamp time index, host string "
        f"primary key, v double) with (num_regions = {regions})"
    )


# ----------------------------------------------------------------------
# typed errors across the Flight boundary
# ----------------------------------------------------------------------

def test_region_not_found_is_typed_across_the_wire(cluster):
    _, fs = cluster.datanodes[0]
    cli = DatanodeClient(f"127.0.0.1:{fs.server.port}")
    try:
        with pytest.raises(RegionNotFoundError):
            cli.flush_region(99_999_999)
    finally:
        cli.close()


def test_flow_not_found_is_typed_across_the_wire(cluster):
    fe = cluster.frontend
    fe.flownode_addr = None
    with pytest.raises(FlowNotFoundError):
        fe.execute_sql("admin flush_flow('no_such_flow')")


def test_writes_ride_the_pipeline_and_read_back(cluster):
    fe = cluster.frontend
    _seed_table(fe)
    table = fe.catalog.table("public", "t")
    assert table.ingest is not None
    n = 4000
    hosts = np.asarray([f"h{i % 37}" for i in range(n)], object)
    ts = np.arange(n, dtype=np.int64) * 1000
    table.write({"host": hosts}, ts, {"v": np.ones(n)})
    assert fe.sql("select count(v), sum(v) from t").rows() == [[n, float(n)]]
    # concurrent small writes coalesce and all land
    errs = []

    def worker(k):
        try:
            for j in range(10):
                t0 = 10_000_000 + (k * 10 + j) * 1000
                fe.execute_sql(
                    f"insert into t (host, ts, v) values "
                    f"('w{k}', {t0}, 1.0)"
                )
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errs
    assert fe.sql("select count(v) from t").rows() == [[n + 80]]
    assert table.ingest.flush(timeout=10.0)


def test_delete_routes_through_pipeline(cluster):
    fe = cluster.frontend
    _seed_table(fe)
    fe.execute_sql(
        "insert into t (host, ts, v) values ('a', 1000, 1.0), "
        "('b', 2000, 2.0)"
    )
    fe.execute_sql("delete from t where host = 'a'")
    assert fe.sql("select host from t").rows() == [["b"]]


# ----------------------------------------------------------------------
# backpressure: a stalled datanode bounds memory + sheds typed
# ----------------------------------------------------------------------

class _StalledFlightServer:
    """Accepts the ingest stream but never acks a group."""

    def __init__(self):
        import pyarrow.flight as flight

        stop = threading.Event()

        class Srv(flight.FlightServerBase):
            def do_put(self, context, descriptor, reader, writer):
                stop.wait(30.0)  # never ack; release on close

        self._stop = stop
        self.server = Srv("grpc://127.0.0.1:0")
        self.addr = f"127.0.0.1:{self.server.port}"

    def close(self):
        self._stop.set()
        self.server.shutdown()


@pytest.mark.slow  # tier-1 budget: backpressure gated by the queue-bound
# + crash-replay ingest tests in this module
def test_stalled_datanode_bounds_memory_and_sheds():
    from greptimedb_tpu.errors import DatanodeUnavailableError

    srv = _StalledFlightServer()
    cli = DatanodeClient(srv.addr)
    cfg = IngestConfig(queue_max_rows=64, block_timeout_s=0.2,
                       ack_timeout_s=0.5, max_delay_ms=0.0)
    pipe = IngestPipeline(cfg)
    try:
        # a waited submit times out typed instead of hanging — as the
        # UNKNOWN-OUTCOME unavailable error, not the retry-inviting 429
        # (the unacked group may still apply later)
        with pytest.raises(DatanodeUnavailableError):
            pipe.submit([_entry(rid=1, n=8, client=cli)])
        # fire-and-forget floods hit the bounded queue and shed
        with pytest.raises(IngestOverloadedError):
            for _ in range(64):
                pipe.submit([_entry(rid=1, n=8, client=cli)],
                            wait=False)
        sender = pipe.sender_for(cli)
        assert sender._pending_rows() <= cfg.queue_max_rows + 8
    finally:
        pipe.close()
        cli.close()
        srv.close()


# ----------------------------------------------------------------------
# migration + crash: retry and replay semantics through the dataplane
# ----------------------------------------------------------------------

def test_migration_reroutes_batches_without_statement_retry(tmp_path):
    from greptimedb_tpu.storage.object_store import FsObjectStore
    from greptimedb_tpu.telemetry.metrics import global_registry

    shared = FsObjectStore(str(tmp_path / "shared"))
    c = MiniCluster(tmp_path, n=2, store=shared, wal_backend="object")
    try:
        fe = c.frontend
        _seed_table(fe, regions=2)
        fe.execute_sql(
            "insert into t (host, ts, v) values ('a', 1000, 1.0), "
            "('b', 2000, 2.0), ('c', 3000, 3.0)"
        )
        ms = c.meta.metasrv
        retry_counter = global_registry.counter(
            "gtpu_ingest_route_retry_total",
            "region batches re-routed after a RegionNotFound ack",
        ).labels()
        before = retry_counter.value
        moved = 0
        for rid in fe.catalog.table("public", "t").info.region_ids():
            src = ms.route_of(rid)
            ms.migrate_region(rid, 1 - src)
            moved += 1
        assert moved == 2
        # the frontend's routes are now stale for EVERY region; the
        # dataplane's typed region-not-found retry re-routes batches
        vals = ", ".join(
            f"('h{i}', {100_000 + i * 1000}, 1.0)" for i in range(12)
        )
        fe.execute_sql(f"insert into t (host, ts, v) values {vals}")
        got = fe.sql("select count(v), sum(v) from t").rows()
        assert got == [[15, 18.0]]
        assert retry_counter.value > before
    finally:
        c.close()


def test_crash_mid_stream_dedup_replay_is_idempotent(tmp_path):
    """A datanode dies with the ingest stream live; the failed
    statement replays after restart and last-write-wins dedup keeps the
    counts exact even though OTHER datanodes may have applied their
    batches the first time."""
    from greptimedb_tpu.errors import (
        DatanodeUnavailableError,
        GreptimeError,
    )

    c = MiniCluster(tmp_path, n=2)
    try:
        fe = c.frontend
        _seed_table(fe, regions=2)
        vals = ", ".join(
            f"('h{i}', {i * 1000}, {float(i)})" for i in range(40)
        )
        insert = f"insert into t (host, ts, v) values {vals}"
        fe.execute_sql(insert)  # stream established to both datanodes
        assert fe.sql("select count(v) from t").rows() == [[40]]
        c.stop_datanode(0)      # hard stop: stream dies mid-life
        with pytest.raises((DatanodeUnavailableError, GreptimeError)):
            fe.execute_sql(insert)  # partial apply on the survivor
        c.start_datanode(0)     # same node id, fresh port
        fe.catalog.refresh()
        fe.execute_sql(insert)  # the REPLAY
        # idempotent: every row exactly once
        got = fe.sql("select count(v), sum(v) from t").rows()
        assert got == [[40, float(sum(range(40)))]]
    finally:
        c.close()


def test_append_mode_batches_are_not_retried(cluster):
    fe = cluster.frontend
    fe.execute_sql(
        "create table ap (ts timestamp time index, host string "
        "primary key, v double) with (num_regions = 2, "
        "append_mode = 'true')"
    )
    table = fe.catalog.table("public", "ap")
    assert table._append_mode
    # the dataplane must mark append-mode batches non-retryable
    fe.execute_sql(
        "insert into ap (host, ts, v) values ('a', 1000, 1.0)"
    )
    assert fe.sql("select count(v) from ap").rows() == [[1]]


def test_pipeline_disabled_falls_back_to_legacy_path(tmp_path):
    c = MiniCluster(tmp_path, ingest_options={"pipeline": False})
    try:
        fe = c.frontend
        _seed_table(fe)
        assert fe.catalog.table("public", "t").ingest is None
        fe.execute_sql(
            "insert into t (host, ts, v) values ('a', 1000, 1.0)"
        )
        assert fe.sql("select count(v) from t").rows() == [[1]]
    finally:
        c.close()


def test_pipeline_metrics_surface_in_information_schema(cluster):
    fe = cluster.frontend
    _seed_table(fe)
    fe.execute_sql(
        "insert into t (host, ts, v) values ('a', 1000, 1.0)"
    )
    rows = fe.sql(
        "select metric_name from information_schema.runtime_metrics "
        "where metric_name like 'gtpu_ingest%'"
    ).rows()
    names = {r[0] for r in rows}
    assert "gtpu_ingest_rows_total" in names
    assert "gtpu_ingest_queued_rows" in names
