"""Merged-scan cache (storage/region.py): the page-cache-hot analog.

Repeated full scans of a big region answer out of the cached deduped
columnar row set (reference counterpart: the SST page/row-group caches in
/root/reference/src/mito2/src/cache/). Correctness contract: cache hits
must be indistinguishable from cold scans across writes, deletes, ALTERs,
truncate, multi-region sid remapping, and ts-bounded reads.
"""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.storage import region as R


@pytest.fixture(autouse=True)
def small_cache_threshold(monkeypatch):
    monkeypatch.setattr(R, "_SCAN_CACHE_MIN_ROWS", 100)


@pytest.fixture
def inst(tmp_path):
    i = Standalone(str(tmp_path))
    yield i
    i.close()


def _load(inst, name="cpu", hosts=8, t=100, regions=1):
    part = ""
    if regions > 1:
        bounds = [f"'h{i}'" for i in range(1, hosts, hosts // regions)]
        part = (" partition on columns (host) (" + ", ".join(
            [f"host < {bounds[0]}"]
            + [f"host >= {a} and host < {b}"
               for a, b in zip(bounds, bounds[1:])]
            + [f"host >= {bounds[-1]}"]) + ")")
    inst.execute_sql(
        f"create table {name} (ts timestamp time index, "
        f"host string primary key, u double, s double){part}"
    )
    tab = inst.catalog.table("public", name)
    ts = np.tile(np.arange(t) * 1000, hosts).astype(np.int64)
    hs = np.repeat([f"h{i}" for i in range(hosts)], t).astype(object)
    rng = np.random.default_rng(3)
    u = (rng.random(hosts * t, np.float32) * 100).astype(np.float64)
    s = (rng.random(hosts * t, np.float32) * 10).astype(np.float64)
    tab.write({"host": hs}, ts, {"u": u, "s": s})
    return tab


def _rows(inst, q):
    return inst.sql(q).rows()


def test_cache_hit_matches_cold(inst):
    _load(inst)
    q = "SELECT ts, host, u FROM cpu WHERE u > 50.0 ORDER BY host, ts"
    cold = _rows(inst, q)
    region = inst.catalog.table("public", "cpu").regions[0]
    assert region._scan_cache is not None
    hot = _rows(inst, q)
    assert hot == cold


def test_write_invalidates(inst):
    tab = _load(inst)
    n0 = inst.sql("SELECT count(*) FROM cpu").rows()[0][0]
    assert tab.regions[0]._scan_cache is not None
    tab.write({"host": np.asarray(["hx"], object)},
              np.asarray([5_000_000], np.int64),
              {"u": np.asarray([99.0]), "s": np.asarray([1.0])})
    n1 = inst.sql("SELECT count(*) FROM cpu").rows()[0][0]
    assert n1 == n0 + 1
    got = _rows(inst, "SELECT host, u FROM cpu WHERE u > 98.9 AND ts > 4000000")
    assert ["hx", 99.0] in got


def test_overwrite_dedup_through_cache(inst):
    tab = _load(inst)
    inst.sql("SELECT count(*) FROM cpu")  # build cache
    # overwrite one (host, ts) key: last write must win on the next scan
    tab.write({"host": np.asarray(["h0"], object)},
              np.asarray([0], np.int64),
              {"u": np.asarray([777.0]), "s": np.asarray([0.0])})
    got = _rows(inst, "SELECT u FROM cpu WHERE host = 'h0' AND ts = 0")
    assert got == [[777.0]]


def test_flush_keeps_cache_valid(inst):
    tab = _load(inst)
    q = "SELECT ts, host, u FROM cpu WHERE u > 90.0 ORDER BY host, ts"
    cold = _rows(inst, q)
    tab.flush()  # physical reorganization, logical data unchanged
    assert _rows(inst, q) == cold


def test_ts_bounds_served_from_cache(inst):
    _load(inst)
    full = _rows(inst, "SELECT count(*) FROM cpu")
    region = inst.catalog.table("public", "cpu").regions[0]
    assert region._scan_cache is not None
    bounded = _rows(
        inst, "SELECT count(*) FROM cpu WHERE ts >= 10000 AND ts < 20000")
    assert bounded == [[8 * 10]]
    assert full == [[8 * 100]]


def test_multi_region_sid_remap_not_poisoned(inst):
    """Table-level sid remapping mutates the returned container; the cached
    arrays must stay in REGION sid space across repeated scans."""
    _load(inst, name="part", hosts=8, t=100, regions=2)
    q = "SELECT host, count(*) c FROM part GROUP BY host ORDER BY host"
    cold = _rows(inst, q)
    for _ in range(3):
        assert _rows(inst, q) == cold


def test_alter_add_drop_invalidates(inst):
    _load(inst)
    inst.sql("SELECT count(*) FROM cpu")
    region = inst.catalog.table("public", "cpu").regions[0]
    assert region._scan_cache is not None
    inst.execute_sql("ALTER TABLE cpu DROP COLUMN s")
    assert region._scan_cache is None
    inst.execute_sql("ALTER TABLE cpu ADD COLUMN s double")
    # post-ALTER reads must match a cold scan (engine semantics keep the
    # physical chunk data; the cache must not serve a stale field LIST)
    cold = _rows(inst, "SELECT count(s) FROM cpu")
    assert _rows(inst, "SELECT count(s) FROM cpu") == cold


def test_truncate_drops_cache(inst):
    tab = _load(inst)
    inst.sql("SELECT count(*) FROM cpu")
    tab.truncate()
    assert tab.regions[0]._scan_cache is None
    assert _rows(inst, "SELECT count(*) FROM cpu") == [[0]]


def test_pool_evicts_over_budget(inst, monkeypatch):
    # budget fits ONE entry (~30KB for 800 rows) but not two
    monkeypatch.setattr(R._scan_pool, "budget", 40_000)
    _load(inst, name="a")
    _load(inst, name="b")
    inst.sql("SELECT count(*) FROM a")
    inst.sql("SELECT count(*) FROM b")  # evicts a (budget 1 byte, keep 1)
    ra = inst.catalog.table("public", "a").regions[0]
    rb = inst.catalog.table("public", "b").regions[0]
    assert ra._scan_cache is None and rb._scan_cache is not None
    # eviction must not affect results
    assert inst.sql("SELECT count(*) FROM a").rows() == [[800]]
