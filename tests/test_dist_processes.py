"""True multi-process topology: metasrv + 3 datanodes + frontend, each
its own OS process started through the real CLI entry points, talking
over loopback sockets — the shape of the reference's
tests-integration distributed runs
(/root/reference/tests-integration/src/cluster.rs), but with actual
process isolation.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.parse
import urllib.request

import pytest

pytest.importorskip("pyarrow.flight")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _child_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _spawn(args, log):
    return subprocess.Popen(
        [sys.executable, "-m", "greptimedb_tpu.cli", *args],
        env=_child_env(), stdout=log, stderr=subprocess.STDOUT,
        cwd=REPO,
    )


def _wait_http(addr, path="/health", timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(f"http://{addr}{path}",
                                        timeout=2):
                return
        except Exception:
            time.sleep(0.2)
    raise TimeoutError(f"{addr}{path} never came up")


def _wait_port(port, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=1):
                return
        except OSError:
            time.sleep(0.2)
    raise TimeoutError(f"port {port} never came up")


def _sql(addr: str, sql: str, timeout=120.0) -> dict:
    body = urllib.parse.urlencode({"sql": sql}).encode()
    req = urllib.request.Request(
        f"http://{addr}/v1/sql", data=body,
        headers={"Content-Type": "application/x-www-form-urlencoded"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _rows(doc: dict) -> list:
    return doc["output"][0]["records"]["rows"]


@pytest.fixture()
def topology(tmp_path):
    procs = []
    logs = []

    def spawn(args, name):
        log = open(tmp_path / f"{name}.log", "w")
        logs.append(log)
        p = _spawn(args, log)
        procs.append(p)
        return p

    meta_port = _free_port()
    spawn(["metasrv", "start", "--data-home", str(tmp_path / "meta"),
           "--metasrv-addr", f"127.0.0.1:{meta_port}",
           "--http-addr", ""], "metasrv")
    _wait_http(f"127.0.0.1:{meta_port}")

    dn_ports = []
    for i in range(3):
        port = _free_port()
        dn_ports.append(port)
        spawn(["datanode", "start",
               "--data-home", str(tmp_path / f"dn{i}"),
               "--flight-addr", f"127.0.0.1:{port}",
               "--metasrv-addr", f"127.0.0.1:{meta_port}",
               "--node-id", str(i), "--http-addr", "", "--mysql-addr",
               "", "--postgres-addr", "", "--no-flows"], f"dn{i}")
    for port in dn_ports:
        _wait_port(port)

    # wait until every datanode registered its peer address
    deadline = time.time() + 120
    while time.time() < deadline:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{meta_port}/peers", timeout=2
        ) as resp:
            peers = json.loads(resp.read())
        if len(peers) >= 3:
            break
        time.sleep(0.2)
    assert len(peers) >= 3, f"datanodes never registered: {peers}"

    flow_port = _free_port()
    spawn(["flownode", "start", "--data-home", str(tmp_path / "flow"),
           "--flight-addr", f"127.0.0.1:{flow_port}",
           "--metasrv-addr", f"127.0.0.1:{meta_port}",
           "--http-addr", "", "--mysql-addr", "", "--postgres-addr",
           ""], "flownode")
    _wait_port(flow_port)

    fe_port = _free_port()
    spawn(["frontend", "start", "--data-home", str(tmp_path / "fe"),
           "--http-addr", f"127.0.0.1:{fe_port}",
           "--metasrv-addr", f"127.0.0.1:{meta_port}",
           "--flownode-addr", f"127.0.0.1:{flow_port}",
           "--mysql-addr", "", "--postgres-addr", "", "--flight-addr",
           ""], "frontend")
    _wait_http(f"127.0.0.1:{fe_port}", path="/health")

    yield {"frontend": f"127.0.0.1:{fe_port}",
           "meta": f"127.0.0.1:{meta_port}",
           "dn_ports": dn_ports, "procs": procs,
           "tmp_path": tmp_path}

    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=15)
        except subprocess.TimeoutExpired:
            p.kill()
    for log in logs:
        log.close()


def test_multiprocess_distributed_query(topology):
    fe = topology["frontend"]
    _sql(fe, "create table cpu (ts timestamp time index, host string "
             "primary key, usage double) with (num_regions = 3)")
    values = ", ".join(
        f"('h{i % 5}', {1_700_000_000_000 + p * 5_000}, {i + p})"
        for p in range(6) for i in range(5)
    )
    _sql(fe, f"insert into cpu (host, ts, usage) values {values}")

    # plain GROUP BY merged across 3 datanode processes
    doc = _sql(fe, "select host, count(usage), sum(usage) from cpu "
                   "group by host order by host")
    rows = _rows(doc)
    assert [r[0] for r in rows] == [f"h{i}" for i in range(5)]
    assert all(r[1] == 6 for r in rows)
    assert sum(r[2] for r in rows) == sum(
        i + p for p in range(6) for i in range(5)
    )

    # the flagship RANGE shape over the wire
    doc = _sql(fe, "select ts, host, avg(usage) range '10s' from cpu "
                   "align '10s' order by ts, host limit 5")
    assert len(_rows(doc)) == 5

    # rows live on the datanodes, spread across >= 2 of them
    spread = 0
    for i, port in enumerate(topology["dn_ports"]):
        home = topology["tmp_path"] / f"dn{i}"
        wal = home / "wal"
        if wal.exists() and any(
            d.startswith("region_") and any(os.scandir(wal / d))
            for d in os.listdir(wal)
        ):
            spread += 1
    assert spread >= 2


def test_multiprocess_flow_mirroring(topology):
    """Insert via the frontend process; the flow result appears in a
    sink table computed by the SEPARATE flownode process."""
    fe = topology["frontend"]
    _sql(fe, "create table reqs (host string primary key, "
             "latency double, ts timestamp time index) "
             "with (num_regions = 3)")
    _sql(fe, "create flow lat_stats sink to lat_summary as "
             "select date_bin('1 minute', ts) as w, host, "
             "count(*) as total, avg(latency) as avg_lat "
             "from reqs group by w, host")
    doc = _sql(fe, "show flows")
    assert _rows(doc) == [["lat_stats"]]
    _sql(fe, "insert into reqs values "
             "('a', 10.0, 1700000000000), ('a', 30.0, 1700000010000), "
             "('b', 50.0, 1700000020000)")
    # the flownode ticks every second; poll the sink via the frontend
    deadline = time.time() + 120
    rows = []
    while time.time() < deadline:
        try:
            rows = _rows(_sql(
                fe, "select host, total, avg_lat from lat_summary "
                    "order by host"
            ))
            if len(rows) == 2:
                break
        except Exception:
            pass
        time.sleep(0.5)
    assert rows == [["a", 2, 20.0], ["b", 1, 50.0]]


@pytest.mark.slow  # tier-1 budget: HA failover exercised nightly; dist
# process coverage stays via trace/flow/query tests in this module
def test_metasrv_ha_leader_kill_and_failover(tmp_path):
    """3 metasrv PROCESSES share one kv (FsKv flock CAS = the etcd
    campaign analog, ref meta-srv/src/election/etcd.rs:161-206): exactly
    one leads; SIGKILLing the leader mid-workload elects a successor,
    datanodes re-register with it through the multi-address MetaClient,
    the frontend keeps serving, and a datanode kill AFTER the leader
    change still fails its regions over to the survivor."""
    procs = []
    logs = []

    def spawn(args, name):
        log = open(tmp_path / f"{name}.log", "w")
        logs.append(log)
        p = _spawn(args, log)
        procs.append(p)
        return p

    try:
        meta_home = str(tmp_path / "meta")
        meta_ports = [_free_port() for _ in range(3)]
        meta_addrs = [f"127.0.0.1:{p}" for p in meta_ports]
        metas = {}
        for i, port in enumerate(meta_ports):
            metas[meta_addrs[i]] = spawn(
                ["metasrv", "start", "--data-home", meta_home,
                 "--metasrv-addr", meta_addrs[i], "--http-addr", ""],
                f"meta{i}",
            )
        for a in meta_addrs:
            _wait_http(a)
        addr_list = ",".join(meta_addrs)

        def leaders():
            out = []
            for a in meta_addrs:
                try:
                    with urllib.request.urlopen(
                        f"http://{a}/health", timeout=2
                    ) as resp:
                        if json.loads(resp.read()).get("is_leader"):
                            out.append(a)
                except Exception:
                    pass
            return out

        deadline = time.time() + 30
        while time.time() < deadline and len(leaders()) != 1:
            time.sleep(0.3)
        led = leaders()
        assert len(led) == 1, f"want exactly one leader, got {led}"
        first_leader = led[0]

        # datanodes share an object store root so failover can reopen
        # flushed regions from the survivor
        shared_root = str(tmp_path / "shared_store")
        cfg = tmp_path / "dn.toml"
        cfg.write_text(
            f'[storage]\ntype = "fs"\nroot = "{shared_root}"\n'
        )
        dn_ports = []
        dn_procs = {}
        for i in range(2):
            port = _free_port()
            dn_ports.append(port)
            dn_procs[i] = spawn(
                ["datanode", "start", "-c", str(cfg),
                 "--data-home", str(tmp_path / f"dn{i}"),
                 "--flight-addr", f"127.0.0.1:{port}",
                 "--metasrv-addr", addr_list,
                 "--node-id", str(i), "--http-addr", "",
                 "--mysql-addr", "", "--postgres-addr", "",
                 "--no-flows"], f"dn{i}")
        for port in dn_ports:
            _wait_port(port)
        deadline = time.time() + 60
        while time.time() < deadline:
            with urllib.request.urlopen(
                f"http://{first_leader}/peers", timeout=2
            ) as resp:
                if len(json.loads(resp.read())) >= 2:
                    break
            time.sleep(0.3)

        fe_port = _free_port()
        spawn(["frontend", "start",
               "--data-home", str(tmp_path / "fe"),
               "--http-addr", f"127.0.0.1:{fe_port}",
               "--metasrv-addr", addr_list,
               "--mysql-addr", "", "--postgres-addr", "",
               "--flight-addr", ""], "frontend")
        fe = f"127.0.0.1:{fe_port}"
        _wait_http(fe, path="/health")

        _sql(fe, "create table t (ts timestamp time index, host string "
                 "primary key, v double) with (num_regions = 2)")
        _sql(fe, "insert into t (host, ts, v) values "
                 "('a', 1000, 1.0), ('b', 2000, 2.0), ('c', 3000, 3.0)")
        _sql(fe, "ADMIN flush_table('t')")
        assert _rows(_sql(fe, "select count(*) from t")) == [[3]]

        # ---- kill the metasrv leader mid-workload -------------------
        metas[first_leader].send_signal(signal.SIGKILL)
        metas[first_leader].wait(timeout=10)
        survivors = [a for a in meta_addrs if a != first_leader]
        deadline = time.time() + 45
        new_leader = None
        while time.time() < deadline:
            led = [a for a in leaders() if a in survivors]
            if len(led) == 1:
                new_leader = led[0]
                break
            time.sleep(0.3)
        assert new_leader, "no successor elected after leader kill"

        # frontend keeps serving through the surviving metasrvs
        _sql(fe, "insert into t (host, ts, v) values ('d', 4000, 4.0)")
        assert _rows(_sql(fe, "select count(*) from t")) == [[4]]
        _sql(fe, "ADMIN flush_table('t')")

        # datanodes re-register with the new leader (its own memory,
        # not just the persisted peer book -> wait for heartbeats)
        deadline = time.time() + 60
        while time.time() < deadline:
            with urllib.request.urlopen(
                f"http://{new_leader}/peers", timeout=2
            ) as resp:
                if len(json.loads(resp.read())) >= 2:
                    break
            time.sleep(0.5)

        def routes():
            with urllib.request.urlopen(
                f"http://{new_leader}/routes", timeout=2
            ) as resp:
                return {int(k): v for k, v in
                        json.loads(resp.read()).items()}

        # ---- now kill a datanode: failover must still work ----------
        victim_nid = 0
        victim = dn_procs[victim_nid]
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=10)
        deadline = time.time() + 120
        moved = False
        while time.time() < deadline:
            r = routes()
            if r and all(nid != victim_nid for nid in r.values()):
                moved = True
                break
            time.sleep(1.0)
        assert moved, f"regions never failed over: {routes()}"
        # flushed rows are readable from the survivor via the frontend
        deadline = time.time() + 60
        ok = False
        while time.time() < deadline:
            try:
                if _rows(_sql(fe, "select count(*) from t")) == [[4]]:
                    ok = True
                    break
            except Exception:
                pass
            time.sleep(1.0)
        assert ok, "frontend query did not recover after failover"
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()


@pytest.mark.slow  # tier-1 budget: flow mirroring gated by
# test_multiprocess_flow_mirroring
def test_flownode_crash_mirror_replay(tmp_path):
    """Kill the flownode PROCESS mid-stream: deltas inserted while it is
    down buffer on the frontend (bounded backlog) and replay in order
    after restart; the flownode reloads its flows from disk and the
    sink table converges to ALL source rows (VERDICT r4 #7)."""
    procs = []
    logs = []

    def spawn(args, name):
        log = open(tmp_path / f"{name}.log", "a")
        logs.append(log)
        p = _spawn(args, log)
        procs.append(p)
        return p

    try:
        meta_port = _free_port()
        spawn(["metasrv", "start", "--data-home", str(tmp_path / "meta"),
               "--metasrv-addr", f"127.0.0.1:{meta_port}",
               "--http-addr", ""], "metasrv")
        _wait_http(f"127.0.0.1:{meta_port}")
        dn_port = _free_port()
        spawn(["datanode", "start",
               "--data-home", str(tmp_path / "dn0"),
               "--flight-addr", f"127.0.0.1:{dn_port}",
               "--metasrv-addr", f"127.0.0.1:{meta_port}",
               "--node-id", "0", "--http-addr", "", "--mysql-addr", "",
               "--postgres-addr", "", "--no-flows"], "dn0")
        _wait_port(dn_port)
        deadline = time.time() + 60
        while time.time() < deadline:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{meta_port}/peers", timeout=2
            ) as resp:
                if len(json.loads(resp.read())) >= 1:
                    break
            time.sleep(0.2)

        flow_port = _free_port()

        def spawn_flownode():
            return spawn(
                ["flownode", "start",
                 "--data-home", str(tmp_path / "flow"),
                 "--flight-addr", f"127.0.0.1:{flow_port}",
                 "--metasrv-addr", f"127.0.0.1:{meta_port}",
                 "--http-addr", "", "--mysql-addr", "",
                 "--postgres-addr", ""], "flownode")

        fn = spawn_flownode()
        _wait_port(flow_port)

        fe_port = _free_port()
        spawn(["frontend", "start",
               "--data-home", str(tmp_path / "fe"),
               "--http-addr", f"127.0.0.1:{fe_port}",
               "--metasrv-addr", f"127.0.0.1:{meta_port}",
               "--mysql-addr", "", "--postgres-addr", "",
               "--flight-addr", ""], "frontend")
        fe = f"127.0.0.1:{fe_port}"
        _wait_http(fe, path="/health")

        _sql(fe, "create table src (host string primary key, v double, "
                 "ts timestamp time index)")
        # flow placed via the metasrv flownode book (no --flownode-addr)
        _sql(fe, "create flow agg sink to sums as select "
                 "date_bin('1 minute', ts) as w, host, count(*) as n, "
                 "sum(v) as s from src group by w, host")
        _sql(fe, "insert into src values ('a', 1.0, 1700000000000)")

        def sink_rows():
            try:
                return _rows(_sql(
                    fe, "select host, n, s from sums order by host"
                ))
            except Exception:
                return []

        deadline = time.time() + 180  # generous: 1-core CI under load
        while time.time() < deadline:
            if sink_rows() == [["a", 1, 1.0]]:
                break
            time.sleep(0.5)
        assert sink_rows() == [["a", 1, 1.0]], "flow never produced"

        # ---- SIGKILL the flownode mid-stream ------------------------
        fn.send_signal(signal.SIGKILL)
        fn.wait(timeout=10)
        # inserts while it is down must not fail the writes...
        _sql(fe, "insert into src values ('a', 2.0, 1700000001000)")
        _sql(fe, "insert into src values ('b', 5.0, 1700000002000)")
        # ...and the source table has them durably
        assert _rows(_sql(fe, "select count(*) from src")) == [[3]]

        # ---- restart on the same address ----------------------------
        spawn_flownode()
        _wait_port(flow_port)
        # a post-restart insert triggers the backlog replay
        _sql(fe, "insert into src values ('b', 7.0, 1700000003000)")
        deadline = time.time() + 180
        want = [["a", 2, 3.0], ["b", 2, 12.0]]
        got = []
        while time.time() < deadline:
            got = sink_rows()
            if got == want:
                break
            time.sleep(0.5)
        assert got == want, f"sink did not converge after restart: {got}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()


def _spawn_env(args, log, extra_env):
    env = _child_env()
    env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, "-m", "greptimedb_tpu.cli", *args],
        env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
    )


def _sql_traced(addr: str, sql: str, traceparent: str, *,
                params: str = "", timeout=120.0):
    body = urllib.parse.urlencode({"sql": sql}).encode()
    req = urllib.request.Request(
        f"http://{addr}/v1/sql{params}", data=body,
        headers={"Content-Type": "application/x-www-form-urlencoded",
                 "traceparent": traceparent},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _trace(addr: str, trace_id: str) -> list:
    with urllib.request.urlopen(
        f"http://{addr}/v1/traces?trace_id={trace_id}", timeout=10
    ) as resp:
        return json.loads(resp.read())["spans"]


def test_distributed_trace_stitching(tmp_path):
    """ONE stitched trace for a distributed query served through the
    real multi-process frontend: sched queue, plan, fan-out RPC,
    per-datanode scan (with cache hit/miss), merge stages and device
    compile/execute/transfer spans all share the trace_id the client
    sent, with parent links that resolve inside the trace. Also: a
    shed (429) and a deadline-expired (503) query still produce KEPT
    traces even at sample_ratio=0 (tail-based sampling), while an
    unremarkable query's trace is dropped."""
    procs, logs = [], []

    def spawn(args, name, extra_env=None):
        log = open(tmp_path / f"{name}.log", "w")
        logs.append(log)
        p = _spawn_env(args, log, extra_env or {})
        procs.append(p)
        return p

    try:
        meta_port = _free_port()
        spawn(["metasrv", "start", "--data-home",
               str(tmp_path / "meta"),
               "--metasrv-addr", f"127.0.0.1:{meta_port}",
               "--http-addr", ""], "metasrv")
        _wait_http(f"127.0.0.1:{meta_port}")

        dn_port = _free_port()
        # prefer_device forces the grid/device fast path on the
        # datanode even for a small table, so the stitched trace
        # carries real device compile/execute/transfer spans
        spawn(["datanode", "start",
               "--data-home", str(tmp_path / "dn0"),
               "--flight-addr", f"127.0.0.1:{dn_port}",
               "--metasrv-addr", f"127.0.0.1:{meta_port}",
               "--node-id", "0", "--http-addr", "", "--mysql-addr",
               "", "--postgres-addr", "", "--no-flows"], "dn0",
              {"GREPTIMEDB_TPU__QUERY__PREFER_DEVICE": "true"})
        _wait_port(dn_port)
        deadline = time.time() + 120
        while time.time() < deadline:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{meta_port}/peers", timeout=2
            ) as resp:
                if len(json.loads(resp.read())) >= 1:
                    break
            time.sleep(0.2)

        fe_port = _free_port()
        spawn(["frontend", "start", "--data-home",
               str(tmp_path / "fe"),
               "--http-addr", f"127.0.0.1:{fe_port}",
               "--metasrv-addr", f"127.0.0.1:{meta_port}",
               "--mysql-addr", "", "--postgres-addr", "",
               "--flight-addr", ""], "frontend")
        fe = f"127.0.0.1:{fe_port}"
        _wait_http(fe, path="/health")

        _sql(fe, "create table cpu (ts timestamp time index, host "
                 "string primary key, usage double) with "
                 "(num_regions = 2)")
        values = ", ".join(
            f"('h{i % 4}', {1_700_000_000_000 + p * 5_000}, {i + p})"
            for p in range(12) for i in range(4)
        )
        _sql(fe, f"insert into cpu (host, ts, usage) values {values}")

        range_sql = ("select ts, host, avg(usage) range '10s' from "
                     "cpu align '10s' by (host) order by ts, host")
        # warm once (device grid build + XLA compile on the datanode),
        # then the traced run: its scan should hit the datanode's
        # merged-scan cache and its device program memo
        tid_warm = "aa" * 16
        _sql_traced(fe, range_sql, f"00-{tid_warm}-{'11' * 8}-01")
        tid = "bb" * 16
        doc = _sql_traced(fe, range_sql, f"00-{tid}-{'22' * 8}-01")
        assert _rows(doc), "traced query returned no rows"

        spans = _trace(fe, tid)
        by_name: dict = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        # every hop of the hot path is present, under ONE trace_id
        for name in ("http /v1/sql", "sql.execute", "sql.Select",
                     "sched.admit", "query.plan", "dist.rpc",
                     "datanode.partial", "datanode.scan",
                     "device.execute"):
            assert name in by_name, (
                f"span {name!r} missing from stitched trace: "
                f"{sorted(by_name)}"
            )
        assert all(s["trace_id"] == tid for s in spans)
        # parent links resolve inside the trace (the cross-process
        # spans parent under the frontend's spans, not dangle) — the
        # sole exception is the HTTP root, whose parent is the span id
        # the CLIENT sent in its traceparent header
        ids = {s["span_id"] for s in spans}
        client_span = "22" * 8
        dangling = [
            s["name"] for s in spans
            if s["parent_id"] is not None and s["parent_id"] not in ids
            and s["parent_id"] != client_span
        ]
        assert not dangling, f"dangling parent links: {dangling}"
        root = by_name["http /v1/sql"][0]
        assert root["parent_id"] == client_span
        # datanode spans hang off the frontend statement span
        dn_parent = by_name["datanode.partial"][0]["parent_id"]
        assert dn_parent in {
            s["span_id"] for s in by_name["sql.Select"]
        }
        # scan-cache attribution on the datanode scan (warm run => hit)
        caches = {
            s["attributes"].get("scan_cache")
            for s in by_name["datanode.scan"]
        }
        assert caches & {"hit", "miss"}, caches
        # device attribution: compile state + execute/readback numbers
        dev = by_name["device.execute"][0]["attributes"]
        assert dev.get("compile") in ("first_call", "cache_hit")
        assert "execute_ms" in dev and "readback_bytes" in dev
        # the warm (cold-compile) run is stitched too
        warm_names = {s["name"] for s in _trace(fe, tid_warm)}
        assert "device.execute" in warm_names

        # ---- tail-kept shed + deadline traces at sample_ratio=0 -----
        fe2_port = _free_port()
        spawn(["frontend", "start", "--data-home",
               str(tmp_path / "fe2"),
               "--http-addr", f"127.0.0.1:{fe2_port}",
               "--metasrv-addr", f"127.0.0.1:{meta_port}",
               "--mysql-addr", "", "--postgres-addr", "",
               "--flight-addr", ""], "frontend2",
              {"GREPTIMEDB_TPU__TRACING__SAMPLE_RATIO": "0",
               "GREPTIMEDB_TPU__SCHEDULER__TENANT_QPS": "0.01",
               "GREPTIMEDB_TPU__SCHEDULER__TENANT_BURST": "1"})
        fe2 = f"127.0.0.1:{fe2_port}"
        _wait_http(fe2, path="/health")

        # burns the single burst token; unremarkable => DROPPED at
        # sample_ratio=0 (tail sampling really drops). The server
        # sends the response BEFORE the root span exits (the tail
        # decision fires at exit), so on a slow box a /v1/traces
        # request can observe the still-in-flight trace — poll until
        # the decision lands: dropped means it vanishes, kept would
        # persist with a finished (duration-stamped) root.
        tid_ok = "cc" * 16
        _sql_traced(fe2, "select 1", f"00-{tid_ok}-{'33' * 8}-01")
        deadline = time.monotonic() + 5.0
        decided_streak = 0
        while True:
            ok_spans = _trace(fe2, tid_ok)
            if ok_spans == []:
                break  # tail-dropped
            # a fully duration-stamped trace is only a KEEP verdict if
            # it PERSISTS: the root stamps end_ms a few statements
            # before the tail decision runs, so a single observation
            # in that window would misread a correct drop
            if all(s["duration_ms"] is not None for s in ok_spans):
                decided_streak += 1
            else:
                decided_streak = 0
            assert decided_streak < 3, (
                "unremarkable trace KEPT at sample_ratio=0", ok_spans,
            )
            assert time.monotonic() < deadline, (
                "trace still undecided after 5s", ok_spans,
            )
            time.sleep(0.05)

        # over-quota => 429, trace KEPT (error survives tail sampling)
        tid_shed = "dd" * 16
        try:
            _sql_traced(fe2, "select 1", f"00-{tid_shed}-{'44' * 8}-01")
            raise AssertionError("expected 429 shed")
        except urllib.error.HTTPError as e:
            assert e.code == 429
        shed_spans = _trace(fe2, tid_shed)
        assert any(
            "error" in s["attributes"] for s in shed_spans
        ), shed_spans
        assert {s["name"] for s in shed_spans} >= {"sched.admit"}

        # deadline expired before execution => 503, trace KEPT
        time.sleep(1.5)  # a fresh qps token for the deadline query
        tid_dl = "ee" * 16
        try:
            _sql_traced(fe2, "select count(*) from cpu",
                        f"00-{tid_dl}-{'55' * 8}-01",
                        params="?timeout=0.000001")
            raise AssertionError("expected 503 deadline")
        except urllib.error.HTTPError as e:
            assert e.code == 503
        dl_spans = _trace(fe2, tid_dl)
        assert any(
            "deadline" in s["attributes"].get("error", "").lower()
            or "Deadline" in s["attributes"].get("error", "")
            for s in dl_spans
        ), dl_spans
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()


def test_dist_statement_statistics_fold_one_row(topology):
    """A distributed query's per-datanode rpc time folds into ONE
    fingerprint row on the FRONTEND: repeated polls of a decomposable
    GROUP BY (fanned over 3 datanode processes) land on a single
    statement_statistics row whose datanode count and rpc_ms reflect
    every fan-out leg, with exec_path=dist."""
    fe = topology["frontend"]
    _sql(fe, "create table cpu (ts timestamp time index, host string "
             "primary key, usage double) with (num_regions = 3)")
    values = ", ".join(
        f"('h{i % 5}', {1_700_000_000_000 + p * 5_000}, {i + p})"
        for p in range(6) for i in range(5)
    )
    _sql(fe, f"insert into cpu (host, ts, usage) values {values}")

    n = 4
    for _ in range(n - 1):
        # identical polls: the repeats hit the datanode scan caches
        doc = _sql(fe, "select host, count(usage), sum(usage) from cpu "
                       "where ts > 0 group by host order by host")
        assert len(_rows(doc)) == 5
    # a different literal is the SAME fingerprint (normalization)
    doc = _sql(fe, "select host, count(usage), sum(usage) from cpu "
                   "where ts > 7 group by host order by host")
    assert len(_rows(doc)) == 5

    doc = _sql(fe, "select calls, datanodes, rpc_ms, exec_path, "
                   "scan_cache_hit_rate from "
                   "information_schema.statement_statistics "
                   "where query like '%count ( usage )%' "
                   "and query like '%where%'")
    rows = _rows(doc)
    assert len(rows) == 1, f"polls must fold into ONE row: {rows}"
    calls, datanodes, rpc_ms, exec_path, sc_rate = rows[0]
    assert calls == n
    # every poll fanned out to all 3 datanode processes
    assert datanodes == 3 * n
    assert rpc_ms > 0.0
    assert exec_path == "dist"
    # repeated identical scans warm the datanode merged-scan caches
    assert sc_rate > 0.0

    # the HTTP face serves the same row
    with urllib.request.urlopen(
        f"http://{fe}/v1/stats/statements?order_by=rpc_ms&limit=1",
        timeout=10,
    ) as resp:
        top = json.loads(resp.read())["statements"][0]
    assert top["datanodes"] == 3 * n
    assert top["exec_path"] == "dist"


@pytest.mark.slow  # tier-1 budget: fleet fan-out gated by
# test_fleet.py::test_wire_fleet_fanout_and_down_degradation
def test_fleet_observability(tmp_path):
    """Fleet observability plane (ISSUE 15) on a REAL wire topology:
    metasrv + 2 datanodes + frontend + flownode, each its own process.
    One frontend SQL poll returns a cluster_node_stats row per live
    node (real addr/uptime/memory from heartbeat payloads); SIGKILL a
    datanode -> its status flips DOWN within the phi window and the
    cluster_* fan-out tables keep answering (degraded, status-marked)
    inside the request deadline; /v1/cluster/metrics federates every
    node's gtpu_* families with node labels."""
    procs = []
    logs = []
    # tightened phi window + heartbeat cadence so the DOWN flip lands
    # in test time, not the production 10s acceptable pause
    fleet_env = {
        "GREPTIMEDB_TPU__METASRV__ACCEPTABLE_PAUSE_MS": "2500",
        "GREPTIMEDB_TPU__FLEET__HEARTBEAT_INTERVAL_S": "0.5",
        "GREPTIMEDB_TPU__FLEET__STATS_INTERVAL_S": "0.5",
    }

    def spawn(args, name):
        log = open(tmp_path / f"{name}.log", "w")
        logs.append(log)
        p = _spawn_env(args, log, fleet_env)
        procs.append(p)
        return p

    try:
        meta_port = _free_port()
        spawn(["metasrv", "start", "--data-home",
               str(tmp_path / "meta"),
               "--metasrv-addr", f"127.0.0.1:{meta_port}",
               "--http-addr", ""], "metasrv")
        _wait_http(f"127.0.0.1:{meta_port}")

        dn_ports = []
        dn_procs = {}
        for i in range(2):
            port = _free_port()
            dn_ports.append(port)
            dn_procs[i] = spawn(
                ["datanode", "start",
                 "--data-home", str(tmp_path / f"dn{i}"),
                 "--flight-addr", f"127.0.0.1:{port}",
                 "--metasrv-addr", f"127.0.0.1:{meta_port}",
                 "--node-id", str(i), "--http-addr", "",
                 "--mysql-addr", "", "--postgres-addr", "",
                 "--no-flows"], f"dn{i}")
        for port in dn_ports:
            _wait_port(port)

        flow_port = _free_port()
        spawn(["flownode", "start",
               "--data-home", str(tmp_path / "flow"),
               "--flight-addr", f"127.0.0.1:{flow_port}",
               "--metasrv-addr", f"127.0.0.1:{meta_port}",
               "--http-addr", "", "--mysql-addr", "",
               "--postgres-addr", ""], "flownode")
        _wait_port(flow_port)

        fe_port = _free_port()
        spawn(["frontend", "start", "--data-home", str(tmp_path / "fe"),
               "--http-addr", f"127.0.0.1:{fe_port}",
               "--metasrv-addr", f"127.0.0.1:{meta_port}",
               "--flownode-addr", f"127.0.0.1:{flow_port}",
               "--mysql-addr", "", "--postgres-addr", "",
               "--flight-addr", ""], "frontend")
        fe = f"127.0.0.1:{fe_port}"
        _wait_http(fe, path="/health")

        # ONE frontend SQL poll eventually returns a row per live node
        # (2 datanodes + flownode + frontend), every one ALIVE with a
        # real addr and uptime carried by its heartbeat payload
        deadline = time.time() + 120
        rows = []
        while time.time() < deadline:
            doc = _sql(fe, "select role, addr, status, uptime_s, "
                           "mem_host_bytes from information_schema."
                           "cluster_node_stats where role != 'metasrv'")
            rows = _rows(doc)
            roles = sorted(r[0] for r in rows
                           if r[2] == "ALIVE" and r[1] and r[3] > 0)
            if roles == ["datanode", "datanode", "flownode",
                         "frontend"]:
                break
            time.sleep(0.5)
        assert sorted(r[0] for r in rows) == [
            "datanode", "datanode", "flownode", "frontend",
        ], rows
        assert all(r[1] and r[2] == "ALIVE" and r[3] > 0
                   for r in rows), rows

        _sql(fe, "create table cpu (ts timestamp time index, host "
                 "string primary key, usage double) "
                 "with (num_regions = 2)")
        _sql(fe, "insert into cpu (host, ts, usage) values "
                 "('h1', 1000, 1.0), ('h2', 2000, 2.0)")

        # region_peers resolves real datanode addrs + detector status
        doc = _sql(fe, "select peer_addr, status from "
                       "information_schema.region_peers")
        peer_rows = _rows(doc)
        assert len(peer_rows) == 2
        assert {a for a, _s in peer_rows} == {
            f"127.0.0.1:{p}" for p in dn_ports
        }
        assert all(s == "ALIVE" for _a, s in peer_rows)

        # cluster fan-out: every peer contributes rows
        doc = _sql(fe, "select distinct peer, peer_status from "
                       "information_schema.cluster_runtime_metrics")
        peers_ok = {p for p, s in _rows(doc) if s == "ok"}
        for port in dn_ports + [flow_port]:
            assert f"127.0.0.1:{port}" in peers_ok

        # federated metrics: every node's gtpu_* families, node-labeled
        with urllib.request.urlopen(
            f"http://{fe}/v1/cluster/metrics", timeout=30
        ) as resp:
            text = resp.read().decode()
        assert "gtpu_fleet_heartbeats_total" in text
        for port in dn_ports + [flow_port]:
            assert f'node="127.0.0.1:{port}"' in text, port
        # deep health: real per-role readiness on the frontend
        with urllib.request.urlopen(
            f"http://{fe}/health?deep=1", timeout=30
        ) as resp:
            hdoc = json.loads(resp.read())
        assert hdoc["status"] == "ok" and hdoc["checks"]

        # SIGKILL one datanode: no shutdown path runs, heartbeats just
        # stop — the phi detector must flip it DOWN within the window
        dn_procs[1].kill()
        dn_procs[1].wait(timeout=10)
        deadline = time.time() + 45
        status = None
        while time.time() < deadline:
            doc = _sql(fe, "select status from information_schema."
                           "cluster_node_stats where peer_id = 1")
            got = _rows(doc)
            status = got[0][0] if got else None
            if status == "DOWN":
                break
            time.sleep(0.5)
        assert status == "DOWN", status

        # fan-out tables degrade to reachable peers + status column,
        # answering inside the request deadline (?timeout= binds it)
        t0 = time.time()
        doc = _sql(fe, "select distinct peer, peer_status from "
                       "information_schema.cluster_runtime_metrics")
        elapsed = time.time() - t0
        got = {p: s for p, s in _rows(doc)}
        assert got[f"127.0.0.1:{dn_ports[0]}"] == "ok"
        assert got[f"127.0.0.1:{flow_port}"] == "ok"
        assert got[f"127.0.0.1:{dn_ports[1]}"] != "ok"
        assert elapsed < 10.0, elapsed

        # federated health reports the dead node, aggregate degraded
        req = urllib.request.Request(f"http://{fe}/v1/cluster/health")
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                hdoc = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            assert e.code == 503
            hdoc = json.loads(e.read())
        assert hdoc["status"] == "degraded"
        dead = [n for n in hdoc["nodes"]
                if n["peer"] == f"127.0.0.1:{dn_ports[1]}"]
        assert dead and dead[0]["status"] == "unreachable"
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()
