"""Distributed kernels over the 8-device CPU mesh: sharded aggregates match
single-device results exactly."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map
import pytest

from greptimedb_tpu.ops import segment as S
from greptimedb_tpu.parallel import dist, mesh as M
from greptimedb_tpu.models import tsbs


@pytest.fixture(scope="module")
def mesh8():
    return M.make_mesh(jax.devices(), time_parallel=2)  # (4 shard, 2 time)


def test_mesh_axes(mesh8):
    assert mesh8.shape == {"shard": 4, "time": 2}


def test_dist_segment_agg_matches_local(mesh8, rng):
    n, g = 1024, 37
    vals = rng.normal(size=n).astype(np.float32)
    seg = rng.integers(0, g, n).astype(np.int32)
    mask = rng.random(n) > 0.15

    sharding = dist.shard_rows_sharding(mesh8)
    dv = jax.device_put(jnp.array(vals), sharding)
    ds = jax.device_put(jnp.array(seg), sharding)
    dm = jax.device_put(jnp.array(mask), sharding)

    for op in ("sum", "count", "min", "max", "mean"):
        got = np.asarray(dist.dist_segment_agg(mesh8, op, g)(dv, ds, dm))
        if op == "sum":
            want = S.seg_sum(jnp.array(vals), jnp.array(seg), jnp.array(mask), g)
        elif op == "count":
            want = S.seg_count(jnp.array(seg), jnp.array(mask), g)
        elif op == "min":
            want = S.seg_min(jnp.array(vals), jnp.array(seg), jnp.array(mask), g)
        elif op == "max":
            want = S.seg_max(jnp.array(vals), jnp.array(seg), jnp.array(mask), g)
        else:
            want = S.seg_mean(jnp.array(vals), jnp.array(seg), jnp.array(mask), g)[0]
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5,
                                   err_msg=op)


def test_halo_exchange_window_sum(mesh8, rng):
    s, t = 16, 64
    halo = 8
    x = rng.random((s, t)).astype(np.float32)
    dx = jax.device_put(
        jnp.array(x), NamedSharding(mesh8, P(M.AXIS_SHARD, M.AXIS_TIME))
    )

    def windowed(xl):
        xh = dist.halo_exchange_prev(xl, halo, M.AXIS_TIME)
        c = jnp.cumsum(xh, axis=1)
        return c[:, halo:] - c[:, :-halo]

    got = np.asarray(shard_map(
        windowed, mesh=mesh8,
        in_specs=P(M.AXIS_SHARD, M.AXIS_TIME),
        out_specs=P(M.AXIS_SHARD, M.AXIS_TIME),
        check_rep=False,
    )(dx))
    c = np.cumsum(np.pad(x, ((0, 0), (halo, 0))), axis=1)
    want = c[:, halo:] - c[:, :-halo]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_dist_topk(mesh8, rng):
    n, k = 256, 7
    vals = rng.normal(size=n).astype(np.float32)
    mask = rng.random(n) > 0.1
    sharding = dist.shard_rows_sharding(mesh8)
    dv = jax.device_put(jnp.array(vals), sharding)
    dm = jax.device_put(jnp.array(mask), sharding)
    top_v, top_i = dist.dist_topk(mesh8, k)(dv, dm)
    masked = np.where(mask, vals, -np.inf)
    want = np.sort(masked)[::-1][:k]
    np.testing.assert_allclose(np.asarray(top_v), want, rtol=1e-6)
    np.testing.assert_array_equal(np.sort(vals[np.asarray(top_i)]),
                                  np.sort(want))


def test_distributed_double_groupby_matches_single(mesh8, rng):
    f, s, t, cpb, k = 3, 32, 48, 12, 5
    fields = rng.random((f, s, t)).astype(np.float32)
    has = rng.random((s, t)) > 0.2

    df = jax.device_put(
        jnp.array(fields),
        NamedSharding(mesh8, P(None, M.AXIS_SHARD, M.AXIS_TIME)),
    )
    dh = jax.device_put(
        jnp.array(has), NamedSharding(mesh8, P(M.AXIS_SHARD, M.AXIS_TIME))
    )
    step = tsbs.build_distributed_query_step(mesh8, t, cpb, k)
    means, top_v, top_i = step(df, dh)

    want_means, _ = tsbs.double_groupby(jnp.array(fields), jnp.array(has), cpb)
    np.testing.assert_allclose(np.asarray(means), np.asarray(want_means),
                               rtol=1e-5)
    score = np.asarray(want_means).sum(axis=(0, 2))
    want_top = np.sort(score)[::-1][:k]
    np.testing.assert_allclose(np.asarray(top_v), want_top, rtol=1e-5)


def test_lastpoint(rng):
    s, t = 10, 30
    vals = rng.random((s, t)).astype(np.float32)
    has = rng.random((s, t)) > 0.5
    tsg = np.broadcast_to(np.arange(t, dtype=np.int32) * 100, (s, t)).copy()
    v, ts, p = tsbs.lastpoint(jnp.array(vals), jnp.array(has), jnp.array(tsg))
    v, ts, p = map(np.asarray, (v, ts, p))
    for i in range(s):
        idx = np.nonzero(has[i])[0]
        if len(idx):
            assert p[i]
            assert v[i] == vals[i, idx[-1]]
            assert ts[i] == tsg[i, idx[-1]]
        else:
            assert not p[i]
