"""Statement statistics: pg_stat_statements for the TPU query path
(telemetry/stmt_stats.py).

Fingerprint normalization stability, device/cache/shed attribution on
the flagship double-groupby shape, cardinality collapse past the knob,
ADMIN reset, and agreement between the three surfaces
(information_schema.statement_statistics, /v1/stats/statements,
gtpu_stmt_* on /metrics).
"""

from __future__ import annotations

import json
import urllib.request

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.session import QueryContext
from greptimedb_tpu.telemetry import stmt_stats as S


@pytest.fixture(autouse=True)
def _fresh_registry():
    """Each test starts from an empty process-wide registry with the
    default config (the registry is process-global by design)."""
    S.configure(None)
    S.global_stmt_stats.reset()
    yield
    S.configure(None)
    S.global_stmt_stats.reset()


@pytest.fixture()
def inst(tmp_path):
    inst = Standalone(str(tmp_path / "data"), prefer_device=False,
                      warm_start=False)
    yield inst
    inst.close()


def _row_for(fp: str, db: str = "public") -> dict | None:
    for doc in S.global_stmt_stats.snapshot():
        if doc["fingerprint"] == fp and doc["schema_name"] == db:
            return doc
    return None


# ---------------------------------------------------------------------------
# fingerprint normalization
# ---------------------------------------------------------------------------

def test_fingerprint_folds_literals_and_in_lists():
    a = S.fingerprint_sql(
        "SELECT ts, avg(v) RANGE '1m' FROM cpu WHERE host IN "
        "('a','b','c') AND ts > 1700000000000 ALIGN '1m' BY (host)"
    )[0]
    b = S.fingerprint_sql(
        "select ts, AVG(v) range '5m' from cpu where host in ('zzz') "
        "and ts > 42 align '5m' by (host)"
    )[0]
    assert a.fp == b.fp
    assert "?" in a.text and "'a'" not in a.text
    # a different SHAPE is a different fingerprint
    c = S.fingerprint_sql(
        "select ts, max(v) range '1m' from cpu align '1m' by (host)"
    )[0]
    assert c.fp != a.fp


def test_fingerprint_collapses_values_rows_and_negatives():
    one = S.fingerprint_sql(
        "insert into t (ts, v) values (1, 2.5)")[0]
    many = S.fingerprint_sql(
        "insert into t (ts, v) values (3, -4.5), (5, 6.5), (7, 8.0)"
    )[0]
    assert one.fp == many.fp
    neg = S.fingerprint_sql("select * from t where v > -5")[0]
    pos = S.fingerprint_sql("select * from t where v > 5")[0]
    assert neg.fp == pos.fp


def test_fingerprint_multi_statement_and_explain_inner():
    fps = S.fingerprint_sql("select 1; select 2; select 'x'")
    assert len(fps) == 3
    assert fps[0].fp == fps[1].fp == fps[2].fp
    exp = S.fingerprint_sql(
        "EXPLAIN ANALYZE SELECT count(v) FROM t WHERE ts > 10")[0]
    plain = S.fingerprint_sql(
        "SELECT count(v) FROM t WHERE ts > 999")[0]
    assert exp.inner_fp == plain.fp
    assert exp.fp != plain.fp
    # strings that do not lex return no fingerprints (parser raises)
    assert S.fingerprint_sql("select 'unterminated") == []


def test_fingerprint_stable_across_whitespace_and_case():
    a = S.fingerprint_sql("SELECT  Count(V)\nFROM  T")[0]
    b = S.fingerprint_sql("select count(v) from t")[0]
    assert a.fp == b.fp
    # quoted identifiers stay case-sensitive
    q1 = S.fingerprint_sql('select "V" from t')[0]
    q2 = S.fingerprint_sql('select "v" from t')[0]
    assert q1.fp != q2.fp


# ---------------------------------------------------------------------------
# attribution on the flagship shape
# ---------------------------------------------------------------------------

def _seed_cpu(inst, hosts=32, cells=64):
    fields = ["usage_user", "usage_system"]
    cols = ", ".join(f"{f} double" for f in fields)
    inst.execute_sql(
        f"create table cpu (ts timestamp time index, "
        f"hostname string primary key, {cols})"
    )
    table = inst.catalog.table("public", "cpu")
    rng = np.random.default_rng(7)
    hostnames = np.asarray([f"host_{i}" for i in range(hosts)],
                           dtype=object)
    ts = np.tile(np.arange(cells, dtype=np.int64) * 10_000, hosts)
    hs = np.repeat(hostnames, cells)
    data = {f: rng.random(len(ts)) * 100.0 for f in fields}
    table.write({"hostname": hs}, ts, data, skip_wal=True)
    table.flush()
    return table


FLAGSHIP = ("SELECT ts, hostname, avg(usage_user) RANGE '1m', "
            "avg(usage_system) RANGE '1m' FROM cpu "
            "ALIGN '1m' BY (hostname)")


def test_device_attribution_one_row_for_repeated_polls(tmp_path):
    """The acceptance shape: a repeatedly-polled dashboard query lands
    on ONE row with device exec path, compile=1/cache-hit>=N-1, and
    non-zero delta-readback bytes on a since-poll."""
    inst = Standalone(str(tmp_path / "dev"), prefer_device=True,
                      warm_start=False)
    try:
        _seed_cpu(inst)
        n = 6
        for _ in range(n):
            assert inst.sql(FLAGSHIP).num_rows > 0
        # delta poll: only the steps past the cursor cross the tunnel
        # (the seeded data spans ~640s => ~11 one-minute align steps;
        # a cursor in the middle leaves a non-empty unseen tail)
        ctx = QueryContext()
        ctx.extensions["since_ms"] = 300_000
        inst.execute_sql(FLAGSHIP, ctx)

        fp = S.fingerprint_sql(FLAGSHIP)[0].fp
        docs = [d for d in S.global_stmt_stats.snapshot()
                if d["fingerprint"] == fp]
        assert len(docs) == 1, "every poll must land on ONE row"
        row = docs[0]
        assert row["calls"] == n + 1
        assert row["exec_path"] == "device"
        assert row["compile_count"] >= 1
        assert row["compile_cache_hits"] >= n - 1
        assert row["readback_full_bytes"] > 0
        assert row["readback_delta_bytes"] > 0
        assert row["session_hit_rate"] > 0.0
        assert row["rows_returned"] > 0
        assert row["p99_ms"] >= row["p50_ms"] >= 0.0
        # the exemplar joins the trace ring
        assert row["last_trace_id"]
        from greptimedb_tpu.telemetry.tracing import global_traces

        assert global_traces.trace(row["last_trace_id"])
    finally:
        inst.close()


def test_result_cache_and_queue_attribution(inst):
    from greptimedb_tpu.query.result_cache import ResultCache

    inst.result_cache = ResultCache(enabled=True)
    inst.catalog.result_cache = inst.result_cache
    inst.execute_sql(
        "create table t (ts timestamp time index, v double)")
    inst.execute_sql("insert into t values (1, 1.0), (2, 2.0)")
    q = "select ts, v from t order by ts"
    for _ in range(4):
        inst.sql(q)
    row = _row_for(S.fingerprint_sql(q)[0].fp)
    assert row is not None
    assert row["calls"] == 4
    # first execution misses, the rest serve from the frontend cache
    assert row["result_cache_hit_rate"] >= 0.5
    # permissive admission still records (near-zero) queue time
    assert row["queue_total_ms"] >= 0.0


def test_shed_and_error_attribution(inst):
    from greptimedb_tpu.errors import QueryOverloadedError
    from greptimedb_tpu.sched import AdmissionController, SchedulerConfig

    inst.execute_sql(
        "create table t (ts timestamp time index, v double)")
    # one-token bucket that refills at 1e-6 qps: the second immediate
    # statement sheds typed
    inst.scheduler = AdmissionController(SchedulerConfig(
        tenant_qps=1e-6, tenant_burst=1.0,
    ))
    q = "select count(v) from t"
    inst.sql(q)
    with pytest.raises(QueryOverloadedError):
        inst.sql(q)
    row = _row_for(S.fingerprint_sql(q)[0].fp)
    assert row["calls"] == 2
    assert row["errors"] == 1
    assert row["errors_by_code"].get(6002) == 1 or \
        row["errors_by_code"].get("6002") == 1
    assert row["shed_count"] == 1
    # a plain table-not-found error lands under its own code (4001)
    inst.scheduler = AdmissionController()
    from greptimedb_tpu.errors import TableNotFoundError

    with pytest.raises(TableNotFoundError):
        inst.sql("select v from no_such_table")
    row = _row_for(S.fingerprint_sql(
        "select v from no_such_table")[0].fp)
    assert row["errors"] == 1
    assert row["shed_count"] == 0


def test_explain_analyze_stamps_inner_fingerprint(inst):
    inst.execute_sql(
        "create table t (ts timestamp time index, v double)")
    inst.execute_sql("insert into t values (1, 1.0)")
    plain = "select count(v) from t"
    res = inst.sql(f"explain analyze {plain}")
    lines = [r[0] for r in res.rows()]
    fp = S.fingerprint_sql(plain)[0].fp
    assert any(f"stmt_fingerprint: {fp}" in ln for ln in lines), lines


def test_slow_query_log_carries_fingerprint(inst):
    from greptimedb_tpu.telemetry.slow_query import SlowQueryLog

    inst.slow_query_log = SlowQueryLog(threshold_s=0.0)
    inst.execute_sql(
        "create table t (ts timestamp time index, v double)")
    q = "select count(v) from t"
    inst.sql(q)
    fp = S.fingerprint_sql(q)[0].fp
    entries = [e for e in inst.slow_query_log.entries()
               if e["query"] == q]
    assert entries and entries[-1]["fingerprint"] == fp
    # the information_schema face joins on the same column
    r = inst.sql("select fingerprint, query from "
                 "information_schema.slow_queries")
    assert [fp, q] in r.rows()


def test_percentiles_count_overflow_observations():
    """Observations past the last histogram bound (60s) must still
    count toward p50/p99 (reported as >= the last bound), not vanish
    — the slowest statements are exactly the rows operators sort by."""
    buckets = [0] * S._N_BUCKETS
    for _ in range(100):
        S._observe_buckets(buckets, 120_000.0)  # 2min, past 60s
    assert sum(buckets) == 100
    assert S._quantile(buckets, 0.50) == S._BUCKETS_MS[-1]
    assert S._quantile(buckets, 0.99) == S._BUCKETS_MS[-1]
    # mixed: half fast, half overflow — p99 lands at the bound, p50
    # inside the fast bucket
    mixed = [0] * S._N_BUCKETS
    for _ in range(50):
        S._observe_buckets(mixed, 1.0)
        S._observe_buckets(mixed, 120_000.0)
    assert S._quantile(mixed, 0.99) == S._BUCKETS_MS[-1]
    assert S._quantile(mixed, 0.50) <= 1.0


# ---------------------------------------------------------------------------
# cardinality collapse + reset
# ---------------------------------------------------------------------------

def test_cardinality_collapse_past_the_knob(inst):
    S.configure({"max_fingerprints": 4, "metric_fingerprints": 2})
    inst.execute_sql(
        "create table t (ts timestamp time index, v double)")
    shapes = [
        "select count(v) from t",
        "select min(v) from t",
        "select max(v) from t",
        "select sum(v) from t",
        "select avg(v) from t",
        "select count(v), min(v) from t",
    ]
    for q in shapes:
        inst.sql(q)
    docs = S.global_stmt_stats.snapshot()
    assert len(docs) <= 4
    other = _row_for(S.OTHER)
    assert other is not None, "evicted rows must collapse into _other"
    total_calls = sum(d["calls"] for d in docs)
    # 1 create + 6 selects: totals survive the collapse
    assert total_calls == 1 + len(shapes)
    assert S.global_stmt_stats.evicted_rows > 0


def test_admin_reset_statement_statistics(inst):
    inst.execute_sql(
        "create table t (ts timestamp time index, v double)")
    inst.sql("select count(v) from t")
    assert len(S.global_stmt_stats.snapshot()) >= 2
    res = inst.sql("admin reset_statement_statistics()")
    assert res.rows()[0][0] >= 2
    # only the reset statement itself (recorded after the wipe) remains
    docs = S.global_stmt_stats.snapshot()
    assert all(d["query"].startswith("admin") for d in docs)


def test_disabled_registry_records_nothing(inst):
    S.configure({"enable": False})
    inst.execute_sql(
        "create table t (ts timestamp time index, v double)")
    inst.sql("select count(v) from t")
    assert S.global_stmt_stats.snapshot() == []


# ---------------------------------------------------------------------------
# surface agreement: information_schema == HTTP == /metrics
# ---------------------------------------------------------------------------

def _http_get(port: int, path: str) -> bytes:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as resp:
        return resp.read()


def test_surfaces_agree(inst):
    from greptimedb_tpu.servers.http import HttpServer

    inst.execute_sql(
        "create table t (ts timestamp time index, v double)")
    inst.execute_sql("insert into t values (1, 1.0), (2, 2.0)")
    q = "select ts, v from t where ts > 0"
    n = 3
    for _ in range(n):
        inst.sql(q)
    fp = S.fingerprint_sql(q)[0].fp

    srv = HttpServer(inst, port=0).start()
    try:
        # 1. information_schema
        r = inst.sql(
            "select calls, rows_returned from "
            "information_schema.statement_statistics "
            f"where fingerprint = '{fp}'"
        )
        assert r.rows() == [[n, 2 * n]]

        # 2. HTTP endpoint, ordered + bounded
        doc = json.loads(_http_get(
            srv.port, "/v1/stats/statements?order_by=calls&limit=1"
        ))
        assert len(doc["statements"]) == 1
        top = doc["statements"][0]
        assert top["fingerprint"] == fp
        assert top["calls"] == n
        # order_by=calls really ordered
        full = json.loads(_http_get(
            srv.port, "/v1/stats/statements?order_by=calls"
        ))["statements"]
        calls = [d["calls"] for d in full]
        assert calls == sorted(calls, reverse=True)
        # bad limit is a client error
        import urllib.error

        with pytest.raises(urllib.error.HTTPError):
            _http_get(srv.port, "/v1/stats/statements?limit=zzz")

        # 3. /metrics: the same calls under the fingerprint label
        metrics = _http_get(srv.port, "/metrics").decode()
        line = next(
            ln for ln in metrics.splitlines()
            if ln.startswith("gtpu_stmt_calls_total")
            and f'fingerprint="{fp}"' in ln
        )
        assert float(line.rsplit(" ", 1)[1]) == float(n)
        # runtime_metrics (information_schema face of /metrics) agrees
        r = inst.sql(
            "select value from information_schema.runtime_metrics "
            f"where metric_name = 'gtpu_stmt_calls_total' "
            f"and labels like '%{fp}%'"
        )
        assert r.rows() == [[float(n)]]
    finally:
        srv.stop()


def test_metric_label_cardinality_collapses_to_other(inst):
    # configure() re-derives the label grant set under the new cap;
    # earlier tests' prometheus series persist, so measure the DELTA
    # of the _other series instead of its absolute value
    S.configure({"max_fingerprints": 64, "metric_fingerprints": 1})
    from greptimedb_tpu.telemetry.metrics import global_registry

    def other_calls() -> float:
        # the gtpu_stmt_* families are PULL-model: values refresh on
        # render (a scrape), not per statement
        global_registry.render()
        return global_registry.get(
            "gtpu_stmt_calls_total").labels("public", S.OTHER).value

    other0 = other_calls()
    inst.execute_sql(
        "create table t (ts timestamp time index, v double)")
    inst.sql("select count(v) from t")
    inst.sql("select min(v) from t")
    # at most one of the three statements got a real label; the rest
    # collapsed to _other
    assert other_calls() - other0 >= 2
