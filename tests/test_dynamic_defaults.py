"""Dynamic column DEFAULTs (now()/current_timestamp()) evaluate per
insert, not at CREATE time; INSERT..SELECT fills defaults too.
(Reference: src/datatypes/src/schema/column_schema.rs
ColumnDefaultConstraint::Function.)"""

import time

from greptimedb_tpu.instance import Standalone


def test_dynamic_default_evaluates_per_insert(tmp_path):
    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table t (ts timestamp time index, "
            "created timestamp default now(), n bigint)"
        )
        inst.execute_sql("insert into t (ts, n) values (5000, 1)")
        time.sleep(1.05)
        inst.execute_sql("insert into t (ts, n) values (6000, 2)")
        r = inst.sql("select created from t order by ts").rows()
        assert r[1][0] - r[0][0] >= 1000, r
        # survives restart (persisted as an expression, not a constant)
        inst.close()
        inst2 = Standalone(str(tmp_path / "d"), prefer_device=False,
                           warm_start=False)
        try:
            before = int(time.time() * 1000)
            inst2.execute_sql("insert into t (ts, n) values (7000, 3)")
            r = inst2.sql("select created from t where ts = 7000").rows()
            assert r[0][0] >= before - 1000
        finally:
            inst2.close()
    finally:
        try:
            inst.close()
        except Exception:
            pass


def test_default_expression_roundtrip_precedence(tmp_path):
    """Grouped arithmetic and CASE in a dynamic DEFAULT survive the
    persist/parse round trip with precedence intact."""
    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table t (ts timestamp time index, "
            "d bigint default (now() - 0) / 1000, "
            "c bigint default case when 1=1 then now() else 0 end)"
        )
        inst.execute_sql("insert into t (ts) values (1000)")
        d, c = inst.sql("select d, c from t").rows()[0]
        assert abs(d - time.time()) < 10            # seconds, not ms
        assert abs(c - time.time() * 1000) < 10_000  # CASE re-evaluated
        # survives restart through the catalog JSON
        inst.close()
        inst2 = Standalone(str(tmp_path / "d"), prefer_device=False,
                           warm_start=False)
        try:
            inst2.execute_sql("insert into t (ts) values (2000)")
            d2 = inst2.sql("select d from t where ts = 2000").rows()[0][0]
            assert abs(d2 - time.time()) < 10
        finally:
            inst2.close()
    finally:
        try:
            inst.close()
        except Exception:
            pass


def test_time_index_default_current_timestamp(tmp_path):
    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table t (ts timestamp time index default "
            "current_timestamp(), n bigint)"
        )
        inst.execute_sql("insert into t (n) values (7)")
        r = inst.sql("select n, ts from t").rows()
        assert r[0][0] == 7 and r[0][1] > 0
    finally:
        inst.close()


def test_insert_select_fills_defaults(tmp_path):
    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table src (ts timestamp time index, n bigint)"
        )
        inst.execute_sql("insert into src values (1000, 1)")
        inst.execute_sql(
            "create table dst (ts timestamp time index, "
            "level string default 'info', n bigint)"
        )
        inst.execute_sql("insert into dst (ts, n) select ts, n from src")
        assert inst.sql("select level from dst").rows() == [["info"]]
    finally:
        inst.close()


def test_show_create_table_includes_defaults(tmp_path):
    """ADVICE r3 (medium): SHOW CREATE TABLE must carry DEFAULT clauses
    (literal + dynamic), or cli export -> import silently drops them."""
    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table t (ts timestamp time index default "
            "current_timestamp(), level string default 'info', "
            "n bigint default 7, note string)"
        )
        ddl = inst.sql("show create table t").rows()[0][1]
        assert "DEFAULT current_timestamp()" in ddl
        assert "DEFAULT 'info'" in ddl
        assert "DEFAULT 7" in ddl
        assert "`note` STRING DEFAULT" not in ddl
        # SHOW COLUMNS agrees with DESCRIBE on the Default column
        r = inst.sql("show columns from t")
        by_name = dict(zip(r.cols[0].values, r.cols[4].values))
        assert by_name["level"] == "info"
        assert by_name["n"] == "7"
    finally:
        inst.close()


def test_export_import_preserves_defaults(tmp_path):
    from greptimedb_tpu.tools import export_data, import_data

    src = str(tmp_path / "src")
    inst = Standalone(src, prefer_device=False, warm_start=False)
    inst.execute_sql(
        "create table logs (ts timestamp time index, "
        "level string default 'info', n bigint)"
    )
    inst.execute_sql("insert into logs values (1000, 'warn', 1)")
    inst.close()
    export_data(src, str(tmp_path / "dump"))
    import_data(str(tmp_path / "dst"), str(tmp_path / "dump"))

    inst2 = Standalone(str(tmp_path / "dst"), prefer_device=False,
                       warm_start=False)
    try:
        inst2.execute_sql("insert into logs (ts, n) values (2000, 2)")
        r = inst2.sql("select level from logs order by ts").rows()
        assert [x[0] for x in r] == ["warn", "info"]
    finally:
        inst2.close()


def test_placeholders_inside_comments_not_counted():
    """ADVICE r3 (low): '?' inside -- or /* */ comments must not count
    as a COM_STMT_PREPARE parameter."""
    from greptimedb_tpu.instance import (
        count_placeholders,
        substitute_placeholders,
    )

    sql = ("select * from t -- what? really?\n"
           "where a = ? /* and b = ? */ and c = ?")
    assert count_placeholders(sql) == 2
    out = substitute_placeholders(sql, [1, 2])
    assert "a = 1" in out and "c = 2" in out
    assert "what? really?" in out and "/* and b = ? */" in out
