"""Dynamic column DEFAULTs (now()/current_timestamp()) evaluate per
insert, not at CREATE time; INSERT..SELECT fills defaults too.
(Reference: src/datatypes/src/schema/column_schema.rs
ColumnDefaultConstraint::Function.)"""

import time

from greptimedb_tpu.instance import Standalone


def test_dynamic_default_evaluates_per_insert(tmp_path):
    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table t (ts timestamp time index, "
            "created timestamp default now(), n bigint)"
        )
        inst.execute_sql("insert into t (ts, n) values (5000, 1)")
        time.sleep(1.05)
        inst.execute_sql("insert into t (ts, n) values (6000, 2)")
        r = inst.sql("select created from t order by ts").rows()
        assert r[1][0] - r[0][0] >= 1000, r
        # survives restart (persisted as an expression, not a constant)
        inst.close()
        inst2 = Standalone(str(tmp_path / "d"), prefer_device=False,
                           warm_start=False)
        try:
            before = int(time.time() * 1000)
            inst2.execute_sql("insert into t (ts, n) values (7000, 3)")
            r = inst2.sql("select created from t where ts = 7000").rows()
            assert r[0][0] >= before - 1000
        finally:
            inst2.close()
    finally:
        try:
            inst.close()
        except Exception:
            pass


def test_default_expression_roundtrip_precedence(tmp_path):
    """Grouped arithmetic and CASE in a dynamic DEFAULT survive the
    persist/parse round trip with precedence intact."""
    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table t (ts timestamp time index, "
            "d bigint default (now() - 0) / 1000, "
            "c bigint default case when 1=1 then now() else 0 end)"
        )
        inst.execute_sql("insert into t (ts) values (1000)")
        d, c = inst.sql("select d, c from t").rows()[0]
        assert abs(d - time.time()) < 10            # seconds, not ms
        assert abs(c - time.time() * 1000) < 10_000  # CASE re-evaluated
        # survives restart through the catalog JSON
        inst.close()
        inst2 = Standalone(str(tmp_path / "d"), prefer_device=False,
                           warm_start=False)
        try:
            inst2.execute_sql("insert into t (ts) values (2000)")
            d2 = inst2.sql("select d from t where ts = 2000").rows()[0][0]
            assert abs(d2 - time.time()) < 10
        finally:
            inst2.close()
    finally:
        try:
            inst.close()
        except Exception:
            pass


def test_time_index_default_current_timestamp(tmp_path):
    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table t (ts timestamp time index default "
            "current_timestamp(), n bigint)"
        )
        inst.execute_sql("insert into t (n) values (7)")
        r = inst.sql("select n, ts from t").rows()
        assert r[0][0] == 7 and r[0][1] > 0
    finally:
        inst.close()


def test_insert_select_fills_defaults(tmp_path):
    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table src (ts timestamp time index, n bigint)"
        )
        inst.execute_sql("insert into src values (1000, 1)")
        inst.execute_sql(
            "create table dst (ts timestamp time index, "
            "level string default 'info', n bigint)"
        )
        inst.execute_sql("insert into dst (ts, n) select ts, n from src")
        assert inst.sql("select level from dst").rows() == [["info"]]
    finally:
        inst.close()
