"""File engine: CREATE EXTERNAL TABLE over CSV/JSON/Parquet
(VERDICT missing #8)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from greptimedb_tpu.errors import UnsupportedError
from greptimedb_tpu.instance import Standalone


@pytest.fixture()
def inst(tmp_path):
    s = Standalone(str(tmp_path / "data"))
    yield s
    s.close()


def test_external_csv(inst, tmp_path):
    p = tmp_path / "m.csv"
    p.write_text(
        "host,v,ts\n"
        "a,1.5,1000\n"
        "b,2.5,2000\n"
        "a,3.5,3000\n"
    )
    inst.sql(
        f"CREATE EXTERNAL TABLE ext (host STRING, v DOUBLE, "
        f"ts TIMESTAMP TIME INDEX, PRIMARY KEY (host)) "
        f"WITH (location = '{p}', format = 'csv')"
    )
    r = inst.sql("SELECT host, v FROM ext ORDER BY host, v")
    assert [list(x) for x in r.rows()] == [
        ["a", 1.5], ["a", 3.5], ["b", 2.5],
    ]
    # aggregates + RANGE work through the normal engine
    r = inst.sql("SELECT host, sum(v) FROM ext GROUP BY host "
                 "ORDER BY host")
    assert [list(x) for x in r.rows()] == [["a", 5.0], ["b", 2.5]]
    # read-only
    with pytest.raises(UnsupportedError):
        inst.sql("INSERT INTO ext (host, v, ts) VALUES ('c', 1.0, 1)")

    # survives restart (file re-read at open)
    inst2 = Standalone(str(inst.engine.config.data_root))
    try:
        r = inst2.sql("SELECT count(*) FROM ext")
        assert int(r.rows()[0][0]) == 3
    finally:
        inst2.close()


def test_external_parquet_and_json(inst, tmp_path):
    pqp = tmp_path / "m.parquet"
    pq.write_table(pa.table({
        "host": ["x", "y"],
        "v": [10.0, 20.0],
        "ts": pa.array(np.asarray([1000, 2000], np.int64),
                       pa.timestamp("ms")),
    }), pqp)
    inst.sql(
        f"CREATE EXTERNAL TABLE extp (host STRING, v DOUBLE, "
        f"ts TIMESTAMP TIME INDEX, PRIMARY KEY (host)) "
        f"WITH (location = '{pqp}', format = 'parquet')"
    )
    r = inst.sql("SELECT host, v FROM extp ORDER BY host")
    assert [list(x) for x in r.rows()] == [["x", 10.0], ["y", 20.0]]

    jp = tmp_path / "m.json"
    jp.write_text(
        '{"host": "j1", "v": 5.0, "ts": 1000}\n'
        '{"host": "j2", "ts": 2000}\n'   # missing v -> NULL
    )
    inst.sql(
        f"CREATE EXTERNAL TABLE extj (host STRING, v DOUBLE, "
        f"ts TIMESTAMP TIME INDEX, PRIMARY KEY (host)) "
        f"WITH (location = '{jp}', format = 'json')"
    )
    r = inst.sql("SELECT host, v FROM extj ORDER BY host")
    rows = [list(x) for x in r.rows()]
    assert rows[0] == ["j1", 5.0]
    assert rows[1][1] is None


def test_missing_file_does_not_break_catalog(inst, tmp_path):
    """A vanished external file must not take down the whole catalog at
    restart: other tables stay queryable, the broken one errors."""
    p = tmp_path / "gone.csv"
    p.write_text("host,v,ts\na,1.0,1000\n")
    inst.sql(
        f"CREATE EXTERNAL TABLE willbreak (host STRING, v DOUBLE, "
        f"ts TIMESTAMP TIME INDEX, PRIMARY KEY (host)) "
        f"WITH (location = '{p}', format = 'csv')"
    )
    inst.sql("CREATE TABLE healthy (v DOUBLE, ts TIMESTAMP TIME INDEX)")
    inst.sql("INSERT INTO healthy (v, ts) VALUES (1.0, 1)")
    p.unlink()
    inst2 = Standalone(str(inst.engine.config.data_root))
    try:
        r = inst2.sql("SELECT count(*) FROM healthy")
        assert int(r.rows()[0][0]) == 1
        from greptimedb_tpu.errors import GreptimeError

        with pytest.raises(GreptimeError):
            inst2.sql("SELECT * FROM willbreak")
    finally:
        inst2.close()


def test_external_missing_location_rejected(inst):
    from greptimedb_tpu.errors import InvalidArgumentError

    with pytest.raises(InvalidArgumentError):
        inst.sql(
            "CREATE EXTERNAL TABLE bad (v DOUBLE, ts TIMESTAMP TIME "
            "INDEX) WITH (format = 'csv')"
        )
