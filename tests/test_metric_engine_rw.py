"""Prometheus remote write rides the metric engine: many logical metric
tables over ONE shared physical table (reference:
src/metric-engine/src/engine.rs:60-115 — "backs Prometheus remote-write
tables")."""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.metric_engine import PHYSICAL_TABLE
from greptimedb_tpu.servers.prom_store import apply_series


@pytest.fixture()
def inst(tmp_path):
    inst = Standalone(str(tmp_path / "data"), prefer_device=False,
                      warm_start=False)
    yield inst
    inst.close()


def _write_metrics(inst, n_metrics: int, t0: int = 0):
    series = []
    for m in range(n_metrics):
        series.append((
            {"__name__": f"metric_{m}", "host": f"h{m % 3}"},
            [(float(m), t0 + 1000), (float(m) + 0.5, t0 + 2000)],
        ))
    return apply_series(inst, series, db="public")


def test_many_metrics_share_one_physical_table(inst, tmp_path):
    n = _write_metrics(inst, 20)
    assert n == 40
    # every metric is a logical table ...
    for m in (0, 7, 19):
        t = inst.catalog.table("public", f"metric_{m}")
        assert t.info.engine == "metric"
    # ... over ONE physical region set (not 20 tables x regions)
    phys = inst.catalog.table("public", PHYSICAL_TABLE)
    region_count = sum(
        1 for r in inst.engine.regions()
    )
    assert region_count == len(phys.regions)
    # logical reads are isolated per metric
    r = inst.sql("select greptime_value from metric_7 order by ts")
    assert [float(x) for x in r.cols[0].values] == [7.0, 7.5]
    # and the physical table holds everything
    r = inst.sql(
        f"select count(greptime_value) from {PHYSICAL_TABLE}"
    )
    assert r.cols[0].values[0] == 40


def test_new_label_widens_physical(inst):
    _write_metrics(inst, 2)
    # same metric reappears with a new label
    apply_series(inst, [(
        {"__name__": "metric_0", "host": "h0", "dc": "west"},
        [(9.0, 5000)],
    )], db="public")
    r = inst.sql(
        "select dc, greptime_value from metric_0 where dc != '' "
    )
    assert r.rows() == [["west", 9.0]]
    phys = inst.catalog.table("public", PHYSICAL_TABLE)
    assert phys.schema.maybe_column("dc") is not None


def test_metric_tables_survive_restart(tmp_path, inst):
    _write_metrics(inst, 5)
    apply_series(inst, [(
        {"__name__": "metric_1", "host": "h9", "zone": "z1"},
        [(42.0, 9000)],
    )], db="public")
    inst.catalog.table("public", PHYSICAL_TABLE).flush()
    inst.close()
    inst2 = Standalone(str(tmp_path / "data"), prefer_device=False,
                       warm_start=False)
    try:
        r = inst2.sql(
            "select greptime_value from metric_1 where zone = 'z1'"
        )
        assert [float(x) for x in r.cols[0].values] == [42.0]
        t = inst2.catalog.table("public", "metric_1")
        assert t.info.engine == "metric"
        assert t.schema.maybe_column("zone") is not None
    finally:
        inst2.close()


def test_discovery_apis_hide_internals(inst):
    """__table_id and the shared physical table never surface through
    the Prometheus discovery APIs or remote read."""
    import json
    import urllib.request

    from greptimedb_tpu.servers.http import HttpServer

    _write_metrics(inst, 2)
    srv = HttpServer(inst, port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}/v1/prometheus/api/v1"

        def get(p):
            with urllib.request.urlopen(base + p, timeout=30) as r:
                return json.load(r)["data"]

        assert get("/labels") == ["__name__", "host"]
        names = get("/label/__name__/values")
        assert PHYSICAL_TABLE not in names
        assert set(names) == {"metric_0", "metric_1"}
        # match[]-scoped label values are isolated per metric
        vals = get("/label/host/values?match[]=metric_1")
        assert vals == ["h1"]
        assert PHYSICAL_TABLE not in get("/metadata")
    finally:
        srv.stop()


def test_alter_collision_leaves_schema_unchanged(inst):
    _write_metrics(inst, 2)
    inst.execute_sql("alter table metric_0 add column foo double")
    with pytest.raises(Exception):
        inst.execute_sql(
            "alter table metric_1 add column foo string primary key"
        )
    t = inst.catalog.table("public", "metric_1")
    assert t.schema.maybe_column("foo") is None
    # ingest for every metric still works
    assert _write_metrics(inst, 2, t0=60_000) == 4


def test_promql_over_metric_engine(inst):
    _write_metrics(inst, 3, t0=1_700_000_000_000)
    from greptimedb_tpu.promql.engine import PromEngine

    engine = PromEngine(inst)
    val, ev = engine.query_instant(
        "metric_2", 1_700_000_000_000 + 2000
    )
    samples = [(lab.get("host"), v) for lab, v, *_ in _to_pairs(val, ev)]
    assert samples == [("h2", 2.5)]


def _to_pairs(val, ev):
    from greptimedb_tpu.promql.engine import _to_vector

    v = _to_vector(val, ev)
    out = []
    for i, lab in enumerate(v.labels):
        out.append((lab, float(np.asarray(v.values[i]).reshape(()))))
    return out
