"""Chaos tier: crash processes and procedures mid-flight, assert
recovery to query-equality.

The fuzz-shaped counterpart of the reference's unstable/migration fuzz
targets (/root/reference/tests-fuzz/targets/unstable/
fuzz_create_table_standalone.rs, targets/migration/
fuzz_migrate_mito_regions.rs): region migrations crash at every
persisted step and must resume or roll back to a consistent, fully
queryable cluster; datanode crashes mid-write must lose nothing that
was acknowledged.
"""

import time

import numpy as np
import pytest

from greptimedb_tpu.cluster import Cluster
from greptimedb_tpu.datatypes.schema import (
    ColumnSchema,
    Schema,
    SemanticType,
)
from greptimedb_tpu.datatypes.types import ConcreteDataType as T
from greptimedb_tpu.meta.metasrv import RegionMigrationProcedure
from greptimedb_tpu.meta.procedure import PROC_PREFIX


def _schema():
    return Schema([
        ColumnSchema("ts", T.timestamp_millisecond(),
                     SemanticType.TIMESTAMP, nullable=False),
        ColumnSchema("host", T.string(), SemanticType.TAG,
                     nullable=False),
        ColumnSchema("v", T.float64(), SemanticType.FIELD),
    ])


def _write(table, base: int, n: int):
    hosts = np.asarray([f"h{(base + i) % 7}" for i in range(n)], object)
    ts = np.asarray([1_700_000_000_000 + (base + i) * 1000
                     for i in range(n)], np.int64)
    table.write({"host": hosts}, ts,
                {"v": np.asarray([float(base + i) for i in range(n)])})


def _count_sum(table):
    data = table.scan(field_names=["v"])
    if data.rows is None:
        return 0, 0.0
    return len(data.rows), float(data.rows.fields["v"].sum())


def _wait_procedures(metasrv, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        metas = metasrv.procedures.list_procedures()
        if all(m.state != "running" for m in metas):
            return metas
        time.sleep(0.05)
    raise TimeoutError("procedures never settled")


@pytest.mark.parametrize("crash_step", [0, 1, 2, 3])
def test_migration_crashes_at_every_persisted_step(tmp_path, crash_step):
    """Run a region migration up to `crash_step` persisted states, kill
    the whole cluster (metasrv included), rebuild over the same kv +
    shared store, and require: the resumed procedure settles, routes
    point at live regions, and every row is still queryable."""
    root = str(tmp_path / "c")
    c = Cluster(root, n_datanodes=3, shared_wal=True)
    table = c.create_table("public", "t", _schema(), num_regions=3)
    _write(table, 0, 60)
    rid = table.info.region_ids()[0]
    src = c.metasrv.route_of(rid)
    dst = next(n for n in c.datanodes if n != src)

    proc = RegionMigrationProcedure(region_id=rid, from_node=src,
                                    to_node=dst)
    for _ in range(crash_step):
        proc.execute(c.metasrv)
    # persist mid-flight state exactly as the manager would, then crash
    c.kv.put_json(PROC_PREFIX + "fuzzmig", {
        "type_name": RegionMigrationProcedure.type_name,
        "state": "running",
        "data": proc.dump(),
    })
    c.shutdown()

    c2 = Cluster(root, n_datanodes=3, shared_wal=True)  # recovers procs
    metas = _wait_procedures(c2.metasrv)
    assert metas, "the persisted migration must be resumed"
    assert all(m.state in ("done", "failed", "rolled_back")
               for m in metas)
    # whatever the outcome, the cluster must serve ALL the data
    cnt, s = _count_sum(c2.table("public", "t"))
    assert cnt == 60 and s == float(sum(range(60)))
    # the region's route points at a node that actually has it
    owner = c2.metasrv.route_of(rid)
    assert c2.datanodes[owner].has_region(rid)
    c2.shutdown()


def test_migration_fuzz_rounds(tmp_path):
    """Randomized write/migrate/crash rounds (the migration fuzz target):
    every round interleaves writes with a migration that may crash at a
    random persisted step, then rebuilds and checks the oracle."""
    rng = np.random.default_rng(11)
    root = str(tmp_path / "c")
    c = Cluster(root, n_datanodes=3, shared_wal=True)
    table = c.create_table("public", "t", _schema(), num_regions=3)
    total = 0
    for round_no in range(6):
        _write(c.table("public", "t"), total, 20)
        total += 20
        rid = int(rng.choice(table.info.region_ids()))
        src = c.metasrv.route_of(rid)
        choices = [n for n in c.datanodes if n != src]
        dst = int(rng.choice(choices))
        crash_step = int(rng.integers(0, 5))
        if crash_step >= 4:
            # clean migration, no crash (raises unless it completes)
            c.metasrv.migrate_region(rid, dst)
        else:
            proc = RegionMigrationProcedure(
                region_id=rid, from_node=src, to_node=dst
            )
            for _ in range(crash_step):
                proc.execute(c.metasrv)
            c.kv.put_json(PROC_PREFIX + f"mig{round_no}", {
                "type_name": RegionMigrationProcedure.type_name,
                "state": "running",
                "data": proc.dump(),
            })
            c.shutdown()
            c = Cluster(root, n_datanodes=3, shared_wal=True)
            _wait_procedures(c.metasrv)
        cnt, s = _count_sum(c.table("public", "t"))
        assert cnt == total, f"round {round_no}: {cnt} != {total}"
        assert s == float(sum(range(total))), f"round {round_no}"
    c.shutdown()


def test_crash_failover_write_fuzz(tmp_path):
    """Random datanode crashes under continuous writes with supervisor
    failover (shared WAL): acknowledged writes always survive."""
    rng = np.random.default_rng(13)
    c = Cluster(str(tmp_path / "c"), n_datanodes=3, shared_wal=True,
                phi_threshold=3.0)
    table = c.create_table("public", "t", _schema(), num_regions=3)
    t0 = 1_000_000.0
    tick = 0

    def beat(n):
        nonlocal tick
        for _ in range(n):
            c.heartbeat_all(t0 + tick * 1000)
            tick += 1

    total = 0
    beat(10)
    for round_no in range(4):
        _write(c.table("public", "t"), total, 15)
        total += 15
        if round_no in (1, 2):
            alive = [n for n, d in c.datanodes.items() if d.alive]
            if len(alive) > 2:
                victim = int(rng.choice(alive))
                c.datanodes[victim].crash()
                beat(14)
                procs = c.supervise(t0 + tick * 1000)
                for pid in procs:
                    c.metasrv.procedures.wait(pid)
        beat(4)
        cnt, s = _count_sum(c.table("public", "t"))
        assert cnt == total, f"round {round_no}: {cnt} != {total}"
        assert s == float(sum(range(total)))
    c.shutdown()


def test_blackholed_datanode_bounds_query_under_admission(tmp_path):
    """Blackhole (hang, not kill) a datanode mid-query under admission
    control: the client gets a typed partial result (allow_partial) or
    the typed deadline error within the deadline — NEVER a hang, and
    no leaked threads (the gtsan plugin enforces leak-freedom when
    this runs under GTPU_SAN=1)."""
    import pytest

    pytest.importorskip("pyarrow.flight")
    import threading

    from test_dist_cluster import DistHarness

    from greptimedb_tpu.errors import QueryDeadlineExceededError
    from greptimedb_tpu.sched import AdmissionController, SchedulerConfig
    from greptimedb_tpu.session import QueryContext

    h = DistHarness(tmp_path, n_datanodes=2)
    release = threading.Event()
    try:
        h.frontend.execute_sql(
            "create table t (ts timestamp time index, host string "
            "primary key, v double) with (num_regions = 3)"
        )
        vals = ", ".join(
            f"('h{i % 6}', {1_700_000_000_000 + i * 1000}, {float(i)})"
            for i in range(60)
        )
        h.frontend.execute_sql(f"insert into t (host, ts, v) values {vals}")
        full = float(h.frontend.sql("select sum(v) from t")
                     .cols[0].values[0])
        assert full == float(sum(range(60)))

        # blackhole datanode 0: its scans park on an event instead of
        # answering — the socket stays open, so only the DEADLINE can
        # bound the query (the unavailable/refused case is covered by
        # tests/test_sched.py::test_partial_result_when_datanode_dies)
        rs0 = h.datanodes[0][0].region_server
        real_scan_entry = rs0.scan_entry

        def blackholed_scan_entry(*args, **kwargs):
            release.wait(30)   # far beyond the query deadline
            return real_scan_entry(*args, **kwargs)

        rs0.scan_entry = blackholed_scan_entry

        # 1) graceful degradation on: typed partial within the deadline
        h.frontend.scheduler = AdmissionController(SchedulerConfig(
            default_deadline_s=2.0, allow_partial_results=True,
        ))
        t0 = time.time()
        res = h.frontend.sql("select sum(v) from t")
        elapsed = time.time() - t0
        assert elapsed < 10.0, f"query not bounded: {elapsed:.1f}s"
        assert getattr(res, "partial", False) is True
        assert res.missing_regions >= 1
        assert float(res.cols[0].values[0]) < full

        # 2) degradation off: the TYPED deadline error, still bounded
        h.frontend.scheduler = AdmissionController(SchedulerConfig(
            default_deadline_s=2.0, allow_partial_results=False,
        ))
        t0 = time.time()
        with pytest.raises(QueryDeadlineExceededError):
            h.frontend.sql("select sum(v) from t")
        assert time.time() - t0 < 10.0

        # 3) un-blackhole: the same instance fully recovers
        release.set()
        rs0.scan_entry = real_scan_entry
        res = h.frontend.sql("select sum(v) from t")
        assert float(res.cols[0].values[0]) == full
        assert not getattr(res, "partial", False)
    finally:
        release.set()   # unpark any handler still waiting
        h.close()


def test_process_kill_mid_write_wal_replay(tmp_path):
    """SIGKILL a datanode OS process during ingest; restart it with the
    same data-home. Every ACKNOWLEDGED insert must be queryable after
    WAL replay (durability >= ack; unacked rows may also survive)."""
    import signal
    import subprocess
    import urllib.error

    from test_dist_processes import (
        _free_port,
        _rows,
        _spawn,
        _sql,
        _wait_http,
        _wait_port,
    )

    procs, logs = [], []

    def spawn(args, name):
        log = open(tmp_path / f"{name}.log", "w")
        logs.append(log)
        p = _spawn(args, log)
        procs.append(p)
        return p

    try:
        meta_port = _free_port()
        spawn(["metasrv", "start", "--data-home",
               str(tmp_path / "meta"),
               "--metasrv-addr", f"127.0.0.1:{meta_port}",
               "--http-addr", ""], "metasrv")
        _wait_http(f"127.0.0.1:{meta_port}")
        dn_ports = [_free_port(), _free_port()]

        def dn_args(i):
            return ["datanode", "start",
                    "--data-home", str(tmp_path / f"dn{i}"),
                    "--flight-addr", f"127.0.0.1:{dn_ports[i]}",
                    "--metasrv-addr", f"127.0.0.1:{meta_port}",
                    "--node-id", str(i), "--http-addr", "",
                    "--mysql-addr", "", "--postgres-addr", "",
                    "--no-flows"]

        dn_procs = [spawn(dn_args(i), f"dn{i}") for i in range(2)]
        for port in dn_ports:
            _wait_port(port)
        import json as _json
        import urllib.request

        deadline = time.time() + 60
        while time.time() < deadline:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{meta_port}/peers", timeout=2
            ) as resp:
                if len(_json.loads(resp.read())) >= 2:
                    break
            time.sleep(0.2)
        fe_port = _free_port()
        spawn(["frontend", "start", "--data-home", str(tmp_path / "fe"),
               "--http-addr", f"127.0.0.1:{fe_port}",
               "--metasrv-addr", f"127.0.0.1:{meta_port}",
               "--mysql-addr", "", "--postgres-addr", "",
               "--flight-addr", ""], "frontend")
        fe = f"127.0.0.1:{fe_port}"
        _wait_http(fe)

        _sql(fe, "create table t (ts timestamp time index, host string "
                 "primary key, v double) with (num_regions = 2)")
        acked: list[tuple[str, int]] = []
        killed = False
        for batch in range(16):
            host = f"h{batch % 4}"   # one host -> one region: atomic
            ts = 1_700_000_000_000 + batch * 1000
            try:
                _sql(fe, f"insert into t (host, ts, v) values "
                         f"('{host}', {ts}, {float(batch)})", timeout=10)
                acked.append((host, ts))
            except (urllib.error.URLError, OSError, Exception):
                pass  # unacked: may or may not survive
            if batch == 7 and not killed:
                dn_procs[0].send_signal(signal.SIGKILL)  # mid-ingest
                dn_procs[0].wait(timeout=10)
                killed = True
        assert killed and len(acked) >= 8

        # restart the killed datanode over the same data-home
        dn_procs[0] = spawn(dn_args(0), "dn0_restarted")
        _wait_port(dn_ports[0])
        # the frontend's cached Flight connection reconnects lazily;
        # poll until the full table scans cleanly
        deadline = time.time() + 60
        pairs = set()
        while time.time() < deadline:
            try:
                rows = _rows(_sql(fe, "select host, ts from t "
                                      "order by ts"))
                pairs = {(r[0], r[1]) for r in rows}
                if pairs >= set(acked):
                    break
            except Exception:
                pass
            time.sleep(0.5)
        missing = set(acked) - pairs
        assert not missing, f"acknowledged rows lost: {missing}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()
