"""Cross-process distributed topology: metasrv + datanodes + frontend.

The reference's distributed mode driven over real sockets
(/root/reference/src/query/src/dist_plan/merge_scan.rs MergeScanExec,
src/datanode/src/region_server.rs): the frontend owns no storage —
tables assemble from remote regions served by datanode Flight services,
scans fan out one RPC per datanode, and results must equal standalone.
"""

import numpy as np
import pytest

pytest.importorskip("pyarrow.flight")

from greptimedb_tpu.dist.client import MetaClient
from greptimedb_tpu.dist.frontend import DistInstance
from greptimedb_tpu.dist.region_server import RegionServer
from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.servers.flight import FlightFrontend
from greptimedb_tpu.servers.meta_http import MetasrvServer
from greptimedb_tpu.storage.engine import EngineConfig


def _make_datanode(tmp_path, i, *, store=None, wal_backend="fs"):
    home = str(tmp_path / f"dn{i}")
    inst = Standalone(
        engine_config=EngineConfig(data_root=home,
                                   enable_background=False,
                                   wal_backend=wal_backend),
        prefer_device=False, warm_start=False, store=store,
    )
    inst.region_server = RegionServer(inst.engine, home)
    fs = FlightFrontend(inst, port=0).start()
    return inst, fs


class DistHarness:
    """In-process wire topology: metasrv HTTP + datanode Flight servers
    over real sockets. `store`/`wal_backend` build shared-storage
    clusters (failover/migration tests)."""

    def __init__(self, tmp_path, n_datanodes=3, *, store=None,
                 wal_backend="fs"):
        self.tmp_path = tmp_path
        self.store = store
        self.wal_backend = wal_backend
        self.meta = MetasrvServer(
            addr="127.0.0.1", port=0, data_home=str(tmp_path / "meta")
        ).start()
        self.meta_addr = f"127.0.0.1:{self.meta.port}"
        self.datanodes = {}
        for i in range(n_datanodes):
            self.start_datanode(i)
        self.frontend = DistInstance(
            str(tmp_path / "fe"), self.meta_addr, prefer_device=False
        )

    def start_datanode(self, i):
        inst, fs = _make_datanode(self.tmp_path, i, store=self.store,
                                  wal_backend=self.wal_backend)
        MetaClient(self.meta_addr).register(
            i, f"127.0.0.1:{fs.server.port}"
        )
        self.datanodes[i] = (inst, fs)
        return inst, fs

    def stop_datanode(self, i):
        inst, fs = self.datanodes.pop(i)
        fs.close()
        inst.close()

    def close(self):
        self.frontend.close()
        for i in list(self.datanodes):
            self.stop_datanode(i)
        self.meta.close()


@pytest.fixture()
def harness(tmp_path):
    h = DistHarness(tmp_path)
    yield h
    h.close()


SEED_SQL = [
    "create table cpu (ts timestamp time index, host string primary key, "
    "dc string primary key, usage double, mem double) "
    "with (num_regions = 3)",
]


def _seed(inst, n_hosts=8, n_points=10):
    hosts = [f"h{i}" for i in range(n_hosts)]
    dcs = [f"dc{i % 3}" for i in range(n_hosts)]
    for sql in SEED_SQL:
        inst.execute_sql(sql)
    rows_host, rows_dc, rows_ts, rows_u, rows_m = [], [], [], [], []
    for p in range(n_points):
        for i, h in enumerate(hosts):
            rows_host.append(h)
            rows_dc.append(dcs[i])
            rows_ts.append(1_700_000_000_000 + p * 5_000)
            rows_u.append(float(i + p * 0.5))
            rows_m.append(float(100 + i))
    values = ", ".join(
        f"('{h}', '{d}', {t}, {u}, {m})"
        for h, d, t, u, m in zip(rows_host, rows_dc, rows_ts, rows_u,
                                 rows_m)
    )
    inst.execute_sql(
        f"insert into cpu (host, dc, ts, usage, mem) values {values}"
    )


@pytest.fixture()
def standalone_ref(tmp_path):
    inst = Standalone(str(tmp_path / "ref"), prefer_device=False,
                      warm_start=False)
    _seed(inst)
    yield inst
    inst.close()


def test_regions_spread_across_datanodes(harness):
    _seed(harness.frontend)
    table = harness.frontend.catalog.table("public", "cpu")
    owners = {id(r.client) for r in table.regions}
    assert len(table.regions) == 3
    assert len(owners) == 3  # round-robin across the 3 datanode processes
    # rows actually landed remotely, spread over >1 datanode engine
    counts = [
        sum(r.memtable.rows for r in inst.engine.regions())
        for inst, _ in harness.datanodes.values()
    ]
    assert sum(counts) == 80
    assert sum(1 for c in counts if c > 0) >= 2


def test_select_equals_standalone(harness, standalone_ref):
    _seed(harness.frontend)
    for sql in [
        "select host, dc, ts, usage from cpu order by ts, host",
        "select count(usage), sum(usage), min(mem), max(mem) from cpu",
        "select dc, avg(usage) from cpu group by dc order by dc",
        "select host, max(usage) from cpu where dc = 'dc1' "
        "group by host order by host",
        # the flagship RANGE shape
        "select ts, host, avg(usage) range '10s' from cpu "
        "align '10s' order by ts, host limit 20",
    ]:
        got = harness.frontend.sql(sql).rows()
        want = standalone_ref.sql(sql).rows()
        assert got == want, sql


def test_dml_and_ddl_round_trip(harness):
    fe = harness.frontend
    _seed(fe)
    # ALTER fans out to every region's datanode
    fe.execute_sql("alter table cpu add column note string")
    fe.execute_sql(
        "insert into cpu (host, dc, ts, usage, mem, note) "
        "values ('h9', 'dc0', 1700000099000, 1.0, 2.0, 'tagged')"
    )
    r = fe.sql("select note from cpu where host = 'h9'").rows()
    assert r == [["tagged"]]
    # DELETE routes to the right region
    fe.execute_sql("delete from cpu where host = 'h9' "
                   "and ts = 1700000099000")
    assert fe.sql("select count(usage) from cpu where host = 'h9'"
                  ).rows()[0][0] == 0
    # SHOW CREATE reflects the dist table
    ddl = fe.sql("show create table cpu").rows()[0][1]
    assert "`note` STRING" in ddl
    fe.execute_sql("drop table cpu")
    assert "cpu" not in fe.catalog.table_names("public")
    # every datanode region is gone
    for inst, _ in harness.datanodes.values():
        assert inst.engine.regions() == []


@pytest.mark.slow  # tier-1 budget: WAL replay gated by the wire-failover
# + chaos process-kill replay tests
def test_datanode_restart_replays_wal(harness, tmp_path):
    fe = harness.frontend
    _seed(fe)
    before = fe.sql(
        "select host, sum(usage) from cpu group by host order by host"
    ).rows()
    # hard-stop every datanode process (no flush), then bring them back
    for i in list(harness.datanodes):
        harness.stop_datanode(i)
    for i in range(3):
        harness.start_datanode(i)
    # fresh frontend (clients reconnect; catalog reloads from metasrv kv)
    fe2 = DistInstance(str(tmp_path / "fe2"), harness.meta_addr,
                       prefer_device=False)
    try:
        after = fe2.sql(
            "select host, sum(usage) from cpu group by host order by host"
        ).rows()
        assert after == before
    finally:
        fe2.close()


def test_flush_then_scan_from_sst(harness):
    fe = harness.frontend
    _seed(fe)
    fe.execute_sql("admin flush_table('cpu')")
    for inst, _ in harness.datanodes.values():
        for r in inst.engine.regions():
            assert r.memtable.rows == 0
    r = fe.sql("select count(usage) from cpu").rows()
    assert r[0][0] == 80


def test_metric_engine_over_dist(harness):
    """Prometheus remote-write's metric engine on the distributed
    frontend: logical tables over ONE shared physical RemoteTable;
    dropping a logical metric must NOT touch the shared regions."""
    from greptimedb_tpu.servers.http import _table_label_values
    from greptimedb_tpu.servers.prom_store import apply_series

    fe = harness.frontend
    t0 = 1_700_000_000_000
    series = [
        ({"__name__": f"m{i}", "host": f"h{i % 2}"}, [(float(i), t0)])
        for i in range(4)
    ]
    assert apply_series(fe, series, db="public") == 4
    r = fe.sql("select greptime_value from m3").rows()
    assert r == [[3.0]]
    # label values ride the remote registry (field-less scan)
    t2 = fe.catalog.table("public", "m2")
    assert _table_label_values(t2, "host") == {"h0"}
    # drop one logical metric; the shared physical regions survive
    fe.execute_sql("drop table m1")
    assert fe.sql("select count(greptime_value) from m2").rows()[0][0] == 1
    assert fe.sql("select count(greptime_value) from m0").rows()[0][0] == 1


def test_partial_aggregate_pushdown(harness, standalone_ref):
    """Decomposable GROUP BY aggregates ship partial plans to the
    datanodes; only partial states cross the wire (MergeScan split)."""
    from greptimedb_tpu.query import stats as qstats

    fe = harness.frontend
    _seed(fe)
    cases = [
        "select dc, avg(usage), count(usage), sum(mem) from cpu "
        "group by dc order by dc",
        "select count(usage), min(usage), max(mem) from cpu",
        "select dc, sum(usage) from cpu group by dc "
        "having sum(usage) > 100 order by sum(usage) desc limit 2",
        "select dc, avg(usage) from cpu where host != 'h0' "
        "group by dc order by dc",
        "select dc, host, max(usage) from cpu group by dc, host "
        "order by dc, host limit 5",
    ]
    for sql in cases:
        with qstats.collect() as st:
            got = fe.sql(sql).rows()
        want = standalone_ref.sql(sql).rows()
        assert got == want, sql
        assert st.counters.get("dist_partial_datanodes", 0) == 3, sql
        assert not st.counters.get("dist_pushdown_errors"), sql
        assert any(k.startswith("datanode_") for k in st.notes), sql


def test_range_pushdown_series_disjoint(harness, standalone_ref):
    from greptimedb_tpu.query import stats as qstats

    fe = harness.frontend
    _seed(fe)
    sql = ("select ts, host, dc, avg(usage) range '10s' from cpu "
           "align '10s' order by ts, host limit 30")
    with qstats.collect() as st:
        got = fe.sql(sql).rows()
    want = standalone_ref.sql(sql).rows()
    assert got == want
    assert st.counters.get("dist_partial_datanodes", 0) == 3
    assert not st.counters.get("dist_pushdown_errors")


def test_explain_analyze_shows_per_datanode_metrics(harness):
    fe = harness.frontend
    _seed(fe)
    r = fe.sql("explain analyze select dc, avg(usage) from cpu "
               "group by dc")
    text = "\n".join(str(row[0]) for row in r.rows())
    assert "datanode_" in text
    assert "rows_scanned" in text


def test_plan_codec_round_trip():
    from greptimedb_tpu.dist import plan_codec
    from greptimedb_tpu.query.planner import plan_select
    from greptimedb_tpu.sql.parser import parse_sql

    for sql in [
        "select dc, avg(usage), count(*) from cpu where host != 'h0' "
        "and ts >= 1000 group by dc having avg(usage) > 1 "
        "order by dc limit 3",
        "select ts, host, min(usage) range '30s' from cpu "
        "align '10s' by (host) order by ts",
        "select host, usage * 2 + 1 from cpu where usage > 0.5 "
        "and host like 'h%'",
    ]:
        stmt = parse_sql(sql)[0]
        plan = plan_select(stmt, ts_name="ts",
                           tag_names=["host", "dc"],
                           all_columns=["ts", "host", "dc", "usage"])
        doc = plan_codec.encode(plan)
        import json

        back = plan_codec.decode(json.loads(json.dumps(doc)))
        assert back == plan, sql


def test_pushdown_with_nulls(harness, standalone_ref):
    """Partial-state merge must respect SQL null semantics (sum of an
    all-null datanode partial, count skipping nulls, avg division)."""
    fe = harness.frontend
    for inst in (fe, standalone_ref):
        inst.execute_sql(
            "create table sparse (ts timestamp time index, host string "
            "primary key, v double, w double) with (num_regions = 3)"
        )
        inst.execute_sql(
            "insert into sparse (host, ts, v) values "
            "('a', 1000, 1.0), ('b', 1000, 2.0), ('c', 1000, 3.0)"
        )
        inst.execute_sql(
            "insert into sparse (host, ts, w) values ('a', 2000, 5.0)"
        )
    for sql in [
        "select host, count(w), sum(w), avg(w) from sparse "
        "group by host order by host",
        "select count(w), min(w), max(w), avg(v) from sparse",
    ]:
        assert fe.sql(sql).rows() == standalone_ref.sql(sql).rows(), sql


def test_range_having_distinct_fall_back_correctly(harness,
                                                   standalone_ref):
    """RANGE + HAVING/DISTINCT are not concat-mergeable; the pushdown
    must bail and the fallback must still give standalone-equal rows."""
    fe = harness.frontend
    _seed(fe)
    for sql in [
        "select ts, host, dc, max(usage) range '10s' as m from cpu "
        "align '10s' having m > 10 order by ts, host",
        "select distinct dc, count(usage) range '1h' from cpu "
        "align '1h' by (host, dc) order by dc",
    ]:
        assert fe.sql(sql).rows() == standalone_ref.sql(sql).rows(), sql


def test_plain_select_pushdown(harness, standalone_ref):
    """Plain SELECT (filters/projections/scalar exprs) is fully
    commutative: the whole plan ships; ORDER BY + LIMIT push as
    per-datanode top-k partials (commutativity.rs:164-189 analog)."""
    from greptimedb_tpu.query import stats as qstats

    fe = harness.frontend
    _seed(fe)
    cases = [
        "select host, usage * 2 + 1 as d from cpu where usage > 3 "
        "order by d, host limit 7",
        "select distinct dc from cpu order by dc",
        "select ts, host, usage from cpu where host like 'h1%' "
        "order by ts desc, host limit 4",
        "select host, usage from cpu order by usage desc, host limit 3",
    ]
    for sql in cases:
        with qstats.collect() as st:
            got = fe.sql(sql).rows()
        want = standalone_ref.sql(sql).rows()
        assert got == want, sql
        assert st.counters.get("dist_partial_datanodes", 0) >= 1, sql
        assert not st.counters.get("dist_pushdown_errors"), sql


def test_plain_pushdown_limits_wire_rows(harness):
    """A pushed top-k must ship at most k rows per datanode, not the
    whole table."""
    import json as _json

    from greptimedb_tpu.query import stats as qstats

    fe = harness.frontend
    _seed(fe)
    with qstats.collect() as st:
        fe.sql("select host, usage from cpu order by usage desc limit 3")
    partial_rows = sum(
        _json.loads(v)["partial_rows"]
        for k, v in st.notes.items() if k.startswith("datanode_")
    )
    assert 0 < partial_rows <= 9  # <= limit x 3 datanodes, not 80


def test_variance_stddev_pushdown(harness, standalone_ref):
    """var/stddev decompose into sum+count+sum-of-squares partials."""
    from greptimedb_tpu.query import stats as qstats

    fe = harness.frontend
    _seed(fe)
    for sql in [
        "select dc, var(usage), stddev(usage) from cpu group by dc "
        "order by dc",
        "select var_pop(usage), stddev_pop(mem) from cpu",
    ]:
        with qstats.collect() as st:
            got = fe.sql(sql).rows()
        want = standalone_ref.sql(sql).rows()
        assert len(got) == len(want), sql
        for grow, wrow in zip(got, want):
            for gv, wv in zip(grow, wrow):
                if isinstance(gv, float):
                    assert abs(gv - wv) < 1e-9 * max(1.0, abs(wv)), sql
                else:
                    assert gv == wv, sql
        assert st.counters.get("dist_partial_datanodes", 0) == 3, sql
        assert not st.counters.get("dist_pushdown_errors"), sql


def test_count_distinct_pushdown(harness, standalone_ref):
    """COUNT(DISTINCT x) ships as GROUP BY (keys, x); the frontend
    counts distinct codes — values, not rows, cross the wire."""
    from greptimedb_tpu.query import stats as qstats

    fe = harness.frontend
    _seed(fe)
    for sql in [
        "select dc, count(distinct host) from cpu group by dc order by dc",
        "select count(distinct dc) from cpu",
    ]:
        with qstats.collect() as st:
            got = fe.sql(sql).rows()
        assert got == standalone_ref.sql(sql).rows(), sql
        assert st.counters.get("dist_partial_datanodes", 0) == 3, sql
        assert not st.counters.get("dist_pushdown_errors"), sql


def test_minmax_merge_preserves_dtype(harness, standalone_ref):
    """BIGINT/timestamp extremes above 2^53 must merge exactly (no float
    round-trip) and keep integer output type across 3 datanodes."""
    fe = harness.frontend
    big = 2**53
    for inst in (fe, standalone_ref):
        inst.execute_sql(
            "create table big (ts timestamp time index, host string "
            "primary key, n bigint) with (num_regions = 3)"
        )
        inst.execute_sql(
            "insert into big (host, ts, n) values "
            f"('a', 1000, {big + 1}), ('b', 2000, {big + 3}), "
            f"('c', 3000, {big + 5})"
        )
    sql = "select min(n), max(n), min(ts), max(ts) from big"
    got = fe.sql(sql).rows()
    assert got == standalone_ref.sql(sql).rows()
    assert got[0][0] == big + 1 and got[0][1] == big + 5
    assert all(isinstance(v, int) for v in got[0])


def test_string_minmax_pushdown(harness, standalone_ref):
    fe = harness.frontend
    _seed(fe)
    sql = "select dc, min(host), max(host) from cpu group by dc order by dc"
    assert fe.sql(sql).rows() == standalone_ref.sql(sql).rows()


def test_range_fill_pushdown_global_grid(harness, standalone_ref):
    """RANGE + FILL pushes down after negotiating the GLOBAL ts extent:
    per-datanode fill grids must be identical to standalone's."""
    from greptimedb_tpu.query import stats as qstats

    fe = harness.frontend
    _seed(fe)
    # make the per-datanode extents differ: one host gets extra points
    for inst in (fe, standalone_ref):
        inst.execute_sql(
            "insert into cpu (host, dc, ts, usage, mem) values "
            "('h0', 'dc0', 1700000200000, 42.0, 1.0)"
        )
    for sql in [
        "select ts, host, dc, avg(usage) range '10s' fill prev from cpu "
        "align '10s' order by ts, host",
        "select ts, host, dc, max(usage) range '10s' fill 0 from cpu "
        "align '10s' order by ts, host limit 40",
        "select ts, host, dc, sum(usage) range '10s' fill linear "
        "from cpu align '10s' order by ts, host",
    ]:
        with qstats.collect() as st:
            got = fe.sql(sql).rows()
        want = standalone_ref.sql(sql).rows()
        assert got == want, sql
        assert st.counters.get("dist_partial_datanodes", 0) >= 3, sql
        assert not st.counters.get("dist_pushdown_errors"), sql


def test_range_having_now_pushes_down(harness, standalone_ref):
    """HAVING over datanode-disjoint range rows ships with the partial
    (row-wise predicate), no longer a fallback."""
    from greptimedb_tpu.query import stats as qstats

    fe = harness.frontend
    _seed(fe)
    sql = ("select ts, host, dc, max(usage) range '10s' as m from cpu "
           "align '10s' having m > 10 order by ts, host")
    with qstats.collect() as st:
        got = fe.sql(sql).rows()
    assert got == standalone_ref.sql(sql).rows()
    assert st.counters.get("dist_partial_datanodes", 0) == 3
    assert not st.counters.get("dist_pushdown_errors")


def test_range_default_order_matches_standalone(harness, standalone_ref):
    """No ORDER BY: merged rows must come back in standalone's default
    (ts, group keys) order, not interleaved datanode blocks (ADVICE r4)."""
    fe = harness.frontend
    _seed(fe)
    sql = ("select ts, host, dc, avg(usage) range '10s' from cpu "
           "align '10s'")
    assert fe.sql(sql).rows() == standalone_ref.sql(sql).rows()


def test_join_scan_sides_push_down(harness, standalone_ref):
    """Join branches route through _select_single, so each scan side
    ships its filter/projection to the datanodes."""
    from greptimedb_tpu.query import stats as qstats

    fe = harness.frontend
    _seed(fe)
    sql = (
        "select a.host, a.usage, b.mem from "
        "(select host, ts, usage from cpu where usage > 3) a join "
        "(select host, ts, mem from cpu where mem < 105) b "
        "on a.host = b.host and a.ts = b.ts "
        "order by a.host, a.usage limit 10"
    )
    with qstats.collect() as st:
        got = fe.sql(sql).rows()
    assert got == standalone_ref.sql(sql).rows()
    # both scan sides fanned out partial plans
    assert st.counters.get("dist_partial_datanodes", 0) >= 2
    assert not st.counters.get("dist_pushdown_errors")


def test_distinct_limit_not_truncated_by_partial(harness, standalone_ref):
    """LIMIT must not push below a datanode-side DISTINCT that dedups
    over a WIDER tuple than the visible one (code-review r5 repro)."""
    fe = harness.frontend
    for inst in (fe, standalone_ref):
        inst.execute_sql(
            "create table m (ts timestamp time index, host string "
            "primary key, v double) with (num_regions = 3)"
        )
        vals = ", ".join(
            f"('h1', {1000 + i * 10_000}, 5.0)" for i in range(6)
        )
        inst.execute_sql(f"insert into m (host, ts, v) values {vals}, "
                         "('h1', 70000, 6.0), ('h2', 1000, 7.0)")
    for sql in [
        "select distinct host, avg(v) range '10s' as a from m "
        "align '10s' order by host, a limit 3",
        "select distinct host, v from m order by host, v limit 3",
    ]:
        assert fe.sql(sql).rows() == standalone_ref.sql(sql).rows(), sql


def test_empty_keyed_aggregate_stays_pushed(harness, standalone_ref):
    """All-datanodes-empty keyed aggregates must merge to zero rows
    without tripping the fallback (code-review r5 repro)."""
    from greptimedb_tpu.query import stats as qstats

    fe = harness.frontend
    _seed(fe)
    sql = "select dc, sum(usage) from cpu where usage > 1e9 group by dc"
    with qstats.collect() as st:
        got = fe.sql(sql).rows()
    assert got == standalone_ref.sql(sql).rows()
    assert not st.counters.get("dist_pushdown_errors")
    assert st.counters.get("dist_partial_datanodes", 0) == 3


def test_failed_create_rolls_back_kv_claim(tmp_path):
    """A create that fails region placement must delete its kv claim so
    the name is reusable (code-review r5 repro)."""
    from greptimedb_tpu.dist.client import MetaClient
    from greptimedb_tpu.dist.frontend import DistInstance
    from greptimedb_tpu.servers.meta_http import MetasrvServer

    meta = MetasrvServer(addr="127.0.0.1", port=0,
                         data_home=str(tmp_path / "meta")).start()
    try:
        fe = DistInstance(str(tmp_path / "fe"),
                          f"127.0.0.1:{meta.port}", prefer_device=False)
        ddl = ("create table t1 (ts timestamp time index, host string "
               "primary key, v double)")
        with pytest.raises(Exception):
            fe.execute_sql(ddl)  # no datanodes registered -> placement fails
        assert MetaClient(f"127.0.0.1:{meta.port}").kv_get(
            "__cat/table/public/t1"
        ) is None
        fe.close()
    finally:
        meta.close()


def test_pushdown_prunes_partitioned_regions(harness, standalone_ref):
    """PARTITION ON routing: a pushdown with a partition-key matcher
    must skip datanodes whose regions cannot match."""
    from greptimedb_tpu.query import stats as qstats

    fe = harness.frontend
    for inst in (fe, standalone_ref):
        inst.execute_sql(
            "create table part (ts timestamp time index, host string "
            "primary key, v double) partition on columns (host) ("
            "host < 'h3', host >= 'h3' and host < 'h6', host >= 'h6')"
        )
        values = ", ".join(
            f"('h{i}', {1_700_000_000_000 + p * 1000}, {i + p})"
            for p in range(3) for i in range(9)
        )
        inst.execute_sql(f"insert into part (host, ts, v) values {values}")
    sql = ("select host, sum(v) from part where host = 'h1' "
           "group by host")
    with qstats.collect() as st:
        got = fe.sql(sql).rows()
    assert got == standalone_ref.sql(sql).rows()
    assert st.counters.get("regions_pruned", 0) == 2
    assert st.counters.get("dist_partial_datanodes", 0) == 1


def test_pushdown_multi_region_datanode_partition_prune(tmp_path):
    """A datanode holding 2+ regions of a partitioned table must not
    re-prune the shipped subset with GLOBAL partition indices (that
    silently dropped the second region's rows)."""
    from greptimedb_tpu.query import stats as qstats

    h = DistHarness(tmp_path, n_datanodes=2)  # 4 partitions over 2 nodes
    try:
        fe = h.frontend
        fe.execute_sql(
            "create table part (ts timestamp time index, host string "
            "primary key, v double) partition on columns (host) ("
            "host < 'h2', host < 'h4', host < 'h6', host >= 'h6')"
        )
        values = ", ".join(
            f"('h{i}', {1_700_000_000_000 + p * 1000}, {i + p})"
            for p in range(2) for i in range(8)
        )
        fe.execute_sql(f"insert into part (host, ts, v) values {values}")
        table = fe.catalog.table("public", "part")
        owners = [id(r.client) for r in table.regions]
        assert len(set(owners)) == 2
        # h2 -> partition 1, h7 -> partition 3; round-robin puts BOTH on
        # the same datanode, whose local region list is [r1, r3]. The
        # old datanode-side re-prune applied GLOBAL keep indices [1, 3]
        # to that 2-element list, silently dropping partition 1's rows.
        assert owners[1] == owners[3]
        sql = ("select host, sum(v) from part "
               "where host in ('h2', 'h7') group by host order by host")
        with qstats.collect() as st:
            got = fe.sql(sql).rows()
        assert got == [["h2", 5.0], ["h7", 15.0]]
        assert st.counters.get("regions_pruned", 0) == 2
        assert not st.counters.get("dist_pushdown_errors")
    finally:
        h.close()


def test_global_aggregate_all_regions_pruned(harness, standalone_ref):
    """Pruning every region away must still yield standalone's one-row
    global aggregate (count=0, NULL extremes)."""
    fe = harness.frontend
    for inst in (fe, standalone_ref):
        inst.execute_sql(
            "create table p2 (ts timestamp time index, host string "
            "primary key, v double) partition on columns (host) ("
            "host < 'm', host >= 'm')"
        )
        inst.execute_sql(
            "insert into p2 (host, ts, v) values ('a', 1000, 1.0)"
        )
    sql = "select count(v), min(v), sum(v) from p2 where host = 'a' and host = 'zz'"
    assert fe.sql(sql).rows() == standalone_ref.sql(sql).rows()


@pytest.fixture()
def flow_harness(tmp_path):
    """DistHarness + a flownode process wired for mirroring."""
    h = DistHarness(tmp_path)
    fn_inst = DistInstance(str(tmp_path / "flownode"), h.meta_addr,
                           prefer_device=False)
    fn_inst.enable_flows()
    fn_inst.flows.tick_interval_s = 3600  # manual flushes in tests
    fn_flight = FlightFrontend(fn_inst, port=0).start()
    h.frontend.flownode_addr = f"127.0.0.1:{fn_flight.server.port}"
    yield h, fn_inst
    fn_flight.close()
    fn_inst.close()
    h.close()


def test_wire_level_flow_mirroring(flow_harness, tmp_path):
    """The reference's frontend->flownode loop over real sockets
    (src/operator/src/insert.rs:284-317, src/flow/src/adapter.rs):
    CREATE FLOW forwards to the flownode, source inserts mirror as
    Flight batches, the flownode writes the sink through the shared
    catalog — and the result is served by a DIFFERENT process."""
    h, fn_inst = flow_harness
    fe = h.frontend
    fe.execute_sql(
        "create table requests (host string primary key, "
        "latency double, ts timestamp time index) "
        "with (num_regions = 3)"
    )
    fe.execute_sql(
        "create flow req_stats sink to req_summary as "
        "select date_bin('1 minute', ts) as time_window, host, "
        "count(*) as total, avg(latency) as avg_latency "
        "from requests group by time_window, host"
    )
    # the flow lives on the flownode, visible through the frontend
    assert fe.sql("show flows").rows() == [["req_stats"]]
    assert fn_inst.flows.flow_names() == ["req_stats"]

    fe.execute_sql(
        "insert into requests values "
        "('h1', 10.0, 1700000000000), "
        "('h1', 20.0, 1700000010000), "
        "('h2', 30.0, 1700000020000)"
    )
    fn_inst.flows.flush_all()

    # sink rows were written through the flownode's dist catalog onto
    # the datanodes; a SEPARATE frontend process serves them
    fe2 = DistInstance(str(tmp_path / "fe2"), h.meta_addr,
                       prefer_device=False)
    try:
        rows = fe2.sql(
            "select host, total, avg_latency from req_summary "
            "order by host"
        ).rows()
        assert rows == [["h1", 2, 15.0], ["h2", 1, 30.0]]
    finally:
        fe2.close()

    # incremental: more mirrored deltas fold into the same windows
    fe.execute_sql(
        "insert into requests values ('h1', 60.0, 1700000030000)"
    )
    fn_inst.flows.flush_all()
    rows = fe.sql(
        "select host, total, avg_latency from req_summary "
        "order by host"
    ).rows()
    assert rows == [["h1", 3, 30.0], ["h2", 1, 30.0]]

    # DROP FLOW forwards too
    fe.execute_sql("drop flow req_stats")
    assert fn_inst.flows.flow_names() == []


def test_concurrent_catalog_writers_do_not_clobber(harness, tmp_path):
    """Per-key kv catalog: a writer with a stale in-memory view must not
    erase tables other processes created after its load (the old
    whole-doc persist lost them)."""
    fe = harness.frontend
    fe2 = DistInstance(str(tmp_path / "fe2"), harness.meta_addr,
                       prefer_device=False)  # loads an empty catalog
    try:
        fe.execute_sql(
            "create table from_fe (ts timestamp time index, v double)"
        )
        # fe2's memory predates from_fe; its own DDL must not erase it
        fe2.execute_sql(
            "create table from_fe2 (ts timestamp time index, v double)"
        )
        fe3 = DistInstance(str(tmp_path / "fe3"), harness.meta_addr,
                           prefer_device=False)
        try:
            names = fe3.catalog.table_names("public")
            assert "from_fe" in names and "from_fe2" in names
        finally:
            fe3.close()
        # distinct CAS-allocated table ids even across stale writers
        t1 = fe3_id = None
        t1 = fe.catalog.table("public", "from_fe").info.table_id
        t2 = fe2.catalog.table("public", "from_fe2").info.table_id
        assert t1 != t2
    finally:
        fe2.close()


def test_duplicate_flow_name_raises_through_the_wire(flow_harness):
    h, fn_inst = flow_harness
    fe = h.frontend
    fe.execute_sql(
        "create table src (ts timestamp time index, v double)"
    )
    fe.execute_sql(
        "create flow f1 sink to s1 as select date_bin('1 minute', ts) "
        "as w, count(*) as n from src group by w"
    )
    with pytest.raises(Exception, match="exists"):
        fe.execute_sql(
            "create flow f1 sink to s2 as select date_bin('1 minute', "
            "ts) as w, sum(v) as n from src group by w"
        )
    # IF NOT EXISTS still no-ops quietly
    fe.execute_sql(
        "create flow if not exists f1 sink to s2 as select "
        "date_bin('1 minute', ts) as w, sum(v) as n from src group by w"
    )


def test_wire_failover_moves_regions_to_live_datanode(tmp_path):
    """A datanode PROCESS dies; the metasrv's failover procedures drive
    the surviving datanodes over Flight (dist/wire_cluster.py) and a
    frontend read self-heals via route refresh — the reference's
    region-failover loop on the wire topology. Datanodes share an
    object store, so flushed data is reachable from the new owner."""
    from greptimedb_tpu.storage.object_store import FsObjectStore

    shared = FsObjectStore(str(tmp_path / "shared_store"))
    h = DistHarness(tmp_path, store=shared)
    try:
        fe = h.frontend
        fe.execute_sql(
            "create table ft (ts timestamp time index, host string "
            "primary key, v double) with (num_regions = 3)"
        )
        values = ", ".join(
            f"('h{i}', {1_700_000_000_000 + p * 1000}, {i + p})"
            for p in range(3) for i in range(9)
        )
        fe.execute_sql(f"insert into ft (host, ts, v) values {values}")
        fe.execute_sql("admin flush_table('ft')")  # shared-store durable
        before = fe.sql(
            "select host, sum(v) from ft group by host order by host"
        ).rows()

        table = fe.catalog.table("public", "ft")
        victim_rid = table.info.region_ids()[0]
        ms = h.meta.metasrv
        victim = ms.route_of(victim_rid)
        # the datanode process dies hard
        h.stop_datanode(victim)
        # deterministic supervision (phi timing is env-dependent)
        procs = ms.failover_node(victim)
        assert procs, "failover must trigger for the dead node's regions"
        for pid in procs:
            meta = ms.procedures.wait(pid)
            assert meta.state == "done", meta.error
        for rid, nid in ms._all_routes().items():
            assert nid != victim
        # the frontend read self-heals: first attempt hits the dead
        # node, the unavailable error triggers a route refresh + retry
        after = fe.sql(
            "select host, sum(v) from ft group by host order by host"
        ).rows()
        assert after == before
    finally:
        h.close()


def test_wire_graceful_migration_carries_unflushed_rows(tmp_path):
    """Manual region migration over the wire: the downgrade step fences
    + flushes the source, and the upgrade step must REOPEN the
    candidate (its first open predates the flush) — unflushed rows
    survive the move."""
    from greptimedb_tpu.storage.object_store import FsObjectStore

    shared = FsObjectStore(str(tmp_path / "shared_store"))
    h = DistHarness(tmp_path, n_datanodes=2, store=shared)
    try:
        fe = h.frontend
        fe.execute_sql(
            "create table gm (ts timestamp time index, host string "
            "primary key, v double)"
        )
        fe.execute_sql(
            "insert into gm (host, ts, v) values ('a', 1000, 1.0), "
            "('b', 2000, 2.0)"
        )  # memtable-only on the source
        ms = h.meta.metasrv
        rid = fe.catalog.table("public", "gm").info.region_ids()[0]
        src = ms.route_of(rid)
        dst = 1 - src
        ms.migrate_region(rid, dst)  # raises unless it completes
        assert ms.route_of(rid) == dst
        # fencing: the source region (still open until close step ran)
        # is gone or read-only; the data now serves from the target
        fe.catalog.refresh()
        rows = fe.sql("select host, v from gm order by ts").rows()
        assert rows == [["a", 1.0], ["b", 2.0]]
        dn_inst, _ = h.datanodes[dst]
        assert dn_inst.engine.region(rid) is not None
    finally:
        h.close()


def test_region_alive_keeper_fences_and_closes(tmp_path):
    """RegionAliveKeeper semantics (reference alive_keeper.rs): lease
    expiry fences writes; a later grant excluding the region closes it;
    re-granting un-fences."""
    import numpy as np

    inst = Standalone(
        engine_config=EngineConfig(data_root=str(tmp_path / "dn"),
                                   enable_background=False),
        prefer_device=False, warm_start=False,
    )
    rs = RegionServer(inst.engine, str(tmp_path / "dn"))
    try:
        from greptimedb_tpu.dist.remote import region_meta_doc
        from greptimedb_tpu.catalog.manager import TableInfo
        from greptimedb_tpu.datatypes.schema import (
            ColumnSchema, Schema, SemanticType,
        )
        from greptimedb_tpu.datatypes.types import ConcreteDataType as T
        from greptimedb_tpu.errors import RegionReadonlyError

        info = TableInfo(
            table_id=9, name="t", database="public",
            schema=Schema([
                ColumnSchema("ts", T.timestamp_millisecond(),
                             SemanticType.TIMESTAMP, nullable=False),
                ColumnSchema("v", T.float64(), SemanticType.FIELD),
            ]),
        )
        rid = info.region_ids()[0]
        rs.open_region(region_meta_doc(info, rid))

        def write_one(ts):
            rs.write(rid, {}, np.asarray([ts], np.int64),
                     {"v": np.asarray([1.0])}, None, op=0)

        write_one(1000)  # no lease known yet: never fenced
        rs.renew_leases([rid], lease_secs=10.0, now=0.0)
        assert rs.enforce_leases(now=5.0) == []
        write_one(2000)
        # lease lapses: the region fences
        assert rs.enforce_leases(now=11.0) == [rid]
        import pytest as _pytest

        with _pytest.raises(RegionReadonlyError):
            write_one(3000)
        # re-grant: un-fenced, writable again
        rs.renew_leases([rid], lease_secs=10.0, now=12.0)
        write_one(4000)
        # a grant EXCLUDING the region after lapse closes it (routes
        # moved away in a failover)
        rs.renew_leases([], lease_secs=10.0, now=30.0)
        assert rid not in rs.region_ids()
    finally:
        inst.close()


def test_wire_failover_replays_unflushed_rows_from_remote_wal(tmp_path):
    """VERDICT r4 missing #6: with wal_backend='object' the log rides
    the SHARED store (the Kafka-remote-WAL analog,
    /root/reference/src/log-store/src/kafka/log_store.rs:45), so a
    failed-over region replays rows that were never flushed to SST —
    datanode dies hard mid-write, survivor serves everything."""
    from greptimedb_tpu.storage.object_store import FsObjectStore

    shared = FsObjectStore(str(tmp_path / "shared_store"))
    h = DistHarness(tmp_path, store=shared, wal_backend="object")
    try:
        fe = h.frontend
        fe.execute_sql(
            "create table rw (ts timestamp time index, host string "
            "primary key, v double) with (num_regions = 3)"
        )
        values = ", ".join(
            f"('h{i}', {1_700_000_000_000 + p * 1000}, {i + p})"
            for p in range(3) for i in range(9)
        )
        fe.execute_sql(f"insert into rw (host, ts, v) values {values}")
        # NO flush: every row lives only in memtables + the remote WAL
        before = fe.sql(
            "select host, sum(v) from rw group by host order by host"
        ).rows()
        assert len(before) == 9

        table = fe.catalog.table("public", "rw")
        victim_rid = table.info.region_ids()[0]
        ms = h.meta.metasrv
        victim = ms.route_of(victim_rid)
        h.stop_datanode(victim)  # SIGKILL-equivalent: memtables gone
        procs = ms.failover_node(victim)
        assert procs, "failover must trigger"
        for pid in procs:
            meta = ms.procedures.wait(pid)
            assert meta.state == "done", meta.error
        after = fe.sql(
            "select host, sum(v) from rw group by host order by host"
        ).rows()
        assert after == before, "unflushed rows lost across failover"
    finally:
        h.close()


def test_wire_migration_fuzz_under_writes(tmp_path):
    """Live-cluster migration fuzz (the reference's
    tests-fuzz/targets/migration/fuzz_migrate_mito_regions.rs analog on
    this wire topology): random region migrations between datanode
    Flight servers interleave with frontend writes; every row written
    must be readable afterwards with standalone-equal aggregates."""
    import random

    from greptimedb_tpu.storage.object_store import FsObjectStore

    rnd = random.Random(17)
    shared = FsObjectStore(str(tmp_path / "shared_store"))
    h = DistHarness(tmp_path, store=shared, wal_backend="object")
    try:
        fe = h.frontend
        fe.execute_sql(
            "create table mf (ts timestamp time index, host string "
            "primary key, v double) with (num_regions = 3)"
        )
        ms = h.meta.metasrv
        rids = fe.catalog.table("public", "mf").info.region_ids()
        expected: dict[str, float] = {}
        counts: dict[str, int] = {}
        t = 1_700_000_000_000
        for round_no in range(8):
            # a write burst...
            vals = []
            for _ in range(20):
                host = f"h{rnd.randrange(6)}"
                v = float(rnd.randrange(100))
                vals.append(f"('{host}', {t}, {v})")
                expected[host] = expected.get(host, 0.0) + v
                counts[host] = counts.get(host, 0) + 1
                t += 1000
            fe.execute_sql(
                f"insert into mf (host, ts, v) values {', '.join(vals)}"
            )
            # ...then a random migration (sometimes mid-flush state)
            rid = rnd.choice(rids)
            src = ms.route_of(rid)
            dst = rnd.choice([n for n in range(3) if n != src])
            ms.migrate_region(rid, dst)
            assert ms.route_of(rid) == dst
        # every write survives every migration
        fe.catalog.refresh()
        got = fe.sql(
            "select host, count(*), sum(v) from mf group by host "
            "order by host"
        ).rows()
        want = [[h_, counts[h_], expected[h_]]
                for h_ in sorted(expected)]
        assert got == want
    finally:
        h.close()
