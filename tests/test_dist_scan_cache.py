"""Datanode merged-scan cache (dist/scan_cache.py): invalidation proof.

A cached partial must NEVER be served after a data-mutating op — write,
flush, truncate, compact, region migration — through the full
frontend -> datanode path. The cache keys on each region's
physical_version (storage/region.py), which every one of those ops
bumps; close/open/alter purge explicitly.
"""

import numpy as np
import pytest

pytest.importorskip("pyarrow.flight")

from greptimedb_tpu.dist.client import MetaClient
from greptimedb_tpu.dist.frontend import DistInstance
from greptimedb_tpu.dist.region_server import RegionServer
from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.servers.flight import FlightFrontend
from greptimedb_tpu.servers.meta_http import MetasrvServer
from greptimedb_tpu.storage.engine import EngineConfig
from greptimedb_tpu.telemetry.metrics import global_registry


def _counter(name: str) -> float:
    return global_registry.counter(name).labels().value


class _Harness:
    def __init__(self, tmp_path, n_datanodes=2, *, store=None):
        self.meta = MetasrvServer(
            addr="127.0.0.1", port=0, data_home=str(tmp_path / "meta")
        ).start()
        self.meta_addr = f"127.0.0.1:{self.meta.port}"
        self.datanodes = {}
        for i in range(n_datanodes):
            home = str(tmp_path / f"dn{i}")
            inst = Standalone(
                engine_config=EngineConfig(data_root=home,
                                           enable_background=False),
                prefer_device=False, warm_start=False, store=store,
            )
            inst.region_server = RegionServer(inst.engine, home)
            fs = FlightFrontend(inst, port=0).start()
            MetaClient(self.meta_addr).register(
                i, f"127.0.0.1:{fs.server.port}"
            )
            self.datanodes[i] = (inst, fs)
        self.frontend = DistInstance(
            str(tmp_path / "fe"), self.meta_addr, prefer_device=False
        )

    def region_servers(self):
        return [inst.region_server for inst, _ in self.datanodes.values()]

    def close(self):
        self.frontend.close()
        for inst, fs in self.datanodes.values():
            fs.close()
            inst.close()
        self.meta.close()


@pytest.fixture()
def harness(tmp_path):
    h = _Harness(tmp_path)
    yield h
    h.close()


Q = "select host, sum(v), count(*) from t1 group by host order by host"


def _seed(fe, rows=40):
    fe.execute_sql(
        "create table t1 (ts timestamp time index, host string "
        "primary key, v double) with (num_regions = 2)"
    )
    values = ", ".join(
        f"('h{i % 4}', {1_000_000 + i * 1000}, {float(i)})"
        for i in range(rows)
    )
    fe.execute_sql(f"insert into t1 (host, ts, v) values {values}")


def test_warm_query_hits_cache(harness):
    fe = harness.frontend
    _seed(fe)
    cold = fe.sql(Q).rows()
    h0 = _counter("gtpu_dist_scan_cache_hits_total")
    warm = fe.sql(Q).rows()
    assert warm == cold
    assert _counter("gtpu_dist_scan_cache_hits_total") > h0
    assert sum(rs.scan_cache.entry_count
               for rs in harness.region_servers()) > 0


def test_write_invalidates_through_frontend(harness):
    fe = harness.frontend
    _seed(fe)
    before = fe.sql(Q).rows()
    fe.sql(Q)  # cached on every datanode
    fe.execute_sql(
        "insert into t1 (host, ts, v) values ('h0', 99000000, 1000.0)"
    )
    after = fe.sql(Q).rows()
    assert after != before
    h0 = next(r for r in after if r[0] == "h0")
    b0 = next(r for r in before if r[0] == "h0")
    assert h0[1] == b0[1] + 1000.0 and h0[2] == b0[2] + 1


def test_delete_and_truncate_invalidate(harness):
    fe = harness.frontend
    _seed(fe)
    fe.sql(Q)
    fe.sql(Q)
    fe.execute_sql("delete from t1 where host = 'h1'")
    rows = fe.sql(Q).rows()
    assert all(r[0] != "h1" for r in rows)
    fe.catalog.table("public", "t1").truncate()
    assert fe.sql("select count(*) from t1").rows() == [[0]]


def test_flush_bumps_physical_version_and_invalidates(harness):
    fe = harness.frontend
    _seed(fe)
    cold = fe.sql(Q).rows()
    fe.sql(Q)
    versions = {
        r.meta.region_id: r.physical_version
        for inst, _ in harness.datanodes.values()
        for r in inst.engine.regions()
        if r.memtable.rows  # an empty region's flush is a no-op
    }
    assert versions
    fe.catalog.table("public", "t1").flush()  # frontend -> datanode RPC
    m0 = _counter("gtpu_dist_scan_cache_misses_total")
    assert fe.sql(Q).rows() == cold
    # flush bumped every flushed region's version: the old entries were
    # NOT served (a fresh build = at least one miss)
    for inst, _ in harness.datanodes.values():
        for region in inst.engine.regions():
            if region.meta.region_id in versions:
                assert region.physical_version != \
                    versions[region.meta.region_id]
    assert _counter("gtpu_dist_scan_cache_misses_total") > m0


def test_compact_bumps_physical_version_and_invalidates(harness):
    fe = harness.frontend
    _seed(fe, rows=20)
    table = fe.catalog.table("public", "t1")
    table.flush()
    for round_ in range(4):  # enough level-0 SSTs in one window to
        fe.execute_sql(      # trip the TWCS picker
            "insert into t1 (host, ts, v) values "
            + ", ".join(
                f"('h{i % 4}', {2_000_000 + round_ * 40_000 + i * 1000},"
                f" {float(i)})"
                for i in range(20)
            )
        )
        table.flush()
    cold = fe.sql(Q).rows()
    fe.sql(Q)
    m0 = _counter("gtpu_dist_scan_cache_misses_total")
    compacted = 0
    for region_proxy in table.regions:
        before = region_proxy.data_version
        if region_proxy.compact():
            compacted += 1
            # logical version is flush/compact-stable...
            assert region_proxy.data_version == before
    assert compacted > 0
    # ...but the scan-cache's physical version is not: no stale serve
    assert fe.sql(Q).rows() == cold
    assert _counter("gtpu_dist_scan_cache_misses_total") > m0


def test_migration_purges_source_cache(tmp_path):
    from greptimedb_tpu.storage.object_store import FsObjectStore

    shared = FsObjectStore(str(tmp_path / "shared_store"))
    h = _Harness(tmp_path, n_datanodes=2, store=shared)
    try:
        fe = h.frontend
        fe.execute_sql(
            "create table gm (ts timestamp time index, host string "
            "primary key, v double)"
        )
        fe.execute_sql(
            "insert into gm (host, ts, v) values ('a', 1000, 1.0), "
            "('b', 2000, 2.0)"
        )
        q = "select host, sum(v) from gm group by host order by host"
        want = fe.sql(q).rows()
        fe.sql(q)  # cached on the source datanode
        ms = h.meta.metasrv
        rid = fe.catalog.table("public", "gm").info.region_ids()[0]
        src = ms.route_of(rid)
        src_rs = h.datanodes[src][0].region_server
        assert src_rs.scan_cache.entry_count > 0
        ms.migrate_region(rid, 1 - src)
        # the close step of the migration purged the source's entries
        assert src_rs.scan_cache.entry_count == 0
        fe.catalog.refresh()
        assert fe.sql(q).rows() == want
        # and a write on the TARGET hosting is visible immediately
        fe.execute_sql(
            "insert into gm (host, ts, v) values ('a', 3000, 10.0)"
        )
        rows = fe.sql(q).rows()
        assert rows == [["a", 11.0], ["b", 2.0]]
    finally:
        h.close()


def test_ttl_regions_bypass_cache(harness):
    """TTL tables derive their effective scan window from the wall
    clock inside Region.scan: a cached merge would keep serving expired
    rows forever (no version bump happens at expiry), so TTL'd regions
    must never enter the cache."""
    import time as _time

    fe = harness.frontend
    fe.execute_sql(
        "create table tt (ts timestamp time index, host string "
        "primary key, v double) with (ttl = '1h', num_regions = 2)"
    )
    now = int(_time.time() * 1000)
    fe.execute_sql(
        "insert into tt (host, ts, v) values "
        f"('a', {now - 2 * 3600_000}, 1.0), "   # already expired
        f"('b', {now - 60_000}, 2.0)"           # live
    )
    q = "select host, sum(v) from tt group by host order by host"
    assert fe.sql(q).rows() == [["b", 2.0]]
    n0 = sum(rs.scan_cache.entry_count for rs in harness.region_servers())
    fe.sql(q)
    assert sum(rs.scan_cache.entry_count
               for rs in harness.region_servers()) == n0


def test_reopen_purges_previous_hosting_entries(tmp_path):
    """RegionServer-level: close + reopen of a region must not serve a
    merge built from the previous hosting."""
    from greptimedb_tpu.catalog.manager import TableInfo
    from greptimedb_tpu.datatypes.schema import (
        ColumnSchema,
        Schema,
        SemanticType,
    )
    from greptimedb_tpu.datatypes.types import ConcreteDataType as T
    from greptimedb_tpu.dist.remote import region_meta_doc

    inst = Standalone(
        engine_config=EngineConfig(data_root=str(tmp_path / "dn"),
                                   enable_background=False),
        prefer_device=False, warm_start=False,
    )
    rs = RegionServer(inst.engine, str(tmp_path / "dn"))
    try:
        info = TableInfo(
            table_id=7, name="t", database="public",
            schema=Schema([
                ColumnSchema("ts", T.timestamp_millisecond(),
                             SemanticType.TIMESTAMP, nullable=False),
                ColumnSchema("host", T.string(), SemanticType.TAG),
                ColumnSchema("v", T.float64(), SemanticType.FIELD),
            ]),
        )
        rid = info.region_ids()[0]
        doc = region_meta_doc(info, rid)
        rs.open_region(doc)
        rs.write(rid, {"host": np.asarray(["a"], object)},
                 np.asarray([1000], np.int64),
                 {"v": np.asarray([1.0])}, None, op=0)
        rows, tags, _names, _st = rs.scan([rid])
        assert len(rows) == 1 and rs.scan_cache.entry_count == 1
        rs.close_region(rid)
        assert rs.scan_cache.entry_count == 0
        rs.open_region(doc)
        rs.write(rid, {"host": np.asarray(["b"], object)},
                 np.asarray([2000], np.int64),
                 {"v": np.asarray([2.0])}, None, op=0)
        rows2, tags2, _n2, _s2 = rs.scan([rid])
        assert sorted(tags2["host"]) == ["a", "b"]
        assert len(rows2) == 2
    finally:
        rs.close()
        inst.close()
