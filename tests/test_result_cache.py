"""Device-resident result path: frontend result cache + persistent
query sessions + `since` delta polls (ISSUE 9).

Invalidation proof through the full stack: a cached result payload (or
a session-resident device buffer) must NEVER be served after a
data-mutating op — insert, flush, compact, truncate, ALTER, region
migration — and a stale-version poll falls back to recompute with
correct results (mirrors tests/test_dist_scan_cache.py for the new
layers)."""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.query.result_cache import ResultCache
from greptimedb_tpu.query import sessions as sessions_mod
from greptimedb_tpu.session import QueryContext
from greptimedb_tpu.storage.engine import EngineConfig
from greptimedb_tpu.telemetry.metrics import global_registry


def _counter(name: str, *labels) -> float:
    return global_registry.get(name).labels(*labels).value


def _enable_rc(inst, **kw) -> ResultCache:
    rc = ResultCache(enabled=True, **kw)
    inst.result_cache = rc
    inst.catalog.result_cache = rc
    return rc


@pytest.fixture()
def inst(tmp_path):
    inst = Standalone(str(tmp_path / "data"), warm_start=False,
                      prefer_device=False)
    yield inst
    inst.close()


@pytest.fixture()
def dev_inst(tmp_path):
    pytest.importorskip("jax")
    inst = Standalone(str(tmp_path / "data"), warm_start=False,
                      prefer_device=True)
    yield inst
    inst.close()


def _seed(inst, table="t", rows=24):
    inst.execute_sql(
        f"create table {table} (ts timestamp time index, host string "
        "primary key, v double)"
    )
    values = ", ".join(
        f"('h{i % 3}', {1_000_000 + i * 1000}, {float(i)})"
        for i in range(rows)
    )
    inst.execute_sql(f"insert into {table} (host, ts, v) values {values}")


Q = "select host, sum(v), count(*) from t group by host order by host"


# ----------------------------------------------------------------------
# frontend result cache: hits, metrics, invalidation (standalone)
# ----------------------------------------------------------------------

def test_result_cache_hit_serves_same_rows(inst):
    rc = _enable_rc(inst)
    _seed(inst)
    cold = inst.sql(Q).rows()
    h0 = _counter("gtpu_result_cache_hits_total")
    warm = inst.sql(Q).rows()
    assert warm == cold
    assert _counter("gtpu_result_cache_hits_total") > h0
    assert rc.entry_count >= 1 and rc.byte_count > 0


def test_insert_invalidates(inst):
    _enable_rc(inst)
    _seed(inst)
    before = inst.sql(Q).rows()
    inst.sql(Q)  # cached
    inst.execute_sql(
        "insert into t (host, ts, v) values ('h0', 99000000, 1000.0)"
    )
    after = inst.sql(Q).rows()
    assert after != before
    h0 = next(r for r in after if r[0] == "h0")
    b0 = next(r for r in before if r[0] == "h0")
    assert h0[1] == b0[1] + 1000.0 and h0[2] == b0[2] + 1


def test_flush_and_compact_invalidate(inst):
    _enable_rc(inst)
    _seed(inst)
    cold = inst.sql(Q).rows()
    inst.sql(Q)
    m0 = _counter("gtpu_result_cache_misses_total")
    table = inst.catalog.table("public", "t")
    table.flush()  # physical version bumps even though rows don't
    assert inst.sql(Q).rows() == cold
    assert _counter("gtpu_result_cache_misses_total") > m0
    # several flushed generations in one window trip the TWCS picker
    for round_ in range(4):
        inst.execute_sql(
            "insert into t (host, ts, v) values "
            + ", ".join(
                f"('h{i % 3}', {2_000_000 + round_ * 40_000 + i * 1000},"
                f" {float(i)})"
                for i in range(12)
            )
        )
        table.flush()
    want = inst.sql(Q).rows()
    inst.sql(Q)
    m1 = _counter("gtpu_result_cache_misses_total")
    compacted = sum(1 for r in table.regions if r.compact())
    assert compacted > 0
    assert inst.sql(Q).rows() == want
    assert _counter("gtpu_result_cache_misses_total") > m1


def test_truncate_and_alter_invalidate(inst):
    _enable_rc(inst)
    _seed(inst)
    inst.sql(Q)
    inst.sql(Q)
    inst.execute_sql("alter table t add column extra double")
    # schema change busts the key (version embeds column names)
    assert inst.sql("select count(*) from t").rows() == [[24]]
    inst.catalog.table("public", "t").truncate()
    assert inst.sql("select count(*) from t").rows() == [[0]]


def test_drop_purges_entries(inst):
    rc = _enable_rc(inst)
    _seed(inst)
    inst.sql(Q)
    assert rc.entry_count >= 1
    inst.execute_sql("drop table t")
    assert rc.entry_count == 0


def test_volatile_ttl_and_explain_bypass(inst):
    rc = _enable_rc(inst)
    _seed(inst)
    n0 = rc.entry_count
    # now() in the projection is evaluation-time dependent: never cached
    inst.sql("select count(*), now() from t")
    assert rc.entry_count == n0
    # a now()-folded WHERE bound re-fingerprints per call: caching it
    # would insert one dead never-hit entry per poll (volatile_bounds)
    inst.sql("select host, v from t where ts > now() - interval '100y'")
    inst.sql("select host, v from t where ts > now() - interval '100y'")
    assert rc.entry_count == n0
    inst.execute_sql(
        "create table tt (ts timestamp time index, host string "
        "primary key, v double) with (ttl = '1h')"
    )
    import time as _time

    now = int(_time.time() * 1000)
    inst.execute_sql(
        f"insert into tt (host, ts, v) values ('a', {now - 60_000}, 1.0)"
    )
    inst.sql("select host, sum(v) from tt group by host")
    assert rc.entry_count == n0  # TTL window is wall-clock-derived
    # EXPLAIN ANALYZE runs a real execution (never a cached payload)
    inst.sql(Q)
    res = inst.sql("explain analyze " + Q)
    text = "\n".join(res.cols[0].values.tolist())
    assert "Metrics:" in text


# ----------------------------------------------------------------------
# `since` delta cursor
# ----------------------------------------------------------------------

def test_since_filters_plain_select(inst):
    _seed(inst)
    ctx = QueryContext()
    ctx.extensions["since_ms"] = 1_000_000 + 11 * 1000
    res = inst.sql("select ts, host, v from t order by ts", ctx)
    ts = np.asarray(res.column("ts").values, np.int64)
    assert len(ts) == 12 and ts.min() > 1_011_000


def test_since_with_result_cache_serves_delta_from_full(inst):
    rc = _enable_rc(inst)
    _seed(inst)
    full = inst.sql("select ts, host, v from t").rows()
    assert rc.entry_count == 1
    h0 = _counter("gtpu_result_cache_hits_total")
    ctx = QueryContext()
    ctx.extensions["since_ms"] = 1_000_000 + 11 * 1000
    delta = inst.sql("select ts, host, v from t", ctx).rows()
    # served from the cached FULL result by a host-side row filter
    assert _counter("gtpu_result_cache_hits_total") > h0
    assert delta == [r for r in full if r[0] > 1_011_000]
    # a cursor past everything returns zero rows
    ctx2 = QueryContext()
    ctx2.extensions["since_ms"] = 99_000_000_000
    assert inst.sql("select ts, host, v from t", ctx2).rows() == []


def test_since_with_limit_executes_delta_not_cached_slice(inst):
    """The cursor applies BEFORE ORDER BY/LIMIT: a LIMIT plan's cached
    payload cannot be row-filtered (it holds only the first page), so a
    since-poll must execute the delta instead of returning []."""
    _enable_rc(inst)
    _seed(inst)
    q = "select ts, host, v from t order by ts limit 10"
    first = inst.sql(q).rows()
    assert len(first) == 10
    ctx = QueryContext()
    ctx.extensions["since_ms"] = first[-1][0]
    delta = inst.sql(q, ctx).rows()
    assert len(delta) == 10
    assert min(r[0] for r in delta) > first[-1][0]


def test_since_without_ts_projection_executes_delta(inst):
    """A plain select that does not project the time index cannot be
    delta-served from the cache (no column to filter on) — the
    execution path's scan tightening must answer instead."""
    _enable_rc(inst)
    _seed(inst)
    inst.sql("select host, v from t")  # cached full payload
    ctx = QueryContext()
    ctx.extensions["since_ms"] = 1_011_000
    delta = inst.sql("select host, v from t", ctx).rows()
    assert len(delta) == 12  # rows past the cursor, ts unprojected


def test_since_range_device_delta_readback(dev_inst):
    """Device RANGE path: a since-poll slices the session-resident
    buffer device-side — delta readback bytes land on
    gtpu_readback_bytes_total{mode=delta} and rows match the full
    result filtered by ts."""
    inst = dev_inst
    _seed(inst, rows=60)
    q = ("select ts, host, avg(v) range '10s' from t "
         "align '10s' by (host) order by ts, host")
    full = inst.sql(q).rows()
    assert inst.query_engine.last_exec_path == "device"
    cut = sorted({r[0] for r in full})[len({r[0] for r in full}) // 2]
    d0 = _counter("gtpu_readback_bytes_total", "delta")
    s0 = _counter("gtpu_session_hits_total")
    ctx = QueryContext()
    ctx.extensions["since_ms"] = cut
    delta = inst.sql(q, ctx).rows()
    assert delta == [r for r in full if r[0] > cut]
    assert _counter("gtpu_readback_bytes_total", "delta") > d0
    # the repeated shape reused the session-resident result buffer
    assert _counter("gtpu_session_hits_total") > s0


def test_since_range_fill_prev_matches_full(dev_inst):
    """FILL PREV + since: the fill math runs over the FULL grid, then
    only post-cursor cells emit — delta rows equal the full result
    filtered by ts (carry-over from pre-cursor steps preserved)."""
    inst = dev_inst
    inst.execute_sql(
        "create table f (ts timestamp time index, host string "
        "primary key, v double)"
    )
    # gaps so PREV actually fills
    rows = [(0, 1.0), (10_000, 2.0), (40_000, 5.0)]
    values = ", ".join(f"('h0', {ts}, {v})" for ts, v in rows)
    inst.execute_sql(f"insert into f (host, ts, v) values {values}")
    q = ("select ts, host, avg(v) range '10s' fill prev from f "
         "align '10s' by (host) order by ts")
    full = inst.sql(q).rows()
    ctx = QueryContext()
    ctx.extensions["since_ms"] = 10_000
    delta = inst.sql(q, ctx).rows()
    assert delta == [r for r in full if r[0] > 10_000]
    # the 20s/30s steps carry the PREV value from the 10s step
    filled = [r for r in delta if r[0] in (20_000, 30_000)]
    assert filled and all(r[2] == 2.0 for r in filled)


def test_session_registry_invalidation(dev_inst):
    inst = dev_inst
    _seed(inst, rows=60)
    q = ("select ts, host, max(v) range '10s' from t "
         "align '10s' by (host)")
    before = inst.sql(q).rows()
    s0 = _counter("gtpu_session_hits_total")
    assert inst.sql(q).rows() == before
    assert _counter("gtpu_session_hits_total") > s0
    inst.execute_sql(
        "insert into t (host, ts, v) values ('h0', 1000000, 500.0)"
    )
    after = inst.sql(q).rows()  # write invalidated the session buffer
    assert after != before
    assert any(r[2] == 500.0 for r in after)


def test_sessions_disabled_still_correct(dev_inst):
    inst = dev_inst
    _seed(inst, rows=60)
    q = ("select ts, host, min(v) range '10s' from t "
         "align '10s' by (host)")
    want = inst.sql(q).rows()
    sessions_mod.configure({"enable": False})
    try:
        assert inst.sql(q).rows() == want
    finally:
        sessions_mod.configure({"enable": True})


# ----------------------------------------------------------------------
# HTTP surface: ?since= param
# ----------------------------------------------------------------------

def test_http_since_param(inst, tmp_path):
    import json
    import urllib.request

    from greptimedb_tpu.servers.http import HttpServer

    _enable_rc(inst)
    _seed(inst)
    srv = HttpServer(inst, port=0).start()
    try:
        def sql(q, since=None):
            url = (f"http://127.0.0.1:{srv.port}/v1/sql?sql="
                   + urllib.parse.quote(q))
            if since is not None:
                url += f"&since={since}"
            with urllib.request.urlopen(url, timeout=10) as resp:
                return json.loads(resp.read())

        q = "select ts, host, v from t order by ts"
        full = sql(q)["output"][0]["records"]["rows"]
        delta = sql(q, since=1_011_000)["output"][0]["records"]["rows"]
        assert delta == [r for r in full if r[0] > 1_011_000]
        # bad cursor -> 400
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as ei:
            sql(q, since="nan")
        assert ei.value.code == 400
    finally:
        srv.stop()


# ----------------------------------------------------------------------
# distributed: full frontend -> datanode path (mirrors
# tests/test_dist_scan_cache.py)
# ----------------------------------------------------------------------

pytest.importorskip("pyarrow.flight")

from greptimedb_tpu.dist.client import MetaClient  # noqa: E402
from greptimedb_tpu.dist.frontend import DistInstance  # noqa: E402
from greptimedb_tpu.dist.region_server import RegionServer  # noqa: E402
from greptimedb_tpu.servers.flight import FlightFrontend  # noqa: E402
from greptimedb_tpu.servers.meta_http import MetasrvServer  # noqa: E402


class _Harness:
    def __init__(self, tmp_path, n_datanodes=2, *, store=None):
        self.meta = MetasrvServer(
            addr="127.0.0.1", port=0, data_home=str(tmp_path / "meta")
        ).start()
        self.meta_addr = f"127.0.0.1:{self.meta.port}"
        self.datanodes = {}
        for i in range(n_datanodes):
            home = str(tmp_path / f"dn{i}")
            inst = Standalone(
                engine_config=EngineConfig(data_root=home,
                                           enable_background=False),
                prefer_device=False, warm_start=False, store=store,
            )
            inst.region_server = RegionServer(inst.engine, home)
            fs = FlightFrontend(inst, port=0).start()
            MetaClient(self.meta_addr).register(
                i, f"127.0.0.1:{fs.server.port}"
            )
            self.datanodes[i] = (inst, fs)
        self.frontend = DistInstance(
            str(tmp_path / "fe"), self.meta_addr, prefer_device=False
        )
        self.rc = _enable_rc(self.frontend)

    def close(self):
        self.frontend.close()
        for inst, fs in self.datanodes.values():
            fs.close()
            inst.close()
        self.meta.close()


@pytest.fixture()
def harness(tmp_path):
    h = _Harness(tmp_path)
    yield h
    h.close()


DQ = "select host, sum(v), count(*) from d1 group by host order by host"


def _seed_dist(fe, rows=40):
    fe.execute_sql(
        "create table d1 (ts timestamp time index, host string "
        "primary key, v double) with (num_regions = 2)"
    )
    values = ", ".join(
        f"('h{i % 4}', {1_000_000 + i * 1000}, {float(i)})"
        for i in range(rows)
    )
    fe.execute_sql(f"insert into d1 (host, ts, v) values {values}")


def test_dist_hit_skips_datanode_execution(harness):
    fe = harness.frontend
    _seed_dist(fe)
    cold = fe.sql(DQ).rows()  # miss: executes the pushdown + caches
    q0 = _counter("gtpu_dist_query_total")
    h0 = _counter("gtpu_result_cache_hits_total")
    warm = fe.sql(DQ).rows()
    assert warm == cold
    assert _counter("gtpu_result_cache_hits_total") > h0
    # the hit ran NO distributed partial execution (version validation
    # is one metadata action, never a plan fan-out)
    assert _counter("gtpu_dist_query_total") == q0


def test_dist_insert_flush_truncate_alter_invalidate(harness):
    fe = harness.frontend
    _seed_dist(fe)
    before = fe.sql(DQ).rows()
    fe.sql(DQ)
    fe.execute_sql(
        "insert into d1 (host, ts, v) values ('h0', 99000000, 1000.0)"
    )
    after = fe.sql(DQ).rows()
    h0 = next(r for r in after if r[0] == "h0")
    b0 = next(r for r in before if r[0] == "h0")
    assert h0[1] == b0[1] + 1000.0 and h0[2] == b0[2] + 1
    # flush: rows unchanged, physical version bumped -> recompute
    m0 = _counter("gtpu_result_cache_misses_total")
    fe.sql(DQ)
    fe.catalog.table("public", "d1").flush()
    assert fe.sql(DQ).rows() == after
    assert _counter("gtpu_result_cache_misses_total") > m0
    # ALTER busts the key (schema rides the version tuple)
    fe.sql(DQ)
    fe.execute_sql("alter table d1 add column extra double")
    assert fe.sql("select count(*) from d1").rows() == [[41]]
    fe.catalog.table("public", "d1").truncate()
    assert fe.sql("select count(*) from d1").rows() == [[0]]


def test_dist_since_delta_through_ticket(harness):
    fe = harness.frontend
    _seed_dist(fe)
    q = "select ts, host, v from d1 order by ts, host"
    full = fe.sql(q).rows()
    ctx = QueryContext()
    ctx.extensions["since_ms"] = 1_000_000 + 19 * 1000
    delta = fe.sql(q, ctx).rows()
    assert delta == [r for r in full if r[0] > 1_019_000]
    assert len(delta) == 20


def test_dist_migration_recomputes_correctly(tmp_path):
    from greptimedb_tpu.storage.object_store import FsObjectStore

    shared = FsObjectStore(str(tmp_path / "shared_store"))
    h = _Harness(tmp_path, n_datanodes=2, store=shared)
    try:
        fe = h.frontend
        fe.execute_sql(
            "create table gm (ts timestamp time index, host string "
            "primary key, v double)"
        )
        fe.execute_sql(
            "insert into gm (host, ts, v) values ('a', 1000, 1.0), "
            "('b', 2000, 2.0)"
        )
        q = "select host, sum(v) from gm group by host order by host"
        want = fe.sql(q).rows()
        fe.sql(q)  # cached on the frontend
        ms = h.meta.metasrv
        rid = fe.catalog.table("public", "gm").info.region_ids()[0]
        src = ms.route_of(rid)
        ms.migrate_region(rid, 1 - src)
        fe.catalog.refresh()
        # version validation decides: a matching physical version may
        # legitimately serve the cached payload (migration preserves
        # data); a re-anchored one recomputes — both must be `want`
        assert fe.sql(q).rows() == want
        # a write on the NEW hosting is visible on the next poll
        fe.execute_sql(
            "insert into gm (host, ts, v) values ('a', 3000, 10.0)"
        )
        assert fe.sql(q).rows() == [["a", 11.0], ["b", 2.0]]
    finally:
        h.close()
