-- RANGE BY () / ALIGN TO semantics (common/range/by.sql)

CREATE TABLE rb (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

INSERT INTO rb (ts, host, v) VALUES
  (0, 'a', 2), (0, 'b', 4), (60000, 'a', 6), (60000, 'b', 8);

SELECT ts, sum(v) RANGE '1m' FROM rb ALIGN '1m' BY () ORDER BY ts;
----
ts|sum(v) RANGE 60000ms
0|6.0
60000|14.0

SELECT ts, host, min(v) RANGE '2m' FROM rb ALIGN '1m' BY (host) ORDER BY ts, host;
----
ts|host|min(v) RANGE 120000ms
-60000|a|2.0
-60000|b|4.0
0|a|2.0
0|b|4.0
60000|a|6.0
60000|b|8.0

SELECT ts, count(v) RANGE '1m' FROM rb ALIGN '1m' TO '1970-01-01 00:00:30' BY () ORDER BY ts;
----
ts|count(v) RANGE 60000ms
-30000|2.0
30000|2.0

DROP TABLE rb;

