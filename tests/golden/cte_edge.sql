-- CTE edges: multiple CTEs, chained references, CTE joined to itself
CREATE TABLE ce (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO ce VALUES (1000, 'a', 1.0), (2000, 'b', 2.0), (3000, 'c', 3.0);

WITH big AS (SELECT g, v FROM ce WHERE v > 1.0) SELECT g FROM big ORDER BY g;
----
g
b
c

WITH a AS (SELECT g, v FROM ce), b AS (SELECT g, v * 2 AS w FROM a) SELECT b.g, b.w FROM b ORDER BY b.g;
----
g|w
a|2.0
b|4.0
c|6.0

WITH x AS (SELECT g, v FROM ce) SELECT l.g, r.v FROM x l JOIN x r ON l.g = r.g ORDER BY l.g;
----
g|v
a|1.0
b|2.0
c|3.0

DROP TABLE ce;
