-- Timestamp functions, interval arithmetic, and column DEFAULTs
-- (behavior ports of the reference's common/timestamp + common/insert
-- sqlness areas)

CREATE TABLE events (
  ts TIMESTAMP TIME INDEX,
  level STRING DEFAULT 'info',
  score DOUBLE DEFAULT 7.5,
  n BIGINT
);

-- omitted columns take their declared DEFAULT
INSERT INTO events (ts, n) VALUES (3600000, 1);

-- explicit NULL stays NULL even with a DEFAULT declared
INSERT INTO events (ts, score, n) VALUES (7200000, NULL, 2);

SELECT level, score, n FROM events ORDER BY ts;
----
level|score|n
info|7.5|1
info|NULL|2

-- EXTRACT standard form and function form agree
SELECT extract(hour FROM ts) AS a, extract('hour', ts) AS b
FROM events ORDER BY ts;
----
a|b
1.0|1.0
2.0|2.0

SELECT date_trunc('hour', ts) FROM events ORDER BY ts;
----
date_trunc('hour', ts)
3600000
7200000

-- interval arithmetic on the time index
SELECT ts + INTERVAL '30 minutes' AS shifted FROM events ORDER BY ts;
----
shifted
5400000
9000000

SELECT n FROM events
WHERE ts >= TIMESTAMP '1970-01-01 02:00:00' - INTERVAL '1s';
----
n
2

-- timestamp string comparison coerces
SELECT n FROM events WHERE ts = '1970-01-01 01:00:00';
----
n
1

SELECT to_unixtime('1970-01-01 00:01:40') AS u;
----
u
100

SELECT date_format(ts, '%Y-%m-%d %H:%M:%S') FROM events ORDER BY ts LIMIT 1;
----
date_format(ts, '%Y-%m-%d %H:%M:%S')
1970-01-01 01:00:00

DROP TABLE events;
