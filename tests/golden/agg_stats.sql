-- Variance / stddev / percentile family (common/function: aggregate fns)

CREATE TABLE st (v DOUBLE, g STRING, ts TIMESTAMP TIME INDEX, PRIMARY KEY(g));

INSERT INTO st (v, g, ts) VALUES (2, 'a', 1000), (4, 'a', 2000), (4, 'a', 3000), (4, 'a', 4000), (5, 'a', 5000), (5, 'a', 6000), (7, 'a', 7000), (9, 'a', 8000);

SELECT stddev_pop(v) FROM st;
----
stddev_pop(v)
2.0

SELECT stddev_samp(v) FROM st;
----
stddev_samp(v)
2.13809

SELECT var_pop(v), var_samp(v) FROM st;
----
var_pop(v)|var_samp(v)
4.0|4.57143

SELECT min(v), max(v) FROM st;
----
min(v)|max(v)
2.0|9.0

SELECT percentile_cont(0.5) WITHIN GROUP (ORDER BY v) FROM st;
----
percentile_cont(0.5, v)
4.5

DROP TABLE st;

