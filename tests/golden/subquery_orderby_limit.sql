-- ORDER BY/LIMIT inside derived tables vs outer ordering
CREATE TABLE sol (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO sol VALUES (1000, 'a', 5.0), (2000, 'b', 1.0), (3000, 'c', 3.0), (4000, 'd', 4.0);

SELECT t.g FROM (SELECT g, v FROM sol ORDER BY v DESC LIMIT 2) t ORDER BY t.g;
----
g
a
d

SELECT t.g, t.v FROM (SELECT g, v FROM sol WHERE v > 1.5) t ORDER BY t.v LIMIT 2;
----
g|v
c|3.0
d|4.0

SELECT count(*) FROM (SELECT DISTINCT g FROM sol) d;
----
count(*)
4

DROP TABLE sol;
