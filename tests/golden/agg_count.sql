-- COUNT semantics (reference sqlness: common/aggregate/count.sql)

CREATE TABLE c (v DOUBLE, s STRING, ts TIMESTAMP TIME INDEX);

INSERT INTO c (v, s, ts) VALUES (1.0, 'a', 1000), (2.0, 'b', 2000);

INSERT INTO c (ts) VALUES (3000);

SELECT count(*) FROM c;
----
count(*)
3

SELECT count(v) FROM c;
----
count(v)
2

SELECT count(s) FROM c;
----
count(s)
2

SELECT count(*) FROM c WHERE v > 10;
----
count(*)
0

SELECT count(*), count(v), count(s) FROM c;
----
count(*)|count(v)|count(s)
3|2|2

DROP TABLE c;

