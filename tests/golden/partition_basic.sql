-- PARTITION ON expressions (partition.sql)

CREATE TABLE pt (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE)
PARTITION ON COLUMNS (host) (host < 'h5', host >= 'h5');

INSERT INTO pt (ts, host, v) VALUES (1000, 'h1', 1), (1000, 'h7', 7), (2000, 'h3', 3), (2000, 'h9', 9);

SELECT host, v FROM pt ORDER BY host;
----
host|v
h1|1.0
h3|3.0
h7|7.0
h9|9.0

SELECT host, v FROM pt WHERE host = 'h7';
----
host|v
h7|7.0

SELECT sum(v) FROM pt;
----
sum(v)
20.0

SELECT partition_name FROM information_schema.partitions WHERE table_name = 'pt';
----
partition_name
p0
p1

DROP TABLE pt;

