-- expressions over RANGE aggregates (common/range/nest.sql)

CREATE TABLE rn (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

INSERT INTO rn (ts, host, v) VALUES (0, 'a', 10), (10000, 'a', 20), (0, 'b', 100), (10000, 'b', 200);

SELECT ts, host, max(v) RANGE '10s' - min(v) RANGE '10s' FROM rn ALIGN '10s' BY (host) ORDER BY ts, host;
----
ts|host|max(v) RANGE 10000ms - min(v) RANGE 10000ms
0|a|0.0
0|b|0.0
10000|a|0.0
10000|b|0.0

SELECT ts, host, (avg(v) RANGE '20s') * 2 AS dbl FROM rn ALIGN '20s' BY (host) ORDER BY ts, host;
----
ts|host|dbl
0|a|30.0
0|b|300.0

SELECT ts, host, sum(v*2) RANGE '10s' FROM rn ALIGN '10s' BY (host) ORDER BY ts, host;
----
ts|host|sum(v * 2) RANGE 10000ms
0|a|20.0
0|b|200.0
10000|a|40.0
10000|b|400.0

DROP TABLE rn;

