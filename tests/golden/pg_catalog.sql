-- pg_catalog shims: the queryable tables psql \d / \dt and ORM
-- introspection hit (reference: src/catalog/src/system_schema/pg_catalog/)
CREATE TABLE metrics (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

SELECT nspname FROM pg_catalog.pg_namespace ORDER BY nspname;
----
nspname
information_schema
pg_catalog
public

SELECT relname, relkind FROM pg_catalog.pg_class ORDER BY relname;
----
relname|relkind
metrics|r

SELECT datname FROM pg_catalog.pg_database;
----
datname
public

SELECT typname, typlen FROM pg_catalog.pg_type WHERE oid = 25;
----
typname|typlen
text|-1

-- the \dt core shape: pg_class JOIN pg_namespace
SELECT c.relname FROM pg_catalog.pg_class c JOIN pg_catalog.pg_namespace n ON n.oid = c.relnamespace WHERE n.nspname = 'public' AND c.relkind = 'r' ORDER BY c.relname;
----
relname
metrics

-- bare names resolve when no user table shadows them
SELECT typname FROM pg_type WHERE oid = 16;
----
typname
bool

CREATE VIEW v_hosts AS SELECT host FROM metrics;

SELECT relname, relkind FROM pg_catalog.pg_class WHERE relkind = 'v';
----
relname|relkind
v_hosts|v

DROP VIEW v_hosts;

DROP TABLE metrics;
