-- EXPLAIN renders the logical plan shape
CREATE TABLE ex (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

EXPLAIN SELECT host, sum(v) FROM ex WHERE v > 1 GROUP BY host ORDER BY host LIMIT 3;
----
plan
SelectPlan[aggregate] table=ex
  Scan: ts=[None, None] matchers=[] residual=v > 1
  Aggregate: keys=['host'] aggs=['sum(v)']
  Sort: __key_0 ASC
  Limit: 3 offset=0

EXPLAIN SELECT ts, host, avg(v) RANGE '1m' FROM ex ALIGN '1m' BY (host);
----
plan
SelectPlan[range] table=ex
  Scan: ts=[None, None] matchers=[] residual=None
  Range: align=60000ms to=0 by=['host'] items=['mean RANGE 60000ms']

DROP TABLE ex;
