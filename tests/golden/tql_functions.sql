-- TQL scalar function coverage (promql/)

CREATE TABLE fx (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, greptime_value DOUBLE);

INSERT INTO fx (ts, host, greptime_value) VALUES (0, 'a', -4), (0, 'b', 9);

TQL EVAL (0, 0, '10s') abs(fx);
----
ts|value|host
0|4.0|a
0|9.0|b

TQL EVAL (0, 0, '10s') sqrt(abs(fx));
----
ts|value|host
0|2.0|a
0|3.0|b

TQL EVAL (0, 0, '10s') clamp_min(fx, 0);
----
ts|value|host
0|0.0|a
0|9.0|b

TQL EVAL (0, 0, '10s') ceil(fx / 2);
----
ts|value|host
0|-2.0|a
0|5.0|b

TQL EVAL (0, 0, '10s') topk(1, fx);
----
ts|value|__name__|host
0|9.0|fx|b

DROP TABLE fx;

