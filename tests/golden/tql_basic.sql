-- TQL basics: selector + range eval (common/tql)

CREATE TABLE http_requests (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, greptime_value DOUBLE);

INSERT INTO http_requests (ts, host, greptime_value) VALUES
  (0, 'a', 1), (10000, 'a', 2), (20000, 'a', 3),
  (0, 'b', 10), (10000, 'b', 20), (20000, 'b', 30);

TQL EVAL (0, 20, '10s') http_requests;
----
ts|value|__name__|host
0|1.0|http_requests|a
0|10.0|http_requests|b
10000|2.0|http_requests|a
10000|20.0|http_requests|b
20000|3.0|http_requests|a
20000|30.0|http_requests|b

TQL EVAL (10, 20, '10s') http_requests{host="a"};
----
ts|value|__name__|host
10000|2.0|http_requests|a
20000|3.0|http_requests|a

TQL EVAL (0, 20, '10s') sum(http_requests);
----
ts|value
0|11.0
10000|22.0
20000|33.0

DROP TABLE http_requests;

