-- CASE expression edges: searched/simple forms, NULL arms, nesting
CREATE TABLE cw (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO cw VALUES (1000, 'a', 1.0), (2000, 'b', NULL), (3000, 'c', 30.0);

SELECT g, CASE WHEN v > 10 THEN 'big' WHEN v IS NULL THEN 'none' ELSE 'small' END AS sz FROM cw ORDER BY g;
----
g|sz
a|small
b|none
c|big

SELECT g, CASE g WHEN 'a' THEN 1 WHEN 'b' THEN 2 END AS code FROM cw ORDER BY g;
----
g|code
a|1
b|2
c|NULL

SELECT g, CASE WHEN v IS NULL THEN NULL ELSE v * 2 END AS dbl FROM cw ORDER BY g;
----
g|dbl
a|2.0
b|NULL
c|60.0

SELECT sum(CASE WHEN v > 0 THEN 1 ELSE 0 END) AS positives FROM cw;
----
positives
2

DROP TABLE cw;
